"""Generation engine: jitted prefill + while-loop decode over a KV cache.

Capability parity: the reference's in-house generation stack
(realhf/impl/model/nn/real_llm_generate.py decode loop + CUDA-graph replay,
and the SGLang server backend realhf/impl/model/backend/sglang.py) — built
TPU-native:

- The whole (prefill → sample → decode*) pipeline is ONE jitted function per
  (batch, prompt-bucket, total-bucket) shape; `lax.while_loop` replaces the
  reference's CUDA-graph replay (XLA compiles the step once; no per-token
  Python).
- Group sampling (n responses/prompt) expands prompts before batching.
- Chunking: requests are length-sorted and packed into fixed-size batches
  so at most a handful of shapes ever compile.
- Weight hot-swap: `set_params` re-places the training params onto the
  generator's mesh/dtype — the colocated-mesh equivalent of the reference's
  save-to-disk + update_weights_from_disk dance (model_worker.py:1040-1067).

A continuous-batching (inflight) refill loop over this same decode step is
the planned next step for the async RL path (reference:
InflightBatchingGenerator, real_llm_generate.py:670).
"""

import dataclasses
import functools
from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

from areal_tpu.api.data_api import MicroBatchSpec, SequenceSample
from areal_tpu.api.model_api import Engine, GenerationHyperparameters
from areal_tpu.base import logging
from areal_tpu.base.topology import batch_sharding_degree
from areal_tpu.engines.packing import bucket_len
from areal_tpu.models import transformer as tfm
from areal_tpu.models.config import ModelConfig
from areal_tpu.ops.sampling import sample_token
from areal_tpu.parallel import sharding

logger = logging.getLogger("generator")


class GeneratorEngine(Engine):
    def __init__(
        self,
        cfg: ModelConfig,
        params: Dict[str, Any],
        mesh: Mesh,
        eos_token_id: int,
        pad_token_id: Optional[int] = None,
        compute_dtype=jnp.bfloat16,
        max_decode_batch: int = 64,
    ):
        if cfg.is_critic:
            raise ValueError("cannot generate from a critic model")
        self.cfg = cfg
        self.mesh = mesh
        self.eos_token_id = int(eos_token_id)
        self.pad_token_id = int(pad_token_id or eos_token_id)
        if jax.default_backend() == "cpu":
            compute_dtype = jnp.float32
        self.compute_dtype = compute_dtype
        self.max_decode_batch = max_decode_batch
        self.batch_shard = batch_sharding_degree(mesh)
        # Generation has no CP/PP path (decode is token-at-a-time and
        # latency-bound); only the flash half of the shared dispatch policy
        # applies to prefill.
        self._use_flash, _, pp_mesh, _, _ = sharding.attn_dispatch(mesh)
        if pp_mesh is not None:
            raise NotImplementedError(
                "GeneratorEngine on a pipe>1 mesh; use a pipe=1 layout for "
                "generation (decoupled gen/train meshes + param realloc)"
            )
        self._gen_fns: Dict[Tuple, Any] = {}
        self.set_params(params)

    # ---------------- weights ----------------

    def set_params(self, params) -> None:
        """Hot-swap weights (cast to compute dtype, shard onto our mesh)."""
        cast = jax.tree.map(
            lambda x: x.astype(self.compute_dtype)
            if jnp.issubdtype(x.dtype, jnp.floating)
            else x,
            params,
        )
        self.params = jax.device_put(
            cast, sharding.tree_named(self.mesh, sharding.param_pspecs(cast))
        )

    def get_params(self):
        return self.params

    # ---------------- generation ----------------

    def train_batch(self, *a, **k):
        raise NotImplementedError("GeneratorEngine is generation-only")

    def forward(self, *a, **k):
        raise NotImplementedError("GeneratorEngine is generation-only")

    def generate(
        self,
        sample: SequenceSample,
        mb_spec: MicroBatchSpec,
        gconfig: GenerationHyperparameters,
        prompt_key: str = "packed_prompts",
        seed: int = 0,
    ) -> SequenceSample:
        """Group-sample `gconfig.n` responses per prompt.

        Returns a SequenceSample (one element per prompt, `n` sequences per
        element — the reference's group layout, data_api docstring) with:
          packed_input_ids  — prompt+response tokens
          packed_logprobs   — seqlen-1 per sequence; response positions carry
                              the behavior logprobs, prompt positions 0
          prompt_mask       — True on prompt tokens
          seq_no_eos_mask   — 1.0 per sequence iff truncated (no EOS)
        """
        prompt_lens = sample.seqlens_of(prompt_key)
        bounds = sample.cu_seqlens(prompt_key)
        prompts = np.asarray(sample.data[prompt_key])
        n = gconfig.n

        # Expand ×n and sort by length (desc) to minimize padding waste.
        reqs = []  # (orig_idx, rep, tokens)
        for i in range(sample.bs):
            toks = prompts[bounds[i] : bounds[i + 1]]
            for r in range(n):
                reqs.append((i, r, toks))
        order = sorted(range(len(reqs)), key=lambda j: -len(reqs[j][2]))

        results: Dict[Tuple[int, int], Tuple[np.ndarray, np.ndarray, bool]] = {}
        key = jax.random.PRNGKey(seed)
        b_cap = max(self.batch_shard, self.max_decode_batch)
        for start in range(0, len(order), b_cap):
            chunk = [reqs[j] for j in order[start : start + b_cap]]
            key, sub = jax.random.split(key)
            self._generate_chunk(chunk, gconfig, sub, results)

        return self._assemble(sample, prompt_key, prompt_lens, results, n)

    # -- one fixed-shape chunk --

    def _generate_chunk(self, chunk, gconfig, key, results) -> None:
        b_real = len(chunk)
        b = b_real
        while b % self.batch_shard:
            b += 1
        sp = bucket_len(max(len(t) for (_, _, t) in chunk))
        s_total = bucket_len(sp + gconfig.max_new_tokens)

        prompt_tok = np.full((b, sp), self.pad_token_id, np.int32)
        prompt_len = np.zeros((b,), np.int32)
        for r, (_, _, toks) in enumerate(chunk):
            prompt_tok[r, : len(toks)] = toks
            prompt_len[r] = len(toks)

        fn = self._get_gen_fn(b, sp, s_total, gconfig)
        toks, logps, gen_len = fn(self.params, prompt_tok, prompt_len, key)
        toks, logps, gen_len = (
            np.asarray(toks),
            np.asarray(logps),
            np.asarray(gen_len),
        )
        for r, (i, rep, _) in enumerate(chunk):
            gl = int(gen_len[r])
            no_eos = gl == gconfig.max_new_tokens and (
                gl == 0 or toks[r, gl - 1] != self.eos_token_id
            )
            results[(i, rep)] = (toks[r, :gl], logps[r, :gl], no_eos)

    def _get_gen_fn(self, b, sp, s_total, g: GenerationHyperparameters):
        sig = (
            b, sp, s_total, g.max_new_tokens, g.min_new_tokens, g.greedy,
            g.top_p, g.top_k, g.temperature,
        )
        if sig in self._gen_fns:
            return self._gen_fns[sig]
        cfg = self.cfg
        eos = self.eos_token_id
        max_new = g.max_new_tokens

        @jax.jit
        def gen(params, prompt_tok, prompt_len, key):
            bsz = prompt_tok.shape[0]
            seg = (
                jnp.arange(sp)[None, :] < prompt_len[:, None]
            ).astype(jnp.int32)
            cache = tfm.init_kv_cache(cfg, bsz, s_total, dtype=self.compute_dtype)
            # prefill returns logits at each row's last prompt token — the
            # distribution over the first response token.
            logits0, cache = tfm.prefill(
                params, cfg, prompt_tok, seg, cache, use_flash=self._use_flash
            )

            out_toks = jnp.zeros((bsz, max_new), jnp.int32)
            out_logps = jnp.zeros((bsz, max_new), jnp.float32)
            done = jnp.zeros((bsz,), bool)
            gen_len = jnp.zeros((bsz,), jnp.int32)

            def cond(state):
                step, _, _, done, *_ = state
                return (step < max_new) & ~jnp.all(done)

            def body(state):
                step, logits, key, done, gen_len, out_toks, out_logps, cache = state
                key, sub = jax.random.split(key)
                if g.min_new_tokens > 0:
                    logits = jnp.where(
                        (step < g.min_new_tokens)
                        & (jnp.arange(logits.shape[-1]) == eos)[None, :],
                        -1e10,
                        logits,
                    )
                tok, logp = sample_token(
                    logits, sub,
                    temperature=g.temperature, top_k=g.top_k, top_p=g.top_p,
                    greedy=g.greedy,
                )
                tok = jnp.where(done, eos, tok)
                out_toks = out_toks.at[:, step].set(jnp.where(done, 0, tok))
                out_logps = out_logps.at[:, step].set(jnp.where(done, 0.0, logp))
                gen_len = gen_len + (~done).astype(jnp.int32)
                new_done = done | (tok == eos)
                pos = prompt_len + step
                next_logits, cache = tfm.decode_step(
                    params, cfg, tok, pos, cache, pos + 1
                )
                return (
                    step + 1, next_logits, key, new_done, gen_len,
                    out_toks, out_logps, cache,
                )

            state = (0, logits0, key, done, gen_len, out_toks, out_logps, cache)
            state = jax.lax.while_loop(cond, body, state)
            _, _, _, _, gen_len, out_toks, out_logps, _ = state
            return out_toks, out_logps, gen_len

        self._gen_fns[sig] = gen
        logger.info(
            f"compiled generator for shape b={b} sp={sp} s_total={s_total}"
        )
        return gen

    # -- output assembly --

    def _assemble(self, sample, prompt_key, prompt_lens, results, n):
        bs = sample.bs
        seq_ids, seq_logps, seq_masks = [], [], []
        seqlens_full: List[List[int]] = []
        seqlens_lp: List[List[int]] = []
        no_eos: List[List[float]] = []
        prompts = np.asarray(sample.data[prompt_key])
        bounds = sample.cu_seqlens(prompt_key)
        for i in range(bs):
            lens_i, lens_lp_i, noeos_i = [], [], []
            ptoks = prompts[bounds[i] : bounds[i + 1]]
            pl = prompt_lens[i]
            for r in range(n):
                gtoks, glogps, ne = results[(i, r)]
                full = np.concatenate([ptoks, gtoks]).astype(np.int32)
                seq_ids.append(full)
                mask = np.zeros(len(full), bool)
                mask[:pl] = True
                seq_masks.append(mask)
                lp = np.zeros(max(len(full) - 1, 0), np.float32)
                lp[pl - 1 : pl - 1 + len(gtoks)] = glogps
                seq_logps.append(lp)
                lens_i.append(len(full))
                lens_lp_i.append(max(len(full) - 1, 0))
                noeos_i.append(1.0 if ne else 0.0)
            seqlens_full.append(lens_i)
            seqlens_lp.append(lens_lp_i)
            no_eos.append(noeos_i)
        return SequenceSample(
            keys={
                "packed_input_ids", "packed_logprobs", "prompt_mask",
                "seq_no_eos_mask",
            },
            ids=list(sample.ids),
            seqlens={
                "packed_input_ids": seqlens_full,
                "prompt_mask": [list(x) for x in seqlens_full],
                "packed_logprobs": seqlens_lp,
                "seq_no_eos_mask": [[1] * n for _ in range(bs)],
            },
            data={
                "packed_input_ids": np.concatenate(seq_ids),
                "prompt_mask": np.concatenate(seq_masks),
                "packed_logprobs": np.concatenate(seq_logps)
                if seq_logps
                else np.zeros(0, np.float32),
                "seq_no_eos_mask": np.asarray(
                    [x for row in no_eos for x in row], np.float32
                ),
            },
        )
