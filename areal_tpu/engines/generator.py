"""Generation engine: jitted prefill + while-loop decode over a KV cache.

Capability parity: the reference's in-house generation stack
(realhf/impl/model/nn/real_llm_generate.py decode loop + CUDA-graph replay,
and the SGLang server backend realhf/impl/model/backend/sglang.py) — built
TPU-native:

- The whole (prefill → sample → decode*) pipeline is ONE jitted function per
  (batch, prompt-bucket, total-bucket) shape; `lax.while_loop` replaces the
  reference's CUDA-graph replay (XLA compiles the step once; no per-token
  Python).
- Group sampling (n responses/prompt) expands prompts before batching.
- Chunking: requests are length-sorted and packed into fixed-size batches
  so at most a handful of shapes ever compile.
- Weight hot-swap: `set_params` re-places the training params onto the
  generator's mesh/dtype — the colocated-mesh equivalent of the reference's
  save-to-disk + update_weights_from_disk dance (model_worker.py:1040-1067).

A continuous-batching (inflight) refill loop over this same decode step is
the planned next step for the async RL path (reference:
InflightBatchingGenerator, real_llm_generate.py:670).
"""

import dataclasses
import functools
import os
import threading
import time
from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

from areal_tpu.api.data_api import MicroBatchSpec, SequenceSample
from areal_tpu.api.model_api import (
    Engine,
    GenerationHyperparameters,
    SlotGoneError,
)
from areal_tpu.base import logging, metrics, tracer
from areal_tpu.base.distributed import to_host
from areal_tpu.base.topology import batch_sharding_degree
from areal_tpu.engines.offload import HostOffloadMixin
from areal_tpu.engines.packing import decode_bucket_len as bucket_len
from areal_tpu.engines.paging import PageAllocator, PagePoolExhausted
from areal_tpu.models import transformer as tfm
from areal_tpu.models.config import ModelConfig
from areal_tpu.ops.sampling import sample_token
from areal_tpu.parallel import sharding

logger = logging.getLogger("generator")


def _cache_nbytes(cache) -> int:
    """Total byte footprint of a KV cache/pool (host-side metadata only)."""
    total = 0
    for a in (cache.k, cache.v, cache.k_scale, cache.v_scale):
        if a is not None:
            total += a.size * a.dtype.itemsize
    return total


# SlotGoneError (typed "your episode's slot was reclaimed" failure) lives
# in api/model_api.py so HTTP/ZMQ clients can raise the same type without
# importing the engines layer; re-exported here for engine-side callers.


def _find_stop_end(toks, scan_from: int, stop_seqs) -> Optional[int]:
    """Earliest index just PAST a completed stop sequence whose match
    ends after `scan_from` — so a sequence straddling two decode chunks
    is still caught, exactly once.  None when nothing matches."""
    best = None
    for seq in stop_seqs:
        L = len(seq)
        if L == 0 or len(toks) < L:
            continue
        target = list(seq)
        for i in range(max(0, scan_from - L + 1), len(toks) - L + 1):
            if toks[i : i + L] == target:
                end = i + L
                if best is None or end < best:
                    best = end
                break
    return best


@dataclasses.dataclass
class _EpisodeSlot:
    """Host bookkeeping for one live episode pinned to a serving slot.

    The transcript itself lives in the shared session (`slot_prompt[s]`
    holds every forwarded token, the page table holds its KV); this
    records the episode-level state machine: turn count, per-turn decode
    budget, the stop-scan low-water mark, and whether an interrupt
    parked the episode mid-turn."""

    ep_id: str
    slot: int
    gconfig: GenerationHyperparameters
    token_budget: int  # max transcript tokens; 0 = session default
    turns: int = 0
    seq: int = 0  # LRU tick (bumped on every touch; eviction takes min)
    turn_start_len: int = 0  # transcript tokens when this turn began
    scan_from: int = 0  # stop-scan position within the current turn
    last_admit_tokens: int = 0  # teacher-forced tokens this call
    turn_max_new: int = 0  # effective per-turn budget (after clamp)
    budget_limited: bool = False  # turn_max_new was clamped by budget
    parked_mid_turn: bool = False  # interrupted inside a turn


@dataclasses.dataclass
class _PagedGenSession:
    """Parked state of an interrupted plain-paged inflight generate call.

    Everything the chunk loop carries between iterations, host AND device
    side, so `resume_generate()` can replay each live slot's last chunk
    under fresh weights and continue exactly where the loop stopped.  The
    PRNG key rides along and the replay consumes no keys, so an
    interrupted-then-resumed run under unchanged weights is token-
    identical to an uninterrupted one."""

    gconfig: GenerationHyperparameters
    key: Any  # jax PRNG key (chunk-split chain continues on resume)
    results: Dict
    n_slots: int
    n_pages: int
    max_pages: int
    chunk_t: int
    alloc: PageAllocator
    pool: Any  # device PagedKVCache
    logits_buf: Any  # device [n_slots, vocab] f32
    cache_len: np.ndarray
    gen_count: np.ndarray
    done_host: np.ndarray
    active: List[Optional[Tuple[int, int]]]
    toks_acc: Dict[int, List[int]]
    logps_acc: Dict[int, List[float]]
    pending: List
    # Per-slot prompt tokens + last chunk's emission count — together they
    # define the tail to replay on resume (history = prompt + toks_acc).
    slot_prompt: Dict[int, np.ndarray]
    last_emit: np.ndarray
    # Assembly context, filled by generate() at park time so
    # resume_generate() can return a finished SequenceSample.
    sample: Any = None
    prompt_key: str = "packed_prompts"
    prompt_lens: Any = None
    n: int = 1
    # ---- unified serving plane (chunked prefill, prefill_chunk > 0) ----
    # Per-row prefill progress lives HERE, not in a second compiled
    # program: prompt_buf[slot] holds the not-yet-forwarded prompt
    # remainder, prefill_rem counts tokens still to consume, prompt_off
    # indexes the next prompt_buf read.  A row with prefill_rem > 0 is
    # an admitting row inside the serving chunk; 0 means decoding.
    prefill_chunk: int = 0  # W = query lanes per row per inner step
    prompt_buf: Any = None  # host np [n_slots, pbw] int32
    prefill_rem: Any = None  # host np [n_slots] int32
    prompt_off: Any = None  # host np [n_slots] int32
    # First PRIVATE flat token position per slot (shared prompt pages
    # end here): 0 for owners, sp*page_size for prefix-cache followers.
    # Resume replay must never write below it.
    shared_from: Any = None  # host np [n_slots] int32
    slot_hash: Any = None  # Dict[slot, bytes] prompt hash per live slot
    # hash -> owner slot currently prefilling it; followers stay pending
    # until the owner registers the prefix (keeps a GRPO group's k
    # members sharing instead of racing k private prefills).
    inflight_prefix: Any = None  # Dict[bytes, int]
    peak_live: int = 0  # max simultaneously live slots (capacity sweep)
    # Speculative decoding through the serving chunk (spec_decode_k > 0):
    # device-resident history buffer (prompt + emitted, read by the
    # in-chunk n-gram proposer) and the one sampled-but-unverified token
    # per row.  Always allocated (cheap) — trace-time K>0 branches in the
    # chunk fn decide whether they are consumed.
    tokens_buf: Any = None  # device [n_slots, buf_w] int32
    pending_tok: Any = None  # device [n_slots] int32
    # ---- agent-serving episodes (engine-lifetime session only) ----
    # ep_id -> _EpisodeSlot for every episode currently pinning a slot;
    # active[s] holds the ep_id string (any non-None marks the slot
    # live for the shared privatize/reserve helpers).
    episodes: Any = None  # Dict[str, _EpisodeSlot]
    ep_seq: int = 0  # monotonic LRU tick source
    ep_budget: int = 0  # session default per-episode token budget


def _spec_emit(
    cfg, g, eos, rows, logits, drafts, sub, pending, cache_len, gen_count,
    done, out_toks, out_logps, out_fill, tokens_buf, active=None,
    n_valid=None,
):
    """Shared post-forward bookkeeping for one speculative decode step
    (dense AND paged cache layouts — one implementation so the two can
    never diverge in emission semantics): min-length EOS masking, exact
    accept/reject (`spec_accept`), first-EOS truncation, appends into
    the chunk output buffers and the device-resident history buffer.

    `active` [B] bool (default: ~done) masks rows that should emit this
    step — the ragged serving chunk passes (~done) & (~is_pref) & got-
    lanes so prefilling rows and lane-starved rows carry their state
    untouched.  `n_valid` [B] int32 forwards to `spec_accept` for lane-
    truncated verification (row b only forwarded n_valid[b] positions).

    Returns (tokens_buf, pending, cache_len, gen_count, done, out_toks,
    out_logps, out_fill) — the post-step carry pieces."""
    from areal_tpu.ops.sampling import spec_accept

    K = g.spec_decode_k
    if active is None:
        active = ~done
    if g.min_new_tokens > 0:
        not_enough = (
            gen_count[:, None] + jnp.arange(K + 1)[None, :]
        ) < g.min_new_tokens
        logits = jnp.where(
            not_enough[:, :, None]
            & (jnp.arange(cfg.vocab_size) == eos)[None, None, :],
            -1e10,
            logits,
        )
    emitted, logps, n_emit = spec_accept(
        logits, drafts, sub,
        temperature=g.temperature, top_k=g.top_k, top_p=g.top_p,
        greedy=g.greedy, n_valid=n_valid,
    )
    n_emit = jnp.where(active, n_emit, 0)
    # Truncate at the first EOS (inclusive).
    j_idx = jnp.arange(K + 1)[None, :]
    is_eos = (emitted == eos) & (j_idx < n_emit[:, None])
    eos_pos = jnp.min(jnp.where(is_eos, j_idx, K + 1), axis=1)
    n_emit = jnp.minimum(n_emit, eos_pos + 1)
    new_done = done | (active & jnp.any(is_eos, axis=1))
    valid = j_idx < n_emit[:, None]
    # Append to the output buffers at per-row fill offsets.
    cols = out_fill[:, None] + j_idx
    out_toks = out_toks.at[rows[:, None], cols].set(
        jnp.where(valid, emitted, -1)
    )
    out_logps = out_logps.at[rows[:, None], cols].set(
        jnp.where(valid, logps, 0.0)
    )
    out_fill = out_fill + n_emit
    # History: emitted tokens live at positions L+1..L+n_emit.
    bcols = jnp.minimum(
        cache_len[:, None] + 1 + j_idx, tokens_buf.shape[1] - 1
    )
    cur = tokens_buf[rows[:, None], bcols]
    tokens_buf = tokens_buf.at[rows[:, None], bcols].set(
        jnp.where(valid, emitted, cur)
    )
    new_pending = jnp.take_along_axis(
        emitted, jnp.clip(n_emit - 1, 0, K)[:, None], axis=1
    )[:, 0]
    pending2 = jnp.where(done | (n_emit == 0), pending, new_pending)
    return (
        tokens_buf, pending2, cache_len + n_emit, gen_count + n_emit,
        new_done, out_toks, out_logps, out_fill,
    )


class GeneratorEngine(HostOffloadMixin, Engine):
    def __init__(
        self,
        cfg: ModelConfig,
        params: Dict[str, Any],
        mesh: Mesh,
        eos_token_id: int,
        pad_token_id: Optional[int] = None,
        compute_dtype=jnp.bfloat16,
        max_decode_batch: int = 64,
        donation_safe_swap: bool = True,
        kv_cache_dtype: str = "auto",
        kv_paged: Optional[bool] = None,
        kv_page_size: int = 128,
        kv_pool_pages: int = 0,
        prefill_chunk_tokens: Optional[int] = None,
        kv_share_prefix: Optional[bool] = None,
        serving_admit_lanes: Optional[int] = None,
    ):
        if cfg.is_critic:
            raise ValueError("cannot generate from a critic model")
        self.cfg = cfg
        self.mesh = mesh
        self.eos_token_id = int(eos_token_id)
        self.pad_token_id = int(pad_token_id or eos_token_id)
        if jax.default_backend() == "cpu":
            compute_dtype = jnp.float32
        self.compute_dtype = compute_dtype
        self.max_decode_batch = max_decode_batch
        # Decode budget above which generate() refuses the static
        # single-program path even when every request fits one pool (see
        # generate() routing): 2048 steps ≈ tens of seconds per program,
        # comfortably under device-runtime watchdogs.
        self.static_path_max_new = 2048
        # "auto" = compute dtype; "int8" halves KV HBM per token (the
        # long-context capacity bound — see models.transformer.KVCache).
        # Applies to every inflight path, INCLUDING the serving plane:
        # chunked admission quantizes fresh KV once per chunk and all
        # query lanes attend the dequantized pool (spec stays
        # distribution-exact because drafts and verification score
        # against the same quantized-cache model).  The static short-
        # decode path keeps full precision (its windows are small).
        # Validated here because YAML/gen_backend_args bypass the CLI's
        # argparse choices — a silently ignored "INT8"/"int4" would OOM
        # the exact 16k decode the flag exists to make fit.
        if kv_cache_dtype not in ("auto", "int8"):
            raise ValueError(
                f"kv_cache_dtype must be 'auto' or 'int8', "
                f"got {kv_cache_dtype!r}"
            )
        self.kv_cache_dtype = kv_cache_dtype
        # Paged KV pool for the inflight family (plain + speculative):
        # fixed-size page pool + host free-list allocator instead of the
        # dense grow-by-doubling window — zero cache copies, exactly one
        # decode compilation per generate call, retired slots' pages
        # recycled into new admits.  Default ON; AREAL_PAGED_KV=0 (or
        # kv_paged=False) falls back to the dense window (kept for
        # parity tests and as the known-good path).
        if kv_paged is None:
            kv_paged = os.environ.get("AREAL_PAGED_KV", "1") != "0"
        self.kv_paged = bool(kv_paged)
        if kv_page_size < 1:
            raise ValueError(f"kv_page_size must be >= 1, got {kv_page_size}")
        if kv_pool_pages < 0:
            raise ValueError(
                f"kv_pool_pages must be >= 0 (0 = auto), got {kv_pool_pages}"
            )
        self.kv_page_size = int(kv_page_size)
        # 0 = auto: size the pool for the worst case (every slot at
        # prompt + max_new_tokens).  A positive value caps pool HBM and
        # makes admission wait for freed pages (PagePoolExhausted if a
        # LIVE slot cannot grow).
        self.kv_pool_pages = int(kv_pool_pages)
        # Unified serving plane (plain paged inflight only): admitted
        # prompts consume their tokens in W-sized slices INSIDE the same
        # ragged chunk step that advances live decodes — no stop-the-
        # world prefill program, no admission-shape zoo, decode_compiles
        # stays 1 under continuous admission.  W > 1 rides the decode
        # step's streamed weights (decode is bandwidth-bound; extra
        # query lanes reuse the stream, same economics as spec decode).
        # 0 = legacy two-program admit path (kept for parity tests).
        if prefill_chunk_tokens is None:
            prefill_chunk_tokens = int(
                os.environ.get("AREAL_PREFILL_CHUNK_TOKENS", "8")
            )
        if prefill_chunk_tokens < 0:
            raise ValueError(
                f"prefill_chunk_tokens must be >= 0 (0 = legacy admit "
                f"path), got {prefill_chunk_tokens}"
            )
        self.prefill_chunk_tokens = int(prefill_chunk_tokens)
        # Copy-on-write prompt sharing (serving plane only): a GRPO
        # group's k responses — and any cross-request repeat of the same
        # prompt — map the owner's full prompt pages and re-forward only
        # the sub-page tail, multiplying effective pool capacity by the
        # group size.  AREAL_KV_SHARE_PREFIX=0 disables.
        if kv_share_prefix is None:
            kv_share_prefix = (
                os.environ.get("AREAL_KV_SHARE_PREFIX", "1") != "0"
            )
        self.kv_share_prefix = bool(kv_share_prefix)
        # Serving-chunk lane budget headroom A: the packed token stream is
        # T = min(n_slots + A, n_slots * Wmax) lanes wide (rounded up to a
        # batch-shard multiple), where Wmax = max(W, K+1).  Every live row
        # always gets >= 1 lane (T >= n_slots); the A spare lanes are
        # shared by rows that want more (prefill slices, spec verify).
        # 0 = auto (4 * Wmax).  Undersizing is graceful: contended rows
        # progress slower, never wrong.
        if serving_admit_lanes is None:
            serving_admit_lanes = int(
                os.environ.get("AREAL_SERVING_ADMIT_LANES", "0")
            )
        if serving_admit_lanes < 0:
            raise ValueError(
                f"serving_admit_lanes must be >= 0 (0 = auto), "
                f"got {serving_admit_lanes}"
            )
        self.serving_admit_lanes = int(serving_admit_lanes)
        # Lane budget of the most recently compiled serving chunk fn
        # (T above) — bench/regression tooling reads it.
        self.serving_lane_budget = 0
        # When True (default), set_params COPIES any leaf whose buffers
        # alias the source tree — required when generation can overlap a
        # train step that donates those buffers (rollout_ahead).  In a
        # strictly synchronous colocated trial the alias is safe (nothing
        # decodes between the optimizer's donation and the rebind), and
        # skipping the copy saves a full extra parameter footprint in HBM
        # — the difference between a 1.5B model fitting or OOMing on one
        # 16 GB chip.
        self.donation_safe_swap = donation_safe_swap
        # Generation has no CP/PP path (decode is token-at-a-time and
        # latency-bound); only the flash half of the shared dispatch policy
        # applies to prefill.  A pipelined allocation is accepted by folding
        # its pipe axis into model: same chips, params stay sharded, no
        # bubble — the TPU answer to the reference's pipelined generation
        # (GenerateSchedule, static_schedule.py:199; see
        # topology.fold_pipe_into_model).
        self._use_flash, _, pp_mesh, _, _ = sharding.attn_dispatch(mesh, cfg)
        if pp_mesh is not None:
            from areal_tpu.base.topology import fold_pipe_into_model

            mesh = fold_pipe_into_model(mesh)
            self.mesh = mesh
            self._use_flash, _, pp_mesh, _, _ = sharding.attn_dispatch(
                mesh, cfg
            )
            assert pp_mesh is None
        self.batch_shard = batch_sharding_degree(mesh)
        self._gen_fns: Dict[Tuple, Any] = {}
        # Device dispatches spent admitting requests into freed slots
        # during the LAST generate() call — tests assert batching (one
        # dispatch per refill cycle, not one per admission).
        self.prefill_dispatches = 0
        # Per-generate() perf counters (reset in generate(); the bench
        # and the recompile-regression tests read them): decode-program
        # compilations, bytes moved by whole-cache grow copies, and the
        # last call's KV-memory utilization stats.
        self.decode_compiles = 0
        self.cache_copy_bytes = 0
        self.last_pool_stats: Dict[str, Any] = {}
        # Ragged-stream lane accounting (serving chunk only; reset in
        # generate()): lanes_dispatched = query lanes launched (chunk
        # steps x T), lanes_live = lanes carrying a real token,
        # lanes_slack = budgeted-but-idle lanes (compute eliminated, not
        # masked — the packed stream simply ends before them), and
        # dead_live_lanes = lanes that were live but mapped to no row /
        # an out-of-grant qpos.  The last is structurally zero; the bench
        # invariant leg asserts it ("dead-lane compute exactly 0").
        self.lanes_dispatched = 0
        self.lanes_live = 0
        self.lanes_slack = 0
        self.dead_live_lanes = 0
        # Interruptible generation (async RL): interrupt() makes the
        # plain-paged inflight loop park at its next chunk boundary
        # (generate() then returns None); resume_generate() replays each
        # live slot's last chunk under the CURRENT weights — rewriting
        # the tail KV on its already-mapped pages and refreshing the
        # next-token logits — then continues the loop.  The other decode
        # paths (dense, spec, static) ignore the event and run to
        # completion, so a weight push there degrades to a full drain.
        self._interrupt_evt = threading.Event()
        self._session: Optional[_PagedGenSession] = None
        self.resume_replays = 0
        # Agent-serving episodes: an engine-LIFETIME serving session
        # (slot pool + page pool) that multi-turn episodes pin slots in;
        # created lazily by the first episode_start().  Counters are
        # cumulative (never reset by generate()) — the agents check leg
        # reads deltas.
        self._ep_session: Optional[_PagedGenSession] = None
        self.episodes_started = 0
        self.episodes_evicted = 0
        self.episode_prefix_hits = 0
        self.episode_prefix_misses = 0
        # Load gauges for gen_server /health queue-depth-aware balancing:
        # slots live in the current chunk loop and the last sampled
        # KV-pool utilization.  `load_state` is the atomically replaced
        # (live_slots, kv_utilization) pair — a single tuple assignment,
        # so a cross-thread health poll can never see the two fields
        # from different chunk boundaries.
        reg = metrics.default_registry()
        self._m_tokens = reg.counter(
            "areal_gen_tokens_total", "response tokens generated"
        )
        self._m_goodput = reg.gauge(
            "areal_gen_goodput_tokens_per_second",
            "tokens/s over the last completed generate call",
        )
        self._m_decode_compiles = reg.counter(
            "areal_gen_decode_compiles_total",
            "jitted decode-chunk program compiles",
        )
        self._m_kv_util = reg.gauge(
            "areal_gen_kv_utilization_ratio",
            "live KV tokens / allocated cache tokens, last chunk",
        )
        self._m_kv_live = reg.gauge(
            "areal_gen_kv_live_tokens", "live KV tokens, last chunk"
        )
        self._m_kv_alloc = reg.gauge(
            "areal_gen_kv_allocated_tokens",
            "allocated KV cache tokens, last chunk",
        )
        self._m_live_slots = reg.gauge(
            "areal_gen_live_slots", "slots live in the current chunk loop"
        )
        self.kv_utilization = 0.0
        self.live_slots = 0
        self.load_state = (0, 0.0)
        self.set_params(params)

    def _set_live_slots(self, n: int) -> None:
        self.live_slots = int(n)
        self.load_state = (int(n), self.kv_utilization)
        self._m_live_slots.set(n)

    def perf_counters(self) -> Dict[str, int]:
        """Memory/compile counters for the worker's MFC spans (profile
        store fields; analysis/profile.py _WATERMARK_ARGS)."""
        out = {"compiles": int(self.decode_compiles)}
        if self.params is not None:
            out["param_bytes"] = int(
                sum(int(x.nbytes) for x in jax.tree.leaves(self.params))
            )
        ps = self.last_pool_stats
        if ps.get("pool_bytes") is not None:
            out["pool_bytes"] = int(ps["pool_bytes"])
        if ps.get("peak_allocated_bytes") is not None:
            out["pool_peak_bytes"] = int(ps["peak_allocated_bytes"])
        return out

    # ---------------- interruption (async weight sync) ----------------

    def interrupt(self) -> None:
        """Request the running generate() to park at the next chunk
        boundary.  Safe from any thread; a no-op for non-paged paths."""
        self._interrupt_evt.set()

    def clear_interrupt(self) -> None:
        self._interrupt_evt.clear()

    @property
    def interrupted(self) -> bool:
        """True iff a parked session is waiting for resume_generate()."""
        return self._session is not None

    @property
    def interrupt_requested(self) -> bool:
        """True while an interrupt is pending (set, not yet cleared) —
        episode drivers poll this before episode_resume() so a resume
        doesn't immediately re-park."""
        return self._interrupt_evt.is_set()

    @property
    def page_budget_tokens(self) -> Optional[int]:
        """Token capacity of an explicitly sized page pool (None when
        the pool is auto-sized) — the admission budget gen_server splits
        request groups against."""
        if not self.kv_paged or self.kv_pool_pages == 0:
            return None
        return self.kv_pool_pages * self.kv_page_size

    def group_footprint_tokens(
        self, prompt_len: int, max_new_tokens: int, n: int
    ) -> int:
        """Worst-case KV pool footprint (in tokens) of a group of `n`
        same-prompt requests, CoW-aware: when the serving plane shares
        prompt pages, the prompt's full pages are paid ONCE and each
        member adds only the sub-page tail plus its new-token budget —
        gen_server splits request groups against page_budget_tokens
        using this instead of the dense n*(prompt+new) product."""
        plen, mnew, n = int(prompt_len), int(max_new_tokens), int(n)
        if (
            not self.kv_paged
            or self.prefill_chunk_tokens <= 0
            or not self.kv_share_prefix
            or n <= 1
        ):
            return n * (plen + mnew)
        sp = max(0, (plen - 1) // self.kv_page_size)
        return sp * self.kv_page_size + n * ((plen - sp * self.kv_page_size) + mnew)

    # ---------------- weights ----------------

    def set_params(self, params) -> None:
        """Hot-swap weights (cast to compute dtype, shard onto our mesh)."""
        cast = jax.tree.map(
            lambda x: x.astype(self.compute_dtype)
            if jnp.issubdtype(x.dtype, jnp.floating)
            else x,
            params,
        )
        # New weights supersede any host-offloaded copy.
        self._host_offload = None
        self._offload_shardings = None
        placed = jax.device_put(
            cast, sharding.tree_named(self.mesh, sharding.param_pspecs(cast))
        )
        # Donation safety: same-dtype/same-sharding astype+device_put can
        # ALIAS the source engine's live buffers, which its optimizer step
        # later DONATES — async rollout would then decode from deleted
        # buffers.  Copy any leaf whose BUFFERS still alias the input
        # (object identity alone misses distinct Arrays sharing storage).
        # Synchronous trials opt out (donation_safe_swap=False): the alias
        # is never read between donation and the post-step rebind, and the
        # saved copy is a full parameter footprint of HBM.
        if self.donation_safe_swap:
            from areal_tpu.engines.offload import buffers_alias

            self.params = jax.tree.map(
                lambda p, orig: (
                    jnp.copy(p) if buffers_alias(p, orig) else p
                ),
                placed, params,
            )
        else:
            self.params = placed

    def get_params(self):
        self._ensure_loaded()
        self._require_params()
        return self.params

    def release_params(self) -> None:
        """Drop the weight reference (colocated synchronous loops).

        With donation_safe_swap=False the generator aliases the train
        master's buffers; a live alias blocks the optimizer step's buffer
        donation (XLA refuses to donate a referenced buffer, costing a
        transient extra parameter copy).  Between the last generate() and
        the post-step set_params() the weights are dead — release them so
        the optimizer updates in place.  Any offloaded host copy is stale
        by the same argument and is dropped too.  Any engine call before
        the next set_params() raises, which is the intended misuse
        signal."""
        self.params = None
        self._host_offload = None
        self._offload_shardings = None

    def _require_params(self) -> None:
        if self.params is None:
            raise RuntimeError(
                "GeneratorEngine weights were release_params()-ed; call "
                "set_params() before using the engine again"
            )

    # ---------------- generation ----------------

    def train_batch(self, *a, **k):
        raise NotImplementedError("GeneratorEngine is generation-only")

    def forward(self, *a, **k):
        raise NotImplementedError("GeneratorEngine is generation-only")

    def generate(
        self,
        sample: SequenceSample,
        mb_spec: MicroBatchSpec,
        gconfig: GenerationHyperparameters,
        prompt_key: str = "packed_prompts",
        seed: int = 0,
        inflight: Optional[bool] = None,
    ) -> SequenceSample:
        """Group-sample `gconfig.n` responses per prompt.

        Two execution modes over the same jitted model step:
        - static: length-sorted fixed-shape chunks (one jitted
          prefill+while-loop program per shape) — best when lengths are
          uniform;
        - inflight (continuous batching): a fixed slot pool where finished
          sequences retire and pending requests join between jitted T-token
          decode chunks — one straggler no longer stalls the whole chunk
          (reference: InflightBatchingGenerator,
          realhf/impl/model/nn/real_llm_generate.py:670).
        Default: inflight when there are more requests than decode slots.

        Returns a SequenceSample (one element per prompt, `n` sequences per
        element — the reference's group layout, data_api docstring) with:
          packed_input_ids  — prompt+response tokens
          packed_logprobs   — seqlen-1 per sequence; response positions carry
                              the behavior logprobs, prompt positions 0
          prompt_mask       — True on prompt tokens
          seq_no_eos_mask   — 1.0 per sequence iff truncated (no EOS)
        """
        self._ensure_loaded()
        self._require_params()
        if self._session is not None:
            raise RuntimeError(
                "an interrupted generation is parked; call "
                "resume_generate() before starting a new one"
            )
        self.prefill_dispatches = 0
        self.decode_compiles = 0
        self.cache_copy_bytes = 0
        self.last_pool_stats = {}
        self.lanes_dispatched = 0
        self.lanes_live = 0
        self.lanes_slack = 0
        self.dead_live_lanes = 0
        self._gen_t0 = time.monotonic()
        prompt_lens = sample.seqlens_of(prompt_key)
        bounds = sample.cu_seqlens(prompt_key)
        prompts = np.asarray(sample.data[prompt_key])
        n = gconfig.n

        # Expand ×n and sort by length (desc) to minimize padding waste.
        reqs = []  # (orig_idx, rep, tokens)
        for i in range(sample.bs):
            toks = prompts[bounds[i] : bounds[i + 1]]
            for r in range(n):
                reqs.append((i, r, toks))
        order = sorted(range(len(reqs)), key=lambda j: -len(reqs[j][2]))

        results: Dict[Tuple[int, int], Tuple[np.ndarray, np.ndarray, bool]] = {}
        key = jax.random.PRNGKey(seed)
        b_cap = max(self.batch_shard, self.max_decode_batch)
        if gconfig.spec_decode_k > 0:
            inflight = True  # spec decoding lives on the inflight path
        elif gconfig.stop:
            # Stop sequences are scanned host-side at chunk boundaries;
            # the static path is one fused device program with no such
            # boundary, so stop-bearing requests always go inflight.
            inflight = True
        elif inflight is None:
            # Static chunks win when every request fits one pool (uniform
            # lengths, no refills, zero per-chunk host round-trips);
            # inflight wins when stragglers would otherwise stall retired
            # slots.  Long decodes ALWAYS go inflight: the static path is
            # one device program whose while_loop runs the whole decode
            # (minutes on-device at 16k+ steps — TPU runtime watchdogs
            # kill it as a stuck kernel) and allocates the full final KV
            # window from step 0, streaming depth it doesn't need yet on
            # every step; the inflight chunk loop keeps each program
            # ~chunk_t tokens and grows the window geometrically.
            inflight = (
                len(reqs) > b_cap
                or gconfig.max_new_tokens > self.static_path_max_new
            )
        # Uncategorized envelope span (the inner prefill/decode spans carry
        # cat="compute"; host assembly gaps inside show as idle).
        with tracer.span(
            "generate",
            n_prompts=sample.bs,
            n_reqs=len(reqs),
            inflight=bool(inflight),
        ):
            if inflight:
                self._generate_inflight(
                    [reqs[j] for j in order], gconfig, key, results
                )
                if self._session is not None:
                    # Parked on interrupt: stash the assembly context so
                    # resume_generate() can finish the call.  None tells
                    # the caller no sample was produced yet.
                    st = self._session
                    st.sample = sample
                    st.prompt_key = prompt_key
                    st.prompt_lens = prompt_lens
                    st.n = n
                    return None
            else:
                for start in range(0, len(order), b_cap):
                    chunk = [reqs[j] for j in order[start : start + b_cap]]
                    key, sub = jax.random.split(key)
                    self._generate_chunk(chunk, gconfig, sub, results)

            return self._assemble(sample, prompt_key, prompt_lens, results, n)

    def resume_generate(self) -> Optional[SequenceSample]:
        """Continue a parked generate() under the engine's CURRENT
        weights.  Re-prefills only each live slot's last chunk of tokens
        (teacher-forced through its existing page table, overwriting the
        tail KV in place and refreshing the next-token logits), then
        re-enters the chunk loop — so a weight push costs one chunk of
        forward, not a drain + full re-prefill.  Returns the finished
        SequenceSample, or None if interrupted again."""
        st = self._session
        if st is None:
            raise RuntimeError("no interrupted generation to resume")
        self._ensure_loaded()
        self._require_params()
        self._session = None
        live = [s for s in range(st.n_slots) if st.active[s] is not None]
        if live:
            Q = st.chunk_t
            tokens = np.full((st.n_slots, Q), self.pad_token_id, np.int32)
            positions = np.zeros((st.n_slots, Q), np.int32)
            write_pos0 = np.zeros((st.n_slots,), np.int32)
            take_idx = np.zeros((st.n_slots,), np.int32)
            live_mask = np.zeros((st.n_slots,), bool)
            q_lens = np.zeros((st.n_slots,), np.int32)
            for s in live:
                hist = np.concatenate(
                    [st.slot_prompt[s], np.asarray(st.toks_acc[s], np.int32)]
                )
                # One KV per FORWARDED token: L == len(hist) for decoding
                # rows; a serving row parked mid-prefill has only
                # hist[:L] in cache (the rest still waits in prompt_buf)
                # and replays from that prefix.
                L = int(st.cache_len[s])
                hl = hist[:L]
                # Replay window: the last chunk's emissions (>= 1 so the
                # fresh logits always come from a real forward).  Padding
                # columns are DEAD queries (q_lens=r): their writes drop
                # in-kernel, so they can never scribble pad-token k/v
                # past the row's valid tail.  SHARED prompt pages
                # (prefix-cache followers) are read-only: clamp the
                # window to the slot's private region so the teacher-
                # forced rewrite can never touch a page other rows map.
                priv = (
                    int(st.shared_from[s])
                    if st.shared_from is not None
                    else 0
                )
                r = int(min(max(int(st.last_emit[s]), 1), Q, L - priv))
                if r <= 0:
                    continue  # nothing private to replay (cannot happen
                    # for rows that ran a chunk; kept as a guard)
                tokens[s, :r] = hl[L - r :]
                write_pos0[s] = L - r
                positions[s] = (L - r) + np.arange(Q)
                take_idx[s] = r - 1
                live_mask[s] = True
                q_lens[s] = r
            with tracer.span("resume_replay", cat="compute", n=len(live)):
                st.logits_buf, st.pool = self._get_paged_replay_fn(
                    st.n_slots, st.n_pages, st.max_pages, st.chunk_t
                )(
                    self.params, jnp.asarray(tokens), jnp.asarray(positions),
                    st.pool, jnp.asarray(st.alloc.table),
                    jnp.asarray(write_pos0), st.logits_buf,
                    jnp.asarray(take_idx), jnp.asarray(live_mask),
                    jnp.asarray(q_lens),
                )
        self.resume_replays += 1
        if st.prefill_chunk > 0:
            # The weight push invalidated every cached prompt KV: drop
            # the prefix-cache holds so post-resume admissions re-prefill
            # under the new weights instead of sharing stale pages (live
            # followers keep their mappings — their whole history KV is
            # equally pre-push, the accepted resume approximation).
            st.alloc.prefix_clear()
            if st.inflight_prefix is not None:
                st.inflight_prefix.clear()
            if st.slot_hash is not None:
                # Rows live across the push carry mixed-weight KV; if one
                # later finishes its prefill it must NOT register the
                # prefix (followers would inherit the mix — a fresh
                # admission re-prefills cleanly instead).
                st.slot_hash.clear()
            finished = self._run_serving_loop(st)
        else:
            finished = self._run_paged_loop(st)
        if not finished:
            return None
        return self._assemble(
            st.sample, st.prompt_key, st.prompt_lens, st.results, st.n
        )

    def _get_paged_replay_fn(
        self, n_slots: int, n_pages: int, max_pages: int, chunk_t: int
    ):
        """Teacher-forced tail replay for resume: Q history tokens per
        row forwarded through the existing page table (KV overwritten in
        place), next-token logits taken at each row's last valid query.
        Inactive rows carry sentinel tables, so their writes drop and
        their (garbage) logits are masked out by live_mask."""
        sig = ("paged_replay", n_slots, n_pages, max_pages, chunk_t)
        if sig in self._gen_fns:
            return self._gen_fns[sig]
        cfg = self.cfg

        @functools.partial(jax.jit, donate_argnums=(3, 6))
        def fn(params, tokens, positions, pool, page_table, write_pos0,
               logits_buf, take_idx, live_mask, q_lens):
            # Ragged replay: only the r real history columns per row are
            # live.  Padding columns and parked rows are DEAD queries —
            # their cache writes drop and their attention is fully masked,
            # so a short replay window can never scribble garbage k/v past
            # a row's valid tail (pages later rows would gather).
            logits_all, pool = tfm.decode_step_spec_paged(
                params, cfg, tokens, positions, pool, page_table, write_pos0,
                q_lens=q_lens,
            )
            fresh = jnp.take_along_axis(
                logits_all, take_idx[:, None, None], axis=1
            )[:, 0]
            logits_buf = jnp.where(
                live_mask[:, None], fresh.astype(logits_buf.dtype), logits_buf
            )
            return logits_buf, pool

        self._gen_fns[sig] = fn
        return fn

    # -- continuous batching (inflight refill) --

    def _generate_inflight(self, reqs, gconfig, key, results) -> None:
        """Fixed slot pool; retire finished rows and admit pending requests
        between jitted T-token decode chunks.  kv_paged (the default)
        routes to the paged-pool variants: fixed shapes, one decode
        compilation, zero grow copies."""
        if self.kv_paged:
            # ONE ragged serving chunk admits, decodes, and (K>0)
            # spec-verifies: every row is just a q_len in the packed
            # token stream, so spec drafts and int8 pools ride the same
            # program as plain decode — no two-program admit carve-outs.
            if self.prefill_chunk_tokens > 0:
                return self._generate_inflight_serving(
                    reqs, gconfig, key, results
                )
            if gconfig.spec_decode_k > 0:
                raise ValueError(
                    "spec_decode_k > 0 over the paged pool requires the "
                    "serving plane (prefill_chunk_tokens > 0); the legacy "
                    "two-program spec admit path was removed"
                )
            return self._generate_inflight_plain_paged(
                reqs, gconfig, key, results
            )
        if gconfig.spec_decode_k > 0:
            return self._generate_inflight_spec(reqs, gconfig, key, results)
        return self._generate_inflight_plain(reqs, gconfig, key, results)

    def _generate_inflight_plain(self, reqs, gconfig, key, results) -> None:
        n_slots = min(max(self.batch_shard, self.max_decode_batch), len(reqs))
        while n_slots % self.batch_shard:
            n_slots += 1
        max_prompt = max(len(t) for (_, _, t) in reqs)
        chunk_t = min(32, gconfig.max_new_tokens)
        # The cache starts at the smallest bucket covering the prompts and
        # GROWS through buckets as sequences lengthen: every decode step
        # streams the whole window, so depth it doesn't need yet is pure
        # wasted HBM bandwidth (the chunk fn recompiles per bucket, a
        # handful of shapes total).
        cur_w = bucket_len(max_prompt + chunk_t)
        cache = tfm.init_kv_cache(
            self.cfg, n_slots, cur_w,
            dtype=(
                "int8"
                if self.kv_cache_dtype == "int8"
                else self.compute_dtype
            ),
        )
        logits_buf = jnp.zeros((n_slots, self.cfg.vocab_size), jnp.float32)
        cache_len = np.zeros((n_slots,), np.int32)
        gen_count = np.zeros((n_slots,), np.int32)
        done_host = np.ones((n_slots,), bool)  # empty slots count as done
        active: List[Optional[Tuple[int, int]]] = [None] * n_slots
        toks_acc: Dict[int, List[int]] = {}
        logps_acc: Dict[int, List[float]] = {}
        pending = list(reversed(reqs))  # pop() takes the longest first

        while pending or any(a is not None for a in active):
            # Refill ALL free slots with ONE jitted multi-row prefill
            # (serial batch-1 admissions would cost ~2k device round-trips
            # at 512 prompts × 4 samples before steady state).
            admits = self._take_admits(active, pending, n_slots)
            if admits:
                rows, plens, slots = self._pack_admits(admits, n_slots)
                with tracer.span("prefill", cat="compute", n=len(admits)):
                    logits_buf, cache = self._get_prefill_slots_fn()(
                        self.params, jnp.asarray(rows), jnp.asarray(plens),
                        cache, logits_buf, jnp.asarray(slots),
                    )
                self.prefill_dispatches += 1
                for s, i, rep, toks in admits:
                    cache_len[s] = len(toks)
                    gen_count[s] = 0
                    done_host[s] = False
                    active[s] = (i, rep)
                    toks_acc[s] = []
                    logps_acc[s] = []

            # Grow the cache window when the next chunk could overflow it.
            # Geometric (doubling) growth bounds recompiles + cache copies
            # to O(log length); dead slots are excluded (cache_len resets
            # on retirement).
            old_bytes = _cache_nbytes(cache)
            cache, new_w = self._grow_kv_cache(
                cache, cur_w, int(cache_len.max()) + chunk_t
            )
            if new_w != cur_w:
                self.cache_copy_bytes += old_bytes
                cur_w = new_w
            self._accum_pool_stats(
                "dense", int(cache_len.sum()), n_slots * cur_w
            )

            # One jitted chunk: up to chunk_t tokens for every live slot.
            decode_fn = self._get_inflight_decode_fn(
                n_slots, cur_w, chunk_t, gconfig
            )
            key, sub = jax.random.split(key)
            # The to_host() calls inside the span force device sync, so
            # the span covers actual chunk execution, not just dispatch.
            with tracer.span("decode_chunk", cat="compute", t=chunk_t):
                (
                    out_toks, out_logps, logits_buf, cache,
                    new_cache_len, new_gen_count, new_done,
                ) = decode_fn(
                    self.params, cache, logits_buf,
                    jnp.asarray(cache_len), jnp.asarray(gen_count),
                    jnp.asarray(done_host), sub,
                )
                out_toks = to_host(out_toks)
                out_logps = to_host(out_logps)
            cache_len = to_host(new_cache_len).copy()
            gen_count = to_host(new_gen_count).copy()
            new_done = to_host(new_done)

            # Host bookkeeping: append tokens, retire finished slots.
            self._drain_chunk_outputs(
                out_toks, out_logps, new_done, active, toks_acc, logps_acc,
                results, done_host, cache_len, gconfig.max_new_tokens,
                stop_seqs=gconfig.stop,
            )

    def _drain_chunk_outputs(
        self, out_toks, out_logps, new_done, active, toks_acc, logps_acc,
        results, done_host, cache_len, max_new: int, on_retire=None,
        stop_seqs=(),
    ) -> None:
        """Shared inflight bookkeeping (plain + speculative loops): append
        each live slot's chunk output (rows are contiguous, -1-terminated),
        finish on EOS, a matched stop sequence (the stop tokens stay in
        the output), or the token budget, retire finished slots (a dead
        slot must not drive cache growth).  `on_retire(slot)` fires when a
        slot finishes — the paged loops hook it to recycle the slot's
        pages into the free list."""
        for s in range(len(active)):
            if active[s] is None:
                continue
            row = out_toks[s]
            stop = np.flatnonzero(row < 0)  # -1-terminated within the chunk
            limit = int(stop[0]) if stop.size else row.shape[0]
            limit = min(limit, max(0, max_new - len(toks_acc[s])))
            eos = np.flatnonzero(row[:limit] == self.eos_token_id)
            if eos.size:  # keep the EOS token itself, drop the tail
                limit = int(eos[0]) + 1
            # One batched host conversion per slot per chunk — a per-token
            # float()/int() here would be a per-scalar sync if a caller
            # ever passed device arrays (rule host-sync).
            prev_len = len(toks_acc[s])
            toks_acc[s].extend(row[:limit].tolist())
            logps_acc[s].extend(out_logps[s, :limit].tolist())
            # Stop sequences are a HOST-side contract (the compiled chunk
            # keys only on geometry + sampling knobs, so adding a stop
            # set never recompiles): scan the accumulated tail, truncate
            # just past the match.
            cut = (
                _find_stop_end(toks_acc[s], prev_len, stop_seqs)
                if stop_seqs
                else None
            )
            if cut is not None:
                del toks_acc[s][cut:]
                del logps_acc[s][cut:]
            finished = (
                cut is not None
                or len(toks_acc[s]) >= max_new
                or (toks_acc[s] and toks_acc[s][-1] == self.eos_token_id)
            )
            if finished:
                i, rep = active[s]
                gtoks = np.asarray(toks_acc[s], np.int32)
                glogps = np.asarray(logps_acc[s], np.float32)
                no_eos = not (len(gtoks) and gtoks[-1] == self.eos_token_id)
                results[(i, rep)] = (gtoks, glogps, no_eos)
                active[s] = None
                done_host[s] = True
                cache_len[s] = 0
                if on_retire is not None:
                    on_retire(s)
            else:
                done_host[s] = new_done[s]

    def _take_admits(self, active, pending, n_slots):
        """Assign pending requests to free slots (longest-prompt first —
        `pending` is kept sorted ascending so pop() takes the longest)."""
        admits = []
        for s in range(n_slots):
            if active[s] is None and pending:
                i, rep, toks = pending.pop()
                admits.append((s, i, rep, toks))
        self._set_live_slots(sum(a is not None for a in active) + len(admits))
        tracer.counter(
            "gen_slots", live=self.live_slots, pending=len(pending)
        )
        return admits

    def _pack_admits(self, admits, n_slots):
        """Pack one refill cycle's admissions into fixed-shape arrays.

        SP buckets to the longest admitted prompt; M buckets to the next
        power of two so only O(log slots × log prompt) admission shapes
        ever compile.  Padding rows carry one pad token (NaN-safe through
        attention) and an out-of-range slot id — the device-side scatters
        drop them (`prefill_into_slots`)."""
        sp = bucket_len(max(len(t) for (_, _, _, t) in admits))
        m = 1
        while m < len(admits):
            m *= 2
        rows = np.full((m, sp), self.pad_token_id, np.int32)
        plens = np.ones((m,), np.int32)
        slots = np.full((m,), n_slots, np.int32)
        for j, (s, _, _, toks) in enumerate(admits):
            rows[j, : len(toks)] = toks
            plens[j] = len(toks)
            slots[j] = s
        return rows, plens, slots

    def _get_prefill_slots_fn(self):
        sig = ("prefill_slots",)
        if sig in self._gen_fns:
            return self._gen_fns[sig]
        cfg = self.cfg
        # Admission batches are ragged (1..n_slots rows): a Mesh
        # (shard_map'd flash) cannot shard them over data/fsdp — fall back
        # to dense for this path only.
        use_flash = (
            False if isinstance(self._use_flash, Mesh) else self._use_flash
        )

        # Cache/logits donated: the caller rebinds both from the outputs,
        # and a non-donated multi-GB cache would be COPIED every refill.
        @functools.partial(jax.jit, donate_argnums=(3, 4))
        def fn(params, rows, plens, cache, logits_buf, slot_rows):
            logits, cache = tfm.prefill_into_slots(
                params, cfg, rows, plens, cache, slot_rows,
                use_flash=use_flash,
            )
            logits_buf = logits_buf.at[slot_rows].set(logits, mode="drop")
            return logits_buf, cache

        self._gen_fns[sig] = fn
        return fn

    def _get_inflight_decode_fn(
        self, n_slots: int, s_max: int, chunk_t: int,
        g: GenerationHyperparameters,
    ):
        sig = (
            "inflight", n_slots, s_max, chunk_t, g.min_new_tokens, g.greedy,
            g.top_p, g.top_k, g.temperature,
        )
        if sig in self._gen_fns:
            return self._gen_fns[sig]
        cfg = self.cfg
        eos = self.eos_token_id

        # Cache/logits donated: rebound from outputs each chunk; without
        # donation every chunk call copies the full KV cache.
        @functools.partial(jax.jit, donate_argnums=(1, 2))
        def fn(params, cache, logits, cache_len, gen_count, done, key):
            out_toks = jnp.full((n_slots, chunk_t), -1, jnp.int32)
            out_logps = jnp.zeros((n_slots, chunk_t), jnp.float32)

            def body(t, st):
                (logits, cache, cache_len, gen_count, done, out_toks,
                 out_logps) = st
                sub = jax.random.fold_in(key, t)
                lg = logits
                if g.min_new_tokens > 0:
                    lg = jnp.where(
                        (gen_count < g.min_new_tokens)[:, None]
                        & (jnp.arange(cfg.vocab_size) == eos)[None, :],
                        -1e10,
                        lg,
                    )
                tok, logp = sample_token(
                    lg, sub,
                    temperature=g.temperature, top_k=g.top_k, top_p=g.top_p,
                    greedy=g.greedy,
                )
                out_toks = jax.lax.dynamic_update_slice(
                    out_toks, jnp.where(done, -1, tok)[:, None], (0, t)
                )
                out_logps = jax.lax.dynamic_update_slice(
                    out_logps, jnp.where(done, 0.0, logp)[:, None], (0, t)
                )
                # Rows already done keep replaying their last slot (the
                # write is harmless garbage past their valid window).
                positions = cache_len
                next_logits, cache2 = tfm.decode_step_inflight(
                    params, cfg, jnp.where(done, eos, tok), positions, cache,
                    slots=jnp.minimum(cache_len, s_max - 1),
                    valid_to=jnp.minimum(cache_len + 1, s_max),
                )
                new_done = done | (tok == eos)
                cache_len = cache_len + (~done).astype(jnp.int32)
                gen_count = gen_count + (~done).astype(jnp.int32)
                return (
                    next_logits, cache2, cache_len, gen_count, new_done,
                    out_toks, out_logps,
                )

            st = (logits, cache, cache_len, gen_count, done, out_toks, out_logps)
            st = jax.lax.fori_loop(0, chunk_t, body, st)
            logits, cache, cache_len, gen_count, done, out_toks, out_logps = st
            return out_toks, out_logps, logits, cache, cache_len, gen_count, done

        self._gen_fns[sig] = fn
        self.decode_compiles += 1
        self._m_decode_compiles.inc()
        logger.info(
            f"compiled inflight decoder n_slots={n_slots} s_max={s_max} "
            f"chunk={chunk_t}"
        )
        return fn

    # -- shared inflight helpers --

    @staticmethod
    def _grow_kv_cache(cache, cur_w: int, need: int):
        """Geometric (doubling) window growth — bounds recompiles and cache
        copies to O(log length); no-op when `need` fits."""
        if need <= cur_w:
            return cache, cur_w
        new_w = bucket_len(max(need, 2 * cur_w))
        pad = [(0, 0), (0, 0), (0, new_w - cur_w), (0, 0), (0, 0)]
        return (
            tfm.KVCache(
                k=jnp.pad(cache.k, pad),
                v=jnp.pad(cache.v, pad),
                k_scale=(
                    jnp.pad(cache.k_scale, pad[:-1])
                    if cache.quantized
                    else None
                ),
                v_scale=(
                    jnp.pad(cache.v_scale, pad[:-1])
                    if cache.quantized
                    else None
                ),
            ),
            new_w,
        )

    def _accum_pool_stats(
        self, kind: str, live_tokens: int, allocated_tokens: int
    ) -> None:
        """Accumulate per-chunk KV-memory utilization (live tokens /
        allocated cache tokens) into last_pool_stats — the bench reports
        this next to tokens/s for the dense-vs-paged comparison."""
        st = self.last_pool_stats
        if st.get("kind") != kind:
            st.clear()
            st.update(
                kind=kind, samples=0, live_tokens=0, allocated_tokens=0
            )
        st["samples"] += 1
        st["live_tokens"] += int(live_tokens)
        st["allocated_tokens"] += int(allocated_tokens)
        st["utilization"] = st["live_tokens"] / max(st["allocated_tokens"], 1)
        # Instantaneous utilization, exposed through gen_server /health.
        self.kv_utilization = int(live_tokens) / max(int(allocated_tokens), 1)
        self.load_state = (self.live_slots, self.kv_utilization)
        self._m_kv_util.set(self.kv_utilization)
        self._m_kv_live.set(int(live_tokens))
        self._m_kv_alloc.set(int(allocated_tokens))
        # Per-chunk sampled gauge: KV pool pressure over time in the trace.
        tracer.counter(
            "kv_pool",
            live_tokens=int(live_tokens),
            allocated_tokens=int(allocated_tokens),
            utilization=int(live_tokens) / max(int(allocated_tokens), 1),
        )

    # -- paged inflight (fixed page pool + host free-list allocator) --

    def _paged_kv_dtype(self):
        return "int8" if self.kv_cache_dtype == "int8" else self.compute_dtype

    def _generate_inflight_plain_paged(
        self, reqs, gconfig, key, results
    ) -> None:
        """The plain inflight loop over a paged KV pool: the pool and the
        decode program have ONE fixed shape for the whole generate call
        (compiled exactly once), window growth is a host-side page-index
        append, and retired slots' pages are recycled into new admits.
        Replaces grow-by-doubling (`_generate_inflight_plain`), which
        pays a full-cache copy + recompile at every bucket boundary."""
        n_slots = min(max(self.batch_shard, self.max_decode_batch), len(reqs))
        while n_slots % self.batch_shard:
            n_slots += 1
        ps = self.kv_page_size
        chunk_t = min(32, gconfig.max_new_tokens)
        max_prompt = max(len(t) for (_, _, t) in reqs)
        # Page-table width: worst-case per-slot footprint (full prompt +
        # the whole new-token budget + chunk slack — within a chunk,
        # writes land up to chunk_t past the pre-chunk live length).
        max_pages = -(-(max_prompt + gconfig.max_new_tokens + chunk_t) // ps)
        n_pages = self.kv_pool_pages or n_slots * max_pages
        st = _PagedGenSession(
            gconfig=gconfig,
            key=key,
            results=results,
            n_slots=n_slots,
            n_pages=n_pages,
            max_pages=max_pages,
            chunk_t=chunk_t,
            alloc=PageAllocator(n_pages, ps, n_slots, max_pages),
            pool=tfm.init_paged_kv_cache(
                self.cfg, n_pages, ps, dtype=self._paged_kv_dtype()
            ),
            logits_buf=jnp.zeros((n_slots, self.cfg.vocab_size), jnp.float32),
            cache_len=np.zeros((n_slots,), np.int32),
            gen_count=np.zeros((n_slots,), np.int32),
            done_host=np.ones((n_slots,), bool),
            active=[None] * n_slots,
            toks_acc={},
            logps_acc={},
            pending=list(reversed(reqs)),
            slot_prompt={},
            last_emit=np.zeros((n_slots,), np.int32),
        )
        st.alloc.page_bytes = _cache_nbytes(st.pool) // n_pages
        self._run_paged_loop(st)

    def _run_paged_loop(self, st: "_PagedGenSession") -> bool:
        """The plain-paged chunk loop, interruptible at chunk boundaries:
        checks the interrupt event at the top of every iteration and
        parks the whole session (device pool + host bookkeeping) when
        set.  Returns True when all requests finished, False when
        parked (self._session then holds the state for
        resume_generate())."""
        gconfig = st.gconfig
        alloc = st.alloc
        n_slots, ps, chunk_t = st.n_slots, alloc.page_size, st.chunk_t
        decode_fn = self._get_paged_decode_fn(
            n_slots, st.n_pages, st.max_pages, chunk_t, gconfig
        )
        while st.pending or any(a is not None for a in st.active):
            if self._interrupt_evt.is_set():
                self._session = st
                tracer.counter(
                    "gen_interrupt",
                    parked_live=sum(a is not None for a in st.active),
                    parked_pending=len(st.pending),
                )
                return False
            admits = self._take_admits_paged(
                st.active, st.pending, n_slots, alloc, chunk_t
            )
            if admits:
                rows, plens, slots, page_rows = self._pack_admits_paged(
                    admits, n_slots, alloc
                )
                with tracer.span("prefill", cat="compute", n=len(admits)):
                    st.logits_buf, st.pool = self._get_prefill_pages_fn()(
                        self.params, jnp.asarray(rows), jnp.asarray(plens),
                        st.pool, st.logits_buf, jnp.asarray(slots),
                        jnp.asarray(page_rows),
                    )
                self.prefill_dispatches += 1
                for s, i, rep, toks in admits:
                    st.cache_len[s] = len(toks)
                    st.gen_count[s] = 0
                    st.done_host[s] = False
                    st.active[s] = (i, rep)
                    st.toks_acc[s] = []
                    st.logps_acc[s] = []
                    st.slot_prompt[s] = np.asarray(toks, np.int32)

            # Map pages covering the next chunk for every live slot —
            # the jitted chunk must never need a page the table lacks.
            # This is the paged replacement for _grow_kv_cache: an int
            # append on the host, no device copy, no recompile.
            for s in range(n_slots):
                if st.active[s] is not None:
                    alloc.reserve(s, int(st.cache_len[s]) + chunk_t)
            self._accum_pool_stats(
                "paged", int(st.cache_len.sum()), alloc.allocated_pages() * ps
            )

            st.key, sub = jax.random.split(st.key)
            prev_gen = st.gen_count.copy()
            with tracer.span("decode_chunk", cat="compute", t=chunk_t):
                (
                    out_toks, out_logps, st.logits_buf, st.pool,
                    new_cache_len, new_gen_count, new_done,
                ) = decode_fn(
                    self.params, st.pool, st.logits_buf,
                    jnp.asarray(alloc.table), jnp.asarray(st.cache_len),
                    jnp.asarray(st.gen_count), jnp.asarray(st.done_host),
                    sub,
                )
                out_toks = to_host(out_toks)
                out_logps = to_host(out_logps)
            st.cache_len = to_host(new_cache_len).copy()
            st.gen_count = to_host(new_gen_count).copy()
            # Tokens each slot emitted THIS chunk = the tail a resume
            # must replay under fresh weights.
            st.last_emit = st.gen_count - prev_gen

            def _retire(s):
                alloc.release(s)
                st.slot_prompt.pop(s, None)

            self._drain_chunk_outputs(
                out_toks, out_logps, to_host(new_done), st.active,
                st.toks_acc, st.logps_acc, st.results, st.done_host,
                st.cache_len, gconfig.max_new_tokens, on_retire=_retire,
                stop_seqs=gconfig.stop,
            )
        self.last_pool_stats.update(
            pool_pages=st.n_pages, page_size=ps,
            pages_recycled=alloc.pages_recycled,
            peak_pages_used=alloc.peak_pages_used,
            pool_bytes=alloc.pool_bytes(),
            peak_allocated_bytes=alloc.peak_pages_used * alloc.page_bytes,
        )
        self._set_live_slots(0)
        return True

    def _take_admits_paged(self, active, pending, n_slots, alloc, slack):
        """`_take_admits` against the page budget: a request is admitted
        only when the allocator can map its prompt plus `slack` decode
        tokens; otherwise it stays pending until retirements free pages.
        Raises PagePoolExhausted when the pool cannot hold even ONE
        request with nothing live to retire (undersized kv_pool_pages —
        waiting would spin forever)."""
        admits = []
        for s in range(n_slots):
            if active[s] is None and pending:
                plen = len(pending[-1][2])
                if not alloc.can_reserve(s, plen + slack):
                    break
                i, rep, toks = pending.pop()
                alloc.reserve(s, plen + slack)
                admits.append((s, i, rep, toks))
        if (
            not admits
            and pending
            and not any(a is not None for a in active)
        ):
            free_slot = next(
                s for s in range(n_slots) if active[s] is None
            )
            alloc.reserve(free_slot, len(pending[-1][2]) + slack)  # raises
        self._set_live_slots(sum(a is not None for a in active) + len(admits))
        tracer.counter(
            "gen_slots", live=self.live_slots, pending=len(pending)
        )
        return admits

    def _pack_admits_paged(self, admits, n_slots, alloc):
        """`_pack_admits` + page alignment: the prefill width SP must be
        a whole number of pages (the row caches scatter as page-size
        chunks), and each admitted row carries its assigned pool pages
        (sentinel past its prompt — those chunks drop)."""
        rows, plens, slots = self._pack_admits(admits, n_slots)
        ps = alloc.page_size
        sp = rows.shape[1]
        if sp % ps:
            rows = np.pad(
                rows, [(0, 0), (0, ps - sp % ps)],
                constant_values=self.pad_token_id,
            )
            sp = rows.shape[1]
        page_rows = np.full(
            (rows.shape[0], sp // ps), alloc.sentinel, np.int32
        )
        for j, (s, _, _, toks) in enumerate(admits):
            np_ = alloc.pages_for(len(toks))
            page_rows[j, :np_] = alloc.table[s, :np_]
        return rows, plens, slots, page_rows

    def _get_prefill_pages_fn(self):
        sig = ("prefill_pages",)
        if sig in self._gen_fns:
            return self._gen_fns[sig]
        cfg = self.cfg
        use_flash = (
            False if isinstance(self._use_flash, Mesh) else self._use_flash
        )

        @functools.partial(jax.jit, donate_argnums=(3, 4))
        def fn(params, rows, plens, pool, logits_buf, slot_rows, page_rows):
            logits, pool = tfm.prefill_into_pages(
                params, cfg, rows, plens, pool, page_rows,
                use_flash=use_flash,
            )
            logits_buf = logits_buf.at[slot_rows].set(logits, mode="drop")
            return logits_buf, pool

        self._gen_fns[sig] = fn
        return fn

    def _get_paged_decode_fn(
        self, n_slots: int, n_pages: int, max_pages: int, chunk_t: int,
        g: GenerationHyperparameters,
    ):
        """The paged decode chunk.  Its signature depends only on the
        pool geometry — fixed for the whole generate call — so it
        compiles EXACTLY ONCE (the dense variant recompiles per window
        bucket); tests assert this via the decode_compiles counter."""
        sig = (
            "paged_inflight", n_slots, n_pages, max_pages, chunk_t,
            g.min_new_tokens, g.greedy, g.top_p, g.top_k, g.temperature,
        )
        if sig in self._gen_fns:
            return self._gen_fns[sig]
        cfg = self.cfg
        eos = self.eos_token_id

        @functools.partial(jax.jit, donate_argnums=(1, 2))
        def fn(params, pool, logits, page_table, cache_len, gen_count,
               done, key):
            out_toks = jnp.full((n_slots, chunk_t), -1, jnp.int32)
            out_logps = jnp.zeros((n_slots, chunk_t), jnp.float32)

            def body(t, st):
                (logits, pool, cache_len, gen_count, done, out_toks,
                 out_logps) = st
                sub = jax.random.fold_in(key, t)
                lg = logits
                if g.min_new_tokens > 0:
                    lg = jnp.where(
                        (gen_count < g.min_new_tokens)[:, None]
                        & (jnp.arange(cfg.vocab_size) == eos)[None, :],
                        -1e10,
                        lg,
                    )
                tok, logp = sample_token(
                    lg, sub,
                    temperature=g.temperature, top_k=g.top_k, top_p=g.top_p,
                    greedy=g.greedy,
                )
                out_toks = jax.lax.dynamic_update_slice(
                    out_toks, jnp.where(done, -1, tok)[:, None], (0, t)
                )
                out_logps = jax.lax.dynamic_update_slice(
                    out_logps, jnp.where(done, 0.0, logp)[:, None], (0, t)
                )
                # Done rows keep rewriting their current position (the
                # allocator keeps it mapped until the slot retires); no
                # clamps — the reserve() before each chunk guarantees
                # capacity, which is what makes the shape static.
                next_logits, pool2 = tfm.decode_step_paged(
                    params, cfg, jnp.where(done, eos, tok), cache_len,
                    pool, page_table, cache_len, cache_len + 1,
                )
                new_done = done | (tok == eos)
                cache_len = cache_len + (~done).astype(jnp.int32)
                gen_count = gen_count + (~done).astype(jnp.int32)
                return (
                    next_logits, pool2, cache_len, gen_count, new_done,
                    out_toks, out_logps,
                )

            st = (logits, pool, cache_len, gen_count, done, out_toks,
                  out_logps)
            st = jax.lax.fori_loop(0, chunk_t, body, st)
            logits, pool, cache_len, gen_count, done, out_toks, out_logps = st
            return (
                out_toks, out_logps, logits, pool, cache_len, gen_count,
                done,
            )

        self._gen_fns[sig] = fn
        self.decode_compiles += 1
        self._m_decode_compiles.inc()
        logger.info(
            f"compiled paged inflight decoder n_slots={n_slots} "
            f"pool={n_pages}x{self.kv_page_size} chunk={chunk_t}"
        )
        return fn

    # -- unified serving plane (chunked prefill + CoW page sharing) --

    def _generate_inflight_serving(self, reqs, gconfig, key, results) -> None:
        """`_generate_inflight_plain_paged` with admission folded INTO the
        chunk step: an admitted prompt is consumed in `prefill_chunk_tokens`
        (W)-sized slices by the same ragged compiled program that advances
        live decodes, so admission never stalls running rows behind a
        stop-the-world prefill and never compiles a second program —
        decode_compiles stays 1 under continuous admission.  Same-prompt
        repeats (a GRPO group's k responses) share the owner's full prompt
        pages copy-on-write via the allocator's prefix cache, multiplying
        the pool's effective concurrency by ~the group size."""
        n_slots = min(max(self.batch_shard, self.max_decode_batch), len(reqs))
        while n_slots % self.batch_shard:
            n_slots += 1
        ps = self.kv_page_size
        chunk_t = min(32, gconfig.max_new_tokens)
        K = gconfig.spec_decode_k
        max_prompt = max(len(t) for (_, _, t) in reqs)
        max_pages = -(
            -(max_prompt + gconfig.max_new_tokens + chunk_t + K) // ps
        )
        n_pages = self.kv_pool_pages or n_slots * max_pages
        pbw = max(max_prompt, 1)
        buf_w = max_prompt + gconfig.max_new_tokens + K + 2
        st = _PagedGenSession(
            gconfig=gconfig,
            key=key,
            results=results,
            n_slots=n_slots,
            n_pages=n_pages,
            max_pages=max_pages,
            chunk_t=chunk_t,
            alloc=PageAllocator(n_pages, ps, n_slots, max_pages),
            pool=tfm.init_paged_kv_cache(
                self.cfg, n_pages, ps, dtype=self._paged_kv_dtype()
            ),
            logits_buf=jnp.zeros((n_slots, self.cfg.vocab_size), jnp.float32),
            cache_len=np.zeros((n_slots,), np.int32),
            gen_count=np.zeros((n_slots,), np.int32),
            done_host=np.ones((n_slots,), bool),
            active=[None] * n_slots,
            toks_acc={},
            logps_acc={},
            pending=list(reversed(reqs)),
            slot_prompt={},
            last_emit=np.zeros((n_slots,), np.int32),
            prefill_chunk=max(1, self.prefill_chunk_tokens),
            prompt_buf=np.full((n_slots, pbw), self.pad_token_id, np.int32),
            prefill_rem=np.zeros((n_slots,), np.int32),
            prompt_off=np.zeros((n_slots,), np.int32),
            shared_from=np.zeros((n_slots,), np.int32),
            slot_hash={},
            inflight_prefix={},
            tokens_buf=jnp.zeros((n_slots, buf_w), jnp.int32),
            pending_tok=jnp.zeros((n_slots,), jnp.int32),
        )
        st.alloc.page_bytes = _cache_nbytes(st.pool) // n_pages
        self._run_serving_loop(st)

    def _run_serving_loop(self, st: "_PagedGenSession") -> bool:
        """The serving chunk loop: every iteration admits into free slots
        (host bookkeeping only — no device dispatch), maps pages for the
        chunk's worst-case advance, privatises any shared page a write
        could touch (CoW safety net), then runs ONE compiled ragged chunk
        in which prefilling rows consume W prompt tokens per inner step
        while decoding rows emit one token.  Interruptible at chunk
        boundaries exactly like `_run_paged_loop` (returns False parked,
        True finished)."""
        gconfig = st.gconfig
        alloc = st.alloc
        n_slots, ps, chunk_t = st.n_slots, alloc.page_size, st.chunk_t
        W = st.prefill_chunk
        pbw = st.prompt_buf.shape[1]
        chunk_fn = self._get_serving_chunk_fn(
            n_slots, st.n_pages, st.max_pages, chunk_t, W, pbw, gconfig
        )
        while st.pending or any(a is not None for a in st.active):
            if self._interrupt_evt.is_set():
                self._session = st
                tracer.counter(
                    "gen_interrupt",
                    parked_live=sum(a is not None for a in st.active),
                    parked_pending=len(st.pending),
                )
                return False
            self._take_admits_serving(st)
            # Map pages covering this chunk's worst-case advance per live
            # slot: a prefilling row consumes up to chunk_t*Wmax prompt
            # tokens (but never more than its remainder + the decode
            # steps that may follow); a decoding row advances at most
            # chunk_t (plain) or chunk_t*(K+1) (spec), clamped to its
            # remaining emission budget + K draft-scratch positions —
            # tokens past max_new are drained away anyway, so reserving
            # for them would make a nearly-finished row hold pages it
            # never usefully writes (over-budget writes drop via the
            # sentinel; the positions they would have filled are only
            # ever attended by tokens that are themselves over budget
            # and discarded at drain).  Host-side int appends only.
            max_new = gconfig.max_new_tokens
            K = gconfig.spec_decode_k
            Wmax = max(W, K + 1)
            for s in range(n_slots):
                if st.active[s] is not None:
                    rem = int(st.prefill_rem[s])
                    left = max(0, max_new - int(st.gen_count[s]))
                    target = int(st.cache_len[s]) + max(
                        1, min(
                            chunk_t * Wmax,
                            rem + chunk_t * (K + 1),
                            rem + left + K,
                        )
                    )
                    self._reserve_with_evict(alloc, s, target)
            self._privatize_write_windows(st)
            self._accum_pool_stats(
                "paged", int(st.cache_len.sum()), alloc.allocated_pages() * ps
            )

            st.key, sub = jax.random.split(st.key)
            prev_gen = st.gen_count.copy()
            prev_rem = st.prefill_rem.copy()
            with tracer.span(
                "serving_chunk", cat="compute", t=chunk_t, w=W
            ):
                (
                    out_toks, out_logps, st.logits_buf, st.pool,
                    new_cache_len, new_gen_count, new_done, new_rem,
                    new_off, st.tokens_buf, st.pending_tok, lane_acc,
                ) = chunk_fn(
                    self.params, st.pool, st.logits_buf,
                    jnp.asarray(alloc.table), jnp.asarray(st.prompt_buf),
                    jnp.asarray(st.prompt_off), jnp.asarray(st.prefill_rem),
                    jnp.asarray(st.cache_len), jnp.asarray(st.gen_count),
                    jnp.asarray(st.done_host), st.tokens_buf,
                    st.pending_tok, sub,
                )
                # ONE host-sync block per chunk (the done/eos flags must
                # be exact before the next admission round) — the lane
                # counters ride it rather than adding a sync of their
                # own.
                out_toks = to_host(out_toks)
                out_logps = to_host(out_logps)
                lane_acc = to_host(lane_acc)
            st.cache_len = to_host(new_cache_len).copy()
            st.gen_count = to_host(new_gen_count).copy()
            st.prefill_rem = to_host(new_rem).copy()
            st.prompt_off = to_host(new_off).copy()
            st.last_emit = st.gen_count - prev_gen
            self.lanes_dispatched += chunk_t * self.serving_lane_budget
            self.lanes_live += int(lane_acc[0])
            self.lanes_slack += int(lane_acc[1])
            self.dead_live_lanes += int(lane_acc[2])

            # Register prefixes that FINISHED prefilling this chunk,
            # before any retirement below can release the owner's pages:
            # the cache's per-page holds then keep them alive for
            # followers regardless of when the owner finishes decoding.
            if self.kv_share_prefix:
                for s in range(n_slots):
                    if (
                        st.active[s] is not None
                        and prev_rem[s] > 0
                        and st.prefill_rem[s] == 0
                    ):
                        self._register_prefix(st, s)

            def _retire(s):
                alloc.release(s)
                st.slot_prompt.pop(s, None)
                h = st.slot_hash.pop(s, None)
                if h is not None and st.inflight_prefix.get(h) == s:
                    del st.inflight_prefix[h]

            self._drain_chunk_outputs(
                out_toks, out_logps, to_host(new_done), st.active,
                st.toks_acc, st.logps_acc, st.results, st.done_host,
                st.cache_len, gconfig.max_new_tokens, on_retire=_retire,
                stop_seqs=gconfig.stop,
            )
        self.last_pool_stats.update(
            pool_pages=st.n_pages, page_size=ps,
            pages_recycled=alloc.pages_recycled,
            peak_pages_used=alloc.peak_pages_used,
            cow_copies=alloc.cow_copies,
            shared_mappings=alloc.shared_mappings,
            prefix_hits=alloc.prefix_hits,
            prefix_misses=alloc.prefix_misses,
            peak_live_slots=st.peak_live,
            # int8-aware: page_bytes is measured off the real device
            # pool, so an int8 pool reports ~1/2 the bf16 bytes (codes
            # + per-token scales), not a dtype guess.
            pool_bytes=alloc.pool_bytes(),
            peak_allocated_bytes=alloc.peak_pages_used * alloc.page_bytes,
        )
        self._set_live_slots(0)
        return True

    def _take_admits_serving(self, st: "_PagedGenSession") -> int:
        """Admission for the serving loop: pure host bookkeeping (the
        compiled chunk does the prompt forwards).  A request whose prompt
        hash is in the prefix cache maps the cached FULL prompt pages
        (refcount bump, zero copies) and re-forwards only the sub-page
        tail — its marginal footprint is tail + decode budget instead of
        prompt + decode budget.  A request whose hash an in-flight owner
        is still prefilling WAITS (admitting it now would duplicate the
        owner's pages); the owner is live, so waiting cannot deadlock.
        Raises PagePoolExhausted via reserve() when nothing is live and
        the head request still cannot fit (undersized pool)."""
        alloc, gconfig = st.alloc, st.gconfig
        n_slots, ps, chunk_t = st.n_slots, alloc.page_size, st.chunk_t
        slack = chunk_t + gconfig.spec_decode_k
        admitted = 0
        for s in range(n_slots):
            if st.active[s] is not None or not st.pending:
                continue
            i, rep, toks = st.pending[-1]
            toks = np.asarray(toks, np.int32)
            plen = len(toks)
            # Only FULL pages are shareable, and the tail must keep >= 1
            # token so the follower's re-forward produces its own
            # end-of-prompt logits: sp = (plen-1)//ps pages cover
            # positions [0, sp*ps), the follower prefills [sp*ps, plen).
            sp = (plen - 1) // ps
            h = toks.tobytes() if (self.kv_share_prefix and sp > 0) else None
            shared = alloc.prefix_lookup(h) if h is not None else None
            if shared is None and h is not None and h in st.inflight_prefix:
                break  # wait one chunk for the owner to register
            if shared is not None:
                need = alloc.pages_for(plen + slack) - len(shared)
                if need > len(alloc.free):
                    alloc.prefix_evict(need)
                if need > len(alloc.free):
                    break
                alloc.share(s, shared)
                start = sp * ps
                alloc.reserve(s, plen + slack)
            else:
                if not alloc.can_reserve(s, plen + slack):
                    alloc.prefix_evict(
                        alloc.pages_for(plen + slack) - int(alloc.used[s])
                    )
                if not alloc.can_reserve(s, plen + slack):
                    break
                alloc.reserve(s, plen + slack)
                start = 0
                if h is not None:
                    st.inflight_prefix[h] = s
                    st.slot_hash[s] = h
            st.pending.pop()
            st.active[s] = (i, rep)
            st.cache_len[s] = start
            st.gen_count[s] = 0
            st.done_host[s] = False
            st.toks_acc[s] = []
            st.logps_acc[s] = []
            st.slot_prompt[s] = toks
            st.shared_from[s] = start
            rem = plen - start
            st.prompt_buf[s, :] = self.pad_token_id
            st.prompt_buf[s, :rem] = toks[start:]
            st.prefill_rem[s] = rem
            st.prompt_off[s] = 0
            st.last_emit[s] = 0
            admitted += 1
        if (
            admitted == 0
            and st.pending
            and not any(a is not None for a in st.active)
        ):
            # Nothing live to retire and the head request does not fit:
            # waiting would spin forever.  (The admission loop above
            # already tried prefix eviction, and inflight_prefix cannot
            # block here — owners are by definition live.)  reserve()
            # raises the clean capacity error.
            free_slot = next(
                s2 for s2 in range(n_slots) if st.active[s2] is None
            )
            alloc.reserve(
                free_slot, len(st.pending[-1][2]) + slack
            )  # raises
        self._set_live_slots(sum(a is not None for a in st.active))
        st.peak_live = max(st.peak_live, self.live_slots)
        tracer.counter(
            "gen_slots", live=self.live_slots, pending=len(st.pending)
        )
        return admitted

    def _register_prefix(self, st: "_PagedGenSession", s: int) -> None:
        """Publish slot `s`'s full prompt pages in the prefix cache (one
        hold per page) now that its prefill is complete — followers with
        the same prompt hash admit against these pages from the next
        chunk on.  Only owners carry a slot_hash entry; a no-op for
        followers and for slots admitted before a weight push (resume
        clears slot_hash so mixed-weight KV is never published)."""
        h = st.slot_hash.get(s)
        if h is None:
            return
        alloc = st.alloc
        sp = (len(st.slot_prompt[s]) - 1) // alloc.page_size
        if sp > 0:
            alloc.prefix_insert(h, alloc.table[s, :sp])
        st.inflight_prefix.pop(h, None)
        del st.slot_hash[s]

    def _reserve_with_evict(
        self, alloc: PageAllocator, s: int, tokens: int
    ) -> None:
        """reserve() that first evicts LRU prefix-cache holds when the
        free list is short — a live slot's growth outranks cached
        prefixes.  Still raises PagePoolExhausted when eviction cannot
        free enough (pool genuinely too small for what is live)."""
        if not alloc.can_reserve(s, tokens):
            alloc.prefix_evict(
                alloc.pages_for(tokens) - int(alloc.used[s])
            )
        alloc.reserve(s, tokens)

    def _privatize_write_windows(self, st: "_PagedGenSession") -> None:
        """Copy-on-write safety net, run before every chunk: privatise
        any SHARED page inside a live row's write window [cache_len,
        used*page_size) and execute the page copies on device.  By
        construction the serving plane never maps a shared page at or
        past a row's write cursor (followers share only pages strictly
        below their starting cache_len), so the steady state is zero
        pairs — but the read-only contract for shared pages is enforced
        here rather than assumed."""
        alloc = st.alloc
        pairs: List[Tuple[int, int]] = []
        for s in range(st.n_slots):
            if st.active[s] is None:
                continue
            pairs.extend(
                alloc.ensure_writable(
                    s,
                    int(st.cache_len[s]),
                    int(alloc.used[s]) * alloc.page_size,
                )
            )
        if not pairs:
            return
        fn = self._get_copy_pages_fn()
        width = 16  # fixed batch width: one compiled shape, sentinel-padded
        for lo in range(0, len(pairs), width):
            batch = pairs[lo : lo + width]
            src = np.full((width,), alloc.sentinel, np.int32)
            dst = np.full((width,), alloc.sentinel, np.int32)
            for j, (a, b) in enumerate(batch):
                src[j], dst[j] = a, b
            st.pool = fn(st.pool, jnp.asarray(src), jnp.asarray(dst))

    def _get_copy_pages_fn(self):
        sig = ("copy_pages",)
        if sig in self._gen_fns:
            return self._gen_fns[sig]

        @functools.partial(jax.jit, donate_argnums=(0,))
        def fn(pool, src, dst):
            return tfm.copy_pages(pool, src, dst)

        self._gen_fns[sig] = fn
        return fn

    def _get_serving_chunk_fn(
        self, n_slots: int, n_pages: int, max_pages: int, chunk_t: int,
        W: int, pbw: int, g: GenerationHyperparameters,
    ):
        """The unified serving chunk over a PACKED ragged token stream:
        chunk_t inner steps, each ONE `decode_step_ragged_paged` forward
        of a static [T]-lane stream in which every row occupies exactly
        the q_len it needs this step — a prefilling row teacher-forces up
        to W prompt tokens, a plain decoding row forwards its 1 sampled
        token, a speculating row (K > 0) forwards its pending token plus
        K n-gram drafts for exact verification, and a done/parked row
        occupies ZERO lanes.  Dead query lanes are ELIMINATED, not
        masked: the stream simply ends at `total` live lanes, and the
        slack tail carries sentinel rows whose compute the ragged kernel
        skips (its flash loop runs zero KV blocks for them).  Extra
        query lanes ride the decode step's streamed weights — decode is
        bandwidth-bound, so prefill slices AND spec verification share
        one weight stream (the spec-decode economics, now one program).

        Lane budget: T = min(n_slots + A, n_slots * Wmax) rounded up to
        a batch-shard multiple, Wmax = max(W, K+1), A the admit-lane
        headroom knob.  Every live row is guaranteed >= 1 lane (T >=
        n_slots); rows wanting more split the spare lanes front-to-back.
        An undersized budget degrades THROUGHPUT only: a lane-starved
        prefill row consumes fewer prompt tokens this step, a lane-
        starved spec row verifies fewer drafts (`spec_accept` n_valid
        truncation — distribution-exact at any grant).

        Like the legacy decode fn the signature depends only on pool
        geometry + hyperparameters, so it compiles EXACTLY ONCE per
        generate call even under continuous admission of mixed
        prefill/decode/spec rows — the admission-shape zoo AND the
        separate spec-decode program are gone.

        Emission is FILL-INDEXED, not step-indexed: a row's sampled
        tokens pack contiguously from column 0 of its out row whatever
        inner steps it spent prefilling, preserving the -1-termination
        contract `_drain_chunk_outputs` relies on."""
        K = g.spec_decode_k
        Wmax = max(W, K + 1)
        A = self.serving_admit_lanes or 4 * Wmax
        T = min(n_slots + A, n_slots * Wmax)
        while T % self.batch_shard:
            T += 1
        self.serving_lane_budget = T
        sig = (
            "serving_chunk", n_slots, n_pages, max_pages, chunk_t, W, pbw,
            K, g.spec_ngram, T,
            g.min_new_tokens, g.greedy, g.top_p, g.top_k, g.temperature,
        )
        if sig in self._gen_fns:
            return self._gen_fns[sig]
        cfg = self.cfg
        eos = self.eos_token_id
        # A spec row can emit up to K+1 tokens per inner step, plus one
        # fresh first token the step it leaves prefill.
        out_w = chunk_t * (K + 1) + 1 if K > 0 else chunk_t
        if K > 0:
            from areal_tpu.ops.ngram import propose_ngram

        @functools.partial(jax.jit, donate_argnums=(1, 2, 10))
        def fn(params, pool, logits, page_table, prompt_buf, prompt_off,
               prefill_rem, cache_len, gen_count, done, tokens_buf,
               pending, key):
            out_toks = jnp.full((n_slots, out_w), -1, jnp.int32)
            out_logps = jnp.zeros((n_slots, out_w), jnp.float32)
            out_fill = jnp.zeros((n_slots,), jnp.int32)
            # (live lanes, slack lanes, live-but-misassigned lanes) —
            # the third is structurally zero; the bench invariant leg
            # asserts it stays so ("dead-lane compute exactly 0").
            lane_acc = jnp.zeros((3,), jnp.int32)
            rows = jnp.arange(n_slots)
            lanes = jnp.arange(Wmax)
            lane_ids = jnp.arange(T)
            buf_w = tokens_buf.shape[1]

            def body(t, st):
                (logits, pool, cache_len, gen_count, done, prefill_rem,
                 prompt_off, tokens_buf, pending, out_toks, out_logps,
                 out_fill, lane_acc) = st
                is_pref = prefill_rem > 0
                sub = jax.random.fold_in(key, t)
                if K > 0:
                    sub, sub_v = jax.random.split(sub)
                lg = logits
                if g.min_new_tokens > 0:
                    lg = jnp.where(
                        (gen_count < g.min_new_tokens)[:, None]
                        & (jnp.arange(cfg.vocab_size) == eos)[None, :],
                        -1e10,
                        lg,
                    )
                # Sampling consumes one fold_in(key, t) per inner step
                # regardless of row mode, so the key chain matches the
                # legacy decode chunk token-for-token on decode rows
                # (prefilling rows' samples are discarded below).
                tok, logp = sample_token(
                    lg, sub,
                    temperature=g.temperature, top_k=g.top_k, top_p=g.top_p,
                    greedy=g.greedy,
                )
                if K > 0:
                    # K>0: the carry sample only seeds rows FRESH out of
                    # prefill (their first pending token, emitted now);
                    # speculating rows emit via spec_accept below.
                    emitting = (~done) & (~is_pref) & (gen_count == 0)
                else:
                    emitting = (~done) & (~is_pref)
                out_toks = out_toks.at[rows, out_fill].set(
                    jnp.where(emitting, tok, out_toks[rows, out_fill])
                )
                out_logps = out_logps.at[rows, out_fill].set(
                    jnp.where(emitting, logp, out_logps[rows, out_fill])
                )
                out_fill = out_fill + emitting.astype(jnp.int32)
                if K > 0:
                    done = done | (emitting & (tok == eos))
                    gen_count = gen_count + emitting.astype(jnp.int32)
                    pending = jnp.where(emitting, tok, pending)
                    # History invariant for speculating rows: cache_len =
                    # plen + gen_count - 1 and tokens_buf[cache_len] is
                    # the pending (sampled, not yet forwarded) token.
                    bp0 = jnp.clip(cache_len, 0, buf_w - 1)
                    tokens_buf = tokens_buf.at[rows, bp0].set(
                        jnp.where(emitting, tok, tokens_buf[rows, bp0])
                    )
                    drafts = propose_ngram(
                        tokens_buf, cache_len + 1, K, g.spec_ngram
                    )  # [n_slots, K]
                # Per-row lane want: a done/parked row wants ZERO lanes
                # (its compute is eliminated from the stream, the legacy
                # EOS-rewrite-in-place is gone), a prefilling row wants
                # its next W-slice, a decoding row 1 (plain) or K+1
                # (pending + drafts).  Everybody gets their base lane
                # (T >= n_slots); the spare splits front-to-back.
                want = jnp.where(
                    done, 0,
                    jnp.where(
                        is_pref, jnp.minimum(prefill_rem, W), K + 1
                    ),
                ).astype(jnp.int32)
                base = (want > 0).astype(jnp.int32)
                extra = want - base
                spare = T - jnp.sum(base)
                excl = jnp.cumsum(extra) - extra
                c = base + jnp.clip(spare - excl, 0, extra)
                c = jnp.where(want > 0, c, 0)
                # Pack: row r owns stream lanes [starts[r], starts[r]+c[r]).
                cu = jnp.cumsum(c)
                starts = cu - c
                total = cu[-1]
                row_of = jnp.searchsorted(
                    cu, lane_ids, side="right"
                ).astype(jnp.int32)
                lane_live = lane_ids < total
                rid = jnp.minimum(row_of, n_slots - 1)
                qpos = lane_ids - starts[rid]
                badlane = lane_live & (
                    (row_of >= n_slots) | (qpos < 0) | (qpos >= c[rid])
                )
                lane_acc = lane_acc + jnp.stack([
                    total, T - total,
                    jnp.sum(badlane.astype(jnp.int32)),
                ])
                # Per-row lane-token slab, gathered into the stream.
                idx = jnp.minimum(
                    prompt_off[:, None] + lanes[None, :], pbw - 1
                )
                pref_toks = jnp.take_along_axis(prompt_buf, idx, axis=1)
                if K > 0:
                    dec = jnp.concatenate(
                        [pending[:, None], drafts], axis=1
                    )
                    if Wmax > K + 1:
                        dec = jnp.pad(dec, [(0, 0), (0, Wmax - (K + 1))])
                    slab = jnp.where(is_pref[:, None], pref_toks, dec)
                    # Prefill rows record their granted prompt slice into
                    # the history buffer (the n-gram proposer reads it).
                    lv = is_pref[:, None] & (lanes[None, :] < c[:, None])
                    bcols = jnp.clip(
                        cache_len[:, None] + lanes[None, :], 0, buf_w - 1
                    )
                    cur = tokens_buf[rows[:, None], bcols]
                    tokens_buf = tokens_buf.at[rows[:, None], bcols].set(
                        jnp.where(lv, pref_toks, cur)
                    )
                else:
                    slab = jnp.where(is_pref[:, None], pref_toks, 0)
                    slab = slab.at[:, 0].set(
                        jnp.where(is_pref, pref_toks[:, 0], tok)
                    )
                qv = jnp.clip(qpos, 0, Wmax - 1)
                stream_tok = jnp.where(lane_live, slab[rid, qv], 0)
                stream_pos = jnp.where(
                    lane_live, cache_len[rid] + qv, 0
                )
                logits_pk, pool2 = tfm.decode_step_ragged_paged(
                    params, cfg, stream_tok, stream_pos, pool,
                    page_table, row_of,
                )  # [T, V]
                # Next-step carry = each granted row's LAST lane logits
                # (end-of-slice for prefill, post-token for decode);
                # zero-lane rows keep their carry untouched.
                last = jnp.clip(starts + c - 1, 0, T - 1)
                logits = jnp.where(
                    (c > 0)[:, None], logits_pk[last], logits
                )
                if K > 0:
                    # Ragged verification: row r's K+1 spec positions are
                    # lanes starts[r]..starts[r]+K; only the first c[r]
                    # were forwarded (n_valid truncation in spec_accept).
                    gidx = jnp.clip(
                        starts[:, None] + jnp.arange(K + 1)[None, :],
                        0, T - 1,
                    )
                    spec_lg = logits_pk[gidx]  # [n_slots, K+1, V]
                    active_m = (~done) & (~is_pref) & (c > 0)
                    (tokens_buf, pending, cache_len_s, gen_count, done,
                     out_toks, out_logps, out_fill) = _spec_emit(
                        cfg, g, eos, rows, spec_lg, drafts, sub_v,
                        pending, cache_len, gen_count, done, out_toks,
                        out_logps, out_fill, tokens_buf,
                        active=active_m, n_valid=c,
                    )
                    cache_len = jnp.where(
                        is_pref, cache_len + c, cache_len_s
                    )
                else:
                    done = jnp.where(is_pref, done, done | (tok == eos))
                    # Decode rows advance by their emission (a row
                    # emitting its EOS still wrote that token); done rows
                    # hold zero lanes and stay put.
                    cache_len = cache_len + c
                    gen_count = gen_count + emitting.astype(jnp.int32)
                prompt_off = prompt_off + jnp.where(is_pref, c, 0)
                prefill_rem = prefill_rem - jnp.where(is_pref, c, 0)
                return (logits, pool2, cache_len, gen_count, done,
                        prefill_rem, prompt_off, tokens_buf, pending,
                        out_toks, out_logps, out_fill, lane_acc)

            st = (logits, pool, cache_len, gen_count, done, prefill_rem,
                  prompt_off, tokens_buf, pending, out_toks, out_logps,
                  out_fill, lane_acc)
            st = jax.lax.fori_loop(0, chunk_t, body, st)
            (logits, pool, cache_len, gen_count, done, prefill_rem,
             prompt_off, tokens_buf, pending, out_toks, out_logps, _,
             lane_acc) = st
            return (
                out_toks, out_logps, logits, pool, cache_len, gen_count,
                done, prefill_rem, prompt_off, tokens_buf, pending,
                lane_acc,
            )

        self._gen_fns[sig] = fn
        self.decode_compiles += 1
        self._m_decode_compiles.inc()
        logger.info(
            f"compiled serving chunk n_slots={n_slots} "
            f"pool={n_pages}x{self.kv_page_size} chunk={chunk_t} W={W} "
            f"K={K} lanes={T}"
        )
        return fn

    # -- agent-serving episodes (multi-turn tool use on persistent KV) --

    def _require_serving_plane(self) -> None:
        if not (self.kv_paged and self.prefill_chunk_tokens > 0):
            raise RuntimeError(
                "episodes require the serving plane: kv_paged=True and "
                "prefill_chunk_tokens > 0"
            )

    def _episode_session_get(
        self, gconfig: GenerationHyperparameters, token_budget: int,
        seed: int,
    ) -> "_PagedGenSession":
        """Lazily create the engine-LIFETIME episode session: one slot
        pool + one page pool shared by every live episode.  Geometry is
        fixed at first use, so the serving chunk program compiles ONCE
        and every later turn of every episode reuses it — the agents
        check leg asserts decode_compiles stays 1 across a whole
        multi-episode run."""
        if self._ep_session is not None:
            return self._ep_session
        n_slots = max(self.batch_shard, self.max_decode_batch)
        while n_slots % self.batch_shard:
            n_slots += 1
        ps = self.kv_page_size
        chunk_t = min(32, gconfig.max_new_tokens)
        budget = int(token_budget) or 2048
        # The admission width bounds any single teacher-forced slab; a
        # conversation re-admitted after SlotGone is the worst case (the
        # whole budget), so pbw == budget keeps that path recompile-free.
        pbw = budget
        K = gconfig.spec_decode_k
        max_pages = -(-(budget + chunk_t + K) // ps)
        n_pages = self.kv_pool_pages or n_slots * max_pages
        st = _PagedGenSession(
            gconfig=gconfig,
            key=jax.random.PRNGKey(seed),
            results={},
            n_slots=n_slots,
            n_pages=n_pages,
            max_pages=max_pages,
            chunk_t=chunk_t,
            alloc=PageAllocator(n_pages, ps, n_slots, max_pages),
            pool=tfm.init_paged_kv_cache(
                self.cfg, n_pages, ps, dtype=self._paged_kv_dtype()
            ),
            logits_buf=jnp.zeros(
                (n_slots, self.cfg.vocab_size), jnp.float32
            ),
            cache_len=np.zeros((n_slots,), np.int32),
            gen_count=np.zeros((n_slots,), np.int32),
            done_host=np.ones((n_slots,), bool),
            active=[None] * n_slots,
            toks_acc={},
            logps_acc={},
            pending=[],
            slot_prompt={},
            last_emit=np.zeros((n_slots,), np.int32),
            prefill_chunk=max(1, self.prefill_chunk_tokens),
            prompt_buf=np.full((n_slots, pbw), self.pad_token_id, np.int32),
            prefill_rem=np.zeros((n_slots,), np.int32),
            prompt_off=np.zeros((n_slots,), np.int32),
            shared_from=np.zeros((n_slots,), np.int32),
            slot_hash={},
            inflight_prefix={},
            episodes={},
            ep_budget=budget,
            tokens_buf=jnp.zeros((n_slots, budget + K + 2), jnp.int32),
            pending_tok=jnp.zeros((n_slots,), jnp.int32),
        )
        st.alloc.page_bytes = _cache_nbytes(st.pool) // n_pages
        self._ep_session = st
        logger.info(
            f"episode session: {n_slots} slots, pool {n_pages}x{ps}, "
            f"chunk={chunk_t}, budget={budget}"
        )
        return st

    def episode_start(
        self,
        ep_id: str,
        prompt_ids,
        gconfig: GenerationHyperparameters,
        token_budget: int = 0,
        seed: int = 0,
    ) -> Optional[Dict[str, Any]]:
        """Open an episode: pin a serving slot, admit the conversation
        through the chunked-prefill serving program (the longest
        page-aligned transcript prefix already published rides the
        prefix cache — shared system prompts and post-SlotGone
        re-admissions both land here), decode turn 0 until a stop
        sequence / EOS / budget, then PARK the slot with its KV pages
        held.  Returns the turn dict, or None when an interrupt parked
        the call mid-turn (episode_resume() continues it)."""
        self._ensure_loaded()
        self._require_params()
        self._require_serving_plane()
        st = self._episode_session_get(gconfig, token_budget, seed)
        if ep_id in st.episodes:
            raise ValueError(f"episode {ep_id!r} already live")
        toks = np.asarray(list(map(int, prompt_ids)), np.int32)
        budget = int(token_budget) or st.ep_budget
        if len(toks) == 0:
            raise ValueError("episode_start needs a non-empty prompt")
        if len(toks) + 1 > budget:
            raise ValueError(
                f"episode prompt ({len(toks)} tokens) leaves no room in "
                f"the token budget ({budget})"
            )
        if len(toks) > st.prompt_buf.shape[1]:
            raise ValueError(
                f"episode prompt ({len(toks)} tokens) exceeds the "
                f"admission width ({st.prompt_buf.shape[1]})"
            )
        s = self._episode_free_slot(st)
        ep = _EpisodeSlot(
            ep_id=ep_id, slot=s, gconfig=gconfig, token_budget=budget,
        )
        st.episodes[ep_id] = ep
        self.episodes_started += 1
        self._episode_admit(st, ep, toks, fresh=True)
        return self._run_episode_turn(st, ep)

    def episode_extend(
        self, ep_id: str, obs_ids
    ) -> Optional[Dict[str, Any]]:
        """Append a tool result / observation onto the episode's SAME
        slot — a chunked-prefill admission over its existing KV pages,
        so nothing already in cache is ever re-forwarded — and decode
        the next turn.  Raises SlotGoneError when the slot was
        reclaimed; the controller then re-admits the full conversation
        via episode_start (the prefix cache pays for most of it)."""
        self._ensure_loaded()
        self._require_params()
        st = self._ep_session
        if st is None or ep_id not in st.episodes:
            raise SlotGoneError(
                ep_id,
                "engine has no episode session" if st is None
                else "slot reclaimed",
            )
        ep = st.episodes[ep_id]
        if ep.parked_mid_turn:
            raise RuntimeError(
                f"episode {ep_id!r} is parked mid-turn; call "
                "episode_resume() first"
            )
        obs = np.asarray(list(map(int, obs_ids)), np.int32)
        if len(obs) == 0:
            raise ValueError("episode_extend needs a non-empty observation")
        if len(obs) > st.prompt_buf.shape[1]:
            raise ValueError(
                f"observation ({len(obs)} tokens) exceeds the admission "
                f"width ({st.prompt_buf.shape[1]})"
            )
        if (
            ep.token_budget
            and int(st.cache_len[ep.slot]) + len(obs) + 1 > ep.token_budget
        ):
            # The observation alone busts the budget: a terminal
            # zero-token turn, no admission (the slot keeps its pages so
            # the transcript stays readable until release).
            ep.turns += 1
            return {
                "episode_id": ep.ep_id,
                "turn_index": ep.turns - 1,
                "tokens": [],
                "logprobs": [],
                "stop_reason": "budget",
                "transcript_len": int(st.cache_len[ep.slot]),
                "prefill_tokens": 0,
                "shared_prefix_tokens": int(st.shared_from[ep.slot]),
                "slot": ep.slot,
            }
        self._episode_admit(st, ep, obs, fresh=False)
        return self._run_episode_turn(st, ep)

    def episode_resume(self, ep_id: str) -> Optional[Dict[str, Any]]:
        """Continue a mid-turn-parked episode under the CURRENT weights:
        replay the slot's last chunk tail through its existing page
        table (resume_generate mechanics, one row), drop the prefix
        cache (stale-weight KV must not be shared into new admissions),
        then re-enter the turn loop."""
        self._ensure_loaded()
        self._require_params()
        st = self._ep_session
        if st is None or ep_id not in st.episodes:
            raise SlotGoneError(
                ep_id,
                "engine has no episode session" if st is None
                else "slot reclaimed",
            )
        ep = st.episodes[ep_id]
        if not ep.parked_mid_turn:
            raise RuntimeError(
                f"episode {ep_id!r} is not parked mid-turn"
            )
        ep.parked_mid_turn = False
        s = ep.slot
        Q = st.chunk_t
        hist = np.concatenate(
            [st.slot_prompt[s], np.asarray(st.toks_acc[s], np.int32)]
        )
        L = int(st.cache_len[s])
        priv = int(st.shared_from[s])
        r = int(min(max(int(st.last_emit[s]), 1), Q, L - priv))
        if r > 0:
            tokens = np.full((st.n_slots, Q), self.pad_token_id, np.int32)
            positions = np.zeros((st.n_slots, Q), np.int32)
            write_pos0 = np.zeros((st.n_slots,), np.int32)
            take_idx = np.zeros((st.n_slots,), np.int32)
            live_mask = np.zeros((st.n_slots,), bool)
            q_lens = np.zeros((st.n_slots,), np.int32)
            tokens[s, :r] = hist[L - r : L]
            write_pos0[s] = L - r
            positions[s] = (L - r) + np.arange(Q)
            take_idx[s] = r - 1
            live_mask[s] = True
            q_lens[s] = r
            with tracer.span("episode_resume_replay", cat="compute", n=1):
                st.logits_buf, st.pool = self._get_paged_replay_fn(
                    st.n_slots, st.n_pages, st.max_pages, Q
                )(
                    self.params, jnp.asarray(tokens),
                    jnp.asarray(positions), st.pool,
                    jnp.asarray(st.alloc.table), jnp.asarray(write_pos0),
                    st.logits_buf, jnp.asarray(take_idx),
                    jnp.asarray(live_mask), jnp.asarray(q_lens),
                )
        self.resume_replays += 1
        st.alloc.prefix_clear()
        st.inflight_prefix.clear()
        st.slot_hash.clear()
        return self._run_episode_turn(st, ep)

    def episode_release(self, ep_id: str) -> bool:
        """Retire an episode: release its pages (prefix-cache holds on
        published transcript prefixes survive) and free the slot.
        Returns False when the episode is already gone."""
        st = self._ep_session
        if st is None or ep_id not in st.episodes:
            return False
        self._drop_episode(st, st.episodes[ep_id])
        return True

    def episode_stats(self) -> Dict[str, Any]:
        """Episode-plane load snapshot (gen_server /health + checks)."""
        st = self._ep_session
        out = {
            "active": 0,
            "parked_mid_turn": 0,
            "started": self.episodes_started,
            "evicted": self.episodes_evicted,
            "prefix_hits": self.episode_prefix_hits,
            "prefix_misses": self.episode_prefix_misses,
        }
        if st is not None:
            out["active"] = len(st.episodes)
            out["parked_mid_turn"] = sum(
                1 for e in st.episodes.values() if e.parked_mid_turn
            )
            out["pool_pages"] = st.n_pages
            out["pages_allocated"] = st.alloc.allocated_pages()
        return out

    def _episode_free_slot(self, st: "_PagedGenSession") -> int:
        for s in range(st.n_slots):
            if st.active[s] is None:
                return s
        # Every slot is pinned: reclaim the least-recently-touched
        # parked episode — its controller sees SlotGoneError on the next
        # continuation and re-admits via the prefix cache.
        if not self._evict_parked_episode(st):
            raise RuntimeError(
                "no free episode slot and nothing parked to evict"
            )
        return next(s for s in range(st.n_slots) if st.active[s] is None)

    def _evict_parked_episode(
        self, st: "_PagedGenSession", exclude: str = ""
    ) -> bool:
        """Reclaim the LRU parked episode's slot + pages.  Mid-turn
        parked episodes are exempt (their resume path owns the slot)."""
        cands = [
            ep for ep in st.episodes.values()
            if not ep.parked_mid_turn and ep.ep_id != exclude
        ]
        if not cands:
            return False
        victim = min(cands, key=lambda e: e.seq)
        logger.info(
            f"evicting parked episode {victim.ep_id!r} "
            f"(slot {victim.slot}, {victim.turns} turns)"
        )
        self._drop_episode(st, victim)
        self.episodes_evicted += 1
        return True

    def _drop_episode(self, st: "_PagedGenSession", ep: _EpisodeSlot):
        s = ep.slot
        st.alloc.release(s)
        st.active[s] = None
        st.done_host[s] = True
        st.cache_len[s] = 0
        st.gen_count[s] = 0
        st.prefill_rem[s] = 0
        st.prompt_off[s] = 0
        st.last_emit[s] = 0
        st.shared_from[s] = 0
        st.slot_prompt.pop(s, None)
        st.toks_acc.pop(s, None)
        st.logps_acc.pop(s, None)
        st.episodes.pop(ep.ep_id, None)

    def _episode_admit(
        self, st: "_PagedGenSession", ep: _EpisodeSlot, toks: np.ndarray,
        fresh: bool,
    ) -> None:
        """Admission is pure host bookkeeping (the serving chunk does
        the forwards).  fresh=True maps a slot for a full conversation:
        the LONGEST page-aligned transcript prefix published in the
        prefix cache is mapped copy-on-write (refcount bump, zero
        copies) and only the tail teacher-forces — this is what makes
        shared system prompts and post-SlotGone re-admission cheap.
        fresh=False appends an observation onto the SAME slot's live
        pages: the new tokens prefill from position cache_len onward,
        overwriting any tail KV a stop-sequence rewind left behind."""
        alloc = st.alloc
        s = ep.slot
        ps = alloc.page_size
        g = ep.gconfig
        # Chunk-advance slack past the transcript: decode steps plus the
        # K draft-scratch positions a speculating row writes past its
        # last accepted token.
        slack = st.chunk_t + g.spec_decode_k
        st.ep_seq += 1
        ep.seq = st.ep_seq
        if fresh:
            plen = len(toks)
            start = 0
            if self.kv_share_prefix and plen > ps:
                # Probe longest-first: published keys are page-aligned
                # transcript prefixes, so the first hit is the best hit.
                # The tail keeps >= 1 token — the re-forward must
                # produce this conversation's own end-of-prompt logits.
                for k in range((plen - 1) // ps, 0, -1):
                    shared = alloc.prefix_lookup(
                        b"ep:" + toks[: k * ps].tobytes()
                    )
                    if shared is None:
                        continue
                    need = alloc.pages_for(plen + slack) - len(shared)
                    if need > len(alloc.free):
                        alloc.prefix_evict(need)
                    if need > len(alloc.free):
                        break  # pool too tight to extend past the share
                    alloc.share(s, shared)
                    start = k * ps
                    break
            if start > 0:
                self.episode_prefix_hits += 1
            else:
                self.episode_prefix_misses += 1
            try:
                self._reserve_with_evict(alloc, s, plen + slack)
            except PagePoolExhausted:
                if not self._evict_parked_episode(st, exclude=ep.ep_id):
                    raise
                self._reserve_with_evict(alloc, s, plen + slack)
            st.active[s] = ep.ep_id
            st.cache_len[s] = start
            st.shared_from[s] = start
            st.slot_prompt[s] = toks
            rem = plen - start
        else:
            # Observation append: teacher-force everything past the KV
            # cursor.  For K > 0 the cursor parks ONE token short of the
            # kept transcript (the final kept token was a pending spec
            # token whose KV was never forwarded — see
            # _finish_episode_turn), so the tail re-forwards it along
            # with the observation.
            st.slot_prompt[s] = np.concatenate([st.slot_prompt[s], toks])
            start = int(st.cache_len[s])
            toks = st.slot_prompt[s][start:]
            rem = len(toks)
        st.toks_acc[s] = []
        st.logps_acc[s] = []
        st.gen_count[s] = 0
        st.done_host[s] = False
        st.prompt_buf[s, :] = self.pad_token_id
        st.prompt_buf[s, :rem] = toks[len(toks) - rem :]
        st.prefill_rem[s] = rem
        st.prompt_off[s] = 0
        st.last_emit[s] = 0
        ep.last_admit_tokens = rem
        ep.turn_start_len = start + rem
        ep.scan_from = 0
        # Per-turn decode budget, clamped so the transcript can never
        # outgrow the episode's token budget (the page reservation and
        # the admission width both rely on that bound).  Callers
        # pre-check, so this is >= 1 here.
        left = (
            ep.token_budget - ep.turn_start_len
            if ep.token_budget
            else g.max_new_tokens
        )
        ep.turn_max_new = max(0, min(g.max_new_tokens, left))
        ep.budget_limited = ep.turn_max_new < g.max_new_tokens

    def _run_episode_turn(
        self, st: "_PagedGenSession", ep: _EpisodeSlot
    ) -> Optional[Dict[str, Any]]:
        """Drive serving chunks until THIS episode's turn ends (stop
        sequence, EOS, per-turn length, or episode budget).  Other
        episodes' slots ride along as done rows — dead queries whose
        writes drop, exactly like retired slots in the batch loop.
        Checks the interrupt event at every chunk boundary: a weight
        push parks the turn in place (returns None) and
        episode_resume() replays the last chunk tail on the same pages
        before continuing."""
        g = ep.gconfig
        s = ep.slot
        alloc = st.alloc
        n_slots, chunk_t, W = st.n_slots, st.chunk_t, st.prefill_chunk
        pbw = st.prompt_buf.shape[1]
        chunk_fn = self._get_serving_chunk_fn(
            n_slots, st.n_pages, st.max_pages, chunk_t, W, pbw, g
        )
        max_new = ep.turn_max_new
        stop_seqs = g.stop
        reason = None
        while reason is None:
            if self._interrupt_evt.is_set():
                ep.parked_mid_turn = True
                tracer.counter(
                    "episode_interrupt",
                    slot=s,
                    cache_len=int(st.cache_len[s]),
                )
                return None
            rem = int(st.prefill_rem[s])
            left = max(0, max_new - int(st.gen_count[s]))
            K = g.spec_decode_k
            Wmax = max(W, K + 1)
            target = int(st.cache_len[s]) + max(
                1, min(
                    chunk_t * Wmax,
                    rem + chunk_t * (K + 1),
                    rem + left + K,
                )
            )
            try:
                self._reserve_with_evict(alloc, s, target)
            except PagePoolExhausted:
                if not self._evict_parked_episode(st, exclude=ep.ep_id):
                    raise
                self._reserve_with_evict(alloc, s, target)
            self._privatize_write_windows(st)
            self._accum_pool_stats(
                "paged", int(st.cache_len.sum()),
                alloc.allocated_pages() * alloc.page_size,
            )
            st.key, sub = jax.random.split(st.key)
            prev_gen = st.gen_count.copy()
            with tracer.span(
                "episode_chunk", cat="compute", t=chunk_t, w=W
            ):
                (
                    out_toks, out_logps, st.logits_buf, st.pool,
                    new_cache_len, new_gen_count, new_done, new_rem,
                    new_off, st.tokens_buf, st.pending_tok, lane_acc,
                ) = chunk_fn(
                    self.params, st.pool, st.logits_buf,
                    jnp.asarray(alloc.table), jnp.asarray(st.prompt_buf),
                    jnp.asarray(st.prompt_off),
                    jnp.asarray(st.prefill_rem),
                    jnp.asarray(st.cache_len), jnp.asarray(st.gen_count),
                    jnp.asarray(st.done_host), st.tokens_buf,
                    st.pending_tok, sub,
                )
                out_toks = to_host(out_toks)
                out_logps = to_host(out_logps)
                lane_acc = to_host(lane_acc)
            self.lanes_dispatched += chunk_t * self.serving_lane_budget
            self.lanes_live += int(lane_acc[0])
            self.lanes_slack += int(lane_acc[1])
            self.dead_live_lanes += int(lane_acc[2])
            st.cache_len = to_host(new_cache_len).copy()
            st.gen_count = to_host(new_gen_count).copy()
            st.prefill_rem = to_host(new_rem).copy()
            st.prompt_off = to_host(new_off).copy()
            st.done_host = to_host(new_done).copy()
            st.last_emit = st.gen_count - prev_gen
            # Drain THIS slot only (parked rows emit nothing).
            row = out_toks[s]
            term = np.flatnonzero(row < 0)
            limit = int(term[0]) if term.size else row.shape[0]
            limit = min(limit, max(0, max_new - len(st.toks_acc[s])))
            eos_at = np.flatnonzero(row[:limit] == self.eos_token_id)
            if eos_at.size:
                limit = int(eos_at[0]) + 1
            prev_len = len(st.toks_acc[s])
            st.toks_acc[s].extend(row[:limit].tolist())
            st.logps_acc[s].extend(out_logps[s, :limit].tolist())
            cut = (
                _find_stop_end(st.toks_acc[s], prev_len, stop_seqs)
                if stop_seqs
                else None
            )
            if cut is not None:
                del st.toks_acc[s][cut:]
                del st.logps_acc[s][cut:]
                reason = "stop"
            elif (
                st.toks_acc[s]
                and st.toks_acc[s][-1] == self.eos_token_id
            ):
                reason = "eos"
            elif (
                int(st.prefill_rem[s]) == 0
                and len(st.toks_acc[s]) >= max_new
            ):
                reason = "budget" if ep.budget_limited else "length"
        return self._finish_episode_turn(st, ep, reason)

    def _finish_episode_turn(
        self, st: "_PagedGenSession", ep: _EpisodeSlot, reason: str
    ) -> Dict[str, Any]:
        s = ep.slot
        kept = len(st.toks_acc[s])
        # Rewind: tokens sampled past the kept boundary (after a stop
        # sequence, or over the turn budget) left KV at positions the
        # transcript no longer covers.  Pulling cache_len back is pure
        # host bookkeeping — attention never reads past a row's write
        # cursor, and the next admission teacher-forces over those
        # positions in place.  With spec decoding the final kept token
        # may be a still-PENDING token (sampled, never forwarded, so no
        # KV exists for it) — park one short and let the next
        # observation admit teacher-force it with the obs tail.
        if ep.gconfig.spec_decode_k > 0:
            st.cache_len[s] = ep.turn_start_len + max(0, kept - 1)
        else:
            st.cache_len[s] = ep.turn_start_len + kept
        st.done_host[s] = True
        st.prefill_rem[s] = 0
        turn_toks = [int(t) for t in st.toks_acc[s]]
        turn_lps = [float(x) for x in st.logps_acc[s]]
        if turn_toks:
            st.slot_prompt[s] = np.concatenate(
                [st.slot_prompt[s], np.asarray(turn_toks, np.int32)]
            )
        st.toks_acc[s] = []
        st.logps_acc[s] = []
        ep.turns += 1
        self._episode_publish_prefix(st, s)
        self._set_live_slots(len(st.episodes))
        return {
            "episode_id": ep.ep_id,
            "turn_index": ep.turns - 1,
            "tokens": turn_toks,
            "logprobs": turn_lps,
            "stop_reason": reason,
            "transcript_len": int(ep.turn_start_len + kept),
            "prefill_tokens": int(ep.last_admit_tokens),
            "shared_prefix_tokens": int(st.shared_from[s]),
            "slot": s,
        }

    def _episode_publish_prefix(
        self, st: "_PagedGenSession", s: int
    ) -> None:
        """Publish the slot's page-aligned transcript prefix so a future
        conversation sharing it — another episode with the same system
        prompt, or a post-SlotGone re-admission of this very transcript
        — maps the pages instead of re-prefilling.  Keys are the prefix
        token bytes, page-aligned, so admission probes longest-first."""
        if not self.kv_share_prefix:
            return
        alloc = st.alloc
        sp = int(st.cache_len[s]) // alloc.page_size
        if sp <= 0:
            return
        alloc.prefix_insert(
            b"ep:" + st.slot_prompt[s][: sp * alloc.page_size].tobytes(),
            alloc.table[s, :sp],
        )

    # -- speculative inflight (n-gram drafts + exact verification) --

    def _generate_inflight_spec(self, reqs, g, key, results) -> None:
        """Continuous batching with speculative decoding: each jitted step
        consumes [pending, K drafts] in ONE forward (weight stream amortized
        over up to K+1 emitted tokens); drafts come from self n-gram lookup
        (ops/ngram.py) and are verified by exact rejection sampling
        (ops/sampling.py spec_accept), so the emitted distribution equals
        plain sampling.  Reference role: the SGLang server's speculative
        decode config; correctness contract from ops/sampling tests."""
        K = g.spec_decode_k
        n_slots = min(max(self.batch_shard, self.max_decode_batch), len(reqs))
        while n_slots % self.batch_shard:
            n_slots += 1
        max_prompt = max(len(t) for (_, _, t) in reqs)
        n_steps = max(1, min(32, g.max_new_tokens) // (K + 1))
        step_cap = n_steps * (K + 1)

        cur_w = bucket_len(max_prompt + step_cap + K + 1)
        # int8 stays distribution-exact here: drafts AND their exact
        # verification both score against the quantized-cache model, so
        # the emitted distribution equals plain decoding with this cache.
        cache = tfm.init_kv_cache(
            self.cfg, n_slots, cur_w,
            dtype=(
                "int8"
                if self.kv_cache_dtype == "int8"
                else self.compute_dtype
            ),
        )
        # History buffer: prompt + emitted tokens per row (device-resident;
        # the in-chunk n-gram proposal reads it).
        tokens_buf = jnp.zeros((n_slots, cur_w + K + 2), jnp.int32)
        pending = jnp.zeros((n_slots,), jnp.int32)
        cache_len = np.zeros((n_slots,), np.int32)
        gen_count = np.zeros((n_slots,), np.int32)
        done_host = np.ones((n_slots,), bool)
        active: List[Optional[Tuple[int, int]]] = [None] * n_slots
        toks_acc: Dict[int, List[int]] = {}
        logps_acc: Dict[int, List[float]] = {}
        pending_list = list(reversed(reqs))

        while pending_list or any(a is not None for a in active):
            admits = self._take_admits(active, pending_list, n_slots)
            if admits:
                rows, plens, slots = self._pack_admits(admits, n_slots)
                key, sub = jax.random.split(key)
                with tracer.span("prefill", cat="compute", n=len(admits)):
                    toks0, logps0, cache, tokens_buf, pending = (
                        self._get_spec_admit_fn(g)(
                            self.params, jnp.asarray(rows),
                            jnp.asarray(plens), cache, tokens_buf, pending,
                            jnp.asarray(slots), sub,
                        )
                    )
                    self.prefill_dispatches += 1
                    # ONE host sync per refill cycle (the eos/done flag must
                    # be exact before the next chunk) — not one per
                    # admission.
                    toks0 = to_host(toks0)
                    logps0 = to_host(logps0)
                for j, (s, i, rep, toks) in enumerate(admits):
                    t0 = int(toks0[j])
                    cache_len[s] = len(toks)
                    gen_count[s] = 1  # the sampled pending token
                    done_host[s] = t0 == self.eos_token_id
                    active[s] = (i, rep)
                    toks_acc[s] = [t0]
                    logps_acc[s] = [float(logps0[j])]

            # Growth: a chunk can add up to step_cap entries (+K scratch).
            need = int(cache_len.max()) + step_cap + K + 1
            old_bytes = _cache_nbytes(cache)
            cache, new_w = self._grow_kv_cache(cache, cur_w, need)
            if new_w != cur_w:
                self.cache_copy_bytes += old_bytes
                tokens_buf = jnp.pad(
                    tokens_buf,
                    [(0, 0), (0, new_w + K + 2 - tokens_buf.shape[1])],
                )
                cur_w = new_w
            self._accum_pool_stats(
                "dense", int(cache_len.sum()), n_slots * cur_w
            )

            fn = self._get_spec_decode_fn(n_slots, cur_w, n_steps, g)
            key, sub = jax.random.split(key)
            with tracer.span("decode_chunk", cat="compute", t=step_cap):
                (
                    out_toks, out_logps, tokens_buf, cache, pending,
                    new_cache_len, new_gen_count, new_done,
                ) = fn(
                    self.params, cache, tokens_buf, pending,
                    jnp.asarray(cache_len), jnp.asarray(gen_count),
                    jnp.asarray(done_host), sub,
                )
                out_toks = to_host(out_toks)
                out_logps = to_host(out_logps)
            cache_len = to_host(new_cache_len).copy()
            gen_count = to_host(new_gen_count).copy()

            self._drain_chunk_outputs(
                out_toks, out_logps, to_host(new_done), active, toks_acc,
                logps_acc, results, done_host, cache_len, g.max_new_tokens,
                stop_seqs=g.stop,
            )

    def _get_spec_admit_fn(self, g):
        sig = ("spec_admit", g.greedy, g.top_p, g.top_k, g.temperature,
               g.min_new_tokens)
        if sig in self._gen_fns:
            return self._gen_fns[sig]
        cfg = self.cfg
        eos = self.eos_token_id
        use_flash = (
            False if isinstance(self._use_flash, Mesh) else self._use_flash
        )

        # Batched admission (see _pack_admits): prefill every admitted
        # prompt, sample its first pending token, and record prompt+token
        # into the device-resident history buffer — all in one dispatch.
        # jit re-specializes per (M, SP, buf_w) shape; padding rows scatter
        # out of range and are dropped.
        @functools.partial(jax.jit, donate_argnums=(3, 4, 5))
        def fn(params, rows, plens, cache, tokens_buf, pending, slot_rows,
               key):
            sp = rows.shape[1]
            logits, cache = tfm.prefill_into_slots(
                params, cfg, rows, plens, cache, slot_rows,
                use_flash=use_flash,
            )
            lg = logits
            if g.min_new_tokens > 0:
                lg = jnp.where(
                    (jnp.arange(cfg.vocab_size) == eos)[None, :], -1e10, lg
                )
            tok, logp = sample_token(
                lg, key, temperature=g.temperature, top_k=g.top_k,
                top_p=g.top_p, greedy=g.greedy,
            )
            tokens_buf = tokens_buf.at[slot_rows, :sp].set(rows, mode="drop")
            tokens_buf = tokens_buf.at[slot_rows, plens].set(tok, mode="drop")
            pending = pending.at[slot_rows].set(tok, mode="drop")
            return tok, logp, cache, tokens_buf, pending

        self._gen_fns[sig] = fn
        return fn

    def _get_spec_decode_fn(
        self, n_slots: int, s_max: int, n_steps: int,
        g: GenerationHyperparameters,
    ):
        K = g.spec_decode_k
        sig = (
            "spec_decode", n_slots, s_max, n_steps, K, g.spec_ngram,
            g.min_new_tokens, g.greedy, g.top_p, g.top_k, g.temperature,
        )
        if sig in self._gen_fns:
            return self._gen_fns[sig]
        cfg = self.cfg
        eos = self.eos_token_id
        from areal_tpu.ops.ngram import propose_ngram

        out_w = n_steps * (K + 1)
        rows = jnp.arange(n_slots)

        @functools.partial(jax.jit, donate_argnums=(1, 2))
        def fn(params, cache, tokens_buf, pending, cache_len, gen_count,
               done, key):
            out_toks = jnp.full((n_slots, out_w), -1, jnp.int32)
            out_logps = jnp.zeros((n_slots, out_w), jnp.float32)
            out_fill = jnp.zeros((n_slots,), jnp.int32)

            def body(t, st):
                (cache, tokens_buf, pending, cache_len, gen_count, done,
                 out_toks, out_logps, out_fill) = st
                drafts = propose_ngram(
                    tokens_buf, cache_len + 1, K, g.spec_ngram
                )  # [B, K]
                inputs = jnp.concatenate(
                    [pending[:, None], drafts], axis=1
                )  # [B, K+1]
                slots0 = jnp.minimum(cache_len, s_max - 1 - K)
                positions = slots0[:, None] + jnp.arange(K + 1)[None, :]
                logits, cache2 = tfm.decode_step_spec(
                    params, cfg,
                    jnp.where(done[:, None], eos, inputs),
                    positions, cache, slots0,
                )  # [B, K+1, V]
                sub = jax.random.fold_in(key, t)
                (
                    tokens_buf, pending2, cache_len2, gen_count2, new_done,
                    out_toks, out_logps, out_fill,
                ) = _spec_emit(
                    cfg, g, eos, rows, logits, drafts, sub, pending,
                    cache_len, gen_count, done, out_toks, out_logps,
                    out_fill, tokens_buf,
                )
                return (
                    cache2, tokens_buf, pending2, cache_len2, gen_count2,
                    new_done, out_toks, out_logps, out_fill,
                )

            st = (cache, tokens_buf, pending, cache_len, gen_count, done,
                  out_toks, out_logps, out_fill)
            st = jax.lax.fori_loop(0, n_steps, body, st)
            (cache, tokens_buf, pending, cache_len, gen_count, done,
             out_toks, out_logps, _) = st
            return (
                out_toks, out_logps, tokens_buf, cache, pending,
                cache_len, gen_count, done,
            )

        self._gen_fns[sig] = fn
        self.decode_compiles += 1
        self._m_decode_compiles.inc()
        logger.info(
            f"compiled spec decoder n_slots={n_slots} s_max={s_max} "
            f"steps={n_steps} K={K}"
        )
        return fn

    # -- one fixed-shape chunk --

    def _generate_chunk(self, chunk, gconfig, key, results) -> None:
        b_real = len(chunk)
        b = b_real
        while b % self.batch_shard:
            b += 1
        sp = bucket_len(max(len(t) for (_, _, t) in chunk))
        s_total = bucket_len(sp + gconfig.max_new_tokens)

        # Right-aligned prompts: every row's next token lands at the SAME
        # cache slot (sp + step), so the decode KV write is one
        # dynamic_update_slice instead of a per-row scatter.
        prompt_tok = np.full((b, sp), self.pad_token_id, np.int32)
        prompt_len = np.zeros((b,), np.int32)
        for r, (_, _, toks) in enumerate(chunk):
            prompt_tok[r, sp - len(toks):] = toks
            prompt_len[r] = len(toks)

        fn = self._get_gen_fn(b, sp, s_total, gconfig)
        with tracer.span("gen_chunk", cat="compute", b=b_real, sp=sp):
            toks, logps, gen_len = fn(
                self.params, prompt_tok, prompt_len, key
            )
            toks, logps, gen_len = (
                to_host(toks),
                to_host(logps),
                to_host(gen_len),
            )
        for r, (i, rep, _) in enumerate(chunk):
            gl = int(gen_len[r])
            no_eos = gl == gconfig.max_new_tokens and (
                gl == 0 or toks[r, gl - 1] != self.eos_token_id
            )
            results[(i, rep)] = (toks[r, :gl], logps[r, :gl], no_eos)

    def _get_gen_fn(self, b, sp, s_total, g: GenerationHyperparameters):
        sig = (
            b, sp, s_total, g.max_new_tokens, g.min_new_tokens, g.greedy,
            g.top_p, g.top_k, g.temperature,
        )
        if sig in self._gen_fns:
            return self._gen_fns[sig]
        cfg = self.cfg
        eos = self.eos_token_id
        max_new = g.max_new_tokens

        @jax.jit
        def gen(params, prompt_tok, prompt_len, key):
            bsz = prompt_tok.shape[0]
            seg = (
                jnp.arange(sp)[None, :] >= (sp - prompt_len)[:, None]
            ).astype(jnp.int32)
            valid_from = sp - prompt_len  # [B] first live cache slot
            cache = tfm.init_kv_cache(cfg, bsz, s_total, dtype=self.compute_dtype)
            # prefill returns logits at each row's last prompt token — the
            # distribution over the first response token.
            logits0, cache = tfm.prefill(
                params, cfg, prompt_tok, seg, cache, use_flash=self._use_flash
            )

            out_toks = jnp.zeros((bsz, max_new), jnp.int32)
            out_logps = jnp.zeros((bsz, max_new), jnp.float32)
            done = jnp.zeros((bsz,), bool)
            gen_len = jnp.zeros((bsz,), jnp.int32)

            def cond(state):
                step, _, _, done, *_ = state
                return (step < max_new) & ~jnp.all(done)

            def body(state):
                step, logits, key, done, gen_len, out_toks, out_logps, cache = state
                key, sub = jax.random.split(key)
                if g.min_new_tokens > 0:
                    logits = jnp.where(
                        (step < g.min_new_tokens)
                        & (jnp.arange(logits.shape[-1]) == eos)[None, :],
                        -1e10,
                        logits,
                    )
                tok, logp = sample_token(
                    logits, sub,
                    temperature=g.temperature, top_k=g.top_k, top_p=g.top_p,
                    greedy=g.greedy,
                )
                tok = jnp.where(done, eos, tok)
                out_toks = out_toks.at[:, step].set(jnp.where(done, 0, tok))
                out_logps = out_logps.at[:, step].set(jnp.where(done, 0.0, logp))
                gen_len = gen_len + (~done).astype(jnp.int32)
                new_done = done | (tok == eos)
                pos = prompt_len + step  # RoPE position per row
                next_logits, cache = tfm.decode_step(
                    params, cfg, tok, pos, cache, sp + step, valid_from
                )
                return (
                    step + 1, next_logits, key, new_done, gen_len,
                    out_toks, out_logps, cache,
                )

            state = (0, logits0, key, done, gen_len, out_toks, out_logps, cache)
            state = jax.lax.while_loop(cond, body, state)
            _, _, _, _, gen_len, out_toks, out_logps, _ = state
            return out_toks, out_logps, gen_len

        self._gen_fns[sig] = gen
        logger.info(
            f"compiled generator for shape b={b} sp={sp} s_total={s_total}"
        )
        return gen

    # -- output assembly --

    def _assemble(self, sample, prompt_key, prompt_lens, results, n):
        toks = sum(len(t[0]) for t in results.values())
        self._m_tokens.inc(toks)
        dt = time.monotonic() - getattr(self, "_gen_t0", time.monotonic())
        if dt > 0:
            # Wall-clock goodput of the whole call, park time included —
            # the per-server throughput the fleet table reports.
            self._m_goodput.set(toks / dt)
        return assemble_rollout(
            sample, prompt_key, n,
            lambda i, r: results[(i, r)],
            prompt_lens=prompt_lens,
        )


def assemble_rollout(
    sample: SequenceSample,
    prompt_key: str,
    n: int,
    fetch,  # (prompt_idx, response_idx) -> (gen_tokens, gen_logprobs, no_eos)
    prompt_lens: "Optional[List[int]]" = None,
) -> SequenceSample:
    """THE rollout packing layout, shared by the in-process generator and
    the remote generation client (system/gen_server.py) so the two can
    never drift: per response, full = prompt + generated tokens;
    prompt_mask covers the prompt; packed_logprobs is length len(full)-1
    with the generated-token logprobs at [pl-1, pl-1+len(gen))."""
    bs = sample.bs
    prompts = np.asarray(sample.data[prompt_key])
    bounds = sample.cu_seqlens(prompt_key)
    if prompt_lens is None:
        prompt_lens = [int(bounds[i + 1] - bounds[i]) for i in range(bs)]
    seq_ids, seq_logps, seq_masks = [], [], []
    seqlens_full: List[List[int]] = []
    seqlens_lp: List[List[int]] = []
    no_eos: List[List[float]] = []
    for i in range(bs):
        lens_i, lens_lp_i, noeos_i = [], [], []
        ptoks = prompts[bounds[i] : bounds[i + 1]]
        pl = prompt_lens[i]
        for r in range(n):
            gtoks, glogps, ne = fetch(i, r)
            gtoks = np.asarray(gtoks, np.int32)
            glogps = np.asarray(glogps, np.float32)
            full = np.concatenate([ptoks, gtoks]).astype(np.int32)
            seq_ids.append(full)
            mask = np.zeros(len(full), bool)
            mask[:pl] = True
            seq_masks.append(mask)
            lp = np.zeros(max(len(full) - 1, 0), np.float32)
            lp[pl - 1 : pl - 1 + len(gtoks)] = glogps
            seq_logps.append(lp)
            lens_i.append(len(full))
            lens_lp_i.append(max(len(full) - 1, 0))
            noeos_i.append(1.0 if ne else 0.0)
        seqlens_full.append(lens_i)
        seqlens_lp.append(lens_lp_i)
        no_eos.append(noeos_i)
    return SequenceSample(
        keys={
            "packed_input_ids", "packed_logprobs", "prompt_mask",
            "seq_no_eos_mask",
        },
        ids=list(sample.ids),
        seqlens={
            "packed_input_ids": seqlens_full,
            "prompt_mask": [list(x) for x in seqlens_full],
            "packed_logprobs": seqlens_lp,
            "seq_no_eos_mask": [[1] * n for _ in range(bs)],
        },
        data={
            "packed_input_ids": np.concatenate(seq_ids),
            "prompt_mask": np.concatenate(seq_masks),
            "packed_logprobs": np.concatenate(seq_logps)
            if seq_logps
            else np.zeros(0, np.float32),
            "seq_no_eos_mask": np.asarray(
                [x for row in no_eos for x in row], np.float32
            ),
        },
    )
