"""Training engine: optax + GSPMD-FSDP, micro-batched grad accumulation.

Capability parity: realhf/impl/model/backend/megatron.py (`ReaLMegatronEngine`
— DDP + DistributedOptimizer/ZeRO-1 + grad-accum train_batch) and
backend/mock_train.py — redesigned for TPU:

- ZeRO/FSDP is not an optimizer wrapper but a sharding: master params (fp32)
  and optimizer state carry the same NamedShardings as the model pytree
  (fsdp/model axes), so optimizer math is automatically distributed.
- Mixed precision Megatron-style: fp32 master params, bf16 compute — the
  jitted step casts to the model's compute dtype inside the graph (XLA fuses
  the casts into the matmuls).
- Grad accumulation across micro-batches keeps one jitted grad_fn and one
  jitted apply_fn regardless of the number of micro-batches, with
  token-weighted loss normalization matching the reference
  (pipe_runner.py loss normalization across mbs).
"""

import functools
from typing import Any, Callable, Dict, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from areal_tpu.api.data_api import MicroBatchSpec, SequenceSample
from areal_tpu.api.model_api import Engine, FinetuneSpec, OptimizerConfig
from areal_tpu.base import faults, integrity, logging
from areal_tpu.base.distributed import is_primary, to_host
from areal_tpu.engines import packing
from areal_tpu.engines.offload import HostOffloadMixin
from areal_tpu.models import transformer as tfm
from areal_tpu.models.config import ModelConfig
from areal_tpu.parallel import sharding

logger = logging.getLogger("train_engine")


def make_lr_schedule(cfg: OptimizerConfig, total_steps: int):
    warmup = max(int(total_steps * cfg.warmup_steps_proportion), 0)
    floor = cfg.lr * cfg.min_lr_ratio
    decay = max(total_steps - warmup, 1)
    if cfg.lr_scheduler_type == "constant":
        main = optax.constant_schedule(cfg.lr)
    elif cfg.lr_scheduler_type == "linear":
        main = optax.linear_schedule(cfg.lr, floor, decay)
    elif cfg.lr_scheduler_type == "cosine":
        main = optax.cosine_decay_schedule(cfg.lr, decay, alpha=cfg.min_lr_ratio)
    else:
        raise ValueError(f"unknown lr_scheduler_type {cfg.lr_scheduler_type!r}")
    if warmup == 0:
        return main
    return optax.join_schedules(
        [optax.linear_schedule(0.0, cfg.lr, warmup), main], [warmup]
    )


def make_optimizer(cfg: OptimizerConfig, total_steps: int) -> optax.GradientTransformation:
    sched = make_lr_schedule(cfg, total_steps)
    chain = []
    if cfg.gradient_clipping and cfg.gradient_clipping > 0:
        chain.append(optax.clip_by_global_norm(cfg.gradient_clipping))
    chain.append(
        optax.adamw(
            learning_rate=sched,
            b1=cfg.beta1,
            b2=cfg.beta2,
            eps=cfg.eps,
            weight_decay=cfg.weight_decay,
        )
    )
    return optax.chain(*chain)


@functools.lru_cache(maxsize=None)
def _moments_fn(value_keys: Tuple[str, ...], mask_key: str):
    @jax.jit
    def f(batch):
        mask = batch[mask_key] > 0
        out = {"count": mask.sum().astype(jnp.float32)}
        for k in value_keys:
            v = jnp.where(mask, batch[k].astype(jnp.float32), 0.0)
            out[k] = jnp.stack([v.sum(), (v * v).sum(), jnp.abs(v).sum()])
        return out

    return f


def _cast_tree(tree, dtype):
    return jax.tree.map(
        lambda x: x.astype(dtype) if jnp.issubdtype(x.dtype, jnp.floating) else x,
        tree,
    )


def _model_out(params, cfg: ModelConfig, x, batch):
    """Per-token model output [B, S] from final hidden states (see
    transformer.per_token_output)."""
    return tfm.per_token_output(
        params, cfg, x, batch["tokens"], batch["segment_ids"]
    )


class TrainEngine(HostOffloadMixin, Engine):
    """Engine holding fp32 master params + optimizer state on a mesh."""

    def __init__(
        self,
        cfg: ModelConfig,
        params: Dict[str, Any],
        mesh: Mesh,
        optimizer_config: Optional[OptimizerConfig] = None,
        ftspec: Optional[FinetuneSpec] = None,
        compute_dtype=jnp.bfloat16,
        # Master-weight / Adam-moment dtype.  fp32 is the Megatron-style
        # default; bf16 halves optimizer memory (params+mu+nu: 12 vs 6
        # bytes/param) for memory-bound single-chip configs — the tradeoff
        # large-model recipes make when HBM, not accuracy, binds.
        master_dtype=jnp.float32,
        # Activation rematerialization per layer: "full" (save nothing),
        # "dots" (save ALL matmul outputs; ~zero recompute when they
        # fit), "dots_small" (save only the two per-layer residual-
        # branch outputs — ~1/8 of "dots" memory, recomputes most of
        # the layer), "none".  See models/transformer.py _backbone.
        remat_policy: str = "full",
        # Pipeline schedule (pipe>1 meshes only):
        #   "gpipe"    — up to 4P in-flight microbatches; bubble
        #                (P-1)/(5P-1), backward residuals for all of them;
        #   "1f1b-mem" — P in-flight microbatches per jitted step: peak
        #                activation memory drops to 1F1B's O(P) bound
        #                (reference: static_schedule.py:323 TrainSchedule),
        #                amortization comes from the engine's grad-
        #                accumulation loop across micro-batches instead of
        #                intra-schedule interleaving (more bubble ticks —
        #                the memory/throughput trade is the caller's).
        pipe_schedule: str = "gpipe",
        # Anomaly sentinels (the numerical-integrity guard plane).
        # Non-finite loss/grad detection is ALWAYS on — a NaN update is
        # never worth applying.  The tunable sentinels default off:
        #   anomaly_grad_norm_mult M > 1: quarantine when the grad norm
        #     exceeds M x a running EWMA of clean-step grad norms (the
        #     EWMA only starts judging after `anomaly_ewma_warmup` clean
        #     steps, so early-training norm drift doesn't trip it);
        #   anomaly_update_norm_max > 0: absolute ceiling on the post-
        #     optimizer update norm.
        # All verdicts are computed inside the jitted apply and returned
        # as ONE packed scalar vector, so the guard costs a single extra
        # host sync per train step and zero retraces.
        anomaly_grad_norm_mult: float = 0.0,
        anomaly_update_norm_max: float = 0.0,
        anomaly_ewma_warmup: int = 5,
    ):
        self.cfg = cfg
        self.mesh = mesh
        self.optimizer_config = optimizer_config or OptimizerConfig()
        self.ftspec = ftspec or FinetuneSpec()
        # On CPU tests bf16 matmuls are slow and loose; use fp32 there.
        if jax.default_backend() == "cpu":
            compute_dtype = jnp.float32
        self.compute_dtype = compute_dtype
        self.master_dtype = master_dtype
        self.remat_policy = remat_policy

        self.param_specs = sharding.param_pspecs(params)
        self.param_shardings = sharding.tree_named(mesh, self.param_specs)
        # Master copy, sharded.
        params = _cast_tree(params, master_dtype)
        self.params = jax.device_put(params, self.param_shardings)
        self.optimizer = make_optimizer(
            self.optimizer_config, max(self.ftspec.total_train_steps, 1)
        )

        # Optimizer state mirrors param shapes; jitting init lets the SPMD
        # partitioner give mu/nu the same shardings as the params (ZeRO-1).
        self.opt_state = jax.jit(self.optimizer.init)(self.params)
        # Commit the state to its shardings (no copy): the apply jits pin
        # their out_shardings to these, so the params/opt/guard carry run
        # through train steps with byte-identical cache keys — one compiled
        # executable per apply fn for the whole trial, checkpoint restores
        # included.
        # Leaves the partitioner left off-mesh (scalar step counts land on
        # a single device) are re-homed as mesh-replicated so the commit
        # never pins state somewhere the apply jits can't accept it.
        mesh_devices = set(self.mesh.devices.flat)
        self.opt_shardings = jax.tree.map(
            lambda a: (
                a.sharding
                if a.sharding.device_set == mesh_devices
                else sharding.named(self.mesh, P())
            ),
            self.opt_state,
        )
        self.opt_state = jax.device_put(self.opt_state, self.opt_shardings)

        if 0.0 < anomaly_grad_norm_mult <= 1.0:
            raise ValueError(
                "anomaly_grad_norm_mult must be > 1 when set (got "
                f"{anomaly_grad_norm_mult}); 0 disables the spike sentinel"
            )
        self.anomaly_grad_norm_mult = float(anomaly_grad_norm_mult)
        self.anomaly_update_norm_max = float(anomaly_update_norm_max)
        self.anomaly_ewma_warmup = int(anomaly_ewma_warmup)
        # (EWMA of clean-step grad norms, clean-step count) — traced args
        # of the guarded apply, so their evolution never retraces.
        self._guard_state = None
        self._faults = faults.FaultInjector.from_env()
        # Counts batched device->host stat transfers; chaos legs assert
        # exactly one per train_batch / stream chunk / stream end call.
        self.host_transfers = 0

        self._grad_fns: Dict[Any, Callable] = {}
        self._fwd_fns: Dict[Any, Callable] = {}
        self._apply_fn = None
        self._scaled_apply_fn = None
        self._batch_sharding = sharding.named(mesh, sharding.batch_pspec())
        (
            self._use_flash,
            self._cp_mesh,
            self._pp_mesh,
            self._pp_microbatches,
            self.batch_shard,
        ) = sharding.attn_dispatch(mesh, cfg)
        if pipe_schedule not in ("gpipe", "1f1b-mem"):
            raise ValueError(f"unknown pipe_schedule {pipe_schedule!r}")
        self.pipe_schedule = pipe_schedule
        if self._pp_mesh is not None and pipe_schedule == "1f1b-mem":
            self._pp_microbatches = self._pp_mesh.shape[
                sharding.PIPE_AXIS
            ]
        # Lazy byte-size cache for perf_counters(): param/opt global
        # bytes never change shape after init, so sum the leaves once.
        self._tree_bytes: Optional[Tuple[int, int]] = None

    def perf_counters(self) -> Dict[str, int]:
        """Memory/compile counters for the worker's MFC spans (profile
        store fields; analysis/profile.py _WATERMARK_ARGS): global
        param/optimizer bytes plus the engine's jit-trace surface."""
        if self._tree_bytes is None:
            self._tree_bytes = (
                sum(int(x.nbytes) for x in jax.tree.leaves(self.params)),
                sum(int(x.nbytes) for x in jax.tree.leaves(self.opt_state)),
            )
        compiles = 0
        for gf, gaf in self._grad_fns.values():
            compiles += gf._cache_size() + gaf._cache_size()
        for fn in (self._apply_fn, self._scaled_apply_fn):
            if fn is not None:
                compiles += fn._cache_size()
        return {
            "param_bytes": self._tree_bytes[0],
            "opt_bytes": self._tree_bytes[1],
            "compiles": compiles,
        }

    # ---------------- core jitted fns ----------------

    def _pack_row_chunks(self, arrays):
        """1f1b-mem schedule: cap rows per jitted step at batch_shard
        (= batch_axes x P, i.e. exactly P in-flight microbatches of
        minimal size) so peak activation memory per step sits at the 1F1B
        bound; the surrounding grad-accumulation loop supplies the
        amortization GPipe gets from 4P in-flight microbatches."""
        if self.pipe_schedule != "1f1b-mem" or self._pp_mesh is None:
            return [arrays]
        cap = self.batch_shard
        b = next(iter(arrays.values())).shape[0]
        if b <= cap:
            return [arrays]
        return [
            {k: v[i : i + cap] for k, v in arrays.items()}
            for i in range(0, b, cap)
        ]

    def _get_grad_fn(self, loss_fn: Callable):
        if loss_fn in self._grad_fns:
            return self._grad_fns[loss_fn]
        cfg, compute_dtype = self.cfg, self.compute_dtype
        use_flash = self._use_flash
        cp_mesh = self._cp_mesh
        pp_mesh, pp_mbs = self._pp_mesh, self._pp_microbatches
        remat = self.remat_policy

        def _value_and_grad(params, batch, loss_scale):
            def losswrap(p):
                pc = _cast_tree(p, compute_dtype)
                x, aux = tfm.hidden_states(
                    pc,
                    cfg,
                    batch["tokens"],
                    batch["segment_ids"],
                    positions=batch["positions"],
                    remat=remat,
                    use_flash=use_flash,
                    cp_mesh=cp_mesh,
                    pp_mesh=pp_mesh,
                    pp_microbatches=pp_mbs,
                )
                # Loss fns receive per-token model outputs, never [B,S,V]
                # logits: critic -> values; LM -> fused chunked next-token
                # logprobs (the 152k-vocab memory/bandwidth fix).
                out = _model_out(pc, cfg, x, batch)
                loss, stats = loss_fn(out, batch)
                total = loss + cfg.moe_aux_loss_coef * aux
                return total * loss_scale, stats

            return jax.value_and_grad(losswrap, has_aux=True)(params)

        @jax.jit
        def grad_fn(params, batch, loss_scale):
            (loss, stats), grads = _value_and_grad(params, batch, loss_scale)
            return grads, loss, stats

        # Fused accumulate: the running grad sum is DONATED and updated
        # in-graph, so accumulation never holds two full grad trees — the
        # term that pushes large single-chip configs out of HBM.
        @functools.partial(jax.jit, donate_argnums=(3,))
        def grad_acc_fn(params, batch, loss_scale, acc):
            (loss, stats), grads = _value_and_grad(params, batch, loss_scale)
            return jax.tree.map(jnp.add, acc, grads), loss, stats

        self._grad_fns[loss_fn] = (grad_fn, grad_acc_fn)
        return self._grad_fns[loss_fn]

    def _guarded_step(self, params, opt_state, grads, guard, loss_sum, ext_trip):
        """In-graph guarded optimizer step (traced inside the apply jits).

        Computes the anomaly verdict, applies the update ONLY when the
        verdict is clean (per-leaf `jnp.where` select, so the donated
        buffers stay reusable and a quarantined step returns the original
        params/opt_state bit-identically), and advances the grad-norm
        EWMA on clean steps.  Thresholds are Python constants captured at
        closure build time; everything data-dependent (verdict, guard,
        ext_trip) is traced — clean and quarantined steps share one trace.
        """
        optimizer = self.optimizer
        mult = self.anomaly_grad_norm_mult
        unorm_max = self.anomaly_update_norm_max
        warmup = float(self.anomaly_ewma_warmup)

        gnorm = optax.global_norm(grads)
        updates, new_opt = optimizer.update(grads, opt_state, params)
        new_params = optax.apply_updates(params, updates)
        unorm = optax.global_norm(updates)

        ewma, count = guard[0], guard[1]
        finite = jnp.isfinite(gnorm) & jnp.isfinite(loss_sum)
        verdict = jnp.where(finite, 0, integrity.NONFINITE).astype(jnp.int32)
        if mult > 0.0:
            # NaN gnorm compares False, so a non-finite step never
            # double-counts as a spike.
            spike = (count >= warmup) & (gnorm > mult * ewma)
            verdict = verdict + jnp.where(spike, integrity.GRAD_SPIKE, 0)
        if unorm_max > 0.0:
            ceil = finite & (unorm > unorm_max)
            verdict = verdict + jnp.where(ceil, integrity.UPDATE_NORM, 0)

        ok = (verdict == 0) & (ext_trip == 0)
        out_params = jax.tree.map(
            lambda new, old: jnp.where(ok, new, old), new_params, params
        )
        out_opt = jax.tree.map(
            lambda new, old: jnp.where(ok, new, old), new_opt, opt_state
        )
        # The EWMA tracks CLEAN grad norms only: a quarantined spike must
        # not drag the baseline up, or a spike streak would self-absolve.
        new_ewma = jnp.where(
            ok,
            jnp.where(count > 0, 0.9 * ewma + 0.1 * gnorm, gnorm),
            ewma,
        )
        new_count = count + jnp.where(ok, 1.0, 0.0)
        new_guard = jnp.stack([new_ewma, new_count])
        packed = jnp.stack(
            [
                loss_sum.astype(jnp.float32),
                gnorm.astype(jnp.float32),
                unorm.astype(jnp.float32),
                verdict.astype(jnp.float32),
            ]
        )
        return out_params, out_opt, new_guard, packed

    def _get_apply_fn(self):
        if self._apply_fn is not None:
            return self._apply_fn
        step = self._guarded_step

        # Donation: params/opt_state/grads buffers are all dead after the
        # step — without it the optimizer step transiently holds 2x params
        # + 2x Adam state, the peak-memory term for large models on one
        # chip.  Grads share the params' shape/dtype set (master dtype), so
        # their buffers are reusable for the updated params.  The guarded
        # select keeps this safe on quarantined steps: jnp.where's output
        # may alias either input, and the original values only ever flow
        # out through the jit's own outputs.
        @functools.partial(
            jax.jit,
            donate_argnums=(0, 1, 2, 3),
            out_shardings=self._apply_out_shardings(),
        )
        def apply_fn(params, opt_state, grads, guard, loss_sum):
            return step(
                params, opt_state, grads, guard, loss_sum, jnp.float32(0.0)
            )

        self._apply_fn = apply_fn
        return apply_fn

    def _apply_out_shardings(self):
        """Output shardings for the guarded apply jits, pinned to the INPUT
        shardings of the state they round-trip.  Left unpinned, GSPMD is
        free to hand params back with collapsed specs (e.g. replicated on a
        1-device mesh), which changes the next call's cache key — the warm
        path would silently compile a second executable, and a checkpoint
        restore (device_put back to the canonical shardings) a third."""
        return (
            self.param_shardings,
            self.opt_shardings,
            sharding.named(self.mesh, P()),
            sharding.named(self.mesh, P()),
        )

    def _get_scaled_apply_fn(self):
        """Optimizer step for the streamed path: the grad sum was
        accumulated at unit loss_scale (the per-chunk weight is unknown
        until the stream closes), so scale by 1/total_weight here before
        clipping/AdamW.  Same donation story as `_get_apply_fn`; the
        extra `ext_trip` traced scalar lets the interface force a
        quarantine (batch-level sentinel tripped mid-stream) so the
        accumulated partial grads are discarded without a retrace."""
        if self._scaled_apply_fn is not None:
            return self._scaled_apply_fn
        step = self._guarded_step

        @functools.partial(
            jax.jit,
            donate_argnums=(0, 1, 2, 3),
            out_shardings=self._apply_out_shardings(),
        )
        def apply_fn(params, opt_state, grads, guard, loss_sum, scale, ext_trip):
            grads = jax.tree.map(lambda g: g * scale, grads)
            return step(params, opt_state, grads, guard, loss_sum, ext_trip)

        self._scaled_apply_fn = apply_fn
        return apply_fn

    def _guard(self):
        if self._guard_state is None:
            # Committed replicated placement, matching the apply jits'
            # pinned guard out_sharding — a fresh guard (first step, or a
            # post-rollback reset) keys identically to an evolved one.
            self._guard_state = jax.device_put(
                jnp.zeros(2, jnp.float32), sharding.named(self.mesh, P())
            )
        return self._guard_state

    def _poison_grads(self, acc):
        """`nan@point=train_grads` chaos hook: poison the accumulated
        grad sum in eager ops, outside every counted jit cache, so the
        injection itself cannot perturb trace-flatness accounting."""
        kind = self._faults.poison("train_grads") if self._faults else None
        if kind == "nan":
            logger.warning(
                "fault injection: NaN-poisoning grad sum (train_grads)"
            )
            return jax.tree.map(lambda g: g * np.float32("nan"), acc)
        return acc

    # ---------------- Engine API ----------------

    def train_batch(
        self,
        sample: SequenceSample,
        mb_spec: MicroBatchSpec,
        loss_fn: Callable,
        loss_weight_fn: Callable[[Dict[str, np.ndarray]], float],
        token_key: str = "packed_input_ids",
        extra_keys: Sequence[str] = (),
        version_steps: int = 0,
    ) -> Dict[str, float]:
        """Accumulate grads over micro-batches, then one optimizer step.

        loss_fn must return a *sum* over valid tokens; normalization across
        micro-batches uses `loss_weight_fn(batch) -> float` (e.g. number of
        loss tokens) so the final gradient equals the full-batch mean.
        """
        self._ensure_loaded()
        sharded_mbs = packing.split_sharded(sample, mb_spec)
        packs = [
            packing.pack_sample(
                mb,
                token_key,
                extra_keys=extra_keys,
                n_rows_multiple=self.batch_shard,
                max_tokens_per_row=mb_spec.max_tokens_per_mb,
                shard_blocks=blocks,
            )
            for mb, blocks in sharded_mbs
        ]
        # 1f1b-mem row chunking slices contiguous row ranges, which would
        # cut across the per-shard row blocks of a sharded batch; the two
        # compose only via the grad-accum loop, so skip chunking there.
        sharded = any(blocks for _, blocks in sharded_mbs)
        chunks = [
            c
            for pk in packs
            for c in (
                [pk.arrays] if sharded else self._pack_row_chunks(pk.arrays)
            )
        ]
        total_weight = float(sum(loss_weight_fn(c) for c in chunks))
        total_weight = max(total_weight, 1.0)

        # Pack efficiency diagnostics: the MFU counter charges REAL
        # tokens, the MXU computes PADDED grids — the ratio is the
        # first thing to check when train MFU disappoints.
        real_tokens = sum(
            int((c["segment_ids"] > 0).sum()) for c in chunks
        )
        grid_tokens = sum(
            int(np.prod(c["segment_ids"].shape)) for c in chunks
        )
        self.last_pack_stats = {
            "real_tokens": real_tokens,
            "grid_tokens": grid_tokens,
            "pack_efficiency": real_tokens / max(grid_tokens, 1),
            "n_micro_batches": len(chunks),
        }

        grad_fn, grad_acc_fn = self._get_grad_fn(loss_fn)
        acc = None
        losses = []
        all_stats = []
        for arrays in chunks:
            batch = self._device_batch(arrays)
            scale = jnp.float32(1.0 / total_weight)
            if acc is None:
                acc, loss, stats = grad_fn(self.params, batch, scale)
            else:
                acc, loss, stats = grad_acc_fn(
                    self.params, batch, scale, acc
                )
            losses.append(loss)
            all_stats.append(stats)

        acc = self._poison_grads(acc)
        loss_sum = jnp.sum(jnp.stack(losses))
        params, opt_state, self._guard_state, packed = self._get_apply_fn()(
            self.params, self.opt_state, acc, self._guard(), loss_sum
        )
        self.params, self.opt_state = params, opt_state

        # Stats from loss_fn are summed across micro-batches then divided by
        # total weight where keys end in '_sum'; plain keys are averaged.
        # Both reductions happen ON DEVICE and ride the packed-verdict
        # vector, so the whole step pays exactly ONE device->host sync.
        keys = list(all_stats[0].keys()) if all_stats else []
        vec = [packed]
        if keys:
            vec.append(
                jnp.stack(
                    [
                        jnp.sum(jnp.stack([s[k] for s in all_stats]))
                        if k.endswith("_sum")
                        else jnp.mean(jnp.stack([s[k] for s in all_stats]))
                        for k in keys
                    ]
                )
            )
        host = np.asarray(jnp.concatenate(vec), np.float64)
        self.host_transfers += 1

        verdict = float(host[3])
        if verdict:
            integrity.record_anomaly(verdict)
        out: Dict[str, float] = {
            "loss": float(host[0]),
            "grad_norm": float(host[1]),
            "update_norm": float(host[2]),
            "anomaly_verdict": verdict,
            "quarantined": 1.0 if verdict else 0.0,
            "n_micro_batches": float(len(chunks)),
        }
        for i, k in enumerate(keys):
            v = float(host[4 + i])
            if k.endswith("_sum"):
                out[k[: -len("_sum")]] = v / total_weight
            else:
                out[k] = v
        return out

    # ---------------- streamed accumulation ----------------
    #
    # Pipeline-overlapped PPO feeds the trainer one rollout chunk at a
    # time while later chunks are still decoding; the donated grad-sum
    # loop above is reused as the accumulator, split across calls:
    #
    #   state = engine.train_stream_begin()
    #   for chunk: engine.train_stream_chunk(state, chunk_sample, ...)
    #   out = engine.train_stream_end(state)   # one optimizer step
    #
    # Chunks accumulate at unit loss_scale (the total token weight is
    # unknown mid-stream); `train_stream_end` scales the grad sum by
    # 1/total_weight inside the donated apply.  sum(g_i)/W equals the
    # barrier path's sum(g_i/W) up to float reassociation — the
    # bit-exact overlap-off guarantee comes from the master dispatching
    # window=1 steps through the unchanged `train_batch` path.

    def train_stream_begin(self) -> Dict[str, Any]:
        """Open a streamed accumulation window; returns mutable state."""
        self._ensure_loaded()
        return {
            "acc": None,
            "loss_sums": [],
            "stat_sums": {},
            "weight": 0.0,
            "n_micro_batches": 0,
            "n_chunks": 0,
            "real_tokens": 0,
            "grid_tokens": 0,
        }

    def train_stream_chunk(
        self,
        state: Dict[str, Any],
        sample: SequenceSample,
        mb_spec: MicroBatchSpec,
        loss_fn: Callable,
        loss_weight_fn: Callable[[Dict[str, np.ndarray]], float],
        token_key: str = "packed_input_ids",
        extra_keys: Sequence[str] = (),
        version_steps: int = 0,
    ) -> Dict[str, float]:
        """Accumulate one chunk's grads into the stream's donated sum.

        Returns this chunk's raw stat sums (keys keep their `_sum`
        suffix) plus `chunk_weight` / `chunk_loss_sum` so callers can
        build `*_denominator`-weighted per-chunk stats.
        """
        sharded_mbs = packing.split_sharded(sample, mb_spec)
        if any(blocks for _, blocks in sharded_mbs):
            raise ValueError(
                "streamed accumulation does not compose with shard-exact "
                "data placement (shard_of metadata); broadcast chunk inputs "
                "or use the barrier train_batch path"
            )
        packs = [
            packing.pack_sample(
                mb,
                token_key,
                extra_keys=extra_keys,
                n_rows_multiple=self.batch_shard,
                max_tokens_per_row=mb_spec.max_tokens_per_mb,
            )
            for mb, _ in sharded_mbs
        ]
        chunks = [
            c for pk in packs for c in self._pack_row_chunks(pk.arrays)
        ]
        chunk_weight = float(sum(loss_weight_fn(c) for c in chunks))

        grad_fn, grad_acc_fn = self._get_grad_fn(loss_fn)
        scale = jnp.float32(1.0)  # traced arg: no retrace vs train_batch
        losses = []
        all_stats = []
        for arrays in chunks:
            batch = self._device_batch(arrays)
            if state["acc"] is None:
                state["acc"], loss, stats = grad_fn(self.params, batch, scale)
            else:
                state["acc"], loss, stats = grad_acc_fn(
                    self.params, batch, scale, state["acc"]
                )
            losses.append(loss)
            all_stats.append(stats)
            state["real_tokens"] += int((arrays["segment_ids"] > 0).sum())
            state["grid_tokens"] += int(np.prod(arrays["segment_ids"].shape))
        # Host conversion AFTER the dispatch loop, as ONE batched
        # transfer (loss sum + every stat sum in a single stacked
        # vector): one sync per chunk, not per micro-batch or per stat;
        # the device-side sum also keeps the window=1 loss bit-identical
        # to train_batch's.
        chunk_loss = 0.0
        chunk_stats: Dict[str, float] = {}
        if losses:
            keys = list(all_stats[0].keys())
            vec = [jnp.sum(jnp.stack(losses))] + [
                jnp.sum(jnp.stack([s[k] for s in all_stats])) for k in keys
            ]
            host = np.asarray(jnp.stack(vec), np.float64)
            self.host_transfers += 1
            chunk_loss = float(host[0])
            chunk_stats = {k: float(host[1 + i]) for i, k in enumerate(keys)}

        state["weight"] += chunk_weight
        state["loss_sums"].append(chunk_loss)
        state["n_micro_batches"] += len(chunks)
        state["n_chunks"] += 1
        for k, v in chunk_stats.items():
            state["stat_sums"][k] = state["stat_sums"].get(k, 0.0) + v
        return {
            **chunk_stats,
            "chunk_weight": chunk_weight,
            "chunk_loss_sum": chunk_loss,
            "chunk_micro_batches": float(len(chunks)),
        }

    def train_stream_end(
        self, state: Dict[str, Any], quarantine: bool = False
    ) -> Dict[str, float]:
        """Close the stream: one scaled optimizer step over the grad sum.

        `quarantine=True` (a batch-level sentinel tripped mid-stream)
        forces the guarded apply to discard the accumulated partial
        grads: params/opt_state come back bit-identical, via the same
        traced select as an engine-level verdict — no retrace.
        """
        if state["acc"] is None:
            raise ValueError("train_stream_end before any train_stream_chunk")
        total_weight = max(state["weight"], 1.0)
        acc = self._poison_grads(state["acc"])
        loss_sum = jnp.float32(sum(state["loss_sums"]))
        params, opt_state, self._guard_state, packed = (
            self._get_scaled_apply_fn()(
                self.params,
                self.opt_state,
                acc,
                self._guard(),
                loss_sum,
                jnp.float32(1.0 / total_weight),
                jnp.float32(1.0 if quarantine else 0.0),
            )
        )
        self.params, self.opt_state = params, opt_state
        state["acc"] = None  # donated: drop the dead reference

        self.last_pack_stats = {
            "real_tokens": state["real_tokens"],
            "grid_tokens": state["grid_tokens"],
            "pack_efficiency": state["real_tokens"]
            / max(state["grid_tokens"], 1),
            "n_micro_batches": state["n_micro_batches"],
        }
        host = np.asarray(packed, np.float64)
        self.host_transfers += 1
        verdict = float(host[3])
        if verdict:
            integrity.record_anomaly(verdict)
        out: Dict[str, float] = {
            "loss": float(sum(state["loss_sums"])) / total_weight,
            "grad_norm": float(host[1]),
            "update_norm": float(host[2]),
            "anomaly_verdict": verdict,
            "quarantined": 1.0 if (verdict or quarantine) else 0.0,
            "n_micro_batches": float(state["n_micro_batches"]),
            "n_stream_chunks": float(state["n_chunks"]),
        }
        for k, v in state["stat_sums"].items():
            if k.endswith("_sum"):
                out[k[: -len("_sum")]] = v / total_weight
            else:
                out[k] = v / max(state["n_micro_batches"], 1)
        return out

    def masked_moments(
        self,
        sample: SequenceSample,
        mb_spec: MicroBatchSpec,
        value_keys: Sequence[str],
        mask_key: str = "loss_mask",
        token_key: str = "packed_input_ids",
    ) -> Dict[str, Any]:
        """Exact batch-global masked reductions, computed ON DEVICE.

        Under sharded data dispatch each member's HOST arrays hold real
        values only for its own rows (the rest are zero-filled
        placeholders), but the PLACED arrays are globally real: every
        process contributes its own row block via
        `sharding.place_rows` / `jax.make_array_from_process_local_data`.
        A jitted global reduction over them is therefore exact and
        identical on every SPMD member — the in-mesh replacement for the
        full-batch redistribution that makes the reference's host-side
        batch statistics trivially global
        (realhf/system/data_manager.py:144-416).  PPO's batch-global
        advantage moments, ref-KL, and value-norm running moments ride
        this; without it those statistics would silently diverge across
        members (each seeing zeros for the others' rows).

        Returns {"count": N} plus, per value key, a float64 numpy vector
        `[masked_sum, masked_sum_of_squares, masked_abs_sum]`.  Values
        and mask must be token-aligned with `token_key`.
        """
        self._ensure_loaded()
        value_keys = tuple(value_keys)
        fn = _moments_fn(value_keys, mask_key)
        count = 0.0
        acc = {k: np.zeros(3, np.float64) for k in value_keys}
        for mb, blocks in packing.split_sharded(sample, mb_spec):
            pk = packing.pack_sample(
                mb,
                token_key,
                extra_keys=value_keys + (mask_key,),
                n_rows_multiple=self.batch_shard,
                max_tokens_per_row=mb_spec.max_tokens_per_mb,
                shard_blocks=blocks,
            )
            out = fn(self._device_batch(pk.arrays))
            count += float(out["count"])
            for k in value_keys:
                acc[k] += np.asarray(out[k], np.float64)
        acc["count"] = count
        return acc

    def forward(
        self,
        sample: SequenceSample,
        mb_spec: MicroBatchSpec,
        post_fn: Callable,
        output_key: str,
        token_key: str = "packed_input_ids",
        extra_keys: Sequence[str] = (),
        output_seqlens: Optional[list] = None,
    ) -> SequenceSample:
        """Forward-only pass; `post_fn(logits, batch) -> [B, S, ...]` runs
        inside jit (e.g. gather next-token logprobs).  Output is re-packed
        into a SequenceSample keyed `output_key`, token-aligned."""
        self._ensure_loaded()
        fwd = self._get_fwd_fn(post_fn)
        outs = []
        for mb, blocks in packing.split_sharded(sample, mb_spec):
            pk = packing.pack_sample(
                mb,
                token_key,
                extra_keys=extra_keys,
                n_rows_multiple=self.batch_shard,
                max_tokens_per_row=mb_spec.max_tokens_per_mb,
                shard_blocks=blocks,
            )
            batch = self._device_batch(pk.arrays)
            dense = to_host(fwd(self.params, batch))
            packed = pk.unpack(dense)
            out = SequenceSample(
                keys={output_key},
                ids=list(mb.ids),
                seqlens={output_key: [list(s) for s in mb.seqlens[token_key]]},
                data={output_key: packed},
            )
            outs.append(out)
        result = SequenceSample.gather(outs)
        # Restore original id order.
        order = {i: n for n, i in enumerate(result.ids)}
        return result.select_idx([order[i] for i in sample.ids])

    def _get_fwd_fn(self, post_fn):
        if post_fn in self._fwd_fns:
            return self._fwd_fns[post_fn]
        cfg, compute_dtype = self.cfg, self.compute_dtype
        use_flash = self._use_flash
        cp_mesh = self._cp_mesh
        pp_mesh, pp_mbs = self._pp_mesh, self._pp_microbatches

        @jax.jit
        def fwd(params, batch):
            pc = _cast_tree(params, compute_dtype)
            x, _ = tfm.hidden_states(
                pc,
                cfg,
                batch["tokens"],
                batch["segment_ids"],
                positions=batch["positions"],
                use_flash=use_flash,
                cp_mesh=cp_mesh,
                pp_mesh=pp_mesh,
                pp_microbatches=pp_mbs,
            )
            return post_fn(_model_out(pc, cfg, x, batch), batch)

        self._fwd_fns[post_fn] = fwd
        return fwd

    def _device_batch(self, arrays: Dict[str, np.ndarray]):
        return {
            k: sharding.place_rows(
                self.mesh,
                v,
                sharding.batch_pspec()
                if v.ndim == 2
                else P(sharding.BATCH, "seq", None),
            )
            for k, v in arrays.items()
        }

    # ---------------- offload (HostOffloadMixin + optimizer state) ------

    def _offload_state(self):
        return (self.params, self.opt_state)

    def _restore_state(self, state):
        self.params, self.opt_state = state

    def _drop_state(self):
        self.params = None
        self.opt_state = None

    # ---------------- params / ckpt ----------------

    def get_params(self):
        self._ensure_loaded()
        return self.params

    def set_params(self, params) -> None:
        # Restore any offloaded state first (the optimizer state must
        # survive; the reloaded params are immediately replaced).
        self._ensure_loaded()
        self.params = jax.device_put(
            _cast_tree(params, self.master_dtype), self.param_shardings
        )

    def save_optimizer_state(self, path: str) -> None:
        import pickle

        self._ensure_loaded()

        # Host gather is collective on process-spanning meshes — every
        # group member calls it; only jax process 0 writes the file.
        host = jax.tree.map(to_host, self.opt_state)
        if not is_primary():
            return
        with open(path, "wb") as f:
            pickle.dump(host, f)

    def load_optimizer_state(self, path: str) -> None:
        import pickle

        self._ensure_loaded()

        with open(path, "rb") as f:
            host = pickle.load(f)
        self.opt_state = jax.tree.map(
            lambda h, cur: jax.device_put(jnp.asarray(h), cur.sharding),
            host,
            self.opt_state,
        )
