"""Host-offload support shared by the engines (OffloadHook backend).

Reference: realhf/impl/model/nn/real_llm_api.py:308-405 (async offload of
idle models) — here a synchronous host round-trip: `offload()` gathers the
device state to host numpy (collective when the mesh spans processes) and
drops the device buffers; `_ensure_loaded()` restores them on the next use.
"""

from typing import Any, Optional, Tuple


class HostOffloadMixin:
    """Params-only offload; TrainEngine extends with optimizer state."""

    _host_offload: Optional[Any] = None
    _offload_shardings: Optional[Any] = None

    def _offload_state(self) -> Tuple[Any, ...]:
        return (self.params,)

    def _restore_state(self, state: Tuple[Any, ...]) -> None:
        (self.params,) = state

    def _drop_state(self) -> None:
        self.params = None

    def offload(self) -> None:
        """Move device state to host, freeing HBM while the model is idle;
        the next engine call reloads transparently."""
        if self._host_offload is not None:
            return
        import jax

        from areal_tpu.base.distributed import to_host

        state = self._offload_state()
        self._offload_shardings = jax.tree.map(
            lambda x: x.sharding, state
        )
        self._host_offload = jax.tree.map(to_host, state)
        self._drop_state()

    def _ensure_loaded(self) -> None:
        if self._host_offload is None:
            return
        import jax

        state = jax.tree.map(
            jax.device_put, self._host_offload, self._offload_shardings
        )
        self._host_offload = None
        self._offload_shardings = None
        self._restore_state(state)
