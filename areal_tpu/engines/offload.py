"""Host-offload support shared by the engines (OffloadHook backend).

Reference: realhf/impl/model/nn/real_llm_api.py:308-405 (async offload of
idle models) — here a synchronous host round-trip: `offload()` gathers the
device state to host numpy (collective when the mesh spans processes) and
drops the device buffers; `_ensure_loaded()` restores them on the next use.
"""

from typing import Any, Optional, Tuple


def buffers_alias(a, b) -> bool:
    """True when two arrays share any device buffer.  Object identity is
    NOT enough: `device_put`/`astype` can return a DISTINCT Array that
    still aliases the source's buffers (no-op cast, partial reshard), and
    decoding from a buffer the source engine later donates reads freed
    memory.  Compare the underlying per-shard buffer pointers instead."""
    if a is b:
        return True
    try:
        pa = {s.data.unsafe_buffer_pointer() for s in a.addressable_shards}
        pb = {s.data.unsafe_buffer_pointer() for s in b.addressable_shards}
        return bool(pa & pb)
    except Exception:  # non-Array leaves / backends without pointer access
        return False


class HostOffloadMixin:
    """Params-only offload; TrainEngine extends with optimizer state."""

    _host_offload: Optional[Any] = None
    _offload_shardings: Optional[Any] = None

    def _offload_state(self) -> Tuple[Any, ...]:
        return (self.params,)

    def _restore_state(self, state: Tuple[Any, ...]) -> None:
        (self.params,) = state

    def _drop_state(self) -> None:
        self.params = None

    def offload(self) -> None:
        """Move device state to host, freeing HBM while the model is idle;
        the next engine call reloads transparently."""
        if self._host_offload is not None:
            return
        import jax

        from areal_tpu.base.distributed import to_host

        state = self._offload_state()
        self._offload_shardings = jax.tree.map(
            lambda x: x.sharding, state
        )
        self._host_offload = jax.tree.map(to_host, state)
        self._drop_state()

    def _ensure_loaded(self) -> None:
        if self._host_offload is None:
            return
        import jax

        state = jax.tree.map(
            jax.device_put, self._host_offload, self._offload_shardings
        )
        self._host_offload = None
        self._offload_shardings = None
        self._restore_state(state)
