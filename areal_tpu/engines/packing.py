"""SequenceSample ⇄ dense packed rows.

The engines' bridge between the host data plane (packed 1D numpy arrays with
seqlens) and XLA-friendly dense [B, S] buffers: sequences are FFD-packed into
rows, rows padded to a bucketed length (bounding the number of distinct
compiled shapes), and outputs are scattered back into the original
per-sequence packed order.

This is the TPU answer to the reference's cu_seqlens/varlen plumbing
(realhf/impl/model/utils/padding + flash_attn_varlen): instead of one long
ragged buffer per micro-batch we build a static [B, S] grid with segment ids.
"""

import dataclasses
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from areal_tpu.api.data_api import SequenceSample
from areal_tpu.base import datapack

# Pad row lengths to multiples of this (TPU lane width × a few sublanes).
_BUCKET_QUANTUM = 128


def bucket_len(n: int, quantum: int = _BUCKET_QUANTUM, large_step: int = 0) -> int:
    """Round up to a bucketed static length: next power of two below 1024,
    then multiples of `large_step` (default `quantum`·8 = 1024) — bounds
    distinct compile shapes for the TRAINING pack path, where every new
    shape costs a full fwd+bwd compile."""
    n = max(n, 1)
    if n <= 128:
        return 128
    if n <= 1024:
        p = 128
        while p < n:
            p *= 2
        return p
    step = large_step or quantum * 8  # 1024
    return ((n + step - 1) // step) * step


def decode_bucket_len(n: int) -> int:
    """Finer buckets (256 above 1024) for DECODE cache windows: every
    decode step streams the whole window, so coarse buckets directly tax
    every generated token (a 1024 quantum made a 1152-token request pay
    for a 2048-deep window); decode-step compiles are far cheaper than
    train-step compiles, so the extra shapes are affordable."""
    return bucket_len(n, large_step=_BUCKET_QUANTUM * 2)


def split_sharded(
    sample: SequenceSample, mb_spec
) -> List[Tuple[SequenceSample, Optional[List[List[int]]]]]:
    """Micro-batch split that stays consistent across data-plane shards.

    When the sample carries per-id `shard_of` tags (set by the worker when
    the master shipped each SPMD member only its own rows), every member
    must derive the SAME number of micro-batches with the SAME per-shard
    membership from metadata alone — a plain global FFD would interleave
    shards' rows and diverge the jitted programs across processes.  Each
    shard is FFD-split independently into a common group count k;
    micro-batch j is the concatenation of every shard's j-th group, and
    the returned per-microbatch shard blocks give each shard's positions
    within it (feeding pack_sample's row-block layout).

    Without shard tags this is exactly `sample.split(mb_spec)`.
    """
    blocks = sample.shard_blocks()
    if not blocks or len(blocks) <= 1:
        return [(mb, None) for mb in sample.split(mb_spec)]
    key = sample.main_key()
    lens = [sum(sample.seqlens[key][i]) for i in range(sample.bs)]
    cap = mb_spec.max_tokens_per_mb or (sum(lens) + 1)
    from areal_tpu.base import datapack

    k = max(mb_spec.n_mbs, 1)
    while True:
        per = [
            datapack.ffd_allocate(
                [lens[i] for i in b], capacity=cap, min_groups=min(k, len(b))
            )
            if b
            else []
            for b in blocks
        ]
        k2 = max((len(g) for g in per), default=1)
        if k2 <= k:
            break
        k = k2  # a shard needed more groups; re-split everyone to match
    out = []
    for j in range(k):
        idx: List[int] = []
        row_blocks: List[List[int]] = []
        for b, gs in zip(blocks, per):
            g = [b[i] for i in gs[j]] if j < len(gs) else []
            row_blocks.append(list(range(len(idx), len(idx) + len(g))))
            idx.extend(g)
        if not idx:
            continue
        mb = sample.select_idx(idx)
        # pack_sample's shard_blocks index SEQUENCES, not batch rows —
        # a PPO row carries `group` sequences, so the two only coincide
        # for 1-sequence rows.  Expand each shard's contiguous row range
        # to its sequence range (rows are ordered shard-major, so the
        # sequence blocks stay contiguous).
        row_nseq = [len(sample.seqlens[key][i]) for i in idx]
        mb_blocks: List[List[int]] = []
        pos = 0
        for rb in row_blocks:
            n_seq = sum(row_nseq[r] for r in rb)
            mb_blocks.append(list(range(pos, pos + n_seq)))
            pos += n_seq
        out.append((mb, mb_blocks))
    return out


@dataclasses.dataclass
class RowPack:
    """Dense row layout + the mapping back to packed-1D order.

    arrays: key -> [B, S, *trailing] dense array (tokens, segment_ids,
    positions, plus aligned extras).
    seq_map: per original sequence (in sample packed order):
    (row, start, length).
    """

    arrays: Dict[str, np.ndarray]
    seq_map: List[Tuple[int, int, int]]
    n_rows: int
    row_len: int

    def unpack(self, dense: np.ndarray) -> np.ndarray:
        """[B, S, ...] -> packed 1D [sum(lens), ...] in original order."""
        parts = [dense[r, s : s + l] for (r, s, l) in self.seq_map]
        return np.concatenate(parts, axis=0)


def pack_sample(
    sample: SequenceSample,
    token_key: str,
    extra_keys: Sequence[str] = (),
    n_rows_multiple: int = 1,
    max_tokens_per_row: Optional[int] = None,
    row_len: Optional[int] = None,
    shard_blocks: Optional[List[List[int]]] = None,
) -> RowPack:
    """Pack every sequence of `sample[token_key]` into dense rows.

    extra_keys must be token-aligned with token_key (same seqlens).  The
    number of rows is padded to a multiple of `n_rows_multiple` (the mesh's
    batch-sharding degree) with empty rows if needed.

    shard_blocks (per-shard lists of sequence indices, together covering
    every sequence exactly once) pins each shard's sequences to its own
    equal-size contiguous ROW block, aligned with the contiguous
    batch-coordinate layout `_device_batch` shards rows by.  On a
    process-spanning mesh each process then materializes real data only
    for its own block (the sharded data plane zero-fills the rest), and
    identical metadata yields an identical layout on every member.
    """
    lens = sample.seqlens_of(token_key)
    for k in extra_keys:
        if sample.seqlens_of(k) != lens:
            raise ValueError(
                f"extra key {k!r} is not token-aligned with {token_key!r}"
            )
    cap = max_tokens_per_row or max(lens, default=1)
    cap = max(cap, max(lens, default=1))
    if shard_blocks is not None and len(shard_blocks) > 1:
        n_shards = len(shard_blocks)
        if sorted(i for b in shard_blocks for i in b) != list(
            range(len(lens))
        ):
            raise ValueError("shard_blocks must partition the sequences")
        per_groups = [
            datapack.ffd_allocate(
                [lens[i] for i in block], capacity=cap
            )
            for block in shard_blocks
        ]
        # Equal row blocks: every shard gets the same row count, itself a
        # multiple of its slice of the batch-sharding degree.
        mult = max(n_rows_multiple, 1)
        per_mult = max(mult // n_shards, 1) if mult % n_shards == 0 else mult
        rows_per_shard = max(len(g) for g in per_groups)
        while rows_per_shard % per_mult:
            rows_per_shard += 1
        groups = []
        for block, gs in zip(shard_blocks, per_groups):
            local = [[block[i] for i in g] for g in gs]
            local += [[] for _ in range(rows_per_shard - len(local))]
            groups.extend(local)
    else:
        groups = datapack.ffd_allocate(lens, capacity=cap)
        # Pad row count up to a multiple.
        while len(groups) % max(n_rows_multiple, 1):
            groups.append([])
    n_rows = len(groups)
    s_pad = row_len or bucket_len(
        max((sum(lens[i] for i in g) for g in groups), default=1)
    )

    tok_src = np.asarray(sample.data[token_key])
    bounds = sample.cu_seqlens(token_key)
    extra_src = {k: np.asarray(sample.data[k]) for k in extra_keys}
    ex_bounds = {k: sample.cu_seqlens(k) for k in extra_keys}

    def alloc(src):
        shape = (n_rows, s_pad) + src.shape[1:]
        return np.zeros(shape, dtype=src.dtype)

    tokens = alloc(tok_src)
    seg = np.zeros((n_rows, s_pad), np.int32)
    pos = np.zeros((n_rows, s_pad), np.int32)
    extras = {k: alloc(v) for k, v in extra_src.items()}

    seq_map: List[Optional[Tuple[int, int, int]]] = [None] * len(lens)
    for r, g in enumerate(groups):
        off = 0
        for seq_no, i in enumerate(g, start=1):
            l = lens[i]
            tokens[r, off : off + l] = tok_src[bounds[i] : bounds[i + 1]]
            seg[r, off : off + l] = seq_no
            pos[r, off : off + l] = np.arange(l)
            for k in extra_keys:
                eb = ex_bounds[k]
                extras[k][r, off : off + l] = extra_src[k][eb[i] : eb[i + 1]]
            seq_map[i] = (r, off, l)
            off += l

    arrays = {"tokens": tokens, "segment_ids": seg, "positions": pos}
    arrays.update(extras)
    return RowPack(
        arrays=arrays, seq_map=seq_map, n_rows=n_rows, row_len=s_pad
    )
