"""SequenceSample ⇄ dense packed rows.

The engines' bridge between the host data plane (packed 1D numpy arrays with
seqlens) and XLA-friendly dense [B, S] buffers: sequences are FFD-packed into
rows, rows padded to a bucketed length (bounding the number of distinct
compiled shapes), and outputs are scattered back into the original
per-sequence packed order.

This is the TPU answer to the reference's cu_seqlens/varlen plumbing
(realhf/impl/model/utils/padding + flash_attn_varlen): instead of one long
ragged buffer per micro-batch we build a static [B, S] grid with segment ids.
"""

import dataclasses
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from areal_tpu.api.data_api import SequenceSample
from areal_tpu.base import datapack

# Pad row lengths to multiples of this (TPU lane width × a few sublanes).
_BUCKET_QUANTUM = 128


def bucket_len(n: int, quantum: int = _BUCKET_QUANTUM, large_step: int = 0) -> int:
    """Round up to a bucketed static length: next power of two below 1024,
    then multiples of `large_step` (default `quantum`·8 = 1024) — bounds
    distinct compile shapes for the TRAINING pack path, where every new
    shape costs a full fwd+bwd compile."""
    n = max(n, 1)
    if n <= 128:
        return 128
    if n <= 1024:
        p = 128
        while p < n:
            p *= 2
        return p
    step = large_step or quantum * 8  # 1024
    return ((n + step - 1) // step) * step


def decode_bucket_len(n: int) -> int:
    """Finer buckets (256 above 1024) for DECODE cache windows: every
    decode step streams the whole window, so coarse buckets directly tax
    every generated token (a 1024 quantum made a 1152-token request pay
    for a 2048-deep window); decode-step compiles are far cheaper than
    train-step compiles, so the extra shapes are affordable."""
    return bucket_len(n, large_step=_BUCKET_QUANTUM * 2)


@dataclasses.dataclass
class RowPack:
    """Dense row layout + the mapping back to packed-1D order.

    arrays: key -> [B, S, *trailing] dense array (tokens, segment_ids,
    positions, plus aligned extras).
    seq_map: per original sequence (in sample packed order):
    (row, start, length).
    """

    arrays: Dict[str, np.ndarray]
    seq_map: List[Tuple[int, int, int]]
    n_rows: int
    row_len: int

    def unpack(self, dense: np.ndarray) -> np.ndarray:
        """[B, S, ...] -> packed 1D [sum(lens), ...] in original order."""
        parts = [dense[r, s : s + l] for (r, s, l) in self.seq_map]
        return np.concatenate(parts, axis=0)


def pack_sample(
    sample: SequenceSample,
    token_key: str,
    extra_keys: Sequence[str] = (),
    n_rows_multiple: int = 1,
    max_tokens_per_row: Optional[int] = None,
    row_len: Optional[int] = None,
) -> RowPack:
    """Pack every sequence of `sample[token_key]` into dense rows.

    extra_keys must be token-aligned with token_key (same seqlens).  The
    number of rows is padded to a multiple of `n_rows_multiple` (the mesh's
    batch-sharding degree) with empty rows if needed.
    """
    lens = sample.seqlens_of(token_key)
    for k in extra_keys:
        if sample.seqlens_of(k) != lens:
            raise ValueError(
                f"extra key {k!r} is not token-aligned with {token_key!r}"
            )
    cap = max_tokens_per_row or max(lens, default=1)
    cap = max(cap, max(lens, default=1))
    groups = datapack.ffd_allocate(lens, capacity=cap)
    # Pad row count up to a multiple.
    while len(groups) % max(n_rows_multiple, 1):
        groups.append([])
    n_rows = len(groups)
    s_pad = row_len or bucket_len(
        max((sum(lens[i] for i in g) for g in groups), default=1)
    )

    tok_src = np.asarray(sample.data[token_key])
    bounds = sample.cu_seqlens(token_key)
    extra_src = {k: np.asarray(sample.data[k]) for k in extra_keys}
    ex_bounds = {k: sample.cu_seqlens(k) for k in extra_keys}

    def alloc(src):
        shape = (n_rows, s_pad) + src.shape[1:]
        return np.zeros(shape, dtype=src.dtype)

    tokens = alloc(tok_src)
    seg = np.zeros((n_rows, s_pad), np.int32)
    pos = np.zeros((n_rows, s_pad), np.int32)
    extras = {k: alloc(v) for k, v in extra_src.items()}

    seq_map: List[Optional[Tuple[int, int, int]]] = [None] * len(lens)
    for r, g in enumerate(groups):
        off = 0
        for seq_no, i in enumerate(g, start=1):
            l = lens[i]
            tokens[r, off : off + l] = tok_src[bounds[i] : bounds[i + 1]]
            seg[r, off : off + l] = seq_no
            pos[r, off : off + l] = np.arange(l)
            for k in extra_keys:
                eb = ex_bounds[k]
                extras[k][r, off : off + l] = extra_src[k][eb[i] : eb[i + 1]]
            seq_map[i] = (r, off, l)
            off += l

    arrays = {"tokens": tokens, "segment_ids": seg, "positions": pos}
    arrays.update(extras)
    return RowPack(
        arrays=arrays, seq_map=seq_map, n_rows=n_rows, row_len=s_pad
    )
