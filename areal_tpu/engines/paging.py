"""Host-side refcounted free-list page allocator for the paged KV pool.

The device side (`models/transformer.py PagedKVCache`) is a dumb pool of
`n_pages` fixed-size pages; ALL placement policy lives here, on the
host, between jitted decode chunks: which pool pages belong to which
slot, in what order, which are free, and — new in the unified serving
plane — which pages are *shared* between slots.  The allocator's `table`
array is shipped to the device as the page table each chunk (a few KB),
so "growing" a sequence is appending one int to a row — no cache copy,
no recompile — and a retired slot's pages go back on the free list for
the next admission.

Sharing model (copy-on-write): a page may be mapped by several slots at
once (a GRPO group's k responses mapping the same prompt pages, or a
prefix-cache hit on a shared system prompt).  `refcount[p]` counts the
mappings (plus one for a prefix-cache hold).  Shared pages are
read-only by contract: before any device write that lands inside a
slot's window, the engine calls `ensure_writable(slot, lo, hi)` which
privatises still-shared pages in that window (allocates a fresh page,
remaps the slot, returns (src, dst) pairs for the device page-copy) —
classic copy-on-write.  In the steady serving plane the engine arranges
windows so writes only ever hit private pages and `ensure_writable` is
a no-op safety net, but the contract is enforced either way (and under
``AREAL_PAGING_CHECK=1`` every mutation re-validates the full
free/mapped/refcount partition).

Reference role: vLLM's BlockAllocator / the block tables behind TPU
ragged paged attention, plus its prefix-caching refcount scheme.
"""

import os
from collections import OrderedDict
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from areal_tpu.base import metrics


class PagePoolExhausted(RuntimeError):
    """The KV page pool has no free page for a required allocation.

    Raised BEFORE any device state is touched: the cache, page table and
    free list are unchanged, so the condition is a clean capacity error
    (raise `kv_pool_pages`, shrink the batch, or let the server admit
    fewer requests), never corruption."""


class PagingInvariantError(AssertionError):
    """The allocator's free/mapped/refcount partition is broken.

    Only raised by `check()` (wired to every mutation under
    ``AREAL_PAGING_CHECK=1``); seeing one means a host-side paging bug,
    not a capacity condition."""


class PageAllocator:
    """Refcounted free-list allocator over `n_pages` pages of
    `page_size` tokens.

    Each of `n_slots` decode slots owns an ordered, contiguous-from-zero
    list of pages: `table[slot, j]` is the pool page holding the slot's
    flat positions [j*page_size, (j+1)*page_size).  Unmapped entries
    hold the sentinel `n_pages` (device scatters drop it, gathers clamp
    + mask).  A page may appear in several rows (prompt sharing); its
    `refcount` tracks the mappings and the page returns to the free
    list only when the last mapping is released."""

    def __init__(
        self, n_pages: int, page_size: int, n_slots: int, max_pages: int
    ):
        if n_pages < 1 or page_size < 1:
            raise ValueError("n_pages and page_size must be positive")
        self.n_pages = int(n_pages)
        self.page_size = int(page_size)
        self.max_pages = int(max_pages)
        self.sentinel = int(n_pages)
        self.free: List[int] = list(range(n_pages - 1, -1, -1))
        self.table = np.full((n_slots, max_pages), self.sentinel, np.int32)
        self.used = np.zeros((n_slots,), np.int32)
        self.refcount = np.zeros((n_pages,), np.int32)
        # Prefix cache: prompt-hash -> page list, LRU-ordered.  Each
        # cached entry holds one ref per page so retiring the inserting
        # slot cannot free pages a later request may still hit.
        self._prefix_cache: "OrderedDict[object, List[int]]" = OrderedDict()
        self.prefix_hits = 0
        self.prefix_misses = 0
        # Stats for the bench/tests: recycled counts pages handed out
        # again after having been freed; cow_copies counts pages
        # privatised by ensure_writable; shared_mappings counts table
        # references served by an already-mapped page (capacity saved).
        self._freed_ever: set = set()
        self.pages_recycled = 0
        self.peak_pages_used = 0
        self.cow_copies = 0
        self.shared_mappings = 0
        self.debug_check = os.environ.get("AREAL_PAGING_CHECK") == "1"
        # Device bytes per pool page (all layers, K+V, codes + scales
        # for int8 pools).  The engine stamps this after building the
        # device pool — the allocator can't know dtypes or model shape —
        # so `allocated_bytes()` reports real HBM held by mapped pages.
        self.page_bytes = 0
        # Process-wide counters (the allocator itself is per-session):
        # the prefix-cache hit rate and CoW traffic the fleet watchdog
        # trends across generate calls.
        reg = metrics.default_registry()
        self._m_prefix_hits = reg.counter(
            "areal_kv_prefix_hits_total", "prefix-cache page-list hits"
        )
        self._m_prefix_misses = reg.counter(
            "areal_kv_prefix_misses_total", "prefix-cache lookups missed"
        )
        self._m_cow_copies = reg.counter(
            "areal_kv_cow_copies_total",
            "pages privatised by copy-on-write",
        )
        self._m_shared = reg.counter(
            "areal_kv_shared_mappings_total",
            "table references served by an already-mapped page",
        )

    # ---------------------------------------------------------------- core

    def pages_for(self, tokens: int) -> int:
        return -(-int(tokens) // self.page_size)

    def allocated_pages(self) -> int:
        return self.n_pages - len(self.free)

    def allocated_bytes(self) -> int:
        """HBM held by currently-mapped pages (0 until the engine
        stamps `page_bytes`); shared pages count once — that is the
        point of sharing."""
        return self.allocated_pages() * int(self.page_bytes)

    def pool_bytes(self) -> int:
        """Total device bytes of the backing pool, free pages included."""
        return self.n_pages * int(self.page_bytes)

    def _alloc_page(self) -> int:
        p = self.free.pop()
        if p in self._freed_ever:
            self.pages_recycled += 1
        self.refcount[p] = 1
        return p

    def _unref(self, p: int) -> None:
        self.refcount[p] -= 1
        if self.refcount[p] == 0:
            self.free.append(p)
            self._freed_ever.add(p)

    def can_reserve(self, slot: int, tokens: int) -> bool:
        need = self.pages_for(tokens)
        if need > self.max_pages:
            return False
        return need - int(self.used[slot]) <= len(self.free)

    def reserve(self, slot: int, tokens: int) -> None:
        """Ensure `slot` has mapped pages covering flat positions
        [0, tokens).  Appends pages from the free list; raises
        `PagePoolExhausted` (leaving all state unchanged for the pages
        already mapped) when the pool or the table width cannot."""
        need = self.pages_for(tokens)
        if need > self.max_pages:
            raise PagePoolExhausted(
                f"slot {slot} needs {need} pages for {tokens} tokens but "
                f"the page table holds max_pages={self.max_pages} "
                f"(page_size={self.page_size})"
            )
        grow = need - int(self.used[slot])
        if grow > len(self.free):
            raise PagePoolExhausted(
                f"KV page pool exhausted: slot {slot} needs {grow} more "
                f"page(s) for {tokens} tokens but only {len(self.free)} of "
                f"{self.n_pages} are free (page_size={self.page_size}); "
                f"raise kv_pool_pages or admit fewer concurrent requests"
            )
        while self.used[slot] < need:
            self.table[slot, self.used[slot]] = self._alloc_page()
            self.used[slot] += 1
        self.peak_pages_used = max(
            self.peak_pages_used, self.allocated_pages()
        )
        self.maybe_check()

    def release(self, slot: int) -> None:
        """Drop all of `slot`'s mappings; pages whose last reference
        this was go back on the free list (prefix-cache holds keep
        theirs alive)."""
        for j in range(int(self.used[slot])):
            self._unref(int(self.table[slot, j]))
        self.table[slot, :] = self.sentinel
        self.used[slot] = 0
        self.maybe_check()

    def page_rows(self, slot: int, tokens: int) -> np.ndarray:
        """The slot's first `pages_for(tokens)` mapped pages (for the
        admission prefill scatter); caller must have reserve()d them."""
        return self.table[slot, : self.pages_for(tokens)].copy()

    # ------------------------------------------------------------- sharing

    def share(self, slot: int, pages: Sequence[int]) -> None:
        """Map `pages` (another slot's or the prefix cache's prompt
        pages, in order) into the FRONT of `slot`'s table, bumping each
        page's refcount.  `slot` must have no mappings yet — sharing is
        an admission-time operation."""
        if int(self.used[slot]) != 0:
            raise ValueError(
                f"share() into non-empty slot {slot} "
                f"(used={int(self.used[slot])})"
            )
        if len(pages) > self.max_pages:
            raise PagePoolExhausted(
                f"slot {slot} cannot map {len(pages)} shared pages: the "
                f"page table holds max_pages={self.max_pages}"
            )
        for j, p in enumerate(pages):
            p = int(p)
            if self.refcount[p] <= 0:
                raise ValueError(f"share() of unmapped page {p}")
            self.refcount[p] += 1
            self.table[slot, j] = p
            self.shared_mappings += 1
            self._m_shared.inc()
        self.used[slot] = len(pages)
        self.peak_pages_used = max(
            self.peak_pages_used, self.allocated_pages()
        )
        self.maybe_check()

    def is_shared(self, slot: int, page_idx: int) -> bool:
        p = int(self.table[slot, page_idx])
        return p != self.sentinel and int(self.refcount[p]) > 1

    def ensure_writable(
        self, slot: int, lo_tok: int, hi_tok: int
    ) -> List[Tuple[int, int]]:
        """Copy-on-write: privatise every still-shared page of `slot`
        covering flat token positions [lo_tok, hi_tok).  Returns the
        (src_page, dst_page) pairs the caller must copy ON DEVICE before
        the next scatter into that window (the allocator only remaps the
        table — it never touches KV data).  No-op ([]) when the window's
        pages are already private."""
        if hi_tok <= lo_tok:
            return []
        j_lo = int(lo_tok) // self.page_size
        j_hi = (int(hi_tok) - 1) // self.page_size
        pairs: List[Tuple[int, int]] = []
        for j in range(j_lo, min(j_hi + 1, int(self.used[slot]))):
            src = int(self.table[slot, j])
            if src == self.sentinel or int(self.refcount[src]) <= 1:
                continue
            if not self.free:
                raise PagePoolExhausted(
                    f"KV page pool exhausted: slot {slot} needs 1 page to "
                    f"privatise shared page {src} (copy-on-write) but 0 of "
                    f"{self.n_pages} are free (page_size={self.page_size}); "
                    f"raise kv_pool_pages or admit fewer concurrent requests"
                )
            dst = self._alloc_page()
            self.refcount[src] -= 1  # never hits 0: it was > 1
            self.table[slot, j] = dst
            self.cow_copies += 1
            self._m_cow_copies.inc()
            pairs.append((src, dst))
        self.peak_pages_used = max(
            self.peak_pages_used, self.allocated_pages()
        )
        self.maybe_check()
        return pairs

    def private_page_count(self, slot: int) -> int:
        """Pages mapped by `slot` alone (its marginal pool footprint)."""
        n = 0
        for j in range(int(self.used[slot])):
            if int(self.refcount[int(self.table[slot, j])]) == 1:
                n += 1
        return n

    # -------------------------------------------------------- prefix cache

    def prefix_lookup(self, key) -> Optional[List[int]]:
        """Pages cached for prompt-hash `key` (LRU-refreshed), or None."""
        pages = self._prefix_cache.get(key)
        if pages is None:
            self.prefix_misses += 1
            self._m_prefix_misses.inc()
            return None
        self._prefix_cache.move_to_end(key)
        self.prefix_hits += 1
        self._m_prefix_hits.inc()
        return list(pages)

    def prefix_insert(self, key, pages: Sequence[int]) -> None:
        """Hold `pages` (a slot's full prompt pages) in the prefix cache
        under `key`, taking one ref per page so they survive the
        inserting slot's retirement."""
        if key in self._prefix_cache or len(pages) == 0:
            return
        for p in pages:
            p = int(p)
            if self.refcount[p] <= 0:
                raise ValueError(f"prefix_insert of unmapped page {p}")
            self.refcount[p] += 1
        self._prefix_cache[key] = [int(p) for p in pages]
        self.maybe_check()

    def prefix_evict(self, need_free: int = 1) -> int:
        """Drop least-recently-used prefix entries until `need_free`
        pages are free (or the cache is empty).  Returns entries
        evicted.  Entries whose pages are still mapped by live slots
        free nothing immediately but still drop the cache hold."""
        evicted = 0
        while self._prefix_cache and len(self.free) < need_free:
            _, pages = self._prefix_cache.popitem(last=False)
            for p in pages:
                self._unref(int(p))
            evicted += 1
        if evicted:
            self.maybe_check()
        return evicted

    def prefix_clear(self) -> int:
        """Drop every prefix-cache hold (weight updates invalidate all
        cached KV).  Returns entries dropped."""
        n = len(self._prefix_cache)
        while self._prefix_cache:
            _, pages = self._prefix_cache.popitem(last=False)
            for p in pages:
                self._unref(int(p))
        if n:
            self.maybe_check()
        return n

    def prefix_len(self) -> int:
        return len(self._prefix_cache)

    # ----------------------------------------------------------- invariants

    def maybe_check(self) -> None:
        if self.debug_check:
            self.check()

    def check(self) -> None:
        """Validate the full allocator state; raises
        `PagingInvariantError` on any violation.

        Invariants: (1) free list ∪ {pages with refcount > 0} is an
        exact partition of the pool, no duplicates on the free list;
        (2) refcounts are nonnegative and each page's refcount equals
        its table mappings + prefix-cache holds (so a shared page can
        never be silently freed or double-freed — the host-side half of
        "CoW never mutates a shared page in place"; the device half is
        that writes only target windows `ensure_writable` has already
        privatised, which this refcount accounting makes checkable);
        (3) every table row is contiguous-from-zero with `used[slot]`
        mapped entries then sentinels."""
        free_set = set(self.free)
        if len(free_set) != len(self.free):
            raise PagingInvariantError("duplicate pages on the free list")
        refs: Dict[int, int] = {}
        n_slots = self.table.shape[0]
        for s in range(n_slots):
            u = int(self.used[s])
            for j in range(self.max_pages):
                p = int(self.table[s, j])
                if j < u:
                    if p == self.sentinel:
                        raise PagingInvariantError(
                            f"slot {s} entry {j} < used={u} is sentinel"
                        )
                    refs[p] = refs.get(p, 0) + 1
                elif p != self.sentinel:
                    raise PagingInvariantError(
                        f"slot {s} entry {j} >= used={u} maps page {p}"
                    )
        for pages in self._prefix_cache.values():
            for p in pages:
                refs[int(p)] = refs.get(int(p), 0) + 1
        for p in range(self.n_pages):
            rc = int(self.refcount[p])
            if rc < 0:
                raise PagingInvariantError(f"page {p} refcount {rc} < 0")
            if rc != refs.get(p, 0):
                raise PagingInvariantError(
                    f"page {p} refcount {rc} != {refs.get(p, 0)} "
                    f"mappings (table + prefix cache)"
                )
            if (p in free_set) != (rc == 0):
                raise PagingInvariantError(
                    f"page {p} refcount {rc} but "
                    f"{'on' if p in free_set else 'not on'} the free list"
                )
        if len(free_set) + sum(1 for p in refs if refs[p] > 0) != self.n_pages:
            raise PagingInvariantError(
                f"free ({len(free_set)}) + mapped ({len(refs)}) pages do "
                f"not partition the pool of {self.n_pages}"
            )
