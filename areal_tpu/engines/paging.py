"""Host-side free-list page allocator for the paged KV pool.

The device side (`models/transformer.py PagedKVCache`) is a dumb pool of
`n_pages` fixed-size pages; ALL placement policy lives here, on the
host, between jitted decode chunks: which pool pages belong to which
slot, in what order, and which are free.  The allocator's `table` array
is shipped to the device as the page table each chunk (a few KB), so
"growing" a sequence is appending one int to a row — no cache copy, no
recompile — and a retired slot's pages go back on the free list for the
next admission.

Reference role: vLLM's BlockAllocator / the block tables behind TPU
ragged paged attention.
"""

from typing import List

import numpy as np


class PagePoolExhausted(RuntimeError):
    """The KV page pool has no free page for a required allocation.

    Raised BEFORE any device state is touched: the cache, page table and
    free list are unchanged, so the condition is a clean capacity error
    (raise `kv_pool_pages`, shrink the batch, or let the server admit
    fewer requests), never corruption."""


class PageAllocator:
    """Free-list allocator over `n_pages` pages of `page_size` tokens.

    Each of `n_slots` decode slots owns an ordered, contiguous-from-zero
    list of pages: `table[slot, j]` is the pool page holding the slot's
    flat positions [j*page_size, (j+1)*page_size).  Unmapped entries
    hold the sentinel `n_pages` (device scatters drop it, gathers clamp
    + mask)."""

    def __init__(
        self, n_pages: int, page_size: int, n_slots: int, max_pages: int
    ):
        if n_pages < 1 or page_size < 1:
            raise ValueError("n_pages and page_size must be positive")
        self.n_pages = int(n_pages)
        self.page_size = int(page_size)
        self.max_pages = int(max_pages)
        self.sentinel = int(n_pages)
        self.free: List[int] = list(range(n_pages - 1, -1, -1))
        self.table = np.full((n_slots, max_pages), self.sentinel, np.int32)
        self.used = np.zeros((n_slots,), np.int32)
        # Stats for the bench/tests: recycled counts pages handed out
        # again after having been freed by a retired slot.
        self._freed_ever: set = set()
        self.pages_recycled = 0
        self.peak_pages_used = 0

    def pages_for(self, tokens: int) -> int:
        return -(-int(tokens) // self.page_size)

    def allocated_pages(self) -> int:
        return self.n_pages - len(self.free)

    def can_reserve(self, slot: int, tokens: int) -> bool:
        need = self.pages_for(tokens)
        if need > self.max_pages:
            return False
        return need - int(self.used[slot]) <= len(self.free)

    def reserve(self, slot: int, tokens: int) -> None:
        """Ensure `slot` has mapped pages covering flat positions
        [0, tokens).  Appends pages from the free list; raises
        `PagePoolExhausted` (leaving all state unchanged for the pages
        already mapped) when the pool or the table width cannot."""
        need = self.pages_for(tokens)
        if need > self.max_pages:
            raise PagePoolExhausted(
                f"slot {slot} needs {need} pages for {tokens} tokens but "
                f"the page table holds max_pages={self.max_pages} "
                f"(page_size={self.page_size})"
            )
        grow = need - int(self.used[slot])
        if grow > len(self.free):
            raise PagePoolExhausted(
                f"KV page pool exhausted: slot {slot} needs {grow} more "
                f"page(s) for {tokens} tokens but only {len(self.free)} of "
                f"{self.n_pages} are free (page_size={self.page_size}); "
                f"raise kv_pool_pages or admit fewer concurrent requests"
            )
        while self.used[slot] < need:
            p = self.free.pop()
            if p in self._freed_ever:
                self.pages_recycled += 1
            self.table[slot, self.used[slot]] = p
            self.used[slot] += 1
        self.peak_pages_used = max(
            self.peak_pages_used, self.allocated_pages()
        )

    def release(self, slot: int) -> None:
        """Return all of `slot`'s pages to the free list."""
        for j in range(int(self.used[slot])):
            p = int(self.table[slot, j])
            self.free.append(p)
            self._freed_ever.add(p)
        self.table[slot, :] = self.sentinel
        self.used[slot] = 0

    def page_rows(self, slot: int, tokens: int) -> np.ndarray:
        """The slot's first `pages_for(tokens)` mapped pages (for the
        admission prefill scatter); caller must have reserve()d them."""
        return self.table[slot, : self.pages_for(tokens)].copy()
