"""Experiment-config validation, run before any device work.

Capability parity: realhf/experiments/common/check.py (+ the scattered
asserts of api/cli_args.py) — fail a misconfigured trial at BUILD time
with a sentence naming the knob, instead of deep in a worker after
minutes of model loading.  Called by build_sft / build_ppo_math.
"""

import os
from typing import Optional

from areal_tpu.api.model_api import GenerationHyperparameters, OptimizerConfig
from areal_tpu.base.topology import ParallelConfig


def _fail(msg: str):
    raise ValueError(f"invalid experiment config: {msg}")


def check_optimizer(opt: OptimizerConfig) -> None:
    if opt.lr <= 0:
        _fail(f"optimizer.lr must be > 0, got {opt.lr}")
    if not 0.0 <= opt.warmup_steps_proportion <= 1.0:
        _fail(
            "optimizer.warmup_steps_proportion must be in [0, 1], got "
            f"{opt.warmup_steps_proportion}"
        )
    min_lr_ratio = getattr(opt, "min_lr_ratio", 0.0)
    if not 0.0 <= min_lr_ratio <= 1.0:
        _fail(f"optimizer.min_lr_ratio must be in [0, 1], got {min_lr_ratio}")


def check_model_path(role: str, spec) -> None:
    if spec is not None and spec.type_ == "hf":
        path = spec.args.get("path", "")
        if not os.path.exists(path):
            _fail(
                f"model path {path!r} for {role!r} does not exist locally "
                "(download the checkpoint first)"
            )


def check_gconfig(g: GenerationHyperparameters) -> None:
    if g.n < 1:
        _fail(f"gconfig.n must be >= 1, got {g.n}")
    if g.max_new_tokens < 1:
        _fail(f"gconfig.max_new_tokens must be >= 1, got {g.max_new_tokens}")
    if g.min_new_tokens > g.max_new_tokens:
        _fail(
            f"gconfig.min_new_tokens ({g.min_new_tokens}) > max_new_tokens "
            f"({g.max_new_tokens})"
        )
    if not g.greedy and g.temperature <= 0:
        _fail(f"gconfig.temperature must be > 0 when sampling, got "
              f"{g.temperature}")
    if not 0.0 < g.top_p <= 1.0:
        _fail(f"gconfig.top_p must be in (0, 1], got {g.top_p}")


def check_batch_vs_parallel(
    role: str,
    n_seqs: int,
    parallel: ParallelConfig,
    n_mbs: int = 1,
) -> None:
    """Every DP shard of every pipeline stage needs at least one sequence
    per microbatch (reference: check_valid_parallel_batch_size)."""
    need = parallel.dp_size * parallel.pipe * max(n_mbs, 1)
    if n_seqs < need:
        _fail(
            f"{role}: batch of {n_seqs} sequences cannot fill "
            f"dp={parallel.dp_size} x pipe={parallel.pipe} x "
            f"n_mbs={n_mbs} (needs >= {need})"
        )


def check_liveness(cfg) -> None:
    """Crash-safe trainer plane knobs: a deadline shorter than the
    heartbeat grace window (3x the beat period) would declare live
    workers dead on their first slow MFC."""
    timeout = getattr(cfg, "mfc_timeout_s", None)
    beat = getattr(cfg, "worker_heartbeat_s", 5.0)
    if beat <= 0:
        _fail(f"worker_heartbeat_s must be > 0, got {beat}")
    if timeout is not None:
        if timeout <= 0:
            _fail(
                f"mfc_timeout_s must be > 0 (omit it for no deadline), "
                f"got {timeout}"
            )
        if timeout <= beat:
            _fail(
                f"mfc_timeout_s ({timeout}) must exceed "
                f"worker_heartbeat_s ({beat}) — at least one beat must "
                "fit inside the deadline to tell slow from dead"
            )
    if getattr(cfg, "max_recoveries", 3) < 0:
        _fail(
            f"max_recoveries must be >= 0, got "
            f"{getattr(cfg, 'max_recoveries', 3)}"
        )


def check_anomaly(cfg) -> None:
    """Numerical-integrity guard-plane knobs (engines/train.py sentinels,
    interfaces/ppo.py batch sentinels, master quarantine escalation)."""
    mult = getattr(cfg, "anomaly_grad_norm_mult", 0.0)
    if mult < 0:
        _fail(
            f"anomaly_grad_norm_mult must be >= 0 (0 disables the "
            f"grad-spike sentinel), got {mult}"
        )
    if 0.0 < mult <= 1.0:
        # A spike threshold at-or-below the running mean would quarantine
        # routine steps — the knob is a MULTIPLIER over the EWMA.
        _fail(
            f"anomaly_grad_norm_mult must be > 1 when enabled (it "
            f"multiplies the running grad-norm EWMA), got {mult}"
        )
    unorm = getattr(cfg, "anomaly_update_norm_max", 0.0)
    if unorm < 0:
        _fail(
            f"anomaly_update_norm_max must be >= 0 (0 disables the "
            f"update-norm ceiling), got {unorm}"
        )
    kl_max = getattr(cfg, "anomaly_kl_max", None)
    if kl_max is not None and kl_max <= 0:
        _fail(
            f"anomaly_kl_max must be > 0 (omit it to disable the KL "
            f"sentinel), got {kl_max}"
        )
    mcq = getattr(cfg, "max_consecutive_quarantines", 3)
    if mcq < 0:
        _fail(
            f"max_consecutive_quarantines must be >= 0 (0 disables "
            f"rollback escalation), got {mcq}"
        )


def check_ppo_math(cfg) -> None:
    """Cross-field checks for PPOMathConfig (cheap, no jax import)."""
    check_optimizer(cfg.optimizer)
    check_gconfig(cfg.gconfig)
    check_liveness(cfg)
    check_anomaly(cfg)
    for role, spec in (
        ("actor", cfg.actor), ("ref", cfg.ref), ("critic", cfg.critic),
    ):
        check_model_path(role, spec)

    kw = cfg.ppo_kwargs
    if kw.get("kl_adaptive") and not kw.get("kl_ctl"):
        _fail(
            "kl_adaptive with kl_ctl=0: the multiplicative controller can "
            "never leave 0 — set a nonzero initial kl_ctl"
        )
    if (kw.get("kl_ctl") or kw.get("kl_adaptive")) and cfg.ref is None:
        _fail("KL control (kl_ctl/kl_adaptive) needs a ref model")
    if kw.get("use_dense_reward") and cfg.critic is None:
        _fail("use_dense_reward needs the critic (value) mode")
    for knob in ("early_stop_imp_ratio", "early_stop_kl"):
        v = kw.get(knob)
        if v is not None and v <= 0:
            # 0.0 would mean "trip on every minibatch" — but in this
            # ppo_kwargs dict 0.0 conventionally means "disabled"
            # (kl_ctl): reject the ambiguity instead of silently
            # collapsing every step to one minibatch.
            _fail(
                f"{knob} must be > 0 (omit it to disable early stopping)"
            )
    gen_size: Optional[int] = kw.get("generation_size")
    if gen_size is not None and gen_size < cfg.gconfig.n:
        _fail(
            f"generation_size ({gen_size}) must be >= group size "
            f"gconfig.n ({cfg.gconfig.n})"
        )
    if cfg.fuse_rew_ref and cfg.ref is None:
        _fail("fuse_rew_ref needs a ref model")
    if cfg.rollout_ahead not in (0, 1):
        _fail(f"rollout_ahead must be 0 or 1, got {cfg.rollout_ahead}")
    mho = getattr(cfg, "max_head_offpolicyness", None)
    if mho is not None:
        if mho < 0:
            _fail(
                f"max_head_offpolicyness must be >= 0, got {mho}"
            )
        if cfg.rollout_ahead > 0:
            # Both knobs claim ownership of the prefetch pipeline; the
            # async-RL replay path subsumes rollout_ahead=1 (it is
            # max_head_offpolicyness=0 plus admission control).
            _fail(
                "max_head_offpolicyness and rollout_ahead are mutually "
                "exclusive (async RL replaces the one-step-ahead path)"
            )
    if getattr(cfg, "replay_capacity", 4) < 1:
        _fail(
            f"replay_capacity must be >= 1, got "
            f"{getattr(cfg, 'replay_capacity', 4)}"
        )
    if getattr(cfg, "pipeline_overlap", False):
        if cfg.rollout_ahead > 0 or mho is not None:
            _fail(
                "pipeline_overlap is mutually exclusive with "
                "rollout_ahead / max_head_offpolicyness: those overlap "
                "generation ACROSS steps, pipeline overlap streams "
                "chunks WITHIN one on-policy step"
            )
        if getattr(cfg, "overlap_window", 2) < 1:
            _fail(
                f"overlap_window must be >= 1, got "
                f"{getattr(cfg, 'overlap_window', 2)}"
            )
        if getattr(cfg, "pipeline_chunk_seqs", 1) < 1:
            _fail(
                f"pipeline_chunk_seqs must be >= 1, got "
                f"{getattr(cfg, 'pipeline_chunk_seqs', 1)}"
            )
    if cfg.gen_server_url and getattr(cfg, "gen_backend_args", None):
        # Decoupled serving builds a weightless remote_generator backend;
        # local GeneratorEngine kwargs would be silently ignored — the
        # user's explicit flag (e.g. kv_cache_dtype) must not no-op.
        _fail(
            "gen_backend_args apply to the in-process GeneratorEngine "
            "and are ignored under gen_server_url (configure the "
            "standalone gen_server instead)"
        )
    mw = getattr(cfg, "mixture_weights", {}) or {}
    for task, w in mw.items():
        if not isinstance(w, (int, float)) or w <= 0:
            _fail(
                f"mixture_weights[{task!r}] must be a positive number "
                f"(got {w!r}); zero-weight tasks should be omitted"
            )
    if getattr(cfg, "mixture_adaptive", False) and not mw:
        _fail(
            "mixture_adaptive needs mixture_weights (the adaptive "
            "scheduler rebalances an explicit task mixture)"
        )
    if getattr(cfg, "verifier_pool", False) and not (
        cfg.experiment_name and cfg.trial_name
    ):
        _fail(
            "verifier_pool needs experiment_name and trial_name to "
            "discover the announced verifier fleet"
        )
    if getattr(cfg, "kv_page_size", 128) < 1:
        _fail(f"kv_page_size must be >= 1, got {cfg.kv_page_size}")
    if getattr(cfg, "kv_pool_pages", 0) < 0:
        _fail(
            f"kv_pool_pages must be >= 0 (0 = auto-size), got "
            f"{cfg.kv_pool_pages}"
        )
    pct = getattr(cfg, "prefill_chunk_tokens", None)
    if pct is not None and pct < 0:
        _fail(
            f"prefill_chunk_tokens must be >= 0 (0 = legacy two-program "
            f"admit, None = env default), got {pct}"
        )
    if cfg.gen_server_url and (
        getattr(cfg, "kv_paged", None) is not None
        or getattr(cfg, "kv_page_size", 128) != 128
        or getattr(cfg, "kv_pool_pages", 0)
        or getattr(cfg, "prefill_chunk_tokens", None) is not None
        or getattr(cfg, "kv_share_prefix", None) is not None
    ):
        # Same reasoning as gen_backend_args below: these configure the
        # in-process GeneratorEngine, which decoupled serving never
        # builds — a silently ignored capacity knob is a footgun.
        _fail(
            "kv_paged/kv_page_size/kv_pool_pages/prefill_chunk_tokens/"
            "kv_share_prefix apply to the in-process GeneratorEngine "
            "and are ignored under gen_server_url (configure the "
            "standalone gen_server instead)"
        )
    if getattr(cfg, "param_push_fanout", 2) < 1:
        _fail(
            f"param_push_fanout must be >= 1, got "
            f"{getattr(cfg, 'param_push_fanout', 2)}"
        )
    if getattr(cfg, "param_push_tree", False) and not cfg.gen_server_url:
        # The broadcast fabric fans out over the remote gen-server
        # fleet; the in-process path hot-swaps weights directly and has
        # nothing to relay through — a tree flag there would no-op.
        _fail(
            "param_push_tree requires gen_server_url (the broadcast "
            "fabric distributes over the remote serving fleet; the "
            "in-process engine swaps weights directly)"
        )
    if (
        cfg.rollout_ahead > 0
        or mho is not None
        or getattr(cfg, "pipeline_overlap", False)
    ) and getattr(cfg, "gen_backend_args", {}).get(
        "donation_safe_swap"
    ) is False:
        # The copy-free hot-swap aliases the train master's buffers; with
        # one-step-ahead rollout, async-RL prefetch, OR within-step
        # pipeline overlap the generator DECODES while the optimizer
        # donates (or is about to donate) those buffers — a
        # use-after-free, not a memory tradeoff.
        _fail(
            "donation_safe_swap=False requires fully synchronous rollout "
            "(rollout_ahead=0, no max_head_offpolicyness, no "
            "pipeline_overlap): overlapped generation would decode from "
            "buffers the optimizer step donates"
        )
    if cfg.dataset_filter:
        lo = cfg.dataset_filter.get("min_accuracy", 0.0)
        hi = cfg.dataset_filter.get("max_accuracy", 1.0)
        if not 0.0 <= lo < hi <= 1.0:
            _fail(
                f"dataset_filter accuracy band [{lo}, {hi}] must satisfy "
                "0 <= min < max <= 1"
            )
    for role, widx in cfg.placement.items():
        idxs = widx if isinstance(widx, list) else [widx]
        if not idxs or any(
            (not isinstance(i, int)) or i < 0 for i in idxs
        ):
            _fail(f"placement[{role!r}] must be a worker index or a "
                  f"non-empty list of them, got {widx!r}")
    n_seqs = cfg.batch_size * cfg.gconfig.n
    check_batch_vs_parallel(
        "actor train", n_seqs, cfg.actor_parallel, cfg.mb_spec.n_mbs
    )
    # Generation folds any pipe axis into model (generator.py
    # fold_pipe_into_model), so only the data axes constrain its batch.
    import dataclasses as _dc

    gen_pc = cfg.gen_parallel or cfg.actor_parallel
    check_batch_vs_parallel(
        "generation", cfg.batch_size, _dc.replace(gen_pc, pipe=1)
    )


def check_sft(cfg) -> None:
    check_optimizer(cfg.optimizer)
    check_liveness(cfg)
    check_anomaly(cfg)
    check_model_path("model", cfg.model)
    check_batch_vs_parallel(
        "train", cfg.batch_size, cfg.parallel, cfg.mb_spec.n_mbs
    )
