"""Experiment builders: user config -> (DFG, workers, placement).

Capability parity: realhf/experiments/common/ — `CommonExperimentConfig`
(allocation parsing, worker-config mapping), `sft_exp.py`, `ppo_math_exp.py`
(the north-star PPO dataflow with generation, reward, ref, critic and the
param-sync hooks wired automatically, reference utils.py resolve_rpc_hooks).
"""

import dataclasses
from typing import Any, Dict, List, Optional

from areal_tpu.api.config import (
    ModelAbstraction,
    ModelBackendAbstraction,
    ModelInterfaceAbstraction,
    ModelInterfaceType,
    ModelName,
)
from areal_tpu.api.data_api import DatasetAbstraction, MicroBatchSpec
from areal_tpu.api.dfg import (
    DFG,
    MFCDef,
    OffloadHook,
    ParamReallocHook,
    build_graph,
)
from areal_tpu.api.model_api import FinetuneSpec, GenerationHyperparameters, OptimizerConfig
from areal_tpu.base.topology import ParallelConfig
from areal_tpu.system.master import ExperimentSaveEvalControl
from areal_tpu.system.worker import ModelShardSpec, WorkerConfig

# Ensure built-in interfaces are registered.
import areal_tpu.interfaces.sft  # noqa: F401
import areal_tpu.interfaces.ppo  # noqa: F401
import areal_tpu.interfaces.reward  # noqa: F401


@dataclasses.dataclass
class ExperimentPlan:
    """Everything the runtime needs to execute a trial."""

    dfg: DFG
    worker_configs: List[WorkerConfig]
    model_placement: Dict[str, int]
    data_worker_ids: List[int]
    ctrl: ExperimentSaveEvalControl
    experiment_name: str = "exp"
    trial_name: str = "trial"
    fileroot: str = "/tmp/areal_tpu/trial"
    # model key -> all worker ids forming its (multi-host) mesh; models
    # absent run on their single placement worker.  group[0] == placement.
    model_groups: Optional[Dict[str, List[int]]] = None
    # model key -> worker ids each holding an independent replica (DP
    # dispatch: generate/inference batches are token-balance-split).
    model_replicas: Optional[Dict[str, List[int]]] = None
    # {"min_accuracy": .., "max_accuracy": ..} -> dynamic difficulty
    # filtering of prompts by per-step group accuracy.
    difficulty_filter: Optional[Dict[str, float]] = None
    # Asynchronous rollout: generate step t+1's rollouts while step t
    # trains (one-step-stale behavior policy; see master._execute_step_async).
    rollout_ahead: int = 0
    # Asynchronous RL (staleness-bounded pipeline, replay-buffer-driven;
    # see master._execute_step_async_rl).  None = off.
    max_head_offpolicyness: Optional[int] = None
    replay_capacity: int = 4
    buffer_max_age_steps: Optional[int] = None
    # Pipeline-overlapped PPO: stream the step's batch through the graph
    # in rollout chunks (see master._execute_step_streamed).  window=1 is
    # the bit-exact overlap-off degenerate form.
    pipeline_overlap: bool = False
    overlap_window: int = 2
    pipeline_chunk_seqs: int = 1
    # Crash-safe trainer plane: per-MFC deadline (None = no deadline) and
    # worker heartbeat period (ZMQ runtime; beats keep long MFCs alive so
    # the deadline distinguishes slow from dead).  max_recoveries bounds
    # how many worker deaths the master absorbs by rolling back to the
    # recover checkpoint before exiting non-zero.
    mfc_timeout_s: Optional[float] = None
    worker_heartbeat_s: float = 5.0
    max_recoveries: int = 3
    # Numerical-integrity guard plane (see system/master.py): quarantine
    # streak length that escalates to a checkpoint rollback (0 = count
    # only), and content checksums on cross-set weight pushes.
    max_consecutive_quarantines: int = 3
    weight_push_checksum: bool = True


@dataclasses.dataclass
class SFTConfig:
    model: ModelAbstraction
    dataset: DatasetAbstraction
    # >1 = lay the model's mesh across this many worker PROCESSES (hosts):
    # each joins the jax.distributed world and `parallel` describes the
    # GLOBAL mesh over all their devices.  Requires the ZMQ runtime.
    n_hosts: int = 1
    parallel: ParallelConfig = dataclasses.field(default_factory=ParallelConfig)
    optimizer: OptimizerConfig = dataclasses.field(default_factory=OptimizerConfig)
    batch_size: int = 8
    total_train_epochs: int = 1
    mb_spec: MicroBatchSpec = dataclasses.field(default_factory=MicroBatchSpec)
    ctrl: ExperimentSaveEvalControl = dataclasses.field(
        default_factory=ExperimentSaveEvalControl
    )
    seed: int = 1
    experiment_name: str = "sft"
    trial_name: str = "trial"
    fileroot: str = "/tmp/areal_tpu/trial"
    # Crash-safe trainer plane knobs (see ExperimentPlan).
    mfc_timeout_s: Optional[float] = None
    worker_heartbeat_s: float = 5.0
    max_recoveries: int = 3
    # Numerical-integrity guard plane: grad-norm-spike multiplier vs the
    # engine's running EWMA (0 = sentinel off; must be > 1 when set),
    # absolute update-norm ceiling (0 = off), quarantine-streak rollback
    # threshold, and checksummed weight pushes (see ExperimentPlan).
    anomaly_grad_norm_mult: float = 0.0
    anomaly_update_norm_max: float = 0.0
    max_consecutive_quarantines: int = 3
    weight_push_checksum: bool = True


def build_sft(cfg: SFTConfig, tokenizer=None) -> ExperimentPlan:
    from areal_tpu.experiments.check import check_sft

    check_sft(cfg)
    model_name = ModelName("default", 0)
    node = MFCDef(
        name="trainDefault",
        model_name=model_name,
        interface_type=ModelInterfaceType.TRAIN_STEP,
        interface_impl=ModelInterfaceAbstraction("sft"),
        input_keys=("packed_input_ids", "prompt_mask"),
        # Tokens feed the device only; prompt_mask stays broadcast (its
        # host-side counts set the global loss weight).
        shard_keys=("packed_input_ids",),
        n_seqs=cfg.batch_size,
        mb_spec=cfg.mb_spec,
    )
    dfg = build_graph([node])
    shard = ModelShardSpec(
        name=model_name,
        model=cfg.model,
        backend=ModelBackendAbstraction(
            "train", _anomaly_backend_args(cfg)
        ),
        interface=ModelInterfaceAbstraction("sft"),
        parallel=cfg.parallel,
        optimizer=cfg.optimizer,
    )
    ftspec = FinetuneSpec(
        total_train_epochs=cfg.total_train_epochs,
        train_batch_size=cfg.batch_size,
    )
    worker_configs = [
        WorkerConfig(
            worker_index=w,
            shards=[shard],
            datasets=[cfg.dataset] if w == 0 else [],
            batch_size=cfg.batch_size,
            seed=cfg.seed,
            ftspec=ftspec,
            dist_process_id=w,
            dist_num_processes=cfg.n_hosts,
        )
        for w in range(cfg.n_hosts)
    ]
    cfg.ctrl.total_train_epochs = cfg.total_train_epochs
    return ExperimentPlan(
        dfg=dfg,
        worker_configs=worker_configs,
        model_placement={str(model_name): 0},
        data_worker_ids=[0],
        ctrl=cfg.ctrl,
        experiment_name=cfg.experiment_name,
        trial_name=cfg.trial_name,
        fileroot=cfg.fileroot,
        model_groups=(
            {str(model_name): list(range(cfg.n_hosts))}
            if cfg.n_hosts > 1
            else None
        ),
        mfc_timeout_s=cfg.mfc_timeout_s,
        worker_heartbeat_s=cfg.worker_heartbeat_s,
        max_recoveries=cfg.max_recoveries,
        max_consecutive_quarantines=cfg.max_consecutive_quarantines,
        weight_push_checksum=cfg.weight_push_checksum,
    )


def _anomaly_backend_args(cfg, base: Optional[Dict[str, Any]] = None):
    """Fold the config's engine-level anomaly knobs into a train-backend
    args dict (explicit train_backend_args entries win)."""
    args: Dict[str, Any] = dict(base or {})
    if cfg.anomaly_grad_norm_mult:
        args.setdefault(
            "anomaly_grad_norm_mult", cfg.anomaly_grad_norm_mult
        )
    if cfg.anomaly_update_norm_max:
        args.setdefault(
            "anomaly_update_norm_max", cfg.anomaly_update_norm_max
        )
    return args


@dataclasses.dataclass
class PPOMathConfig:
    actor: ModelAbstraction
    dataset: DatasetAbstraction
    # None -> GRPO (disable_value), matching the reference's disable_value.
    critic: Optional[ModelAbstraction] = None
    ref: Optional[ModelAbstraction] = None
    reward_interface_args: Dict[str, Any] = dataclasses.field(default_factory=dict)
    # Override the reward interface entirely (default: "rw-math-code" with
    # reward_interface_args).  A custom interface emitting per-token
    # "dense_rewards" pairs with ppo_kwargs={"use_dense_reward": True}.
    reward_interface: Optional[ModelInterfaceAbstraction] = None
    actor_parallel: ParallelConfig = dataclasses.field(default_factory=ParallelConfig)
    gen_parallel: Optional[ParallelConfig] = None  # None = same as actor
    # Device placement within the worker's local devices (None = worker
    # offset).  Set by `--allocation search` for disjoint gen/train meshes.
    actor_device_offset: Optional[int] = None
    gen_device_offset: Optional[int] = None
    critic_parallel: ParallelConfig = dataclasses.field(default_factory=ParallelConfig)
    # None = the actor's layout.  An independent ref layout makes every
    # MFC re-parallelizable on its own (the reference's "global reshard"
    # shape, tests/experiments/test_math_ppo.py:124-199).
    ref_parallel: Optional[ParallelConfig] = None
    # Extra kwargs for the critic interface (e.g. value_norm=True,
    # value_norm_type="exp" — reference ppo_interface.py:175-210).
    critic_interface_args: Dict[str, Any] = dataclasses.field(
        default_factory=dict
    )
    optimizer: OptimizerConfig = dataclasses.field(
        default_factory=lambda: OptimizerConfig(lr=2e-5)
    )
    gconfig: GenerationHyperparameters = dataclasses.field(
        default_factory=GenerationHyperparameters
    )
    ppo_kwargs: Dict[str, Any] = dataclasses.field(default_factory=dict)
    # Remove prompts whose group accuracy falls outside this band after
    # each step (dynamic difficulty filtering; reference
    # model_worker.py:574-639).  e.g. {"min_accuracy": 0.05,
    # "max_accuracy": 0.95}.
    dataset_filter: Optional[Dict[str, float]] = None
    # Asynchronous rollout: overlap next-step generation with training
    # (one-step-stale behavior policy, PPO-ratio-corrected).
    rollout_ahead: int = 0
    # Asynchronous RL (AReaL-style, arxiv 2505.24298): keep
    # max_head_offpolicyness + 1 rollout batches in flight, admit them to
    # training through a staleness-bounded replay buffer, and correct the
    # off-policy gap with decoupled PPO (behav_imp_weight_cap is wired
    # into the actor interface automatically when the cap is > 0).
    # 0 = bounded pipeline that degrades to synchronous ordering.
    # None = async RL off.  Mutually exclusive with rollout_ahead.
    max_head_offpolicyness: Optional[int] = None
    # Replay capacity in batches for the async-RL pipeline.
    replay_capacity: int = 4
    # Pipeline-overlapped PPO (ROADMAP item 3; OPPO, arxiv 2509.25762):
    # stream the step's batch through gen -> ref/reward inference ->
    # train grad accumulation in chunks of `pipeline_chunk_seqs` prompts
    # with `overlap_window` chunks in flight, so post-generation stages
    # run while later chunks still decode and the optimizer step fires
    # once after the last chunk.  overlap_window=1 = overlap off: the
    # whole batch flows through the unchanged barrier node path
    # (bit-exact with pipeline_overlap=False).  Mutually exclusive with
    # rollout_ahead / max_head_offpolicyness; requires
    # donation_safe_swap on colocated generators (enforced in check.py).
    pipeline_overlap: bool = False
    overlap_window: int = 2
    pipeline_chunk_seqs: int = 1
    # Importance-weight cap for decoupled PPO; tokens whose behavior
    # weight exceeds it are masked out.  Only applied when
    # max_head_offpolicyness > 0 (at 0 the plain PPO loss keeps exact
    # synchronous numerics).  ppo_kwargs["behav_imp_weight_cap"] wins.
    behav_imp_weight_cap: float = 5.0
    # Interruptible weight sync for gen_server_url trials: pause the
    # servers at a chunk boundary around each weight push instead of
    # draining in-flight requests (GenerationServer pause/resume;
    # interrupted requests resume on their existing KV pages).  The
    # in-process path always hot-swaps in memory.
    inmem_weight_sync: bool = False
    # Broadcast-tree weight distribution (system/paramstore.py): when
    # True, set_params on the remote generator publishes ONE serialized
    # payload into a versioned ParamStore and pushes it down a fan-out
    # tree over the live fleet (each server relays to `param_push_fanout`
    # children before applying) instead of N serial point-to-point
    # pushes — O(log N) push wall-time at fleet scale.  Requires
    # gen_server_url (remote serving); the in-process path has no fleet
    # to fan out over.
    param_push_tree: bool = False
    param_push_fanout: int = 2
    # Extra GeneratorEngine kwargs (e.g. max_decode_batch, or forcing
    # donation_safe_swap — config check rejects the alias mode under
    # rollout_ahead>0).  Defaults supplied by build_ppo_math win unless
    # overridden here.
    gen_backend_args: Dict[str, Any] = dataclasses.field(
        default_factory=dict
    )
    # Paged-KV decode knobs (engines/generator.py): None = env default
    # (AREAL_PAGED_KV, on unless "0"); False = dense grow-by-doubling
    # window.  kv_pool_pages=0 auto-sizes the pool for the worst case;
    # a positive value caps KV HBM and makes admission wait for freed
    # pages (gen_server splits request groups against the resulting
    # token budget).  gen_backend_args may still override all three.
    kv_paged: Optional[bool] = None
    kv_page_size: int = 128
    kv_pool_pages: int = 0
    # Serving-plane knobs: prefill_chunk_tokens>0 folds admission
    # prefill INTO the decode chunk (one compiled program, no admission
    # stall); 0 = legacy two-program admit; None = env default
    # (AREAL_PREFILL_CHUNK_TOKENS).  kv_share_prefix maps a group's common
    # prompt pages copy-on-write across rows (None = on when serving).
    prefill_chunk_tokens: Optional[int] = None
    kv_share_prefix: Optional[bool] = None
    # Extra TrainEngine kwargs for actor/critic (remat_policy,
    # master_dtype, pipe_schedule) — the single-chip 1.5B fit needs
    # master_dtype="bfloat16" here, exactly like bench.py.
    train_backend_args: Dict[str, Any] = dataclasses.field(
        default_factory=dict
    )
    # Host-offload the reference model's params after each ref_inf call
    # (OffloadHook; frees its HBM between steps).
    offload_ref: bool = False
    # Run reward verification and ref-model inference as ONE fused MFC on
    # the ref worker (reference: FusedThreadingForwardInterface,
    # ppo_math_exp.py:132-136) — CPU reward grading overlaps the device
    # forward.  Requires a ref model.
    fuse_rew_ref: bool = False
    # EMA reference policy: after each actor train step, ref <-
    # eta*actor + (1-eta)*ref (reference: ppo_math_exp.py:345-364
    # ref_ema_eta option via ParamReallocHook).  None = frozen ref.
    ref_ema_eta: Optional[float] = None
    # Decoupled serving: URL of a standalone GenerationServer
    # (areal_tpu/system/gen_server.py).  actor_gen then uses the
    # remote_generator backend — this worker holds NO generation weights,
    # and the weight-sync hook ships checkpoints to the server (reference:
    # sglang decoupled allocations, backend/sglang.py).
    gen_server_url: Optional[str] = None
    # Model role -> worker index (e.g. {"actor_gen": 1} puts generation on a
    # second worker; the data/param planes move bytes between them) or a
    # LIST of worker indices (independent replicas: generate/inference
    # batches are token-balance-split across them — the reference's DP
    # dispatch).  Roles not listed run on worker 0.  Reference: device-mesh
    # allocations like `sglang.d64p1m1+d32p2m1` (api/cli_args.py).
    placement: Dict[str, Any] = dataclasses.field(default_factory=dict)
    # Per-worker first local device (in-process multi-worker trials carve
    # one host's device list into disjoint meshes).
    worker_device_offsets: Dict[int, int] = dataclasses.field(
        default_factory=dict
    )
    batch_size: int = 8  # prompts per step
    total_train_epochs: int = 1
    mb_spec: MicroBatchSpec = dataclasses.field(default_factory=MicroBatchSpec)
    ctrl: ExperimentSaveEvalControl = dataclasses.field(
        default_factory=ExperimentSaveEvalControl
    )
    seed: int = 1
    experiment_name: str = "ppo-math"
    trial_name: str = "trial"
    fileroot: str = "/tmp/areal_tpu/trial"
    # Crash-safe trainer plane knobs (see ExperimentPlan).
    mfc_timeout_s: Optional[float] = None
    worker_heartbeat_s: float = 5.0
    max_recoveries: int = 3
    # Numerical-integrity guard plane: engine-level grad-spike multiplier
    # vs running EWMA and absolute update-norm ceiling (0 = off; folded
    # into train_backend_args, explicit entries win); batch-level KL
    # sentinel for the actor interface (None = off; ppo_kwargs wins);
    # quarantine-streak rollback threshold; checksummed weight pushes.
    anomaly_grad_norm_mult: float = 0.0
    anomaly_update_norm_max: float = 0.0
    anomaly_kl_max: Optional[float] = None
    max_consecutive_quarantines: int = 3
    weight_push_checksum: bool = True
    # Agent-serving runtime (system/episode.py): >0 max turns switches
    # rollout into multi-turn tool-use episodes parked on persistent KV
    # slots; token budget caps the whole transcript (0 = engine default);
    # tool_timeout_s bounds each ToolExecutor call; reward_backend forces
    # a verifier backend for every sample ("" = route by per-row task).
    episode_max_turns: int = 0
    episode_token_budget: int = 0
    tool_timeout_s: float = 10.0
    reward_backend: str = ""
    # Verifier service fleet (system/verifier_pool.py): route grading
    # through the trial's announced verifier workers — load-balanced with
    # per-server breakers and retry-to-a-different-server, degrading to
    # the in-process registry when no worker is live.  Precedence over a
    # fixed remote_url in reward_interface_args.
    verifier_pool: bool = False
    # Task-mixture curriculum (data/mixture.py): task -> weight for the
    # weighted multi-dataset prompt stream ({} = single prompt source).
    # Adaptive mode upweights tasks whose reward EMA sits below their
    # watermark (struggling tasks get more rollout budget).
    mixture_weights: Dict[str, float] = dataclasses.field(
        default_factory=dict
    )
    mixture_adaptive: bool = False


def _remote_gen_shard(cfg: "PPOMathConfig", actor_gen, actor_if):
    """actor_gen as a weightless client of a GenerationServer."""
    model_type = "qwen2"
    if cfg.actor.type_ == "random":
        model_cfg = cfg.actor.args["config"]
        model_type = cfg.actor.args.get("model_type", model_type)
    elif cfg.actor.type_ == "hf":
        from areal_tpu.models.hf import registry as hf

        path = cfg.actor.args["path"]
        model_cfg = hf.load_model_config(path)
        # Weight-sync checkpoints must round-trip through the actor's OWN
        # HF family converter, not a default one.
        model_type = hf.load_hf_config(path)["model_type"]
    else:
        raise ValueError(
            f"gen_server_url with actor abstraction {cfg.actor.type_!r}"
        )
    return ModelShardSpec(
        name=actor_gen,
        model=ModelAbstraction("config", {"config": model_cfg}),
        backend=ModelBackendAbstraction(
            "remote_generator",
            {
                # Comma-separated = one GenerationServer per DP rank
                # (requests round-robin, weight updates broadcast).
                "url": [
                    u.strip()
                    for u in cfg.gen_server_url.split(",")
                    if u.strip()
                ],
                "model_type": model_type,
                "inmem_sync": cfg.inmem_weight_sync,
                "push_mode": (
                    "fabric" if cfg.param_push_tree else "disk"
                ),
                "push_fanout": cfg.param_push_fanout,
            },
        ),
        interface=actor_if,
        parallel=ParallelConfig(),
    )


def build_ppo_math(cfg: PPOMathConfig, tokenizer=None) -> ExperimentPlan:
    """The reference's ppo-math DFG (ppo_math_exp.py:335): generate ->
    {reward, ref, critic-inf} -> actor/critic train, with a weight-sync
    pre-hook on generation (train -> generator hot-swap)."""
    from areal_tpu.experiments.check import check_ppo_math

    check_ppo_math(cfg)
    disable_value = cfg.critic is None
    actor = ModelName("actor", 0)
    actor_gen = ModelName("actor_gen", 0)
    reward = ModelName("reward", 0)
    ref = ModelName("ref", 0) if cfg.ref is not None else None
    critic = ModelName("critic", 0) if not disable_value else None

    ppo_kwargs = dict(cfg.ppo_kwargs)
    ppo_kwargs.setdefault("disable_value", disable_value)
    if cfg.anomaly_kl_max is not None:
        ppo_kwargs.setdefault("anomaly_kl_max", cfg.anomaly_kl_max)
    train_backend_args = _anomaly_backend_args(
        cfg, cfg.train_backend_args
    )
    if (cfg.max_head_offpolicyness or 0) > 0:
        # Off-policy samples are admissible -> decoupled PPO corrects for
        # them.  At cap 0 the plain loss keeps exact synchronous numerics.
        ppo_kwargs.setdefault(
            "behav_imp_weight_cap", cfg.behav_imp_weight_cap
        )
    use_dense = bool(ppo_kwargs.get("use_dense_reward"))
    if use_dense and cfg.reward_interface is None:
        raise ValueError(
            "use_dense_reward needs a custom reward_interface that emits "
            "'dense_rewards' (the default rw-math-code grades scalars only)"
        )
    rew_args = dict(cfg.reward_interface_args)
    if cfg.reward_backend:
        rew_args.setdefault("reward_backend", cfg.reward_backend)
    if cfg.verifier_pool:
        rew_args.setdefault("verifier_pool", True)
        rew_args.setdefault("pool_experiment", cfg.experiment_name)
        rew_args.setdefault("pool_trial", cfg.trial_name)
    rew_if = cfg.reward_interface or ModelInterfaceAbstraction(
        "rw-math-code", rew_args
    )
    rew_outputs = (
        ("rewards", "dense_rewards") if use_dense else ("rewards",)
    )
    actor_if = ModelInterfaceAbstraction(
        "ppo_actor", {"gconfig": cfg.gconfig, **ppo_kwargs}
    )
    critic_if = ModelInterfaceAbstraction(
        "ppo_critic",
        {
            **{
                k: v for k, v in ppo_kwargs.items()
                if k in ("n_minibatches", "kl_ctl")
            },
            **cfg.critic_interface_args,
        },
    )
    nodes = [
        MFCDef(
            name="actor_gen",
            model_name=actor_gen,
            interface_type=ModelInterfaceType.GENERATE,
            interface_impl=actor_if,
            input_keys=("packed_prompts",),
            output_keys=(
                "packed_input_ids", "packed_logprobs", "prompt_mask",
                "seq_no_eos_mask",
            ),
            n_seqs=cfg.batch_size,
            mb_spec=cfg.mb_spec,
            pre_hooks=[],
        ),
    ]
    if cfg.fuse_rew_ref and ref is None:
        raise ValueError(
            "fuse_rew_ref=True requires a ref model (the fused MFC runs on "
            "the ref worker); set PPOMathConfig.ref or disable fusion"
        )
    fuse = cfg.fuse_rew_ref and ref is not None
    fused_if = ModelInterfaceAbstraction(
        "fused",
        {
            "interfaces": {
                "rew": {"type_": rew_if.type_, "args": rew_if.args},
                "ref": {"type_": "ppo_actor", "args": {}},
            }
        },
    )
    if fuse:
        # One MFC on the ref worker grades rewards (CPU process pool) while
        # the ref forward runs on device (reference: "fused-threading" MFC,
        # ppo_math_exp.py:132-136).
        nodes.append(
            MFCDef(
                name="fused_rew_ref",
                model_name=ref,
                interface_type=ModelInterfaceType.INFERENCE,
                interface_impl=fused_if,
                input_keys=("packed_input_ids", "prompt_mask"),
                output_keys=rew_outputs + ("packed_ref_logprobs",),
                output_key_remap={"logprobs": "packed_ref_logprobs"},
                n_seqs=cfg.batch_size,
                mb_spec=cfg.mb_spec,
                post_hooks=[OffloadHook()] if cfg.offload_ref else [],
            )
        )
    else:
        nodes.append(
            MFCDef(
                name="rew_inf",
                model_name=reward,
                interface_type=ModelInterfaceType.INFERENCE,
                interface_impl=rew_if,
                input_keys=("packed_input_ids", "prompt_mask"),
                output_keys=rew_outputs,
                n_seqs=cfg.batch_size,
                mb_spec=cfg.mb_spec,
            )
        )
    train_inputs = [
        "packed_input_ids", "prompt_mask", "packed_logprobs",
        "seq_no_eos_mask", "rewards",
    ]
    if use_dense:
        train_inputs.append("dense_rewards")
    if ref is not None:
        if not fuse:
            nodes.append(
                MFCDef(
                    name="ref_inf",
                    model_name=ref,
                    interface_type=ModelInterfaceType.INFERENCE,
                    interface_impl=ModelInterfaceAbstraction("ppo_actor"),
                    input_keys=("packed_input_ids",),
                    shard_keys=("packed_input_ids",),
                    output_keys=("packed_ref_logprobs",),
                    output_key_remap={"logprobs": "packed_ref_logprobs"},
                    n_seqs=cfg.batch_size,
                    mb_spec=cfg.mb_spec,
                    post_hooks=[OffloadHook()] if cfg.offload_ref else [],
                )
            )
        train_inputs.append("packed_ref_logprobs")
    if critic is not None:
        nodes.append(
            MFCDef(
                name="critic_inf",
                model_name=critic,
                interface_type=ModelInterfaceType.INFERENCE,
                interface_impl=critic_if,
                input_keys=("packed_input_ids", "prompt_mask"),
                shard_keys=("packed_input_ids",),
                output_keys=("values",),
                n_seqs=cfg.batch_size,
                mb_spec=cfg.mb_spec,
            )
        )
        train_inputs.append("values")
    # Sharded dispatch for the train steps: per-row math consumes only
    # the member's own (real) rows, and batch-GLOBAL statistics —
    # advantage moments, ref-KL (incl. the adaptive controller), the
    # critic's value-norm running moments — come from an exact in-mesh
    # reduction over the placed arrays (TrainEngine.masked_moments), so
    # every PPO configuration dispatches shard-exact.  prompt_mask stays
    # broadcast: sequence layout (loss masks, prompt lengths) must be
    # derivable by every member from global data.  (The reference
    # redistributes full batches instead, data_manager.py:144-416.)
    _heavy = (
        "packed_input_ids", "packed_logprobs", "packed_ref_logprobs",
        "values", "dense_rewards",
    )
    train_shard_keys = tuple(k for k in train_inputs if k in _heavy)
    train_post_hooks = [ParamReallocHook(target=actor_gen)]
    if cfg.ref_ema_eta is not None:
        if ref is None:
            raise ValueError("ref_ema_eta requires a ref model")
        train_post_hooks.append(
            ParamReallocHook(target=ref, eta=cfg.ref_ema_eta)
        )
        if cfg.offload_ref:
            # The EMA update reloads the ref onto device; push it back to
            # host so offload_ref keeps its HBM freed between steps.
            train_post_hooks.append(OffloadHook(target=ref))
    nodes.append(
        MFCDef(
            name="actor_train",
            model_name=actor,
            interface_type=ModelInterfaceType.TRAIN_STEP,
            interface_impl=actor_if,
            input_keys=tuple(train_inputs),
            shard_keys=train_shard_keys,
            n_seqs=cfg.batch_size,
            mb_spec=cfg.mb_spec,
            # After training, push fresh weights into the generator
            # (reference: param_realloc post-hook / update_weights_from_disk);
            # optionally EMA-update the reference policy.
            post_hooks=train_post_hooks,
        )
    )
    if critic is not None:
        nodes.append(
            MFCDef(
                name="critic_train",
                model_name=critic,
                interface_type=ModelInterfaceType.TRAIN_STEP,
                interface_impl=critic_if,
                input_keys=(
                    "packed_input_ids", "prompt_mask", "packed_logprobs",
                    "seq_no_eos_mask", "rewards", "values",
                ),
                shard_keys=(
                    "packed_input_ids", "packed_logprobs", "values",
                ),
                n_seqs=cfg.batch_size,
                mb_spec=cfg.mb_spec,
            )
        )
    dfg = build_graph(nodes)

    ftspec = FinetuneSpec(
        total_train_epochs=cfg.total_train_epochs,
        train_batch_size=cfg.batch_size,
    )
    shards = [
        ModelShardSpec(
            name=actor,
            model=cfg.actor,
            backend=ModelBackendAbstraction(
                "train", dict(train_backend_args)
            ),
            interface=actor_if,
            parallel=cfg.actor_parallel,
            optimizer=cfg.optimizer,
            device_offset=cfg.actor_device_offset,
        ),
        (
            _remote_gen_shard(cfg, actor_gen, actor_if)
            if cfg.gen_server_url
            else ModelShardSpec(
                name=actor_gen,
                model=cfg.actor,
                # Synchronous trials (rollout_ahead=0): generation never
                # overlaps the donating optimizer step, so the generator
                # may ALIAS the train master's buffers instead of copying
                # them (set_params' defensive copy is what the copy-vs-OOM
                # margin is for 1.5B on a 16 GB chip); the master releases
                # the alias before each aliased train step (see
                # MasterWorker._release_aliased_generators).  One-step-
                # ahead rollout decodes DURING training and must keep the
                # defensive copy.  Reference mechanism this replaces:
                # the weight-refresh dance in model_worker.py:1040-1067.
                backend=ModelBackendAbstraction(
                    "generator",
                    {
                        # Both async modes — and the within-step pipeline
                        # overlap, whose later chunks decode while earlier
                        # chunks accumulate grads — run generation
                        # concurrently with the donating optimizer step ->
                        # the generator MUST keep its defensive copy.
                        "donation_safe_swap": cfg.rollout_ahead > 0
                        or cfg.max_head_offpolicyness is not None
                        or cfg.pipeline_overlap,
                        "kv_paged": cfg.kv_paged,
                        "kv_page_size": cfg.kv_page_size,
                        "kv_pool_pages": cfg.kv_pool_pages,
                        "prefill_chunk_tokens": cfg.prefill_chunk_tokens,
                        "kv_share_prefix": cfg.kv_share_prefix,
                        **cfg.gen_backend_args,
                    },
                ),
                interface=actor_if,
                parallel=cfg.gen_parallel or cfg.actor_parallel,
                device_offset=cfg.gen_device_offset,
            )
        ),
    ]
    if not fuse:
        shards.append(
            ModelShardSpec(
                name=reward,
                model=ModelAbstraction("null"),
                backend=ModelBackendAbstraction("null"),
                interface=rew_if,
            )
        )
    if ref is not None:
        shards.append(
            ModelShardSpec(
                name=ref,
                model=cfg.ref,
                backend=ModelBackendAbstraction("inference"),
                interface=(
                    fused_if if fuse
                    else ModelInterfaceAbstraction("ppo_actor")
                ),
                parallel=cfg.ref_parallel or cfg.actor_parallel,
                device_offset=cfg.actor_device_offset,
            )
        )
    if critic is not None:
        shards.append(
            ModelShardSpec(
                name=critic,
                model=cfg.critic,
                backend=ModelBackendAbstraction(
                    "train", dict(train_backend_args)
                ),
                interface=critic_if,
                parallel=cfg.critic_parallel,
                optimizer=cfg.optimizer,
            )
        )
    workers_of: Dict[str, List[int]] = {}
    replicas: Dict[str, List[int]] = {}
    for s in shards:
        where = cfg.placement.get(s.name.role, 0)
        if isinstance(where, int):
            workers_of[str(s.name)] = [where]
        else:
            workers_of[str(s.name)] = list(where)
            if len(where) > 1:
                replicas[str(s.name)] = list(where)
    placement = {k: v[0] for k, v in workers_of.items()}
    n_workers = max(w for ws in workers_of.values() for w in ws) + 1
    worker_configs = []
    for w in range(n_workers):
        worker_configs.append(
            WorkerConfig(
                worker_index=w,
                shards=[s for s in shards if w in workers_of[str(s.name)]],
                # Datasets live on worker 0 (the data worker); outputs move
                # to consumers via the master-planned transfer plane.
                datasets=[cfg.dataset] if w == 0 else [],
                batch_size=cfg.batch_size,
                seed=cfg.seed,
                ftspec=ftspec,
                device_offset=cfg.worker_device_offsets.get(w, 0),
            )
        )
    cfg.ctrl.total_train_epochs = cfg.total_train_epochs
    return ExperimentPlan(
        dfg=dfg,
        worker_configs=worker_configs,
        model_placement=placement,
        data_worker_ids=[0],
        ctrl=cfg.ctrl,
        experiment_name=cfg.experiment_name,
        trial_name=cfg.trial_name,
        fileroot=cfg.fileroot,
        model_replicas=replicas or None,
        difficulty_filter=cfg.dataset_filter,
        rollout_ahead=cfg.rollout_ahead,
        max_head_offpolicyness=cfg.max_head_offpolicyness,
        replay_capacity=cfg.replay_capacity,
        pipeline_overlap=cfg.pipeline_overlap,
        overlap_window=cfg.overlap_window,
        pipeline_chunk_seqs=cfg.pipeline_chunk_seqs,
        mfc_timeout_s=cfg.mfc_timeout_s,
        worker_heartbeat_s=cfg.worker_heartbeat_s,
        max_recoveries=cfg.max_recoveries,
        max_consecutive_quarantines=cfg.max_consecutive_quarantines,
        weight_push_checksum=cfg.weight_push_checksum,
    )


def run_experiment(plan: ExperimentPlan, tokenizer=None):
    """In-process runner: build workers, drive the master loop to completion.
    (The multi-process ZMQ runtime is areal_tpu/apps/main.py run_experiment.)
    """
    import asyncio

    from areal_tpu.base import tracer
    from areal_tpu.system.master import InProcessPool, MasterWorker
    from areal_tpu.system.transfer import InProcTransfer
    from areal_tpu.system.worker import ModelWorker

    # One process hosts everything here, so all spans land in the master's
    # shard (threads are separate trace rows); set the shared dir before
    # any component configures the tracer.
    tracer.default_dir(
        plan.fileroot, plan.experiment_name, plan.trial_name
    )
    planes = InProcTransfer.make_group(len(plan.worker_configs))
    workers = [
        ModelWorker(wc, tokenizer=tokenizer, transfer=planes[i])
        for i, wc in enumerate(plan.worker_configs)
    ]
    pool = InProcessPool(workers, mfc_timeout_s=plan.mfc_timeout_s)
    master = MasterWorker(
        dfg=plan.dfg,
        pool=pool,
        model_placement=plan.model_placement,
        data_worker_ids=plan.data_worker_ids,
        ctrl=plan.ctrl,
        fileroot=plan.fileroot,
        experiment_name=plan.experiment_name,
        trial_name=plan.trial_name,
        model_groups=plan.model_groups,
        model_replicas=plan.model_replicas,
        difficulty_filter=plan.difficulty_filter,
        rollout_ahead=plan.rollout_ahead,
        max_head_offpolicyness=plan.max_head_offpolicyness,
        replay_capacity=plan.replay_capacity,
        buffer_max_age_steps=plan.buffer_max_age_steps,
        pipeline_overlap=plan.pipeline_overlap,
        overlap_window=plan.overlap_window,
        pipeline_chunk_seqs=plan.pipeline_chunk_seqs,
        max_recoveries=plan.max_recoveries,
        max_consecutive_quarantines=plan.max_consecutive_quarantines,
        weight_push_checksum=plan.weight_push_checksum,
    )
    master.load_recover_info()
    stats = asyncio.run(master.run())
    return master, stats
