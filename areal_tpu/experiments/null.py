"""Null experiments: exercise the full runtime with zero device compute.

Capability parity: realhf/experiments/common/null_exp.py (NullSFTConfig /
NullPPOConfig registered for system tests) — a trial whose MFCs use the
"null" interface and backend, so master/worker dispatch, the data plane,
buffer readiness, and epoch accounting all run exactly as in a real trial
while each MFC is a no-op.  Used to smoke-test launchers and schedulers.
"""

import dataclasses

from areal_tpu.api.config import (
    ModelAbstraction,
    ModelBackendAbstraction,
    ModelInterfaceAbstraction,
    ModelInterfaceType,
    ModelName,
)
from areal_tpu.api.data_api import DatasetAbstraction, MicroBatchSpec
from areal_tpu.api.dfg import MFCDef, build_graph
from areal_tpu.api.model_api import FinetuneSpec
from areal_tpu.experiments.common import ExperimentPlan
from areal_tpu.system.master import ExperimentSaveEvalControl
from areal_tpu.system.worker import ModelShardSpec, WorkerConfig


@dataclasses.dataclass
class NullSFTConfig:
    dataset: DatasetAbstraction
    batch_size: int = 8
    total_train_epochs: int = 1
    n_workers: int = 1
    ctrl: ExperimentSaveEvalControl = dataclasses.field(
        default_factory=ExperimentSaveEvalControl
    )
    seed: int = 1
    experiment_name: str = "null-sft"
    trial_name: str = "trial"
    fileroot: str = "/tmp/areal_tpu/trial"


def build_null_sft(cfg: NullSFTConfig) -> ExperimentPlan:
    """Single no-op train MFC over a real dataset (null_exp.py NullSFT)."""
    model_name = ModelName("default", 0)
    node = MFCDef(
        name="trainDefault",
        model_name=model_name,
        interface_type=ModelInterfaceType.TRAIN_STEP,
        interface_impl=ModelInterfaceAbstraction("null"),
        input_keys=("packed_input_ids", "prompt_mask"),
        n_seqs=cfg.batch_size,
        mb_spec=MicroBatchSpec(),
    )
    shard = ModelShardSpec(
        name=model_name,
        model=ModelAbstraction("null"),
        backend=ModelBackendAbstraction("null"),
        interface=ModelInterfaceAbstraction("null"),
    )
    ftspec = FinetuneSpec(
        total_train_epochs=cfg.total_train_epochs,
        train_batch_size=cfg.batch_size,
    )
    worker_configs = [
        WorkerConfig(
            worker_index=w,
            shards=[shard] if w == 0 else [],
            datasets=[cfg.dataset] if w == 0 else [],
            batch_size=cfg.batch_size,
            seed=cfg.seed,
            ftspec=ftspec,
        )
        for w in range(cfg.n_workers)
    ]
    cfg.ctrl.total_train_epochs = cfg.total_train_epochs
    return ExperimentPlan(
        dfg=build_graph([node]),
        worker_configs=worker_configs,
        model_placement={str(model_name): 0},
        data_worker_ids=[0],
        ctrl=cfg.ctrl,
        experiment_name=cfg.experiment_name,
        trial_name=cfg.trial_name,
        fileroot=cfg.fileroot,
    )


def build_null_ppo(cfg: NullSFTConfig) -> ExperimentPlan:
    """Two-MFC null graph (reward inference -> train) over prompt data —
    the minimal multi-node DFG for runtime tests (null_exp.py NullPPO)."""
    rew = ModelName("reward", 0)
    actor = ModelName("actor", 0)
    nodes = [
        MFCDef(
            name="rew_inf",
            model_name=rew,
            interface_type=ModelInterfaceType.INFERENCE,
            interface_impl=ModelInterfaceAbstraction("null"),
            input_keys=("packed_prompts",),
            output_keys=("rewards",),
            n_seqs=cfg.batch_size,
            mb_spec=MicroBatchSpec(),
        ),
        MFCDef(
            name="actor_train",
            model_name=actor,
            interface_type=ModelInterfaceType.TRAIN_STEP,
            interface_impl=ModelInterfaceAbstraction("null"),
            input_keys=("packed_prompts", "rewards"),
            n_seqs=cfg.batch_size,
            mb_spec=MicroBatchSpec(),
        ),
    ]
    shards = [
        ModelShardSpec(
            name=name,
            model=ModelAbstraction("null"),
            backend=ModelBackendAbstraction("null"),
            interface=ModelInterfaceAbstraction("null"),
        )
        for name in (rew, actor)
    ]
    ftspec = FinetuneSpec(
        total_train_epochs=cfg.total_train_epochs,
        train_batch_size=cfg.batch_size,
    )
    worker_configs = [
        WorkerConfig(
            worker_index=w,
            shards=shards if w == 0 else [],
            datasets=[cfg.dataset] if w == 0 else [],
            batch_size=cfg.batch_size,
            seed=cfg.seed,
            ftspec=ftspec,
        )
        for w in range(cfg.n_workers)
    ]
    cfg.ctrl.total_train_epochs = cfg.total_train_epochs
    return ExperimentPlan(
        dfg=build_graph(nodes),
        worker_configs=worker_configs,
        model_placement={str(rew): 0, str(actor): 0},
        data_worker_ids=[0],
        ctrl=cfg.ctrl,
        experiment_name=cfg.experiment_name,
        trial_name=cfg.trial_name,
        fileroot=cfg.fileroot,
    )
