"""Profiling experiment: time each MFC kind across parallel layouts.

Capability parity: realhf/experiments/benchmark/profile_exp.py
(ProfileConfig enumerates (MFC × ParallelismConfig) and runs each setup
sequentially, feeding measured timings to logs/the search engine) — TPU
version drives the engines directly on one process: for every enumerated
`ParallelConfig` that fits the device count it builds the mesh, runs
train_batch / forward / generate on synthetic packed batches, and reports
wall time + analytic TFLOP/s per (mfc, layout).  The output JSON is the
measured counterpart of the allocation-search estimator
(areal_tpu/search_engine/estimate.py) and calibrates it against hardware.

Per-layer (rather than per-MFC) timing lives in apps/profile_layers.py.
"""

import dataclasses
import json
import os
import time
from typing import Any, Dict, List, Optional, Sequence

import numpy as np

from areal_tpu.api.data_api import MicroBatchSpec, SequenceSample
from areal_tpu.api.model_api import (
    FinetuneSpec,
    GenerationHyperparameters,
    OptimizerConfig,
)
from areal_tpu.base import logging, monitor
from areal_tpu.base.topology import ParallelConfig, make_mesh
from areal_tpu.models.config import ModelConfig

logger = logging.getLogger("profile_exp")


def decompose_parallel_configs(
    n_devices: int, model_config: Optional[ModelConfig] = None
) -> List[ParallelConfig]:
    """(data, fsdp, model) factorizations of n_devices (reference:
    base/topology.py decompose_to_three_factors feeding profile_exp).
    With a model config, infeasible layouts (head/hidden-dim divisibility)
    are filtered up front — same rules the allocation search uses."""
    if model_config is not None:
        from areal_tpu.search_engine.search import _factorizations

        return _factorizations(n_devices, model_config, allow_pipe=False)
    out = []
    for data in range(1, n_devices + 1):
        if n_devices % data:
            continue
        rest = n_devices // data
        for fsdp in range(1, rest + 1):
            if rest % fsdp:
                continue
            model = rest // fsdp
            out.append(ParallelConfig(data=data, fsdp=fsdp, model=model))
    return out


@dataclasses.dataclass
class ProfileConfig:
    model_config: ModelConfig
    n_devices: int = 1
    # None = enumerate every (data, fsdp, model) factorization.
    parallel_configs: Optional[Sequence[ParallelConfig]] = None
    mfcs: Sequence[str] = ("train_step", "inference", "generate")
    batch_size: int = 8
    seqlen: int = 128
    gen_new_tokens: int = 32
    n_iters: int = 3
    seed: int = 0
    fileroot: str = "/tmp/areal_tpu/profile"


def _synthetic_batch(cfg: ModelConfig, bs: int, seqlen: int, seed: int):
    rng = np.random.default_rng(seed)
    seqlens = [seqlen] * bs
    tokens = rng.integers(0, cfg.vocab_size, size=sum(seqlens)).astype(
        np.int32
    )
    pmask = np.zeros(sum(seqlens), bool)
    off = 0
    for l in seqlens:
        pmask[off : off + max(1, l // 4)] = True
        off += l
    return SequenceSample(
        keys={"packed_input_ids", "prompt_mask"},
        ids=[f"p{i}" for i in range(bs)],
        seqlens={
            "packed_input_ids": [[l] for l in seqlens],
            "prompt_mask": [[l] for l in seqlens],
        },
        data={"packed_input_ids": tokens, "prompt_mask": pmask},
    )


def run_profile(cfg: ProfileConfig) -> List[Dict[str, Any]]:
    import jax

    from areal_tpu.engines.generator import GeneratorEngine
    from areal_tpu.engines.inference import InferenceEngine
    from areal_tpu.engines.train import TrainEngine
    from areal_tpu.models import transformer as tfm
    from areal_tpu.ops import functional as F

    devices = jax.devices()[: cfg.n_devices]
    if len(devices) < cfg.n_devices:
        raise ValueError(
            f"need {cfg.n_devices} devices, have {len(jax.devices())}"
        )
    layouts = list(cfg.parallel_configs or decompose_parallel_configs(
        cfg.n_devices, cfg.model_config
    ))
    mcfg = cfg.model_config
    rows: List[Dict[str, Any]] = []
    sample = _synthetic_batch(mcfg, cfg.batch_size, cfg.seqlen, cfg.seed)
    n_tokens = cfg.batch_size * cfg.seqlen
    sum_sq = float(cfg.batch_size * cfg.seqlen * cfg.seqlen)

    for pc in layouts:
        mesh = make_mesh(pc, devices)

        def _time(fn) -> float:
            fn()  # warmup / compile
            t0 = time.perf_counter()
            for _ in range(cfg.n_iters):
                fn()
            return (time.perf_counter() - t0) / cfg.n_iters

        engine = None
        for mfc in cfg.mfcs:
            # Fresh params per engine: TrainEngine donates the incoming
            # tree to its master copy, deleting the caller's arrays.  The
            # PREVIOUS engine is dropped first so its params/opt-state free
            # before the next allocation (peak HBM = one engine, not two).
            engine = None
            params = tfm.init_params(mcfg, jax.random.PRNGKey(cfg.seed))
            try:
                if mfc == "train_step":
                    engine = TrainEngine(
                        mcfg, params, mesh,
                        optimizer_config=OptimizerConfig(
                            lr=1e-4, warmup_steps_proportion=0.0
                        ),
                        ftspec=FinetuneSpec(1, 1000, 1000),
                    )
                    t = _time(lambda: engine.train_batch(
                        sample, MicroBatchSpec(),
                        loss_fn=F.sft_loss,
                        loss_weight_fn=F.sft_label_count,
                        token_key="packed_input_ids",
                        extra_keys=("prompt_mask",),
                    ))
                    flops = monitor.flops_train(mcfg, n_tokens, sum_sq)
                elif mfc == "inference":
                    from areal_tpu.interfaces.ppo import _logprob_post

                    engine = InferenceEngine(mcfg, params, mesh)
                    t = _time(lambda: engine.forward(
                        sample, MicroBatchSpec(),
                        post_fn=_logprob_post, output_key="logprobs",
                    ))
                    flops = monitor.flops_forward(mcfg, n_tokens, sum_sq)
                elif mfc == "generate":
                    engine = GeneratorEngine(
                        mcfg, params, mesh,
                        eos_token_id=mcfg.vocab_size - 1,
                        max_decode_batch=cfg.batch_size,
                    )
                    g = GenerationHyperparameters(
                        n=1, max_new_tokens=cfg.gen_new_tokens,
                        temperature=1.0, top_p=1.0, greedy=True,
                    )
                    prompts = SequenceSample(
                        keys={"packed_prompts"},
                        ids=list(sample.ids),
                        seqlens={
                            "packed_prompts": sample.seqlens[
                                "packed_input_ids"
                            ]
                        },
                        data={
                            "packed_prompts": sample.data[
                                "packed_input_ids"
                            ]
                        },
                    )
                    t = _time(lambda: engine.generate(
                        prompts, MicroBatchSpec(), g, seed=cfg.seed
                    ))
                    flops = monitor.flops_generate(
                        mcfg,
                        [cfg.seqlen] * cfg.batch_size,
                        [cfg.gen_new_tokens] * cfg.batch_size,
                    )
                else:
                    raise ValueError(f"unknown mfc {mfc!r}")
            except Exception as e:  # noqa: BLE001 — layout may not fit
                logger.warning(f"profile {mfc} @ {pc.to_str()} failed: {e!r}")
                rows.append(
                    {"mfc": mfc, "parallel": pc.to_str(), "error": repr(e)}
                )
                continue
            rows.append(
                {
                    "mfc": mfc,
                    "parallel": pc.to_str(),
                    "time_s": round(t, 5),
                    "tflops_per_device": round(
                        flops / t / cfg.n_devices / 1e12, 3
                    ),
                }
            )
            logger.info(f"profiled {mfc} @ {pc.to_str()}: {t:.4f}s")
        engine = None  # free the last engine before the next layout

    os.makedirs(cfg.fileroot, exist_ok=True)
    out_path = os.path.join(cfg.fileroot, "profile.json")
    with open(out_path, "w") as f:
        json.dump(rows, f, indent=2)
    logger.info(f"profile table written to {out_path}")
    return rows
