"""Pipeline parallelism: GPipe-style microbatch pipelining over the `pipe`
mesh axis.

Capability parity: realhf/impl/model/parallelism/pipeline_parallel/
(static_schedule.py InferenceSchedule/TrainSchedule + backend/pipe_runner.py)
— re-designed for XLA instead of an interpreted instruction stream:

- Layer-stacked block params are sharded over `pipe` on their leading axis
  (areal_tpu/parallel/sharding.py), so stage s holds layers
  [s*L/P, (s+1)*L/P).
- The schedule is ONE `lax.scan` over M + P - 1 ticks inside a `shard_map`
  that manualizes only the pipe axis (`axis_names={"pipe"}`); tensor/fsdp/
  seq axes stay under GSPMD inside each stage.  Each tick every stage runs
  its local layers on its current microbatch and hands the activation to the
  next stage with `ppermute` — XLA overlaps the transfer with the next
  tick's compute.
- Backward is plain autodiff through the scan: the transposed ppermutes
  run the reverse pipeline, giving the 1F1B-equivalent dataflow without an
  instruction VM.  `jax.checkpoint` around the per-tick stage body keeps
  activation memory at one microbatch per stage.
- Bubble fraction is (P-1)/(M+P-1), the GPipe bound; callers pick
  n_microbatches >= 4*P to amortize.

Generation under PP (the reference's GenerateSchedule token feedback loop)
is not routed through this module: decode is latency-bound and runs on
pipe=1 meshes; see areal_tpu/engines/generator.py.
"""

import functools
from typing import Any, Dict, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from areal_tpu.base.compat import shard_map
from areal_tpu.base.topology import PIPE_AXIS, SEQ_AXIS


def _stage_scan(blocks_local, cfg, use_flash, cp_manual, x, seg, cos, sin):
    """Run this stage's local layer stack on one microbatch."""
    from areal_tpu.models.transformer import _block_forward

    def body(carry, blk):
        y, aux = _block_forward(
            carry, blk, cfg, seg, cos, sin, use_flash, cp_manual=cp_manual
        )
        return y, aux

    y, auxes = jax.lax.scan(body, x, blocks_local)
    return y, jnp.sum(auxes)


def pipelined_blocks(
    blocks: Dict[str, jax.Array],
    cfg,
    x: jax.Array,  # [B, S, D] embedded activations
    segment_ids: jax.Array,  # [B, S]
    cos: jax.Array,
    sin: jax.Array,
    mesh: Mesh,
    n_microbatches: int,
    use_flash: "bool | None" = False,
    cp: bool = False,
) -> Tuple[jax.Array, jax.Array]:
    """Transformer block stack under pipeline parallelism -> (y, aux_loss).

    `cp=True` composes ring context parallelism INSIDE each stage: the
    shard_map manualizes BOTH pipe and seq, every stage computes on its
    local sequence chunk (all stage ops are per-token except attention),
    and attention runs the ring body (`ops/ring_attention._ring_shard`)
    directly on the chunk.  Nesting a fresh seq shard_map per stage is
    NOT used — jax rejects that composition once operands vary over the
    manual pipe axis (and silently mistrains under check_vma=False).

    `n_microbatches` is a REQUEST: the schedule uses the largest multiple
    of `pipe` that divides B and is <= the request (padding rows only up
    to B % pipe == 0 beats forcing B % 4P == 0 — the reference's
    TrainSchedule likewise takes whatever microbatch count the batch
    admits).  Requires B % pipe == 0 and n_layers % pipe == 0.
    """
    n_stages = mesh.shape[PIPE_AXIS]
    b = x.shape[0]
    if b % n_stages:
        raise ValueError(
            f"batch rows {b} not divisible by {n_stages} pipe stages"
        )
    m = max(n_stages, min(n_microbatches, b))
    m -= m % n_stages
    while b % m:
        m -= n_stages
    if cfg.n_layers % n_stages:
        raise ValueError(
            f"{cfg.n_layers} layers not divisible by {n_stages} pipe stages"
        )
    cp_manual = None
    if cp:
        n_seq = mesh.shape[SEQ_AXIS]
        if x.shape[1] % n_seq:
            raise ValueError(
                f"row length {x.shape[1]} not divisible by seq={n_seq}"
            )
        if cfg.is_moe and cfg.moe_dispatch == "topk":
            # Capacity dispatch computes expert capacity from the tokens
            # it SEES: per-(CP-chunk, microbatch) capacity would silently
            # differ from the global dispatch the non-pipelined CP path
            # computes (different drops => different numerics).  The
            # dropless dispatches ("grouped", "dense") are per-token
            # chunk-invariant and pass through; only the load-balancing
            # aux becomes a mean of per-chunk terms instead of the global
            # batch term (gradient pressure per chunk, same fixed point).
            raise NotImplementedError(
                "capacity (topk) MoE under combined CP + PP; use "
                "moe_dispatch='grouped' (dropless, chunk-invariant)"
            )
        cp_manual = (SEQ_AXIS, n_seq)
        use_flash = False  # dense ring blocks inside the manual region

    def to_mbs(t):
        return t.reshape(m, b // m, *t.shape[1:])

    x_mbs, seg_mbs = to_mbs(x), to_mbs(segment_ids)
    cos_mbs, sin_mbs = to_mbs(cos), to_mbs(sin)

    def pipe_body(sids, qids, blocks_local, x_mbs, seg_mbs, cos_mbs, sin_mbs):
        # Explicit per-shard index inputs instead of lax.axis_index: old
        # jax lowers axis_index inside a partial-manual region through a
        # partition_id HLO that the SPMD partitioner rejects.
        stage = sids[0]
        cp_info = cp_manual and (*cp_manual, qids[0])
        fwd = functools.partial(
            _stage_scan, blocks_local, cfg, use_flash, cp_info
        )
        fwd = jax.checkpoint(
            fwd, policy=jax.checkpoint_policies.nothing_saveable
        )
        perm = [(i, i + 1) for i in range(n_stages - 1)]

        outputs = jnp.zeros_like(x_mbs)
        aux0 = jnp.zeros((), jnp.float32)
        recv = jnp.zeros_like(x_mbs[0])

        def tick(carry, t):
            recv, outputs, aux_sum = carry
            # Stage s works on microbatch (t - s) this tick.
            mb = jnp.clip(t - stage, 0, m - 1)
            feed = jnp.where(t - stage < m, x_mbs[jnp.clip(t, 0, m - 1)], 0.0)
            inp = jnp.where(stage == 0, feed, recv)
            seg1 = seg_mbs[mb]
            out, aux = fwd(inp, seg1, cos_mbs[mb], sin_mbs[mb])
            valid = (t - stage >= 0) & (t - stage < m)
            aux_sum = aux_sum + jnp.where(valid, aux, 0.0)
            # The last stage finishes microbatch (t - (P-1)) at tick t.
            out_idx = jnp.clip(t - (n_stages - 1), 0, m - 1)
            write = valid & (stage == n_stages - 1)
            slot = jax.lax.dynamic_index_in_dim(
                outputs, out_idx, 0, keepdims=False
            )
            outputs = jax.lax.dynamic_update_index_in_dim(
                outputs, jnp.where(write, out, slot), out_idx, 0
            )
            if n_stages > 1:
                recv = jax.lax.ppermute(out, PIPE_AXIS, perm)
            return (recv, outputs, aux_sum), None

        (recv, outputs, aux_sum), _ = jax.lax.scan(
            tick,
            (recv, outputs, aux0),
            jnp.arange(m + n_stages - 1, dtype=jnp.int32),
        )
        # Only the last stage holds real outputs; replicate over the pipe
        # axis (stages' own garbage is zeroed by masking before the psum).
        outputs = jnp.where(stage == n_stages - 1, outputs, 0.0)
        outputs = jax.lax.psum(outputs, PIPE_AXIS)
        # Aux (MoE balancing) is an intensive per-layer statistic; average
        # over microbatches so it matches the non-pipelined scan's scale.
        # (Under CP aux stays pipe-summed only: MoE is fenced there.)
        aux_sum = jax.lax.psum(aux_sum, PIPE_AXIS) / m
        return outputs, aux_sum

    # Under CP the seq axis is manual too: activations/segments/rotary
    # tables enter as per-chunk shards ([m, rows, S/n_seq, ...]).
    seq = SEQ_AXIS if cp_manual else None
    act = P(None, None, seq)
    fn = shard_map(
        pipe_body,
        mesh=mesh,
        in_specs=(
            P(PIPE_AXIS),
            P(SEQ_AXIS) if cp_manual else P(),
            P(PIPE_AXIS),
            act, act, act, act,
        ),
        out_specs=(act, P()),
        axis_names={PIPE_AXIS, SEQ_AXIS} if cp_manual else {PIPE_AXIS},
        check_vma=False,
    )
    sids = jnp.arange(n_stages, dtype=jnp.int32)
    qids = jnp.arange(
        mesh.shape[SEQ_AXIS] if cp_manual else 1, dtype=jnp.int32
    )
    y_mbs, aux = fn(sids, qids, blocks, x_mbs, seg_mbs, cos_mbs, sin_mbs)
    return y_mbs.reshape(b, *x.shape[1:]), aux
