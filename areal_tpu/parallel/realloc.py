"""Parameter reallocation: reshard a pytree between arbitrary mesh layouts.

Capability parity: the reference's signature feature — each model function
call runs under its own 3D layout, and parameters are *reallocated* between
layouts between calls (realhf/impl/model/comm/param_realloc.py: pairwise
NCCL groups + per-layer interval plans; default impl is disk save/load,
system/model_worker.py:1009-1068).

The TPU design collapses all of that machinery: a layout is a
`jax.sharding.NamedSharding` per leaf, and moving between layouts is
`jax.device_put` onto the destination shardings — XLA emits the collectives
(ICI when the meshes share devices, host/DCN transfer otherwise).  With
`donate=True` the source buffers are reused, avoiding the 2x memory spike
the reference dodges via disk.

This module is what `ParamReallocHook`s resolve to at runtime (see
areal_tpu/system/worker.py param-sync handling).
"""

from typing import Any, Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from areal_tpu.base import tracer
from areal_tpu.parallel import sharding


def reshard(
    tree: Any,
    dst_shardings: Any,
    dtype: Optional[Any] = None,
    donate: bool = False,
) -> Any:
    """Move an (on-device or host) pytree onto `dst_shardings`.

    dst_shardings: a pytree of NamedSharding matching `tree`'s structure (or
    a single sharding applied to every leaf).  `dtype` optionally casts
    floating leaves in the same XLA program (casting before the transfer
    halves the bytes moved when going fp32 -> bf16).
    """
    with tracer.span("reshard", cat="comms") as targs:
        if dtype is not None:
            tree = jax.tree.map(
                lambda x: x.astype(dtype)
                if jnp.issubdtype(jnp.asarray(x).dtype, jnp.floating)
                else x,
                tree,
            )
        out = jax.device_put(tree, dst_shardings, donate=donate)
        if tracer.enabled():
            # device_put is async; block so the span measures the actual
            # transfer rather than dispatch.  Only paid when tracing.
            out = jax.block_until_ready(out)
            targs["bytes"] = int(
                sum(x.nbytes for x in jax.tree.leaves(out))
            )
    return out


def reshard_params(
    params: Any,
    dst_mesh: Mesh,
    dtype: Optional[Any] = None,
    donate: bool = False,
) -> Any:
    """Reallocate a transformer param pytree onto `dst_mesh` under the
    framework's canonical sharding rules (areal_tpu/parallel/sharding.py).

    Works between any two layouts: same devices re-partitioned (pure ICI
    collectives), overlapping subsets, or fully disjoint device sets (the
    reference's decoupled gen/train meshes, e.g. sglang.d64p1m1+d32p2m1).
    """
    specs = sharding.param_pspecs(params)
    shardings = sharding.tree_named(dst_mesh, specs)
    return reshard(params, shardings, dtype=dtype, donate=donate)


def replicate_to(tree: Any, dst_mesh: Mesh, donate: bool = False) -> Any:
    """Reallocate with full replication on the destination mesh."""
    return reshard(tree, NamedSharding(dst_mesh, P()), donate=donate)
