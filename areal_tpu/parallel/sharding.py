"""GSPMD sharding rules for the transformer pytree.

Capability parity: realhf/impl/model/parallelism/ — but instead of
Megatron-style explicit Column/RowParallelLinear modules with hand-written
collectives, we annotate the SAME pure-functional model with
`jax.sharding.PartitionSpec`s and let the XLA SPMD partitioner insert
all-gathers / reduce-scatters / psums (sequence parallelism falls out
automatically).  One rule table replaces ~2.5k LoC of TP modules.

Conventions (mesh axes from areal_tpu/base/topology.py):
- `model`  — tensor parallel: attention heads + MLP hidden + vocab.
- `fsdp`   — ZeRO-style: remaining param dim sharded; batch also sharded.
- `data`   — pure DP: params replicated, batch sharded.
- `seq`    — context parallel: sequence dim of activations (ring attention).
- `pipe`   — pipeline stages (layer-stacked leading axis).
"""

from typing import Any, Dict, Optional

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from areal_tpu.base.topology import (
    DATA_AXIS,
    FSDP_AXIS,
    MODEL_AXIS,
    PIPE_AXIS,
    SEQ_AXIS,
)

BATCH = (DATA_AXIS, FSDP_AXIS)

# Param rules: leaf name -> PartitionSpec.  The leading layer-stack axis of
# block params shards over `pipe`: stage s holds layers [s*L/P, (s+1)*L/P)
# (see areal_tpu/parallel/pipeline.py); on pipe=1 meshes it is a no-op.
_BLOCK_RULES: Dict[str, P] = {
    "ln1": P(PIPE_AXIS, None),
    "ln2": P(PIPE_AXIS, None),
    "ln1_b": P(PIPE_AXIS, None),
    "ln2_b": P(PIPE_AXIS, None),
    "bo": P(PIPE_AXIS, None),
    "bproj": P(PIPE_AXIS, None),
    "bfc": P(PIPE_AXIS, MODEL_AXIS),  # matches wg's model-sharded output
    "wq": P(PIPE_AXIS, FSDP_AXIS, MODEL_AXIS),
    "wk": P(PIPE_AXIS, FSDP_AXIS, MODEL_AXIS),
    "wv": P(PIPE_AXIS, FSDP_AXIS, MODEL_AXIS),
    "bq": P(PIPE_AXIS, MODEL_AXIS),
    "bk": P(PIPE_AXIS, MODEL_AXIS),
    "bv": P(PIPE_AXIS, MODEL_AXIS),
    "wo": P(PIPE_AXIS, MODEL_AXIS, FSDP_AXIS),
    # Dense MLP
    "wg": P(PIPE_AXIS, FSDP_AXIS, MODEL_AXIS),
    "wu": P(PIPE_AXIS, FSDP_AXIS, MODEL_AXIS),
    "wd": P(PIPE_AXIS, MODEL_AXIS, FSDP_AXIS),
    # MoE: expert axis = expert parallelism over fsdp; hidden over model.
    "router": P(PIPE_AXIS, FSDP_AXIS, None),
    "moe_wg": P(PIPE_AXIS, FSDP_AXIS, None, MODEL_AXIS),
    "moe_wu": P(PIPE_AXIS, FSDP_AXIS, None, MODEL_AXIS),
    "moe_wd": P(PIPE_AXIS, FSDP_AXIS, MODEL_AXIS, None),
}

_TOP_RULES: Dict[str, P] = {
    "embed": P(MODEL_AXIS, FSDP_AXIS),
    "pos_embed": P(None, FSDP_AXIS),
    "final_ln": P(None),
    "final_ln_b": P(None),
    "lm_head": P(FSDP_AXIS, MODEL_AXIS),
    "value_head": P(FSDP_AXIS, None),
}


def param_pspecs(params: Dict[str, Any]) -> Dict[str, Any]:
    """PartitionSpec pytree matching the transformer params structure."""
    out: Dict[str, Any] = {}
    for k, v in params.items():
        if k == "blocks":
            blocks = {}
            for bk, bv in v.items():
                if bk in ("wg", "wu", "wd") and np.ndim(bv) == 4:
                    blocks[bk] = _BLOCK_RULES["moe_" + bk]
                else:
                    blocks[bk] = _BLOCK_RULES[bk]
            out[k] = blocks
        else:
            out[k] = _TOP_RULES[k]
    return out


def batch_pspec(with_seq: bool = True) -> P:
    """Sharding for [B, S] token/segment arrays."""
    return P(BATCH, SEQ_AXIS if with_seq else None)


def act_pspec() -> P:
    """Sharding for [B, S, D] activations."""
    return P(BATCH, SEQ_AXIS, None)


def logits_pspec() -> P:
    return P(BATCH, SEQ_AXIS, MODEL_AXIS)


def kv_cache_pspec() -> P:
    """[L, B, S, n_kv, d] — batch over (data,fsdp), heads over model."""
    return P(None, BATCH, None, MODEL_AXIS, None)


def attn_dispatch(mesh: Mesh, cfg=None):
    """Shared engine policy -> (use_flash, cp_mesh, pp_mesh, pp_microbatches,
    rows_multiple).

    use_flash: None (auto: flash on TPU) on single-device meshes; the MESH
    itself on multi-device tp/fsdp layouts — packed_attention shard_maps
    the Pallas kernel over it (batch on data/fsdp, heads on model) when the
    backend is TPU and head counts divide the model axis (pass `cfg` to
    check; without cfg multi-device flash stays off).  Ring context
    parallelism owns any mesh with a nontrivial `seq` axis; the block stack
    is microbatch-pipelined whenever `pipe` > 1 with 4 microbatches per
    stage (GPipe bubble (P-1)/(M+P-1) < ~20%).

    `rows_multiple` is what packed-batch row counts must divide by: the
    batch-sharding degree, times the microbatch count under PP (each
    microbatch must itself split over the batch axes — product, not lcm).
    """
    import numpy as np

    from areal_tpu.base.topology import BATCH_AXES

    if mesh.devices.size == 1:
        use_flash = None
    else:
        from areal_tpu.base.distributed import is_tpu_backend

        m = mesh.shape[MODEL_AXIS]
        eligible = (
            is_tpu_backend()
            and mesh.shape[SEQ_AXIS] == 1
            and mesh.shape[PIPE_AXIS] == 1
            and cfg is not None
            and cfg.n_kv_heads % m == 0
            and cfg.n_q_heads % m == 0
        )
        use_flash = mesh if eligible else False
    cp_mesh = mesh if mesh.shape[SEQ_AXIS] > 1 else None
    pp_mesh = mesh if mesh.shape[PIPE_AXIS] > 1 else None
    # REQUESTED in-flight microbatches: 4P amortizes the GPipe bubble to
    # (P-1)/(5P-1).  The schedule steps down to the largest multiple of P
    # that divides the actual row count (pipeline.py), so rows only need
    # padding to batch_axes x P — small PPO minibatches no longer pad to
    # 8P rows (the old rows_multiple = batch x 4P).
    pp_microbatches = 4 * mesh.shape[PIPE_AXIS]
    rows_multiple = int(np.prod([mesh.shape[a] for a in BATCH_AXES]))
    if pp_mesh is not None:
        rows_multiple *= mesh.shape[PIPE_AXIS]
    return use_flash, cp_mesh, pp_mesh, pp_microbatches, rows_multiple


def named(mesh: Mesh, spec: P) -> NamedSharding:
    return NamedSharding(mesh, spec)


def place_rows(mesh: Mesh, value, spec: P):
    """Put a row-major [B, ...] host batch array onto the mesh.

    Single-process meshes (and meshes whose process boundaries cut only
    non-batch axes) take the plain device_put path.  When the batch axis
    SPANS processes, each process contributes only its own contiguous row
    block via jax.make_array_from_process_local_data — the sharded data
    plane ships a member only those rows (zero placeholders elsewhere), and
    device_put's cross-process value check would (rightly) reject the
    now-divergent full host arrays.  With unsharded full data the local
    slice is identical, so this path is always safe when n > 1.
    """
    import numpy as np

    from areal_tpu.base.topology import local_batch_shard

    sh = NamedSharding(mesh, spec)
    rank, n = local_batch_shard(mesh)
    if n <= 1:
        return jax.device_put(value, sh)
    b = value.shape[0]
    if b % n:
        raise ValueError(
            f"batch rows ({b}) must divide the process shard count ({n}); "
            "the packer pads rows to the mesh batch degree"
        )
    lo = rank * (b // n)
    local = np.ascontiguousarray(value[lo : lo + b // n])
    return jax.make_array_from_process_local_data(sh, local, value.shape)


def tree_named(mesh: Mesh, specs) -> Any:
    return jax.tree.map(
        lambda s: NamedSharding(mesh, s),
        specs,
        is_leaf=lambda x: isinstance(x, P),
    )


def shard_params(params: Dict[str, Any], mesh: Mesh) -> Dict[str, Any]:
    """Place a (host or device) param pytree onto the mesh per the rules."""
    shardings = tree_named(mesh, param_pspecs(params))
    return jax.device_put(params, shardings)


def replicate(tree: Any, mesh: Mesh) -> Any:
    return jax.device_put(tree, NamedSharding(mesh, P()))


def check_divisibility(params: Dict[str, Any], mesh: Mesh) -> Optional[str]:
    """Return an error string if any param dim doesn't divide by its mesh
    axes (callers can fall back to replication or a smaller mesh)."""
    specs = param_pspecs(params)

    def _chk(path, leaf, spec):
        for dim, axes in zip(np.shape(leaf), spec):
            if axes is None:
                continue
            axes = (axes,) if isinstance(axes, str) else axes
            total = int(np.prod([mesh.shape[a] for a in axes]))
            if dim % total:
                return f"{'/'.join(map(str, path))}: dim {dim} % {axes}={total}"
        return None

    flat_p, _ = jax.tree_util.tree_flatten_with_path(params)
    flat_s = jax.tree_util.tree_leaves(
        specs, is_leaf=lambda x: isinstance(x, P)
    )
    for (path, leaf), spec in zip(flat_p, flat_s):
        err = _chk([getattr(k, "key", k) for k in path], leaf, spec)
        if err:
            return err
    return None
