"""Hermetic math answer verification.

Capability parity: realhf/functioncall/math/verify.py + math_parser.py (the
local verification path; the remote FaaS path is an HTTP wrapper around the
same grading).  Grading: extract the last \\boxed{...} (or final-answer
line) from the generated text and compare against any of the gold
solutions — a fast string/Fraction pre-filter first, then sympy-grade
symbolic equivalence (math_sympy.py, the qwen-grader parity layer) for
everything the fast path cannot decide.
"""

import re
from fractions import Fraction
from typing import List, Optional


def extract_boxed(text: str) -> Optional[str]:
    """Last \\boxed{...} content, handling nested braces."""
    idx = text.rfind("\\boxed{")
    if idx == -1:
        idx = text.rfind("\\fbox{")
        if idx == -1:
            return None
        start = idx + len("\\fbox{")
    else:
        start = idx + len("\\boxed{")
    depth = 1
    out = []
    for ch in text[start:]:
        if ch == "{":
            depth += 1
        elif ch == "}":
            depth -= 1
            if depth == 0:
                return "".join(out)
        out.append(ch)
    return None


def extract_answer(text: str) -> Optional[str]:
    boxed = extract_boxed(text)
    if boxed is not None:
        return boxed
    # "The answer is X" fallback (reference parser has the same heuristic).
    m = re.findall(
        r"(?:answer is|answer:)\s*([^\n\.,]+)", text, flags=re.IGNORECASE
    )
    if m:
        return m[-1].strip()
    return None


_STRIP_PATTERNS = [
    (re.compile(r"\\left|\\right"), ""),
    (re.compile(r"\\,|\\;|\\!|\\ |\s+"), ""),
    (re.compile(r"\\text\{[^}]*\}"), ""),
    (re.compile(r"\\mathrm\{[^}]*\}"), ""),
    (re.compile(r"^\$+|\$+$"), ""),
    (re.compile(r"\\%|%"), ""),
    (re.compile(r"^\{(.*)\}$"), r"\1"),
]


def normalize(ans: str) -> str:
    s = ans.strip()
    for pat, rep in _STRIP_PATTERNS:
        s = pat.sub(rep, s)
    s = s.rstrip(".")
    # \frac{a}{b} -> a/b
    s = re.sub(r"\\d?frac\{([^{}]+)\}\{([^{}]+)\}", r"\1/\2", s)
    s = re.sub(r"\\d?frac(\d)(\d)", r"\1/\2", s)
    return s


def _as_number(s: str) -> Optional[Fraction]:
    s = s.replace(",", "")
    try:
        return Fraction(s)
    except (ValueError, ZeroDivisionError):
        pass
    try:
        return Fraction(float(s)).limit_denominator(10**9)
    except (ValueError, OverflowError):
        return None


# A-E is the reference's range (grader.py:30); F-J extends it for
# 10-option sets (MMLU-Pro style), where the reference would crash.
CHOICE_LETTERS = "ABCDEFGHIJ"
_CHOICE_RE = re.compile(r"\b([A-J])\b")


_PAREN_CHOICE_RE = re.compile(r"\(([A-J])\)")


def choice_answer_clean(pred: str) -> str:
    """Multiple-choice extraction (reference: evaluation/grader.py:30 /
    parser.py:373 last-standalone-letter-wins, extended to A-J).
    POSITIONAL: the last letter in the text wins whether it is
    parenthesized or standalone — '(A) is wrong, the answer is B' must
    grade B (a paren-beats-standalone priority would grade A).  The
    English words 'A' and 'I' are ambiguous when bare (not
    parenthesized) and only count when no other candidate exists
    ('The answer is (B). I am sure.' must grade B, not I)."""
    pred = pred.strip("\n").rstrip(".").rstrip("/").strip(" ").lstrip(":")
    up = pred.upper()
    cands = [
        (m.start(1), m.group(1), True)
        for m in _PAREN_CHOICE_RE.finditer(up)
    ]
    taken = {p for p, _, _ in cands}
    cands += [
        (m.start(1), m.group(1), False)
        for m in _CHOICE_RE.finditer(up)
        if m.start(1) not in taken
    ]
    strong = [(p, c) for p, c, paren in cands if paren or c not in ("A", "I")]
    if strong:
        return max(strong)[1]
    if cands:
        return max(cands)[1]
    out = pred.strip().strip(".")
    return out.rstrip(".").rstrip("/")


def is_multi_choice(gold: str, is_choice: Optional[bool] = None) -> bool:
    """True when the gold should grade through choice extraction.

    `is_choice` is ROW-LEVEL evidence (the row carried a `choices`
    field, or its task tag marks it multiple-choice): True/False decide
    outright; None falls back to gold-string inference — one or more
    choice letters (GPQA/MMLU-style), e.g. 'B' or 'ACD' (reference:
    math_eval.py:369).  The inference alone misgrades math rows whose
    honest answer happens to be a letter string (a variable named 'C',
    interval endpoints 'AB'), so callers that know the row pass the
    evidence down (interfaces/reward.py, scheduler/evaluator.py)."""
    g = gold.strip()
    looks_like_letters = bool(g) and all(c in CHOICE_LETTERS for c in g)
    if is_choice is None:
        return looks_like_letters
    # Even with row evidence the gold must be letters — a choice row
    # whose gold is the option TEXT still grades as a plain answer.
    return bool(is_choice) and looks_like_letters


def choice_match(pred: str, gold: str) -> bool:
    gold = gold.strip()
    if len(gold) == 1:
        return choice_answer_clean(pred) == gold
    # Multi-letter golds: collect STANDALONE letters (word-boundary, like
    # the single-letter path) so prose ("the answers are A, C and D")
    # doesn't shed stray capitals into the comparison; a bare compact
    # answer ("ACD") has no \b-separated letters and falls back to the
    # reference's char filter over the extracted answer
    # (math_eval.py:596).
    # Order- and duplicate-insensitive: "the correct options are (C)
    # and (A)" must match gold "AC"; restating a letter must not break
    # the comparison.  Bare 'A'/'I' are ambiguous (English words), so
    # the prediction matches if ANY consistent reading — parenthesized
    # letters only, standalone letters without A/I, standalone letters
    # with them, or the reference's raw char filter — equals the gold
    # set.  (The reference's char filter alone has both failure modes;
    # trying each reading strictly dominates it.)
    up = pred.upper()
    want = "".join(sorted(set(gold)))
    readings = (
        _PAREN_CHOICE_RE.findall(up),
        [c for c in _CHOICE_RE.findall(up) if c not in ("A", "I")],
        _CHOICE_RE.findall(up),
        [c for c in up if c in CHOICE_LETTERS],
    )
    return any(
        r and "".join(sorted(set(r))) == want for r in readings
    )


def answers_match(pred: str, gold: str) -> bool:
    p, g = normalize(pred), normalize(gold)
    if p == g:
        return True
    pn, gn = _as_number(p), _as_number(g)
    if pn is not None and gn is not None:
        if pn == gn:
            return True
        # Reference numeric semantics (evaluation/grader.py:106,278):
        # percent-flexible (x matches x/100 and 100x) with rel_tol=1e-4.
        for cand in (gn, gn / 100, gn * 100):
            if abs(pn - cand) <= 1e-4 * max(abs(cand), 1e-12):
                return True
    return False


def verify_math(
    generated_text: str,
    solutions: List[str],
    use_sympy: bool = True,
    is_choice: Optional[bool] = None,
) -> bool:
    """True iff the generated answer matches any gold solution (each gold
    may itself be a \\boxed{...} wrapper or a raw answer).  The cheap
    string/Fraction path decides most cases; symbolically equivalent forms
    (0.5 vs \\frac{\\sqrt2}{2}-style mismatches, intervals, matrices) fall
    through to the sympy grader with a hard per-call timeout."""
    pred = extract_answer(generated_text)
    golds = []
    for sol in solutions:
        gold = extract_boxed(sol)
        if gold is None:
            gold = sol
        # Multiple-choice golds (GPQA-style) grade through choice
        # extraction — a boxed answer is not required; without one, the
        # last non-empty line stands in (prose earlier in the generation
        # is full of stray capitals the \b(A|..)\b scan would hit).
        if is_multi_choice(gold, is_choice):
            cand = pred
            if cand is None:
                lines = [
                    l for l in generated_text.strip().splitlines() if l.strip()
                ]
                cand = lines[-1] if lines else ""
            if choice_match(cand, gold):
                return True
            continue
        if pred is not None and answers_match(pred, gold):
            return True
        golds.append(gold)
    if pred is None:
        return False
    if use_sympy:
        from areal_tpu.interfaces.math_sympy import answers_match_sympy

        for gold in golds:
            if answers_match_sympy(pred, gold):
                return True
    return False
