"""Hermetic math answer verification.

Capability parity: realhf/functioncall/math/verify.py + math_parser.py (the
local verification path; the remote FaaS path is an HTTP wrapper around the
same grading).  Grading: extract the last \\boxed{...} (or final-answer
line) from the generated text and compare against any of the gold
solutions — a fast string/Fraction pre-filter first, then sympy-grade
symbolic equivalence (math_sympy.py, the qwen-grader parity layer) for
everything the fast path cannot decide.
"""

import re
from fractions import Fraction
from typing import List, Optional


def extract_boxed(text: str) -> Optional[str]:
    """Last \\boxed{...} content, handling nested braces."""
    idx = text.rfind("\\boxed{")
    if idx == -1:
        idx = text.rfind("\\fbox{")
        if idx == -1:
            return None
        start = idx + len("\\fbox{")
    else:
        start = idx + len("\\boxed{")
    depth = 1
    out = []
    for ch in text[start:]:
        if ch == "{":
            depth += 1
        elif ch == "}":
            depth -= 1
            if depth == 0:
                return "".join(out)
        out.append(ch)
    return None


def extract_answer(text: str) -> Optional[str]:
    boxed = extract_boxed(text)
    if boxed is not None:
        return boxed
    # "The answer is X" fallback (reference parser has the same heuristic).
    m = re.findall(
        r"(?:answer is|answer:)\s*([^\n\.,]+)", text, flags=re.IGNORECASE
    )
    if m:
        return m[-1].strip()
    return None


_STRIP_PATTERNS = [
    (re.compile(r"\\left|\\right"), ""),
    (re.compile(r"\\,|\\;|\\!|\\ |\s+"), ""),
    (re.compile(r"\\text\{[^}]*\}"), ""),
    (re.compile(r"\\mathrm\{[^}]*\}"), ""),
    (re.compile(r"^\$+|\$+$"), ""),
    (re.compile(r"\\%|%"), ""),
    (re.compile(r"^\{(.*)\}$"), r"\1"),
]


def normalize(ans: str) -> str:
    s = ans.strip()
    for pat, rep in _STRIP_PATTERNS:
        s = pat.sub(rep, s)
    s = s.rstrip(".")
    # \frac{a}{b} -> a/b
    s = re.sub(r"\\d?frac\{([^{}]+)\}\{([^{}]+)\}", r"\1/\2", s)
    s = re.sub(r"\\d?frac(\d)(\d)", r"\1/\2", s)
    return s


def _as_number(s: str) -> Optional[Fraction]:
    s = s.replace(",", "")
    try:
        return Fraction(s)
    except (ValueError, ZeroDivisionError):
        pass
    try:
        return Fraction(float(s)).limit_denominator(10**9)
    except (ValueError, OverflowError):
        return None


def answers_match(pred: str, gold: str) -> bool:
    p, g = normalize(pred), normalize(gold)
    if p == g:
        return True
    pn, gn = _as_number(p), _as_number(g)
    if pn is not None and gn is not None:
        return pn == gn
    return False


def verify_math(
    generated_text: str, solutions: List[str], use_sympy: bool = True
) -> bool:
    """True iff the generated answer matches any gold solution (each gold
    may itself be a \\boxed{...} wrapper or a raw answer).  The cheap
    string/Fraction path decides most cases; symbolically equivalent forms
    (0.5 vs \\frac{\\sqrt2}{2}-style mismatches, intervals, matrices) fall
    through to the sympy grader with a hard per-call timeout."""
    pred = extract_answer(generated_text)
    if pred is None:
        return False
    golds = []
    for sol in solutions:
        gold = extract_boxed(sol)
        if gold is None:
            gold = sol
        if answers_match(pred, gold):
            return True
        golds.append(gold)
    if use_sympy:
        from areal_tpu.interfaces.math_sympy import answers_match_sympy

        for gold in golds:
            if answers_match_sympy(pred, gold):
                return True
    return False
