"""Running mean/std normalizers for critic value targets.

Capability parity: realhf/impl/model/modules/rms.py
(`ExponentialRunningMeanStd`, `MovingAverageRunningMeanStd`) used by the
PPO interfaces via `value_norm*` options (ppo_interface.py:175-210,
:1005-1078): the critic head learns NORMALIZED returns; its predictions
are denormalized before GAE.  Host-side numpy with float64 accumulators
and debiasing (the reference keeps these as fp64 torch buffers).

State lives on the critic's training primary; with DP replicas the master
broadcasts the primary's moments to inference-only replicas after every
train step (system/master.py _sync_interface_state), so all replicas
denormalize with the same statistics (the reference instead all-reduces
batch moments across DP during update).
"""

from typing import Dict, Optional

import numpy as np


class ExponentialRunningMeanStd:
    def __init__(self, beta: float = 0.99995, epsilon: float = 1e-5):
        self.beta = float(beta)
        self.eps = float(epsilon)
        self.reset()

    def reset(self):
        self._mean = 0.0
        self._mean_sq = 0.0
        self._debias = 0.0

    def update(self, x: np.ndarray, mask: Optional[np.ndarray] = None):
        x = np.asarray(x, np.float64)
        if mask is not None:
            mask = np.asarray(mask, np.float64)
            denom = mask.sum()
            if denom == 0:
                return
            bm = float((x * mask).sum() / denom)
            bmsq = float((np.square(x) * mask).sum() / denom)
        else:
            bm = float(x.mean())
            bmsq = float(np.square(x).mean())
        self.update_moments(bm, bmsq, 1.0)

    def update_moments(self, mean: float, mean_sq: float, count: float):
        """Update from precomputed batch moments — the entry point for
        sharded data dispatch, where the batch mean/mean² come from an
        exact in-mesh reduction (TrainEngine.masked_moments) instead of
        host arrays that are zero-filled for other members' rows."""
        if count <= 0:
            return
        self._mean = self.beta * self._mean + (1.0 - self.beta) * mean
        self._mean_sq = self.beta * self._mean_sq + (1.0 - self.beta) * mean_sq
        self._debias = self.beta * self._debias + (1.0 - self.beta)

    def mean_std(self):
        if self._debias == 0.0:
            return 0.0, 1.0
        m = self._mean / self._debias
        var = max(self._mean_sq / self._debias - m * m, 0.0)
        return m, float(np.sqrt(var + self.eps))

    def normalize(self, x: np.ndarray) -> np.ndarray:
        m, s = self.mean_std()
        return ((np.asarray(x, np.float64) - m) / s).astype(np.float32)

    def denormalize(self, x: np.ndarray) -> np.ndarray:
        m, s = self.mean_std()
        return (np.asarray(x, np.float64) * s + m).astype(np.float32)

    def state_dict(self) -> Dict[str, float]:
        return {
            "mean": self._mean,
            "mean_sq": self._mean_sq,
            "debias": self._debias,
        }

    def load_state_dict(self, sd: Dict[str, float]):
        self._mean = float(sd["mean"])
        self._mean_sq = float(sd["mean_sq"])
        self._debias = float(sd["debias"])


class MovingAverageRunningMeanStd:
    """Unweighted all-history moments (value_norm_type="ma")."""

    def __init__(self, epsilon: float = 1e-5):
        self.eps = float(epsilon)
        self.reset()

    def reset(self):
        self._sum = 0.0
        self._sum_sq = 0.0
        self._count = 0.0

    def update(self, x: np.ndarray, mask: Optional[np.ndarray] = None):
        x = np.asarray(x, np.float64)
        if mask is not None:
            mask = np.asarray(mask, np.float64)
            self._sum += float((x * mask).sum())
            self._sum_sq += float((np.square(x) * mask).sum())
            self._count += float(mask.sum())
        else:
            self._sum += float(x.sum())
            self._sum_sq += float(np.square(x).sum())
            self._count += float(x.size)

    def update_moments(self, mean: float, mean_sq: float, count: float):
        """See ExponentialRunningMeanStd.update_moments."""
        if count <= 0:
            return
        self._sum += mean * count
        self._sum_sq += mean_sq * count
        self._count += count

    def mean_std(self):
        if self._count == 0.0:
            return 0.0, 1.0
        m = self._sum / self._count
        var = max(self._sum_sq / self._count - m * m, 0.0)
        return m, float(np.sqrt(var + self.eps))

    normalize = ExponentialRunningMeanStd.normalize
    denormalize = ExponentialRunningMeanStd.denormalize

    def state_dict(self) -> Dict[str, float]:
        return {
            "sum": self._sum, "sum_sq": self._sum_sq, "count": self._count
        }

    def load_state_dict(self, sd: Dict[str, float]):
        self._sum = float(sd["sum"])
        self._sum_sq = float(sd["sum_sq"])
        self._count = float(sd["count"])


def make_value_norm(kind: str, beta: float, eps: float):
    if kind == "exp":
        return ExponentialRunningMeanStd(beta=beta, epsilon=eps)
    if kind == "ma":
        return MovingAverageRunningMeanStd(epsilon=eps)
    raise ValueError(f"unknown value_norm_type {kind!r}")
