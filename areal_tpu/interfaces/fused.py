"""Fused inference interface: run several sub-interfaces in one MFC.

Capability parity: realhf/impl/model/interface/fused_interface.py
(`FusedThreadingForwardInterface`, registered "fused-threading") — the
reference fuses reward verification and reference-model inference into a
single MFC so the CPU-bound reward grading overlaps the device-bound ref
forward pass (ppo_math_exp.py:132-136).  Same shape here: each
sub-interface's `inference` runs on its own thread; JAX dispatch releases
the GIL while the TPU computes, so the math verifier's process pool grades
concurrently.

Results merge with `SequenceSample.update_` in sorted-name order (the
sub-interfaces produce disjoint keys, so order only matters for
determinism of error attribution).
"""

import dataclasses
from concurrent.futures import ThreadPoolExecutor
from typing import Any, Dict, Optional, Union

from areal_tpu.api.config import ModelInterfaceAbstraction
from areal_tpu.api.data_api import MicroBatchSpec, SequenceSample
from areal_tpu.api.model_api import (
    Model,
    ModelInterface,
    make_interface,
    register_interface,
)
from areal_tpu.base import logging

logger = logging.getLogger("fused")


class FusedThreadingInterface(ModelInterface):
    def __init__(
        self,
        interfaces: Dict[
            str, Union[ModelInterfaceAbstraction, Dict[str, Any]]
        ],
    ):
        self.sub_interfaces: Dict[str, ModelInterface] = {}
        for key, spec in interfaces.items():
            if isinstance(spec, dict):
                spec = ModelInterfaceAbstraction(
                    spec["type_"], spec.get("args", {})
                )
            self.sub_interfaces[key] = make_interface(
                spec.type_, **spec.args
            )

    def inference(
        self, model: Model, sample: SequenceSample, mb_spec: MicroBatchSpec
    ) -> Optional[SequenceSample]:
        def run_one(name: str):
            import time

            t0 = time.monotonic()
            res = self.sub_interfaces[name].inference(model, sample, mb_spec)
            logger.info(
                f"fused sub-interface {name} took {time.monotonic() - t0:.3f}s"
            )
            return res

        with ThreadPoolExecutor(
            max_workers=len(self.sub_interfaces)
        ) as pool:
            futures = {
                name: pool.submit(run_one, name)
                for name in self.sub_interfaces
            }
            results = {
                name: fut.result() for name, fut in sorted(futures.items())
            }

        merged: Optional[SequenceSample] = None
        for name in sorted(results):
            res = results[name]
            if res is None:
                continue
            if merged is None:
                merged = res
            else:
                merged.update_(res)
        return merged


register_interface("fused", FusedThreadingInterface)
