"""SFT algorithm interface.

Capability parity: realhf/impl/model/interface/sft_interface.py — packed
cross-entropy over answer tokens, save as HF checkpoint, eval loss.
"""

from typing import Dict

from areal_tpu.api.data_api import MicroBatchSpec, SequenceSample
from areal_tpu.api.model_api import Model, ModelInterface, register_interface
from areal_tpu.base import logging
from areal_tpu.ops import functional as F

logger = logging.getLogger("sft")


class SFTInterface(ModelInterface):
    def train_step(
        self, model: Model, sample: SequenceSample, mb_spec: MicroBatchSpec
    ) -> Dict[str, float]:
        stats = model.engine.train_batch(
            sample,
            mb_spec,
            loss_fn=F.sft_loss,
            loss_weight_fn=F.sft_label_count,
            token_key="packed_input_ids",
            extra_keys=("prompt_mask",),
            version_steps=model.version,
        )
        model.inc_version()
        return stats

    def evaluate(self, model: Model, eval_dataloader) -> Dict[str, float]:
        import numpy as np

        losses, counts = [], []
        for batch in eval_dataloader:
            out = model.engine.forward(
                batch,
                MicroBatchSpec(),
                post_fn=_eval_nll_post,
                output_key="nll",
                token_key="packed_input_ids",
                extra_keys=("prompt_mask",),
            )
            nll = out.data["nll"]
            losses.append(float(np.sum(nll)))
            counts.append(float(np.sum(nll != 0)))
        total_n = max(sum(counts), 1.0)
        return {"eval_nll": sum(losses) / total_n}

    def save(self, model: Model, save_dir: str) -> None:
        from areal_tpu.models.hf import registry as hf

        # Host conversion happens inside save_hf_checkpoint (collective for
        # process-spanning params; only jax process 0 writes files).
        hf.save_hf_checkpoint(
            save_dir, model.config, model.engine.get_params(),
            model_type=hf.infer_model_type(model.config),
            tokenizer=model.tokenizer,
        )
        logger.info(f"saved SFT checkpoint to {save_dir}")


def _eval_nll_post(logp, batch):
    import jax.numpy as jnp

    seg = batch["segment_ids"]
    label_is_prompt = jnp.pad(
        batch["prompt_mask"][:, 1:], ((0, 0), (0, 1)), constant_values=True
    )
    mask = F.shifted_label_mask(seg) & (~label_is_prompt)
    return jnp.where(mask, -logp, 0.0)


register_interface("sft", SFTInterface)
