"""PPO / GRPO actor+critic algorithm interfaces.

Capability parity: realhf/impl/model/interface/ppo_interface.py
(`PPOActorInterface` :234-723, `PPOCriticInterface` :873) and
utils/ppo_functional.py (clipped losses, `get_packed_rewards`, KL control):

- generate: group sampling via the GeneratorEngine
- inference: recompute token logprobs (actor) / values (critic)
- train_step: KL rewards + terminal reward -> GAE (associative-scan kernel)
  or GRPO group-normalized advantages (`disable_value`), advantage
  normalization (global or per-group), minibatched clipped-PPO updates.

Alignment convention (established by the generator): every per-token key is
full-sequence-length aligned with packed_input_ids; index t carries the
quantity for predicting token t+1 (entries at t = L-1 are unused).
"""

import dataclasses
from typing import Dict, Optional, Tuple

import jax.numpy as jnp
import numpy as np

from areal_tpu.api.data_api import MicroBatchSpec, SequenceSample
from areal_tpu.api.model_api import (
    GenerationHyperparameters,
    Model,
    ModelInterface,
    register_interface,
)
from areal_tpu.base import integrity, logging
from areal_tpu.base.stats import merge_stats
from areal_tpu.ops import functional as F
from areal_tpu.ops.gae import gae_packed

logger = logging.getLogger("ppo")


# ---------------- jit loss fns (module-level: stable cache keys) ----------------


def _ppo_actor_loss_factory(
    eps_clip: float, behav_imp_weight_cap: Optional[float] = None
):
    """With `behav_imp_weight_cap` set, this is the DECOUPLED PPO objective
    (reference: ppo_functional.actor_loss_fn `proximal_logprobs` branch +
    arxiv 2505.24298 §4.2): the proximal policy (recomputed under the
    weights at train-step start) anchors the clipped ratio, while the
    behavior policy (the generator that sampled the tokens, possibly
    several versions old) enters as an importance weight
    exp(prox_logp - old_logp) on the per-token loss.  Tokens whose
    behavior weight exceeds the cap are masked out entirely — the
    variance-control rule AReaL uses instead of truncating the weight."""
    decoupled = behav_imp_weight_cap is not None

    def loss_fn(new_logp, batch):
        # `new_logp`: the engine's fused per-token next-token logprobs [B,S].
        mask = batch["loss_mask"] > 0
        old_logp = batch["old_logp"]
        adv = batch["advantages"]
        prox_logp = batch["prox_logp"] if decoupled else old_logp
        ratio = jnp.exp(jnp.where(mask, new_logp - prox_logp, 0.0))
        clipped = jnp.clip(ratio, 1.0 - eps_clip, 1.0 + eps_clip)
        pg = -jnp.minimum(ratio * adv, clipped * adv)
        stats = {}
        if decoupled:
            behav = jnp.exp(jnp.where(mask, prox_logp - old_logp, 0.0))
            capped = mask & (behav > behav_imp_weight_cap)
            pg = pg * jnp.where(capped, 0.0, behav)
            stats["behav_imp_weight_sum"] = jnp.where(mask, behav, 0.0).sum()
            stats["behav_cap_clip_sum"] = capped.sum().astype(jnp.float32)
        loss = jnp.where(mask, pg, 0.0).sum()
        n_clipped = (
            jnp.where(mask, (ratio * adv > clipped * adv), False)
        ).sum()
        approx_kl = jnp.where(mask, old_logp - new_logp, 0.0).sum()
        stats.update(
            actor_loss_sum=loss,
            importance_weight_sum=jnp.where(mask, ratio, 0.0).sum(),
            clip_ratio_sum=n_clipped.astype(jnp.float32),
            approx_kl_sum=approx_kl,
            # |adv| rides the device stats (not host numpy) so the value
            # is exact under sharded dispatch, where host arrays are
            # zero-filled for other members' rows but the placed batch is
            # globally real.
            advantage_abs_sum=jnp.where(mask, jnp.abs(adv), 0.0).sum(),
        )
        return loss, stats

    return loss_fn


def _ppo_critic_loss_factory(value_eps_clip: float):
    def loss_fn(values, batch):
        # `values` comes from the critic head: [B, S] fp32.
        mask = batch["loss_mask"] > 0
        old_v = batch["old_values"]
        ret = batch["returns"]
        v_clip = old_v + jnp.clip(
            values - old_v, -value_eps_clip, value_eps_clip
        )
        l1 = jnp.square(values - ret)
        l2 = jnp.square(v_clip - ret)
        loss = 0.5 * jnp.where(mask, jnp.maximum(l1, l2), 0.0).sum()
        return loss, {
            "value_loss_sum": loss,
            "value_clip_ratio_sum": jnp.where(mask, l2 > l1, False)
            .sum()
            .astype(jnp.float32),
        }

    return loss_fn


def _logprob_post(logp, batch):
    return logp  # engines already emit masked next-token logprobs [B, S]


def _value_post(values, batch):
    return jnp.where(batch["segment_ids"] > 0, values, 0.0)


def _mask_count(arrays) -> float:
    return float((arrays["loss_mask"] > 0).sum())


# ---------------- shared host-side plumbing ----------------


def _extract_layout(sample: SequenceSample):
    """Per-sequence (start, L, prompt_len, group_idx) from the packed batch."""
    lens = sample.seqlens_of("packed_input_ids")
    bounds = sample.cu_seqlens("packed_input_ids")
    pmask = np.asarray(sample.data["prompt_mask"])
    layout = []
    for i, L in enumerate(lens):
        s = bounds[i]
        pl = int(pmask[s : s + L].sum())
        layout.append((int(s), int(L), pl))
    # group index per sequence (batch element owning it)
    group_of = []
    for gi, group in enumerate(sample.seqlens["packed_input_ids"]):
        group_of += [gi] * len(group)
    return layout, group_of


def _seq_align_minus1(sample: SequenceSample, key: str) -> np.ndarray:
    """Re-align a (L-1)-per-seq key to full length L (trailing zero)."""
    src = np.asarray(sample.data[key])
    sb = sample.cu_seqlens(key)
    lens = sample.seqlens_of("packed_input_ids")
    out = np.zeros(sum(lens), np.float32)
    off = 0
    for i, L in enumerate(lens):
        seg = src[sb[i] : sb[i + 1]]
        out[off : off + len(seg)] = seg
        off += L
    return out


def _add_aligned_keys(sample: SequenceSample, arrays: Dict[str, np.ndarray]):
    seqlens = [list(s) for s in sample.seqlens["packed_input_ids"]]
    add = SequenceSample(
        keys=set(arrays.keys()),
        ids=list(sample.ids),
        seqlens={k: [list(s) for s in seqlens] for k in arrays},
        data=dict(arrays),
    )
    sample.update_(add)


def _select_group_seqs(sample: SequenceSample, keep) -> SequenceSample:
    """Rebuild a packed sample keeping only sequences `keep[gi]` (indices
    into each group) for every key carrying one entry per group sequence.
    Keys with a different per-group arity (e.g. a single prompt per group)
    pass through whole.  Host-side slicing — used once per train step by
    best-of-k selection."""
    k = max(len(g) for g in sample.seqlens["packed_input_ids"])
    new_seqlens: Dict[str, list] = {}
    new_data: Dict[str, np.ndarray] = {}
    for key in sample.keys:
        sl = sample.seqlens[key]
        bounds = sample.cu_seqlens(key)
        arr = np.asarray(sample.data[key])
        slices, new_sl = [], []
        si = 0
        for gi, group in enumerate(sl):
            idxs = keep[gi] if len(group) == k else range(len(group))
            new_sl.append([group[j] for j in idxs])
            for j in idxs:
                slices.append((int(bounds[si + j]), int(bounds[si + j + 1])))
            si += len(group)
        new_data[key] = (
            np.concatenate([arr[a:b] for a, b in slices])
            if slices
            else arr[:0]
        )
        new_seqlens[key] = new_sl
    return SequenceSample(
        keys=set(sample.keys),
        ids=list(sample.ids),
        seqlens=new_seqlens,
        data=new_data,
        # Same ids in the same order: per-id metadata — crucially the
        # shard_of tags that keep the batch on the sharded-dispatch
        # statistics path — carries over verbatim.
        metadata={k: list(v) for k, v in sample.metadata.items()},
    )


@dataclasses.dataclass
class PPOActorInterface(ModelInterface):
    """Reference defaults follow blog/AReaL_v0_2.md:85-103."""

    gconfig: GenerationHyperparameters = dataclasses.field(
        default_factory=GenerationHyperparameters
    )
    n_minibatches: int = 4
    eps_clip: float = 0.2
    kl_ctl: float = 0.0
    # Adaptive KL control (reference: ppo_functional.py AdaptiveKLController,
    # enabled by ppo_interface.py adaptive_kl_ctl): `kl_ctl` becomes the
    # INITIAL coefficient and drifts to hold the measured policy↔ref KL at
    # `adaptive_kl_target` (interfaces/kl.py).  The live value rides recover
    # checkpoints via state_dict.
    kl_adaptive: bool = False
    adaptive_kl_target: float = 6.0
    adaptive_kl_horizon: float = 10000.0
    # Best-of-k (reference: ppo_interface.py generation_size vs group_size):
    # sample `generation_size` responses per prompt but train on only the
    # top `gconfig.n` by reward (ties broken toward longer responses).
    generation_size: Optional[int] = None
    discount: float = 1.0
    gae_lambda: float = 1.0
    max_reward_clip: float = 5.0
    reward_scaling: float = 1.0
    reward_bias: float = 0.0
    # Early stopping (reference: ppo_interface.py early_stop_imp_ratio /
    # early_stop_kl, checked inside the loss fn): when a minibatch's mean
    # importance ratio or approx-KL crosses the threshold, the REMAINING
    # minibatches of this step are skipped — the policy has drifted too
    # far off the behavior policy for more clipped updates to be sound.
    # (The reference aborts before applying the offending minibatch; the
    # fused jitted update here applies it, then stops.)
    early_stop_imp_ratio: Optional[float] = None  # e.g. 10.0
    early_stop_kl: Optional[float] = None  # e.g. 0.1
    disable_value: bool = False  # GRPO mode
    adv_norm: bool = True
    group_adv_norm: bool = False
    mask_no_eos_with_zero: bool = False
    # Per-token rewards (reference: ppo_interface.py use_dense_reward +
    # get_packed_reward_dense): read key "dense_rewards" (one score per
    # token, aligned with packed_input_ids) instead of a terminal scalar;
    # reward_delta uses consecutive-score differences (potential shaping).
    use_dense_reward: bool = False
    reward_delta: bool = True
    # Decoupled PPO for asynchronous RL (reference: ppo_functional.py
    # `proximal_logprobs` + behav_imp_weight_cap): when set, the proximal
    # policy is recomputed under the CURRENT weights at train-step start
    # and anchors the clipped ratio; the behavior (generator) logprobs
    # enter as an importance weight capped at this value (tokens above
    # the cap are masked out).  None = standard PPO — exactly today's
    # numerics, which is what `max_head_offpolicyness=0` configures.
    behav_imp_weight_cap: Optional[float] = None
    # Batch-level anomaly sentinels (numerical-integrity guard plane),
    # evaluated on host statistics BEFORE any gradient work is
    # dispatched — unlike early_stop_*, which reacts to per-minibatch
    # training stats, these reject the whole batch as unsound input:
    #   anomaly_kl_max: mean |logp - ref_logp| over response tokens
    #     above this -> KL blowup, quarantine the step;
    #   anomaly_imp_ratio_max R > 1: mean behavior importance weight
    #     exp(prox_logp - old_logp) outside [1/R, R] -> the behavior
    #     policy is too stale for clipped updates (decoupled PPO only);
    #   anomaly_degenerate_variance: every GRPO group's scores have
    #     zero variance -> all advantages are 0/eps noise (a poisoned or
    #     saturated reward).  Off by default: tiny eval trials with
    #     constant rewards are routine.
    # A tripped sentinel quarantines the step: the barrier path skips
    # all minibatches; the streamed path stops accumulating and forces
    # the engine to discard partial grads at train_stream_end.
    anomaly_kl_max: Optional[float] = None
    anomaly_imp_ratio_max: Optional[float] = None
    anomaly_degenerate_variance: bool = False

    def _batch_verdict(self, aux) -> int:
        """OR of interface-level verdict bits for this batch (0 = clean).

        Host-side means under sharded dispatch are computed over this
        member's own rows only — every member sees the same broadcast
        per-seq keys, and per-token anomalies large enough to matter
        dominate any single shard's mean, so the verdict stays
        SPMD-consistent in practice for the blowup thresholds it guards.
        """
        v = 0
        if (
            self.anomaly_kl_max is not None
            and aux.get("kl_abs_mean") is not None
            and aux["kl_abs_mean"] > self.anomaly_kl_max
        ):
            v |= integrity.KL_BLOWUP
        if (
            self.anomaly_imp_ratio_max is not None
            and aux.get("behav_imp_mean") is not None
        ):
            r = aux["behav_imp_mean"]
            cap = self.anomaly_imp_ratio_max
            if not (1.0 / cap <= r <= cap):
                v |= integrity.IMP_RATIO
        if self.anomaly_degenerate_variance and aux.get("degenerate_var"):
            v |= integrity.DEGENERATE_VAR
        return v

    def _kl(self):
        if getattr(self, "_kl_inst", None) is None:
            from areal_tpu.interfaces.kl import make_kl_controller

            object.__setattr__(
                self,
                "_kl_inst",
                make_kl_controller(
                    self.kl_ctl,
                    self.kl_adaptive,
                    self.adaptive_kl_target,
                    self.adaptive_kl_horizon,
                ),
            )
        return self._kl_inst

    def state_dict(self) -> Dict[str, float]:
        return self._kl().state_dict() if self.kl_adaptive else {}

    def load_state_dict(self, sd) -> None:
        if self.kl_adaptive and sd:
            self._kl().load_state_dict(sd)

    def generate(
        self, model: Model, sample: SequenceSample, mb_spec: MicroBatchSpec
    ) -> SequenceSample:
        g = self.gconfig
        if self.generation_size is not None:
            if self.generation_size < g.n:
                raise ValueError(
                    f"generation_size={self.generation_size} must be >= "
                    f"group size n={g.n}"
                )
            g = dataclasses.replace(g, n=self.generation_size)
        return model.engine.generate(
            sample, mb_spec, g, prompt_key="packed_prompts",
            seed=model.version,
        )

    def inference(
        self, model: Model, sample: SequenceSample, mb_spec: MicroBatchSpec
    ) -> SequenceSample:
        out = model.engine.forward(
            sample, mb_spec, post_fn=_logprob_post, output_key="logprobs",
            token_key="packed_input_ids",
        )
        return out

    def _filter_best_of_k(self, sample: SequenceSample) -> SequenceSample:
        """Keep the top `gconfig.n` of `generation_size` responses per
        prompt by reward, ties toward longer responses (reference topk,
        ppo_interface.py:43-48).  Runs before any advantage math so GRPO
        groups and GAE windows see only the kept sequences."""
        scores = np.asarray(sample.data["rewards"], np.float32)
        layout, _ = _extract_layout(sample)
        keep = []
        si = 0
        for group in sample.seqlens["packed_input_ids"]:
            k = len(group)
            resp_lens = [
                layout[si + j][1] - layout[si + j][2] for j in range(k)
            ]
            order = sorted(
                range(k),
                key=lambda j: (scores[si + j], resp_lens[j]),
                reverse=True,
            )[: self.gconfig.n]
            keep.append(sorted(order))
            si += k
        return _select_group_seqs(sample, keep)

    def _prepare_train_sample(
        self, model: Model, sample: SequenceSample, mb_spec: MicroBatchSpec
    ):
        """Everything before the minibatch loop: best-of-k filtering,
        KL-shaped rewards, GAE or GRPO advantages, advantage
        normalization, and the packed train sample with aligned keys.

        Shared by the barrier `train_step` (whole batch) and the
        streamed `train_stream_chunk` (one retired rollout chunk at a
        time); in the streamed case batch-global statistics (advantage
        moments for adv_norm, the ref-KL term) are computed over the
        chunk — the streaming per-micro-batch form of the estimator.
        GRPO group normalization is group-local either way, so it is
        exact under streaming as long as chunks respect group bounds.

        Returns (train_sample, extra_keys, aux)."""
        if (
            self.generation_size is not None
            and self.generation_size > self.gconfig.n
        ):
            sample = self._filter_best_of_k(sample)
        klv = self._kl().value
        # Sharded data plane: heavy per-token inputs hold real values only
        # for this member's own rows (layout metadata and per-seq keys are
        # global).  Per-row math below stays SPMD-consistent — loss_mask
        # and total weight derive from layout, GRPO group stats from
        # broadcast per-seq scores, per-token arrays are only consumed by
        # the rows' own devices.  Batch-GLOBAL statistics (advantage
        # moments for adv_norm, the policy↔ref KL for the stat and the
        # adaptive controller) cannot come from these host arrays; they
        # are computed by an exact in-mesh reduction over the placed
        # arrays instead (TrainEngine.masked_moments) — identical on
        # every member, so adaptive kl_ctl stays in lockstep.
        sharded = sample.shard_blocks() is not None
        layout, group_of = _extract_layout(sample)
        total = sum(L for (_, L, _) in layout)

        # --- behavior logprobs, ref logprobs, values: full-length aligned
        old_logp = _seq_align_minus1(sample, "packed_logprobs")
        # Decoupled PPO: one extra forward pass under the CURRENT weights
        # gives the proximal logprobs.  Runs before any update so all
        # minibatches share the same anchor (reference recomputes in the
        # inference MFC; here train_step owns it so the sync path pays
        # nothing when the cap is unset).
        prox_logp = None
        if self.behav_imp_weight_cap is not None:
            prox_out = model.engine.forward(
                sample.select_keys({"packed_input_ids"}),
                mb_spec,
                post_fn=_logprob_post,
                output_key="prox_logp",
                token_key="packed_input_ids",
            )
            prox_logp = np.asarray(
                prox_out.data["prox_logp"], np.float32
            )
        ref_logp = (
            _seq_align_minus1(sample, "packed_ref_logprobs")
            if "packed_ref_logprobs" in sample.keys
            else None
        )
        values = (
            np.asarray(sample.data["values"], np.float32)
            if "values" in sample.keys
            else np.zeros(total, np.float32)
        )
        scores = np.asarray(sample.data["rewards"], np.float32).copy()
        scores = np.clip(
            (scores + self.reward_bias) * self.reward_scaling,
            -self.max_reward_clip,
            self.max_reward_clip,
        )
        no_eos = np.asarray(sample.data["seq_no_eos_mask"], np.float32)
        if self.mask_no_eos_with_zero:
            scores = scores * (1.0 - no_eos)

        # --- per-token rewards on predict positions t in [pl-1, L-2]
        rewards = np.zeros(total, np.float32)
        loss_mask = np.zeros(total, np.float32)
        adv_full = np.zeros(total, np.float32)
        if ref_logp is not None and klv != 0.0:
            rewards -= klv * (old_logp - ref_logp)

        dense = None
        if self.use_dense_reward:
            if self.disable_value:
                raise ValueError(
                    "use_dense_reward requires the value (critic) mode — "
                    "GRPO group advantages are defined on scalar scores"
                )
            if "dense_rewards" not in sample.keys:
                raise ValueError(
                    "use_dense_reward needs a 'dense_rewards' key (one "
                    "score per token, aligned with packed_input_ids)"
                )
            dense = np.asarray(sample.data["dense_rewards"], np.float32)
            if len(dense) != total:
                raise ValueError(
                    f"dense_rewards must align with packed_input_ids: got "
                    f"{len(dense)} scores for {total} tokens"
                )
            # Same transform as scalar scores (bias/scale/clip); no-EOS
            # masking zeroes the whole truncated sequence's rewards.
            dense = np.clip(
                (dense + self.reward_bias) * self.reward_scaling,
                -self.max_reward_clip,
                self.max_reward_clip,
            )

        seq_slices = []
        for si, (s, L, pl) in enumerate(layout):
            lo, hi = s + max(pl - 1, 0), s + L - 1  # predict positions
            loss_mask[lo:hi] = 1.0
            if dense is not None:
                # Transition t (predicting token t+1) earns token t+1's
                # score — or the score DELTA (potential-based shaping) when
                # reward_delta (reference: get_packed_reward_dense).
                gain = dense[lo + 1 : hi + 1]
                if self.reward_delta:
                    gain = gain - dense[lo:hi]
                if self.mask_no_eos_with_zero:
                    gain = gain * (1.0 - no_eos[si])
                rewards[lo:hi] += gain
            else:
                rewards[hi - 1] += scores[si] if hi > lo else 0.0
            seq_slices.append((lo, hi))
        rewards *= loss_mask

        degenerate_var = None
        if self.disable_value:
            # GRPO: group-normalized terminal score broadcast over response.
            adv_seq = np.zeros(len(layout), np.float32)
            groups: Dict[int, list] = {}
            for si in range(len(layout)):
                groups.setdefault(group_of[si], []).append(si)
            degenerate_var = len(groups) > 0
            for gi, sis in groups.items():
                g_scores = scores[sis]
                mean = g_scores.mean()
                std = g_scores.std()
                if std > 0:
                    degenerate_var = False
                adv_seq[sis] = (g_scores - mean) / (std + 1e-5)
            for si, (lo, hi) in enumerate(seq_slices):
                adv_full[lo:hi] = adv_seq[si]
                # KL penalty still contributes per-token if configured.
            if ref_logp is not None and klv != 0.0:
                adv_full += -klv * (old_logp - ref_logp) * loss_mask
        else:
            # Pack response-only windows for GAE.
            r_parts, v_parts, seg_parts, boot_parts, lens_resp = (
                [], [], [], [], []
            )
            for si, (lo, hi) in enumerate(seq_slices):
                n = hi - lo
                if n == 0:
                    lens_resp.append(0)
                    continue
                r_parts.append(rewards[lo:hi])
                v_parts.append(values[lo:hi])
                seg_parts.append(np.full(n, si + 1, np.int32))
                b = np.zeros(n, np.float32)
                _, L, _ = layout[si]
                b[-1] = no_eos[si] * values[layout[si][0] + L - 1]
                boot_parts.append(b)
                lens_resp.append(n)
            if r_parts:
                r1 = np.concatenate(r_parts)
                adv1, ret1 = gae_packed(
                    jnp.asarray(r1),
                    jnp.asarray(np.concatenate(v_parts)),
                    jnp.asarray(np.concatenate(seg_parts)),
                    jnp.asarray(np.concatenate(boot_parts)),
                    self.discount,
                    self.gae_lambda,
                )
                adv1 = np.asarray(adv1)
                off = 0
                for si, (lo, hi) in enumerate(seq_slices):
                    n = hi - lo
                    adv_full[lo:hi] = adv1[off : off + n]
                    off += n

        # Batch-global moments: under sharded dispatch, reduce on device
        # (one cheap extra placement of [adv, klterm, mask]); otherwise
        # host numpy.  ref_kl uses the same pass — computed here, the
        # controller update stays at its reference timing (post-update
        # loop, ppo_interface.py:105).
        ref_kl = None
        batch_norm = self.adv_norm and not (
            self.group_adv_norm and not self.disable_value
        )
        if sharded and (batch_norm or ref_logp is not None):
            probe = sample.select_keys({"packed_input_ids"})
            arrays = {"loss_mask": loss_mask}
            vkeys = []
            if batch_norm:
                arrays["adv_probe"] = adv_full
                vkeys.append("adv_probe")
            if ref_logp is not None:
                arrays["klterm"] = (old_logp - ref_logp) * loss_mask
                vkeys.append("klterm")
            _add_aligned_keys(probe, arrays)
            mom = model.engine.masked_moments(
                probe, mb_spec, vkeys, mask_key="loss_mask"
            )
            cnt = mom["count"]
            if batch_norm and cnt > 0:
                s, ssq, _ = mom["adv_probe"]
                mean = s / cnt
                std = float(np.sqrt(max(ssq / cnt - mean * mean, 0.0)))
                m = loss_mask > 0
                adv_full[m] = (adv_full[m] - mean) / (std + 1e-5)
            if ref_logp is not None and cnt > 0:
                ref_kl = float(mom["klterm"][0] / cnt)
        if self.adv_norm:
            m = loss_mask > 0
            if not batch_norm:
                # group_adv_norm is row-local (a group is one batch
                # element, never split across shards): each member
                # normalizes with its own rows' real data; garbage
                # normalizations of other members' zero-filled rows are
                # never consumed by their devices.
                for gi in set(group_of):
                    gm = np.zeros_like(m)
                    for si, (lo, hi) in enumerate(seq_slices):
                        if group_of[si] == gi:
                            gm[lo:hi] = m[lo:hi]
                    if gm.any():
                        vals = adv_full[gm]
                        adv_full[gm] = (vals - vals.mean()) / (
                            vals.std() + 1e-5
                        )
            elif not sharded and m.any():
                vals = adv_full[m]
                adv_full[m] = (vals - vals.mean()) / (vals.std() + 1e-5)
            # (sharded batch_norm already applied from device moments)

        train_sample = sample.select_keys(
            {"packed_input_ids", "prompt_mask"}
        )
        aligned = {
            "old_logp": old_logp,
            "advantages": adv_full,
            "loss_mask": loss_mask,
        }
        extra_keys = ("old_logp", "advantages", "loss_mask")
        if prox_logp is not None:
            aligned["prox_logp"] = prox_logp
            extra_keys = extra_keys + ("prox_logp",)
        _add_aligned_keys(train_sample, aligned)
        # Sentinel inputs (host means over this member's rows).
        mt = float(loss_mask.sum())
        kl_abs_mean = None
        if ref_logp is not None and mt > 0:
            kl_abs_mean = float(
                (np.abs(old_logp - ref_logp) * loss_mask).sum() / mt
            )
        behav_imp_mean = None
        if prox_logp is not None and mt > 0:
            behav_imp_mean = float(
                (np.exp((prox_logp - old_logp) * loss_mask) * loss_mask).sum()
                / mt
            )
        aux = {
            "klv": klv,
            "n_seqs": len(layout),
            "loss_mask": loss_mask,
            "old_logp": old_logp,
            "ref_logp": ref_logp,
            "scores": scores,
            "no_eos": no_eos,
            "ref_kl": ref_kl,
            "kl_abs_mean": kl_abs_mean,
            "behav_imp_mean": behav_imp_mean,
            "degenerate_var": degenerate_var,
        }
        return train_sample, extra_keys, aux

    def train_step(
        self, model: Model, sample: SequenceSample, mb_spec: MicroBatchSpec
    ) -> Dict[str, float]:
        train_sample, extra_keys, aux = self._prepare_train_sample(
            model, sample, mb_spec
        )
        loss_mask = aux["loss_mask"]
        old_logp, ref_logp = aux["old_logp"], aux["ref_logp"]

        verdict = self._batch_verdict(aux)
        if verdict:
            # Quarantine BEFORE any gradient dispatch: no minibatch of
            # this batch touches the optimizer; the master records a
            # skipped step (and escalates to rollback on a streak).
            integrity.record_anomaly(verdict)
            logger.warning(
                "batch sentinel quarantined train step: "
                f"{integrity.verdict_kinds(verdict)} "
                f"(kl_abs_mean={aux['kl_abs_mean']} "
                f"behav_imp_mean={aux['behav_imp_mean']} "
                f"degenerate_var={aux['degenerate_var']})"
            )
            model.inc_version()
            return {
                "anomaly_verdict": float(verdict),
                "quarantined": 1.0,
                "task_reward": float(aux["scores"].mean()),
                "no_eos_ratio": float(aux["no_eos"].mean()),
                "n_response_tokens": float(loss_mask.sum()),
                "kl_ctl_value": aux["klv"],
                "n_minibatches_skipped": float(
                    min(self.n_minibatches, train_sample.bs)
                ),
            }

        loss_fn = self._get_loss_fn()
        all_stats = []
        n_skipped = 0
        mbs_list = train_sample.split_balanced(
            min(self.n_minibatches, train_sample.bs)
        )
        for mi, mb in enumerate(mbs_list):
            stats = model.engine.train_batch(
                mb,
                mb_spec,
                loss_fn=loss_fn,
                loss_weight_fn=_mask_count,
                token_key="packed_input_ids",
                extra_keys=extra_keys,
                version_steps=model.version,
            )
            all_stats.append(stats)
            imp = stats.get("importance_weight", 1.0)
            akl = abs(stats.get("approx_kl", 0.0))
            if (
                self.early_stop_imp_ratio is not None
                and imp > self.early_stop_imp_ratio
            ) or (
                self.early_stop_kl is not None and akl > self.early_stop_kl
            ):
                n_skipped = len(mbs_list) - (mi + 1)
                logger.warning(
                    f"early stop after minibatch {mi + 1}/{len(mbs_list)}: "
                    f"importance_weight={imp:.3f} approx_kl={akl:.4f} "
                    f"(thresholds {self.early_stop_imp_ratio}/"
                    f"{self.early_stop_kl}); skipping {n_skipped} minibatches"
                )
                break
        model.inc_version()

        out = {
            k: float(np.mean([s[k] for s in all_stats]))
            for k in all_stats[0]
        }
        # Adaptive KL control: steer next step's coefficient by this
        # batch's measured policy↔ref KL (reference updates inside the loss
        # fn with the same post-reward timing, ppo_interface.py:105).
        # Under sharded dispatch ref_kl was already device-reduced above
        # (exact + identical on every member, so the controller cannot
        # drift across the SPMD group); the host formula here would be
        # understated ~1/n_shards by the zero-filled rows.
        ref_kl = aux["ref_kl"]
        if ref_kl is None:
            ref_kl = 0.0
            if ref_logp is not None and loss_mask.sum() > 0:
                ref_kl = float(
                    ((old_logp - ref_logp) * loss_mask).sum()
                    / loss_mask.sum()
                )
        if ref_logp is not None and loss_mask.sum() > 0:
            self._kl().update(ref_kl, n_steps=aux["n_seqs"])

        out.update(
            task_reward=float(aux["scores"].mean()),
            no_eos_ratio=float(aux["no_eos"].mean()),
            # advantage_abs arrives from the jitted loss stats (exact
            # under sharding); out already carries it.
            n_response_tokens=float(loss_mask.sum()),
            kl_ctl_value=aux["klv"],
            ref_kl=ref_kl,
            n_minibatches_skipped=float(n_skipped),
        )
        return out

    # ------------- streamed (pipeline-overlapped) train -------------

    def train_stream_begin(
        self, model: Model, mb_spec: MicroBatchSpec
    ) -> Dict:
        """Open a pipeline-overlapped train stream.

        Chunks arrive via `train_stream_chunk` as their rollout groups
        retire from generation; advantages (and their normalization
        moments) are computed chunk-locally and grads accumulate into
        the engine's donated sum.  The single optimizer step fires in
        `train_stream_end`.  Overlap-off (in-flight window = 1) never
        reaches this path — the master dispatches window-1 steps
        through the unchanged barrier `train_step`, which is the
        bit-exactness guarantee.
        """
        return {
            "engine": model.engine.train_stream_begin(),
            "chunk_stats": [],
            "kl_num": 0.0,
            "kl_den": 0.0,
            "n_seqs": 0,
            "score_sum": 0.0,
            "score_n": 0,
            "no_eos_sum": 0.0,
            "no_eos_n": 0,
            "resp_tokens": 0.0,
            "klv": self._kl().value,
            "stopped": False,
            "n_chunks_skipped": 0,
            # Batch-sentinel trip: stop accumulating AND force the
            # engine to discard the partial grad sum at stream end.
            "quarantine_verdict": 0,
        }

    def train_stream_chunk(
        self,
        model: Model,
        state: Dict,
        sample: SequenceSample,
        mb_spec: MicroBatchSpec,
    ) -> Dict[str, float]:
        """Advantages + grad accumulation for one retired rollout chunk.

        Returns the chunk's stats in `*_denominator`-weighted form so
        `merge_stats` recovers the token-weighted step means even when
        chunks carry uneven token counts."""
        if state["stopped"]:
            state["n_chunks_skipped"] += 1
            return {"n_chunks_skipped": 1.0}
        if sample.shard_blocks() is not None:
            raise ValueError(
                "pipeline overlap does not compose with shard-exact "
                "dispatch; chunk inputs must be broadcast"
            )
        train_sample, extra_keys, aux = self._prepare_train_sample(
            model, sample, mb_spec
        )
        verdict = self._batch_verdict(aux)
        if verdict:
            # Sentinel tripped mid-stream: this chunk never reaches the
            # engine, later chunks short-circuit via `stopped`, and the
            # whole step's partial grad sum is discarded at stream end.
            state["stopped"] = True
            state["quarantine_verdict"] |= verdict
            state["n_chunks_skipped"] += 1
            integrity.record_anomaly(verdict)
            logger.warning(
                "batch sentinel quarantined stream chunk "
                f"{len(state['chunk_stats']) + 1}: "
                f"{integrity.verdict_kinds(verdict)}; the step's "
                "accumulated gradient will be discarded"
            )
            return {
                "n_chunks_skipped": 1.0,
                "anomaly_verdict": float(verdict),
            }
        raw = model.engine.train_stream_chunk(
            state["engine"],
            train_sample,
            mb_spec,
            loss_fn=self._get_loss_fn(),
            loss_weight_fn=_mask_count,
            token_key="packed_input_ids",
            extra_keys=extra_keys,
            version_steps=model.version,
        )
        w = max(raw.pop("chunk_weight"), 1.0)
        loss_sum = raw.pop("chunk_loss_sum")
        raw.pop("chunk_micro_batches", None)
        stats: Dict[str, float] = {
            "loss": loss_sum / w,
            "loss_denominator": w,
        }
        for k, v in raw.items():
            base = k[: -len("_sum")] if k.endswith("_sum") else k
            stats[base] = v / w
            stats[base + "_denominator"] = w

        loss_mask = aux["loss_mask"]
        old_logp, ref_logp = aux["old_logp"], aux["ref_logp"]
        mt = float(loss_mask.sum())
        if ref_logp is not None and mt > 0:
            state["kl_num"] += float(
                ((old_logp - ref_logp) * loss_mask).sum()
            )
            state["kl_den"] += mt
        state["n_seqs"] += aux["n_seqs"]
        state["score_sum"] += float(aux["scores"].sum())
        state["score_n"] += len(aux["scores"])
        state["no_eos_sum"] += float(aux["no_eos"].sum())
        state["no_eos_n"] += len(aux["no_eos"])
        state["resp_tokens"] += mt
        state["chunk_stats"].append(stats)

        imp = stats.get("importance_weight", 1.0)
        akl = abs(stats.get("approx_kl", 0.0))
        if (
            self.early_stop_imp_ratio is not None
            and imp > self.early_stop_imp_ratio
        ) or (self.early_stop_kl is not None and akl > self.early_stop_kl):
            state["stopped"] = True
            logger.warning(
                f"early stop after stream chunk "
                f"{len(state['chunk_stats'])}: importance_weight="
                f"{imp:.3f} approx_kl={akl:.4f} (thresholds "
                f"{self.early_stop_imp_ratio}/{self.early_stop_kl}); "
                f"remaining chunks accumulate no gradient"
            )
        return stats

    def train_stream_end(
        self, model: Model, state: Dict, mb_spec: MicroBatchSpec
    ) -> Dict[str, float]:
        """One optimizer step over the streamed grad sum + merged stats."""
        verdict = int(state["quarantine_verdict"])
        if verdict and state["engine"]["acc"] is None:
            # Sentinel tripped before any chunk reached the engine:
            # there is no grad sum to discard and no optimizer step.
            eng_out: Dict[str, float] = {
                "grad_norm": 0.0,
                "update_norm": 0.0,
                "n_micro_batches": 0.0,
                "n_stream_chunks": 0.0,
            }
        else:
            eng_out = model.engine.train_stream_end(
                state["engine"], quarantine=bool(verdict)
            )
        model.inc_version()
        out = (
            merge_stats(state["chunk_stats"]) if state["chunk_stats"] else {}
        )
        # The engine's stream totals are authoritative for the keys both
        # report (they agree up to float reassociation).
        out.update(eng_out)
        if verdict:
            out["anomaly_verdict"] = float(
                int(out.get("anomaly_verdict", 0.0)) | verdict
            )
            out["quarantined"] = 1.0
        ref_kl = 0.0
        if state["kl_den"] > 0:
            ref_kl = state["kl_num"] / state["kl_den"]
            self._kl().update(ref_kl, n_steps=state["n_seqs"])
        out.update(
            task_reward=state["score_sum"] / max(state["score_n"], 1),
            no_eos_ratio=state["no_eos_sum"] / max(state["no_eos_n"], 1),
            n_response_tokens=state["resp_tokens"],
            kl_ctl_value=state["klv"],
            ref_kl=ref_kl,
            n_minibatches_skipped=float(state["n_chunks_skipped"]),
        )
        return out

    _loss_fn_cache = None

    def _get_loss_fn(self):
        if self._loss_fn_cache is None:
            object.__setattr__(
                self,
                "_loss_fn_cache",
                _ppo_actor_loss_factory(
                    self.eps_clip, self.behav_imp_weight_cap
                ),
            )
        return self._loss_fn_cache

    def save(self, model: Model, save_dir: str) -> None:
        from areal_tpu.interfaces.sft import SFTInterface

        SFTInterface().save(model, save_dir)


@dataclasses.dataclass
class PPOCriticInterface(ModelInterface):
    n_minibatches: int = 4
    value_eps_clip: float = 0.2
    discount: float = 1.0
    gae_lambda: float = 1.0
    max_reward_clip: float = 5.0
    kl_ctl: float = 0.0
    # Running-mean/std normalization of returns (reference:
    # ppo_interface.py:175-210 + modules/rms.py): the critic head learns
    # normalized targets; predictions are denormalized before GAE.
    value_norm: bool = False
    value_norm_type: str = "exp"  # "exp" | "ma"
    value_norm_beta: float = 0.99995
    value_norm_eps: float = 1e-5

    def _rms(self):
        if getattr(self, "_rms_inst", None) is None:
            from areal_tpu.interfaces.value_norm import make_value_norm

            object.__setattr__(
                self,
                "_rms_inst",
                make_value_norm(
                    self.value_norm_type,
                    self.value_norm_beta,
                    self.value_norm_eps,
                ),
            )
        return self._rms_inst

    def state_dict(self) -> Dict[str, float]:
        # Running moments ride recover checkpoints: a restored critic head
        # (trained on normalized targets) must keep its statistics or
        # inference denormalizes with the identity.
        return self._rms().state_dict() if self.value_norm else {}

    def load_state_dict(self, sd) -> None:
        if self.value_norm and sd:
            self._rms().load_state_dict(sd)

    def save(self, model: Model, save_dir: str) -> None:
        # Critic checkpoints (incl. the trained value head) roundtrip via
        # the HF registry — without this, value-mode recover restores a
        # fresh critic (the bug the recover test pins down).
        from areal_tpu.interfaces.sft import SFTInterface

        SFTInterface().save(model, save_dir)

    def inference(
        self, model: Model, sample: SequenceSample, mb_spec: MicroBatchSpec
    ) -> SequenceSample:
        out = model.engine.forward(
            sample, mb_spec, post_fn=_value_post, output_key="values",
            token_key="packed_input_ids",
        )
        if self.value_norm:
            # Head outputs live in normalized-return space; hand real-scale
            # values to the consumers (actor GAE, our own train_step).
            out.data["values"] = self._rms().denormalize(
                np.asarray(out.data["values"], np.float32)
            )
        return out

    def _prepare_train_sample(
        self, model: Model, sample: SequenceSample, mb_spec: MicroBatchSpec
    ) -> SequenceSample:
        """KL-shaped rewards → GAE returns → (optional) value-norm →
        packed train sample.  Shared by the barrier `train_step` and the
        streamed `train_stream_chunk`; under streaming the value-norm
        running moments advance chunk-by-chunk (the streaming form of
        the running-statistics update)."""
        layout, _ = _extract_layout(sample)
        total = sum(L for (_, L, _) in layout)
        old_logp = _seq_align_minus1(sample, "packed_logprobs")
        ref_logp = (
            _seq_align_minus1(sample, "packed_ref_logprobs")
            if "packed_ref_logprobs" in sample.keys
            else None
        )
        values = np.asarray(sample.data["values"], np.float32)
        scores = np.clip(
            np.asarray(sample.data["rewards"], np.float32),
            -self.max_reward_clip,
            self.max_reward_clip,
        )
        no_eos = np.asarray(sample.data["seq_no_eos_mask"], np.float32)

        rewards = np.zeros(total, np.float32)
        loss_mask = np.zeros(total, np.float32)
        returns_full = np.zeros(total, np.float32)
        if ref_logp is not None and self.kl_ctl != 0.0:
            rewards -= self.kl_ctl * (old_logp - ref_logp)
        seq_slices = []
        for si, (s, L, pl) in enumerate(layout):
            lo, hi = s + max(pl - 1, 0), s + L - 1
            loss_mask[lo:hi] = 1.0
            if hi > lo:
                rewards[hi - 1] += scores[si]
            seq_slices.append((lo, hi))
        rewards *= loss_mask

        r_parts, v_parts, seg_parts, boot_parts = [], [], [], []
        for si, (lo, hi) in enumerate(seq_slices):
            n = hi - lo
            if n == 0:
                continue
            r_parts.append(rewards[lo:hi])
            v_parts.append(values[lo:hi])
            seg_parts.append(np.full(n, si + 1, np.int32))
            b = np.zeros(n, np.float32)
            b[-1] = no_eos[si] * values[layout[si][0] + layout[si][1] - 1]
            boot_parts.append(b)
        if r_parts:
            _, ret1 = gae_packed(
                jnp.asarray(np.concatenate(r_parts)),
                jnp.asarray(np.concatenate(v_parts)),
                jnp.asarray(np.concatenate(seg_parts)),
                jnp.asarray(np.concatenate(boot_parts)),
                self.discount,
                self.gae_lambda,
            )
            ret1 = np.asarray(ret1)
            off = 0
            for (lo, hi) in seq_slices:
                returns_full[lo:hi] = ret1[off : off + (hi - lo)]
                off += hi - lo

        if self.value_norm:
            # Update running moments with this batch's real-scale returns,
            # then train the head against NORMALIZED targets (old values
            # re-normalized so the clip window lives in the same space).
            # Sharded dispatch: host returns are garbage for other
            # members' rows (their `values` are zero-filled), so the
            # batch moments come from the exact in-mesh reduction —
            # identical on every member, keeping the running stats in
            # lockstep across the SPMD group.
            rms = self._rms()
            if sample.shard_blocks() is not None:
                probe = sample.select_keys({"packed_input_ids"})
                _add_aligned_keys(
                    probe,
                    {"ret_probe": returns_full, "loss_mask": loss_mask},
                )
                mom = model.engine.masked_moments(
                    probe, mb_spec, ("ret_probe",), mask_key="loss_mask"
                )
                cnt = mom["count"]
                if cnt > 0:
                    s, ssq, _ = mom["ret_probe"]
                    rms.update_moments(s / cnt, ssq / cnt, cnt)
            else:
                rms.update(returns_full, mask=loss_mask)
            returns_full = rms.normalize(returns_full)
            values = rms.normalize(values)

        train_sample = sample.select_keys({"packed_input_ids", "prompt_mask"})
        _add_aligned_keys(
            train_sample,
            {
                "old_values": values,
                "returns": returns_full,
                "loss_mask": loss_mask,
            },
        )
        return train_sample

    def train_step(
        self, model: Model, sample: SequenceSample, mb_spec: MicroBatchSpec
    ) -> Dict[str, float]:
        train_sample = self._prepare_train_sample(model, sample, mb_spec)
        loss_fn = self._get_loss_fn()
        all_stats = []
        for mb in train_sample.split_balanced(
            min(self.n_minibatches, train_sample.bs)
        ):
            stats = model.engine.train_batch(
                mb,
                mb_spec,
                loss_fn=loss_fn,
                loss_weight_fn=_mask_count,
                token_key="packed_input_ids",
                extra_keys=("old_values", "returns", "loss_mask"),
                version_steps=model.version,
            )
            all_stats.append(stats)
        model.inc_version()
        return {
            k: float(np.mean([s[k] for s in all_stats])) for k in all_stats[0]
        }

    # ------------- streamed (pipeline-overlapped) train -------------

    def train_stream_begin(
        self, model: Model, mb_spec: MicroBatchSpec
    ) -> Dict:
        return {
            "engine": model.engine.train_stream_begin(),
            "chunk_stats": [],
        }

    def train_stream_chunk(
        self,
        model: Model,
        state: Dict,
        sample: SequenceSample,
        mb_spec: MicroBatchSpec,
    ) -> Dict[str, float]:
        if sample.shard_blocks() is not None:
            raise ValueError(
                "pipeline overlap does not compose with shard-exact "
                "dispatch; chunk inputs must be broadcast"
            )
        train_sample = self._prepare_train_sample(model, sample, mb_spec)
        raw = model.engine.train_stream_chunk(
            state["engine"],
            train_sample,
            mb_spec,
            loss_fn=self._get_loss_fn(),
            loss_weight_fn=_mask_count,
            token_key="packed_input_ids",
            extra_keys=("old_values", "returns", "loss_mask"),
            version_steps=model.version,
        )
        w = max(raw.pop("chunk_weight"), 1.0)
        loss_sum = raw.pop("chunk_loss_sum")
        raw.pop("chunk_micro_batches", None)
        stats: Dict[str, float] = {
            "loss": loss_sum / w,
            "loss_denominator": w,
        }
        for k, v in raw.items():
            base = k[: -len("_sum")] if k.endswith("_sum") else k
            stats[base] = v / w
            stats[base + "_denominator"] = w
        state["chunk_stats"].append(stats)
        return stats

    def train_stream_end(
        self, model: Model, state: Dict, mb_spec: MicroBatchSpec
    ) -> Dict[str, float]:
        eng_out = model.engine.train_stream_end(state["engine"])
        model.inc_version()
        out = (
            merge_stats(state["chunk_stats"]) if state["chunk_stats"] else {}
        )
        out.update(eng_out)
        return out

    _loss_fn_cache = None

    def _get_loss_fn(self):
        if self._loss_fn_cache is None:
            object.__setattr__(
                self,
                "_loss_fn_cache",
                _ppo_critic_loss_factory(self.value_eps_clip),
            )
        return self._loss_fn_cache


register_interface("ppo_actor", PPOActorInterface)
register_interface("ppo_critic", PPOCriticInterface)
