"""No-op interface for system tests (reference: model_api.py:719-760
`NullInterface`, registered "null", used by null_exp.py).

`inference` fabricates one random reward per sequence — shaped exactly like
the math reward interface's output — so the full runtime (dispatch, data
plane, buffer readiness) can be exercised with zero device compute.
`train_step` consumes its batch and returns empty stats.
"""

from typing import Dict

import numpy as np

from areal_tpu.api.data_api import MicroBatchSpec, SequenceSample
from areal_tpu.api.model_api import Model, ModelInterface, register_interface


class NullInterface(ModelInterface):
    def __init__(self, seed: int = 0):
        self._rng = np.random.default_rng(seed)

    def inference(
        self, model: Model, sample: SequenceSample, mb_spec: MicroBatchSpec
    ) -> SequenceSample:
        key = (
            "packed_prompts"
            if "packed_prompts" in sample.keys
            else "packed_input_ids"
        )
        groups = [len(row) for row in sample.seqlens[key]]
        scores = self._rng.standard_normal(sum(groups)).astype(np.float32)
        return SequenceSample(
            keys={"rewards"},
            ids=list(sample.ids),
            seqlens={"rewards": [[1] * g for g in groups]},
            data={"rewards": scores},
        )

    def train_step(
        self, model: Model, sample: SequenceSample, mb_spec: MicroBatchSpec
    ) -> Dict[str, float]:
        n_seqs = len(sample.ids)
        return {"null/n_seqs": float(n_seqs)}


register_interface("null", NullInterface)
