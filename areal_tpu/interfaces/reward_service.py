"""Remote reward verification service (reward FaaS).

Capability parity: realhf/functioncall/ (the HTTP verification service the
reference calls for math/code grading at scale, functioncall/math/verify.py
+ the FaaS deployment it wraps) — a stdlib HTTP server exposing the SAME
local graders (`verify_math`, code execution) so verification can run on
separate CPU hosts instead of stealing cycles from TPU workers, plus a
client with transparent local fallback.

Server:
    python -m areal_tpu.interfaces.reward_service --port 8090
Client (used by MultiTaskRewardInterface when `remote_url` is set):
    RemoteVerifier("http://host:8090").verify_batch(items)
"""

import dataclasses
import json
import os
import threading
import time
import urllib.error
import urllib.request
from concurrent.futures import ThreadPoolExecutor
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Callable, Dict, List, Optional

from areal_tpu.base import logging, metrics, tracer

logger = logging.getLogger("reward_service")

# Remote round-trips that failed, by failure class — the signal that
# separates "the FaaS is down" (network/timeout) from "we disagree about
# the wire format" (http/protocol) on the fleet dashboard.
_M_REMOTE_ERRORS = metrics.default_registry().counter(
    "areal_reward_remote_errors_total",
    "remote reward verification failures, by reason",
    ("reason",),
)

# The failure classes verify_batch retries on.  Everything else (a
# programming error) propagates — a typo must not silently degrade every
# batch to local grading forever.
_RETRYABLE = (urllib.error.URLError, TimeoutError, OSError, ValueError,
              KeyError)


class VerifierShapeError(ValueError):
    """The remote returned a result list whose length mismatches the
    submitted batch.  Typed (instead of a bare ValueError) so the
    failure lands under `areal_reward_remote_errors_total{reason=shape}`
    and is never zipped against the prompts — a short reply silently
    misaligning rewards with items is the one wire bug retries can't
    paper over."""


def _error_reason(e: BaseException) -> str:
    """Map a transport/protocol failure onto its counter label."""
    if isinstance(e, VerifierShapeError):
        return "shape"
    if isinstance(e, urllib.error.HTTPError):
        return "http"
    if isinstance(e, TimeoutError):
        return "timeout"
    if isinstance(e, urllib.error.URLError):
        if isinstance(getattr(e, "reason", None), TimeoutError):
            return "timeout"
        return "network"
    if isinstance(e, OSError):
        return "network"
    return "protocol"


# ---------------------------------------------------------------------------
# verifier-backend registry (the pluggable reward fabric)
# ---------------------------------------------------------------------------
#
# Grading dispatches on the item's ``task`` key over an open registry,
# and items travel in an OPAQUE schema::
#
#     {"task": "code", "text": "<response>", "payload": {...backend args}}
#
# The server never interprets ``payload`` — it hands it to the backend
# verbatim — so a new backend round-trips client → FaaS → grader without
# anyone in between remapping keys.  The pre-registry flat schema (math
# keys at the top level) is accepted for one release with a log-once
# warning; new callers must send ``payload``.

_VERIFIERS: Dict[str, Callable[[str, Dict[str, Any]], bool]] = {}


def register_verifier(
    task: str, fn: Callable[[str, Dict[str, Any]], bool]
) -> None:
    """Register (or replace) the grader for a ``task`` key.  ``fn`` takes
    ``(text, payload)`` and returns pass/fail; it runs on the service's
    grading pool, so sandboxed subprocess work is fine."""
    _VERIFIERS[task] = fn


def verifier_names() -> List[str]:
    return sorted(_VERIFIERS)


def _verify_math_backend(text: str, payload: Dict[str, Any]) -> bool:
    from areal_tpu.interfaces import math_verify
    from areal_tpu.interfaces.reward import _row_is_choice

    return bool(
        math_verify.verify_math(
            text,
            payload.get("solutions") or [],
            is_choice=_row_is_choice(payload),
        )
    )


def _verify_code_backend(text: str, payload: Dict[str, Any]) -> bool:
    from areal_tpu.interfaces.reward import MultiTaskRewardInterface

    iface = MultiTaskRewardInterface(
        code_timeout_s=float(payload.get("timeout_s", 8.0))
    )
    return bool(
        iface._verify_code(
            text, {"input_output": payload.get("input_output")}
        )
    )


def _verify_judge_backend(text: str, payload: Dict[str, Any]) -> bool:
    """Judge-model STUB: case-insensitive reference containment over the
    response tail (``payload["reference"]``, optional ``tail_chars``).
    Deterministic placeholder that keeps the wire format and registry
    seam honest until a real judge-model client lands; absent reference
    grades False rather than guessing."""
    ref = str(payload.get("reference", "")).strip()
    if not ref:
        return False
    tail = int(payload.get("tail_chars", 0))
    hay = text[-tail:] if tail > 0 else text
    return ref.lower() in hay.lower()


register_verifier("math", _verify_math_backend)
register_verifier("code", _verify_code_backend)
register_verifier("judge", _verify_judge_backend)

_legacy_schema_warned = False
_unknown_tasks_warned: set = set()


def _normalize_item(item: Dict[str, Any]):
    """Split an item into (task, text, payload), accepting the legacy
    flat schema — backend keys at the top level — with a log-once
    deprecation warning."""
    global _legacy_schema_warned
    task = str(item.get("task", "math"))
    text = str(item.get("text", ""))
    payload = item.get("payload")
    if isinstance(payload, dict):
        return task, text, payload
    payload = {
        k: v
        for k, v in item.items()
        if k not in ("task", "text", "trace_id")
    }
    if payload and not _legacy_schema_warned:
        _legacy_schema_warned = True
        logger.warning(
            "verify item without 'payload' — accepting the legacy flat "
            "schema for one release; send {'task','text','payload'} "
            "(warned once)"
        )
    return task, text, payload


def grade_item(item: Dict[str, Any]) -> bool:
    """Grade one item via the verifier registry — the single dispatch
    shared by the FaaS handler, the RemoteVerifier local fallback, and
    the in-process reward fabric.  An item carrying a ``trace_id`` gets
    a per-backend grade span plus a ``graded`` lineage stamp, joining
    verification into the sample's causal timeline."""
    task, text, payload = _normalize_item(item)
    trace_id = str(item.get("trace_id") or "")
    fn = _VERIFIERS.get(task)
    if fn is None:
        if task not in _unknown_tasks_warned:
            _unknown_tasks_warned.add(task)
            logger.warning(
                f"no verifier backend for task {task!r} "
                f"(registered: {verifier_names()}); reward 0"
            )
        if trace_id:
            tracer.lineage(
                "graded", trace_id, task=task, passed=False,
                backend="missing",
            )
        return False
    with tracer.span(f"grade:{task}", cat="host", task=task):
        ok = bool(fn(text, payload))
    if trace_id:
        tracer.lineage("graded", trace_id, task=task, passed=ok)
    return ok


# Pre-registry name, kept for existing call sites.
_grade_one = grade_item


class _Handler(BaseHTTPRequestHandler):
    def log_message(self, fmt, *args):  # route through our logger
        logger.debug(fmt % args)

    def _send(self, code: int, payload: Dict):
        body = json.dumps(payload).encode()
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def do_GET(self):
        if self.path == "/health":
            self._send(200, {"status": "ok"})
        else:
            self._send(404, {"error": "unknown path"})

    def do_POST(self):
        if self.path != "/verify":
            self._send(404, {"error": "unknown path"})
            return
        token = getattr(self.server, "auth_token", None)
        if token and self.headers.get("X-Areal-Token") != token:
            self._send(403, {"error": "bad token"})
            return
        try:
            n = int(self.headers.get("Content-Length", 0))
            req = json.loads(self.rfile.read(n))
            items = req["items"]
            # Code grading runs sandboxed subprocesses with multi-second
            # timeouts; grade the batch in parallel.
            with tracer.span("verify", cat="host", n=len(items)):
                with ThreadPoolExecutor(max_workers=8) as ex:
                    results = list(ex.map(grade_item, items))
            tracer.flush()
            self._send(200, {"results": results})
        except Exception as e:  # noqa: BLE001 — report to the client
            self._send(500, {"error": repr(e)})


def serve(
    host: str = "127.0.0.1",
    port: int = 8090,
    background: bool = False,
    token: str = "",
) -> ThreadingHTTPServer:
    """Run the verification server; `background=True` returns immediately
    with the server thread running (tests / embedded use).

    Code grading EXECUTES submitted programs: the default bind is loopback,
    and any non-loopback deployment should set a shared token
    (--token / AREAL_REWARD_TOKEN; clients send X-Areal-Token)."""
    tracer.configure(role="reward", rank=port)
    srv = ThreadingHTTPServer((host, port), _Handler)
    srv.auth_token = token or os.environ.get("AREAL_REWARD_TOKEN", "")
    logger.info(f"reward service listening on {host}:{srv.server_port}")
    if background:
        threading.Thread(target=srv.serve_forever, daemon=True).start()
        return srv
    try:
        srv.serve_forever()
    except KeyboardInterrupt:
        pass
    return srv


def post_verify(
    url: str,
    items: List[Dict[str, Any]],
    timeout_s: float,
    token: str = "",
) -> List[bool]:
    """One POST /verify round trip against a verification server — the
    wire protocol shared by RemoteVerifier (single fixed URL) and
    VerifierPool (load-balanced fleet).  Raises VerifierShapeError on a
    result/batch length mismatch; callers decide retry policy."""
    headers = {"Content-Type": "application/json"}
    tok = token or os.environ.get("AREAL_REWARD_TOKEN", "")
    if tok:
        headers["X-Areal-Token"] = tok
    req = urllib.request.Request(
        url.rstrip("/") + "/verify",
        data=json.dumps({"items": items}).encode(),
        headers=headers,
    )
    with urllib.request.urlopen(req, timeout=timeout_s) as r:
        out = json.loads(r.read())
    results = [bool(x) for x in out["results"]]
    if len(results) != len(items):
        raise VerifierShapeError(
            f"result length mismatch: sent {len(items)} items, got "
            f"{len(results)} results"
        )
    return results


@dataclasses.dataclass
class RemoteVerifier:
    """Client for the reward service with local fallback.

    The reference tolerates FaaS flakiness by retrying then falling back;
    here each batch gets `attempts` tries (per-attempt `timeout_s`,
    linear `backoff_s` between tries) over the TYPED failure set —
    transport errors, timeouts, and malformed replies — before falling
    back to in-process grading, so a dead service degrades throughput,
    never correctness.  Every failed round-trip bumps
    `areal_reward_remote_errors_total{reason}`; the degradation itself is
    logged at warning once per client, then demoted to debug so a
    long-dead service doesn't flood the trial log once per batch."""

    url: str
    timeout_s: float = 600.0
    token: str = ""
    attempts: int = 3
    backoff_s: float = 0.5
    _degraded: bool = dataclasses.field(
        default=False, init=False, repr=False
    )

    def __post_init__(self):
        if self.attempts < 1:
            raise ValueError(
                f"RemoteVerifier.attempts must be >= 1, got {self.attempts}"
            )
        if self.backoff_s < 0:
            raise ValueError(
                f"RemoteVerifier.backoff_s must be >= 0, got "
                f"{self.backoff_s}"
            )

    def _round_trip(self, items: List[Dict[str, Any]]) -> List[bool]:
        return post_verify(self.url, items, self.timeout_s, self.token)

    def verify_batch(self, items: List[Dict[str, Any]]) -> List[bool]:
        for attempt in range(1, self.attempts + 1):
            try:
                results = self._round_trip(items)
                if self._degraded:
                    self._degraded = False
                    logger.info(
                        f"remote verification at {self.url} recovered"
                    )
                return results
            except _RETRYABLE as e:
                reason = _error_reason(e)
                _M_REMOTE_ERRORS.labels(reason).inc()
                if attempt < self.attempts:
                    logger.debug(
                        f"remote verification attempt {attempt}/"
                        f"{self.attempts} failed ({reason}: {e!r}); "
                        f"retrying in {self.backoff_s * attempt:.1f}s"
                    )
                    time.sleep(self.backoff_s * attempt)
                    continue
                log = logger.debug if self._degraded else logger.warning
                log(
                    f"remote verification failed after {self.attempts} "
                    f"attempts (last: {reason}: {e!r}); grading locally"
                )
                self._degraded = True
        return [grade_item(it) for it in items]


def main():
    import argparse

    p = argparse.ArgumentParser(prog="areal_tpu.interfaces.reward_service")
    p.add_argument("--host", default="127.0.0.1",
                   help="bind address; non-loopback binds should set --token")
    p.add_argument("--port", type=int, default=8090)
    p.add_argument("--token", default="",
                   help="shared secret (or AREAL_REWARD_TOKEN)")
    args = p.parse_args()
    serve(args.host, args.port, token=args.token)


if __name__ == "__main__":
    main()
