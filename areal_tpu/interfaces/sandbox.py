"""Sandboxed execution of untrusted reward-verification programs.

Capability parity: the reference offloads code grading to a remote FaaS
sandbox (realhf/functioncall/code/verify.py); its local fallback
(local_verify) is a bare subprocess.  Here the LOCAL path itself is
fenced, since TPU trials routinely grade model-written code in-process:

- rlimits (a `sh -c 'ulimit ...'` wrapper — no preexec_fn, which is
  fork-unsafe in threaded hosts): CPU seconds, address
  space, file size, process/thread count, open files, core dumps off;
- a throwaway tmpdir jail as cwd (the program file lives there; the dir
  is deleted after grading);
- minimal environment and a fresh session (process group) so timeout
  kills reach grandchildren;
- a user+network namespace (`unshare -rn`) when the kernel allows it,
  removing network access entirely — probed once and cached.

Trust model: this blocks the accident class (fork bombs, memory bombs,
giant files, stray network calls, clobbering the trial's cwd) but it is
NOT a container boundary — a kernel exploit or writes to world-writable
paths remain possible.  Grade genuinely hostile code only behind the
remote reward service on an isolated machine (interfaces/reward_service).
"""

import os
import shutil
import subprocess
from typing import List, Optional, Tuple

from areal_tpu.base import logging

logger = logging.getLogger("sandbox")

_UNSHARE: Optional[List[str]] = None


def _unshare_prefix() -> List[str]:
    """`unshare -rn` argv prefix when user+net namespaces work here."""
    global _UNSHARE
    if _UNSHARE is None:
        exe = shutil.which("unshare")
        ok = False
        if exe:
            try:
                ok = (
                    subprocess.run(
                        [exe, "-rn", "true"], capture_output=True, timeout=5
                    ).returncode
                    == 0
                )
            except Exception:
                ok = False
        _UNSHARE = [exe, "-rn"] if ok else []
        if not _UNSHARE:
            logger.warning(
                "unshare -rn unavailable: sandboxed code keeps network "
                "access (rlimits + tmpdir jail still apply)"
            )
    return _UNSHARE


def _ulimit_wrapper(
    cpu_s: int, mem_mb: int, fsize_mb: int, nproc: Optional[int]
) -> List[str]:
    """Apply rlimits via a `sh -c 'ulimit ...; exec "$@"'` wrapper rather
    than preexec_fn: running Python between fork and exec is documented
    deadlock-prone in multithreaded processes, and reward grading runs
    inside model workers full of ZMQ/JAX threads — a child wedged in
    _set_limits would burn the whole timeout and grade a correct solution
    as wrong.  The shell applies limits post-exec (posix_spawn-safe).

    NPROC is a PER-UID limit (threads included): the cap must sit above
    the trial user's existing task count — a busy JAX host easily holds
    hundreds — or legitimate solutions that fork/thread fail with EAGAIN
    and grade as wrong.  The default (4096) only stops runaway fork
    bombs; nproc=None skips it.  `ulimit -v` is in KiB, `-f` in 512-byte
    blocks, `-t` in seconds."""
    # Mandatory limits are &&-joined: if one fails to apply, the graded
    # program must NOT run unlimited (fail closed, like the setrlimit
    # error the old preexec_fn surfaced) — the run grades False via the
    # nonzero shell exit.
    parts = [
        f"ulimit -t {cpu_s + 1}",
        f"ulimit -v {mem_mb << 10}",
        f"ulimit -f {(fsize_mb << 20) // 512}",
        "ulimit -n 256",
        "ulimit -c 0",
    ]
    script = " && ".join(parts)
    if nproc is not None:
        # Not all shells implement -u; failing to tighten this optional
        # fork-bomb cap must not fail the grading run.
        script += f" && {{ ulimit -u {nproc} 2>/dev/null || true; }}"
    script += ' && exec "$@"'
    return ["sh", "-c", script, "sh"]


def run_sandboxed(
    argv: List[str],
    input_text: str = "",
    timeout_s: float = 8.0,
    cwd: Optional[str] = None,
    mem_mb: int = 1024,
    fsize_mb: int = 32,
    nproc: Optional[int] = 4096,
) -> Tuple[int, str]:
    """Run `argv` jailed; returns (returncode, stdout).  Timeouts and
    resource kills surface as nonzero returncodes (-1 for wall timeout)."""
    proc = subprocess.Popen(
        _unshare_prefix()
        + _ulimit_wrapper(max(1, int(timeout_s)), mem_mb, fsize_mb, nproc)
        + argv,
        stdin=subprocess.PIPE,
        stdout=subprocess.PIPE,
        stderr=subprocess.PIPE,
        text=True,
        cwd=cwd,
        env={"PATH": "/usr/bin:/bin", "HOME": cwd or "/tmp"},
        start_new_session=True,
    )
    try:
        stdout, _ = proc.communicate(input=input_text, timeout=timeout_s)
        return proc.returncode, stdout
    except subprocess.TimeoutExpired:
        # Kill the whole session, not just the child: a graded program's
        # own subprocesses must not outlive the timeout.
        try:
            os.killpg(proc.pid, 9)
        except ProcessLookupError:
            pass
        proc.wait()
        return -1, ""
