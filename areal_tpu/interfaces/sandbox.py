"""Sandboxed execution of untrusted reward-verification programs.

Capability parity: the reference offloads code grading to a remote FaaS
sandbox (realhf/functioncall/code/verify.py); its local fallback
(local_verify) is a bare subprocess.  Here the LOCAL path itself is
fenced, since TPU trials routinely grade model-written code in-process:

- rlimits (preexec, applied inside the child): CPU seconds, address
  space, file size, process/thread count, open files, core dumps off;
- a throwaway tmpdir jail as cwd (the program file lives there; the dir
  is deleted after grading);
- minimal environment and a fresh session (process group) so timeout
  kills reach grandchildren;
- a user+network namespace (`unshare -rn`) when the kernel allows it,
  removing network access entirely — probed once and cached.

Trust model: this blocks the accident class (fork bombs, memory bombs,
giant files, stray network calls, clobbering the trial's cwd) but it is
NOT a container boundary — a kernel exploit or writes to world-writable
paths remain possible.  Grade genuinely hostile code only behind the
remote reward service on an isolated machine (interfaces/reward_service).
"""

import os
import resource
import shutil
import subprocess
from typing import List, Optional, Tuple

from areal_tpu.base import logging

logger = logging.getLogger("sandbox")

_UNSHARE: Optional[List[str]] = None


def _unshare_prefix() -> List[str]:
    """`unshare -rn` argv prefix when user+net namespaces work here."""
    global _UNSHARE
    if _UNSHARE is None:
        exe = shutil.which("unshare")
        ok = False
        if exe:
            try:
                ok = (
                    subprocess.run(
                        [exe, "-rn", "true"], capture_output=True, timeout=5
                    ).returncode
                    == 0
                )
            except Exception:
                ok = False
        _UNSHARE = [exe, "-rn"] if ok else []
        if not _UNSHARE:
            logger.warning(
                "unshare -rn unavailable: sandboxed code keeps network "
                "access (rlimits + tmpdir jail still apply)"
            )
    return _UNSHARE


def _set_limits(cpu_s: int, mem_mb: int, fsize_mb: int, nproc: Optional[int]):
    def apply():
        resource.setrlimit(resource.RLIMIT_CPU, (cpu_s, cpu_s + 1))
        resource.setrlimit(
            resource.RLIMIT_AS, (mem_mb << 20, mem_mb << 20)
        )
        resource.setrlimit(
            resource.RLIMIT_FSIZE, (fsize_mb << 20, fsize_mb << 20)
        )
        # NPROC is a PER-UID limit (threads included): the cap must sit
        # above the trial user's existing task count — a busy JAX host
        # easily holds hundreds — or legitimate solutions that fork/thread
        # fail with EAGAIN and grade as wrong.  The default (4096) only
        # stops runaway fork bombs; pass nproc=None to skip entirely.
        if nproc is not None:
            resource.setrlimit(resource.RLIMIT_NPROC, (nproc, nproc))
        resource.setrlimit(resource.RLIMIT_NOFILE, (256, 256))
        resource.setrlimit(resource.RLIMIT_CORE, (0, 0))

    return apply


def run_sandboxed(
    argv: List[str],
    input_text: str = "",
    timeout_s: float = 8.0,
    cwd: Optional[str] = None,
    mem_mb: int = 1024,
    fsize_mb: int = 32,
    nproc: Optional[int] = 4096,
) -> Tuple[int, str]:
    """Run `argv` jailed; returns (returncode, stdout).  Timeouts and
    resource kills surface as nonzero returncodes (-1 for wall timeout)."""
    proc = subprocess.Popen(
        _unshare_prefix() + argv,
        stdin=subprocess.PIPE,
        stdout=subprocess.PIPE,
        stderr=subprocess.PIPE,
        text=True,
        cwd=cwd,
        env={"PATH": "/usr/bin:/bin", "HOME": cwd or "/tmp"},
        start_new_session=True,
        preexec_fn=_set_limits(
            max(1, int(timeout_s)), mem_mb, fsize_mb, nproc
        ),
    )
    try:
        stdout, _ = proc.communicate(input=input_text, timeout=timeout_s)
        return proc.returncode, stdout
    except subprocess.TimeoutExpired:
        # Kill the whole session, not just the child: a graded program's
        # own subprocesses must not outlive the timeout.
        try:
            os.killpg(proc.pid, 9)
        except ProcessLookupError:
            pass
        proc.wait()
        return -1, ""
