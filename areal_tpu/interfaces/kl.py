"""KL-coefficient controllers for the PPO reward penalty.

Capability parity: realhf/impl/model/utils/ppo_functional.py:14-48
(FixedKLController / AdaptiveKLController).  The adaptive rule is the
Ziegler et al. (arXiv:1909.08593) proportional controller: after each
train step, nudge the coefficient toward holding the measured
policy↔reference KL at `target`:

    err   = clip(observed_kl / target - 1, -0.2, 0.2)
    value *= 1 + err * n_steps / horizon

This is host-side per-step control flow (one scalar update per train
step), so it stays in Python rather than jax — nothing here is traced.
The controller value is algorithm state: it rides recover checkpoints via
the owning interface's state_dict (like value-norm moments), otherwise a
restored trial would restart the schedule from the initial coefficient.
"""

import dataclasses


@dataclasses.dataclass
class FixedKLController:
    value: float = 0.0

    def update(self, observed_kl: float, n_steps: int) -> None:
        pass

    def state_dict(self):
        return {"value": float(self.value)}

    def load_state_dict(self, sd) -> None:
        if sd:
            self.value = float(sd["value"])


@dataclasses.dataclass
class AdaptiveKLController(FixedKLController):
    target: float = 6.0
    horizon: float = 10000.0

    def update(self, observed_kl: float, n_steps: int) -> None:
        err = min(max(observed_kl / self.target - 1.0, -0.2), 0.2)
        self.value *= 1.0 + err * n_steps / self.horizon


def make_kl_controller(
    init: float, adaptive: bool, target: float, horizon: float
):
    if adaptive:
        return AdaptiveKLController(
            value=init, target=target, horizon=horizon
        )
    return FixedKLController(value=init)
