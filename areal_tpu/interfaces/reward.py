"""Multi-task reward interface: verification-based rewards for math & code.

Capability parity: realhf/impl/model/interface/math_rw_interface.py
(`MultiTaskRewardInterface`, registered "rw-math-code") + the local
verification paths of realhf/functioncall/.  Dispatches each sequence by its
task metadata, decodes the response, verifies, and emits ±`reward_value`
scores (one scalar per sequence, the reference's reward layout).
"""

import dataclasses
import json
import os
import sys
import tempfile
from typing import Any, Dict, List, Optional

import numpy as np

from areal_tpu.api.data_api import MicroBatchSpec, SequenceSample
from areal_tpu.base import logging
from areal_tpu.api.model_api import Model, ModelInterface, register_interface

logger = logging.getLogger("reward")


def _row_is_choice(info: Dict[str, Any]) -> Optional[bool]:
    """Row-level multiple-choice evidence for is_multi_choice gating:
    an explicit flag or a rendered `choices` list decides; absent both,
    None lets the gold-string inference stand (rows without metadata
    must keep grading letter golds)."""
    if info.get("is_choice") is not None:
        return bool(info["is_choice"])
    if "choices" in info and info["choices"] is not None:
        return bool(info["choices"])
    return None


@dataclasses.dataclass
class MultiTaskRewardInterface(ModelInterface):
    """id2info maps query_id -> row dict with task/solutions/input_output
    (loaded from the dataset jsonl, reference math_code_dataset.load_metadata)."""

    id2info: Dict[str, Dict[str, Any]] = dataclasses.field(default_factory=dict)
    dataset_path: Optional[str] = None
    reward_value: float = 5.0
    code_timeout_s: float = 8.0
    # http://host:port of a reward_service.py deployment; verification is
    # batched to it (local fallback on failure).  None = grade in-process.
    remote_url: Optional[str] = None
    # Generous default: code batches can run minutes of sandboxed tests.
    remote_timeout_s: float = 600.0
    # When set, overrides every row's task key — forces one verifier
    # backend (e.g. "judge") for the whole run regardless of dataset
    # metadata.  "" = dispatch per-row.
    reward_backend: str = ""
    # Route grading through the announced verifier fleet
    # (system/verifier_pool.py) instead of a fixed remote_url: batches
    # load-balance across live workers with per-server breakers and
    # retry-to-a-different-server, degrading to the in-process registry
    # when no worker is live.  Takes precedence over remote_url.
    verifier_pool: bool = False
    pool_experiment: str = ""
    pool_trial: str = ""
    pool_attempt_timeout_s: float = 60.0
    _pool: Any = dataclasses.field(default=None, init=False, repr=False)

    def __post_init__(self):
        if self.dataset_path and not self.id2info:
            with open(self.dataset_path) as f:
                for line in f:
                    if not line.strip():
                        continue
                    row = json.loads(line)
                    row.setdefault("task", "math")
                    self.id2info[str(row.get("query_id", row.get("id")))] = row

    def inference(
        self, model: Optional[Model], sample: SequenceSample, mb_spec: MicroBatchSpec
    ) -> SequenceSample:
        """Scores every sequence; returns key 'rewards' (1 scalar/seq).

        `model` supplies the tokenizer; no forward pass happens (the
        reference's rw interface also runs verification, not a model)."""
        tokenizer = model.tokenizer if model is not None else None
        assert tokenizer is not None, "reward interface needs a tokenizer"
        tokens = np.asarray(sample.data["packed_input_ids"])
        pmask = np.asarray(sample.data["prompt_mask"])
        bounds = sample.cu_seqlens("packed_input_ids")
        seqlens_r: List[List[int]] = []
        todo: List[Dict[str, Any]] = []
        si = 0
        for ei, group in enumerate(sample.seqlens["packed_input_ids"]):
            qid = str(sample.ids[ei])
            info = self.id2info.get(qid, {})
            task = info.get("task", "math")
            seqlens_r.append([1] * len(group))
            for _ in group:
                lo, hi = bounds[si], bounds[si + 1]
                resp_tokens = tokens[lo:hi][~pmask[lo:hi].astype(bool)]
                text = tokenizer.decode(resp_tokens.tolist())
                todo.append(
                    {
                        "task": self.reward_backend or task,
                        "text": text,
                        # Opaque backend payload (reward_service registry
                        # schema): backends read it verbatim, so adding a
                        # backend never remaps keys here.
                        "payload": {
                            "solutions": info.get("solutions") or [],
                            "input_output": info.get("input_output"),
                            "choices": info.get("choices"),
                            "reference": info.get("reference"),
                            "timeout_s": self.code_timeout_s,
                        },
                    }
                )
                si += 1
        if self.verifier_pool:
            oks = self._verifier_pool().verify_batch(todo)
        elif self.remote_url:
            from areal_tpu.interfaces.reward_service import RemoteVerifier

            oks = RemoteVerifier(
                self.remote_url, timeout_s=self.remote_timeout_s
            ).verify_batch(todo)
        else:
            oks = [
                self.verify(it["task"], it["text"], it["payload"])
                for it in todo
            ]
        n_correct = sum(map(int, oks))
        rewards = [
            self.reward_value if ok else -self.reward_value for ok in oks
        ]
        logger.info(
            f"reward verification: {n_correct}/{len(rewards)} correct"
        )
        return SequenceSample(
            keys={"rewards"},
            ids=list(sample.ids),
            seqlens={"rewards": seqlens_r},
            data={"rewards": np.asarray(rewards, np.float32)},
            metadata={},
        )

    def _verifier_pool(self):
        """Lazily build (and cache) the fleet-discovering pool client —
        one client per interface, so breaker state and membership view
        survive across inference calls."""
        if self._pool is None:
            from areal_tpu.system.verifier_pool import (
                VerifierPool, verifier_discovery,
            )

            if not (self.pool_experiment and self.pool_trial):
                raise ValueError(
                    "verifier_pool=True needs pool_experiment and "
                    "pool_trial to discover the announced fleet"
                )
            self._pool = VerifierPool(
                discovery=verifier_discovery(
                    self.pool_experiment, self.pool_trial
                ),
                attempt_timeout_s=self.pool_attempt_timeout_s,
            )
        return self._pool

    def verify(self, task: str, text: str, info: Dict[str, Any]) -> bool:
        """Grade one response for ``task`` via the verifier-backend
        registry (reward_service) — public so the offline evaluator
        shares the exact training-reward graders, and so a backend
        registered once is available to every grading path."""
        from areal_tpu.interfaces import reward_service

        payload = dict(info)
        payload.setdefault("timeout_s", self.code_timeout_s)
        return reward_service.grade_item(
            {
                "task": self.reward_backend or task,
                "text": text,
                "payload": payload,
            }
        )

    # -- code verification: run extracted program against input/output pairs
    # in a SANDBOXED subprocess — rlimits + tmpdir jail + (where available)
    # a network namespace; see interfaces/sandbox.py for the trust model
    # (reference: functioncall/code/local_verify, whose hostile-code path
    # is the remote FaaS sandbox like our reward_service).
    def _verify_code(self, text: str, info: Dict[str, Any]) -> bool:
        from areal_tpu.interfaces.sandbox import run_sandboxed

        m = _extract_code_block(text)
        if m is None:
            return False
        try:
            io_spec = info.get("input_output")
            io_spec = json.loads(io_spec) if isinstance(io_spec, str) else io_spec
            inputs, outputs = io_spec["inputs"], io_spec["outputs"]
        except (KeyError, TypeError, json.JSONDecodeError):
            return False
        with tempfile.TemporaryDirectory(prefix="areal_grade_") as jail:
            path = os.path.join(jail, "prog.py")
            with open(path, "w") as f:
                f.write(m)
            for inp, expected in zip(inputs, outputs):
                rc, stdout = run_sandboxed(
                    [sys.executable, path],
                    input_text=inp,
                    timeout_s=self.code_timeout_s,
                    cwd=jail,
                )
                if rc != 0 or stdout.strip() != expected.strip():
                    return False
        return True


def _extract_code_block(text: str) -> Optional[str]:
    import re

    blocks = re.findall(r"```(?:python)?\n(.*?)```", text, flags=re.DOTALL)
    return blocks[-1] if blocks else None


register_interface("rw-math-code", MultiTaskRewardInterface)
