"""Sympy-grade math answer equivalence.

Capability parity: the reference's qwen-grade verifier
(/root/reference/math_verify_utils_qwen.py + realhf/impl/dataset/
math_parser.py:98) — symbolic equality between a predicted and a gold
answer written in LaTeX: fractions vs decimals, radicals, intervals,
finite sets, tuples, matrices, simple equations.  Re-implemented from
scratch for this codebase: a brace-aware LaTeX -> sympy translator (the
antlr-based `sympy.parsing.latex` is unavailable here) plus a structural
comparator, executed in a worker process with a hard timeout because
`sympy.simplify` can hang on adversarial inputs (the reference wraps its
grader in a process pool for the same reason).
"""

import re
from typing import List, Optional, Tuple

# ---------------- LaTeX -> sympy-parseable text ----------------


def _match_brace(s: str, start: int) -> int:
    """Index just past the brace group opening at s[start] == '{'."""
    depth = 0
    for i in range(start, len(s)):
        if s[i] == "{":
            depth += 1
        elif s[i] == "}":
            depth -= 1
            if depth == 0:
                return i + 1
    return len(s)


def _take_group(s: str, i: int) -> Tuple[str, int]:
    """Read one latex argument at position i: {..}, a digit, or a token."""
    while i < len(s) and s[i] in " \t":
        i += 1
    if i >= len(s):
        return "", i
    if s[i] == "{":
        end = _match_brace(s, i)
        return s[i + 1 : end - 1], end
    if s[i] == "\\":  # a command token like \pi
        m = re.match(r"\\[a-zA-Z]+", s[i:])
        if m:
            return m.group(0), i + m.end()
    return s[i], i + 1


def _rewrite_frac(s: str) -> str:
    out = []
    i = 0
    while i < len(s):
        m = re.match(r"\\[dt]?frac", s[i:])
        if m:
            num, j = _take_group(s, i + m.end())
            den, j = _take_group(s, j)
            out.append(f"(({_rewrite_frac(num)})/({_rewrite_frac(den)}))")
            i = j
        else:
            out.append(s[i])
            i += 1
    return "".join(out)


def _rewrite_sqrt(s: str) -> str:
    out = []
    i = 0
    while i < len(s):
        if s.startswith("\\sqrt", i):
            j = i + len("\\sqrt")
            order = None
            if j < len(s) and s[j] == "[":
                k = s.index("]", j)
                order = s[j + 1 : k]
                j = k + 1
            arg, j = _take_group(s, j)
            arg = _rewrite_sqrt(arg)
            if order:
                out.append(f"(({arg})**(1/({order})))")
            else:
                out.append(f"(sqrt({arg}))")
            i = j
        else:
            out.append(s[i])
            i += 1
    return "".join(out)


_SIMPLE_SUBS = [
    (re.compile(r"\\left|\\right|\\limits"), ""),
    (re.compile(r"\\(?:,|;|!|:|\s)"), " "),
    (re.compile(r"\\text\s*\{[^{}]*\}"), ""),
    (re.compile(r"\\(?:mathrm|mathbf|mathit|operatorname)\s*\{([^{}]*)\}"), r"\1"),
    (re.compile(r"\\(?:cdot|times)"), "*"),
    (re.compile(r"\\div"), "/"),
    (re.compile(r"\\pi\b"), " pi "),
    (re.compile(r"\\infty\b"), " oo "),
    (re.compile(r"\\circ\b"), ""),  # degrees marker (with ^ stripped below)
    (re.compile(r"(?:\^\s*)(?=\s|$|[+\-*/,)\]])"), ""),  # dangling ^ from ^\circ
    (re.compile(r"\\%|%"), ""),
    (re.compile(r"\\(?:log|ln)\b"), " log"),
    (re.compile(r"\\(sin|cos|tan|cot|sec|csc|exp|sinh|cosh|tanh)\b"), r" \1"),
    (re.compile(r"\$"), ""),
    (re.compile(r"\\degree"), ""),
]


def latex_to_expr(ans: str) -> str:
    """Best-effort LaTeX -> a string `sympy.parse_expr` understands."""
    s = ans.strip()
    s = _rewrite_frac(s)
    s = _rewrite_sqrt(s)
    for pat, rep in _SIMPLE_SUBS:
        s = pat.sub(rep, s)
    # Mixed numbers: 1((1)/(2)) means 1 + 1/2 when both parts are numeric.
    s = re.sub(r"(\d)\s*\(\((\d+)\)/\((\d+)\)\)", r"(\1+(\2)/(\3))", s)
    s = s.replace("^", "**")
    s = re.sub(r"(\d)\{,\}(?=\d{3})", r"\1", s)  # 1{,}000 thousands braces
    # Remaining (non-set) braces are latex grouping: {x} -> (x).
    s = s.replace("{", "(").replace("}", ")")
    s = s.replace("°", "")
    s = re.sub(r"(\d),(?=\d{3}\b)", r"\1", s)  # thousands separators
    return s.strip()


# ---------------- structured answers ----------------


def _split_top(s: str, sep: str = ",") -> List[str]:
    parts, depth, cur = [], 0, []
    for ch in s:
        if ch in "([{":
            depth += 1
        elif ch in ")]}":
            depth -= 1
        if ch == sep and depth == 0:
            parts.append("".join(cur))
            cur = []
        else:
            cur.append(ch)
    parts.append("".join(cur))
    return parts


_MATRIX_RE = re.compile(
    r"\\begin\{[pbvV]?matrix\}(.*?)\\end\{[pbvV]?matrix\}", re.DOTALL
)


def _parse_structure(ans: str):
    """Classify an answer: ('matrix', rows) | ('set', elems) |
    ('intervals', [(lb, lo, hi, rb), ...]) | ('tuple', elems) |
    ('expr', text)."""
    s = ans.strip()
    m = _MATRIX_RE.search(s)
    if m:
        rows = [
            [c.strip() for c in row.split("&")]
            for row in re.split(r"\\\\", m.group(1))
            if row.strip()
        ]
        return ("matrix", rows)
    if s.startswith("\\{") and s.endswith("\\}"):
        return ("set", _split_top(s[2:-2]))
    # Interval or union of intervals: (a,b] \cup [c,d) ...
    pieces = re.split(r"\\cup", s)
    ivs = []
    for p in pieces:
        p = re.sub(r"\\left|\\right", "", p).strip()
        if (
            len(p) >= 2
            and p[0] in "([" and p[-1] in ")]"
            and len(_split_top(p[1:-1])) == 2
        ):
            lo, hi = _split_top(p[1:-1])
            ivs.append((p[0], lo, hi, p[-1]))
        else:
            ivs = None
            break
    if ivs is not None and len(ivs) >= 1:
        if len(ivs) > 1:
            return ("intervals", ivs)
        # A single (a,b): ambiguous — tuple/point vs open interval; compare
        # as an ordered pair either way (bracket kinds checked separately).
        return ("intervals", ivs)
    return ("expr", s)


# ---------------- the in-process worker ----------------


def _parse(s: str):
    import sympy
    from sympy.parsing.sympy_parser import (
        implicit_multiplication_application,
        parse_expr,
        standard_transformations,
    )

    txt = latex_to_expr(s)
    # Single-variable equation: grade the rhs (e.g. "x = 5" vs "5").
    if txt.count("=") == 1:
        lhs, rhs = txt.split("=")
        if re.fullmatch(r"\s*[a-zA-Z]\w*\s*", lhs):
            txt = rhs
    expr = parse_expr(
        txt,
        transformations=standard_transformations
        + (implicit_multiplication_application,),
        evaluate=True,
    )
    # Grading convention: a bare `e` is Euler's number.
    return expr.subs(sympy.Symbol("e"), sympy.E)


def _exprs_equal(a: str, b: str) -> bool:
    import sympy

    ta, tb = latex_to_expr(a), latex_to_expr(b)
    # General equations (lhs = rhs on both sides): compare the zero-forms
    # up to overall sign — "-34x-45y+20z-100=0" must equal
    # "34x+45y-20z+100=0" (reference: grader.py:312 compares
    # |lhs-rhs| symbolically).
    if ta.count("=") == 1 and tb.count("=") == 1:
        da = _parse_equation_diff(ta)
        db = _parse_equation_diff(tb)
        if da is not None and db is not None:
            return bool(
                sympy.simplify(da - db) == 0
                or sympy.simplify(da + db) == 0
            )

    ea, eb = _parse(a), _parse(b)
    if ea == eb:
        return True
    diff = sympy.simplify(ea - eb)
    if diff == 0:
        return True
    try:
        if abs(complex(sympy.N(diff, 15))) < 1e-9:
            return True
    except (TypeError, ValueError):
        pass
    # Pure numbers: the reference grades digit pairs with rel_tol=1e-4
    # (grader.py:278) — "2.6667" equals 8/3.
    if not ea.free_symbols and not eb.free_symbols:
        try:
            fa, fb = complex(sympy.N(ea, 15)), complex(sympy.N(eb, 15))
            if abs(fa - fb) <= 1e-4 * max(abs(fb), 1e-12):
                return True
        except (TypeError, ValueError):
            pass
    res = ea.equals(eb)
    return bool(res)


def _parse_equation_diff(txt: str):
    """lhs-rhs of a general equation, or None when either side does not
    parse as an expression (single-variable 'x = 5' keeps its dedicated
    grade-the-rhs path in `_parse`)."""
    lhs, rhs = txt.split("=")
    if re.fullmatch(r"\s*[a-zA-Z]\w*\s*", lhs):
        return None
    from sympy.parsing.sympy_parser import (
        implicit_multiplication_application,
        parse_expr,
        standard_transformations,
    )

    try:
        tr = standard_transformations + (
            implicit_multiplication_application,
        )
        return parse_expr(lhs, transformations=tr, evaluate=True) - parse_expr(
            rhs, transformations=tr, evaluate=True
        )
    except Exception:
        return None


def sympy_match_worker(pred: str, gold: str) -> bool:
    """Runs inside the grading process (see answers_match_sympy)."""
    try:
        kp, vp = _parse_structure(pred)
        kg, vg = _parse_structure(gold)
        if kp != kg:
            return False
        if kp == "expr":
            return _exprs_equal(vp, vg)
        if kp == "matrix":
            if len(vp) != len(vg) or any(
                len(rp) != len(rg) for rp, rg in zip(vp, vg)
            ):
                return False
            return all(
                _exprs_equal(cp, cg)
                for rp, rg in zip(vp, vg)
                for cp, cg in zip(rp, rg)
            )
        if kp == "set":
            if len(vp) != len(vg):
                return False
            used = set()
            for p in vp:
                for i, g in enumerate(vg):
                    if i not in used and _exprs_equal(p, g):
                        used.add(i)
                        break
                else:
                    return False
            return True
        if kp == "intervals":
            if len(vp) != len(vg):
                return False
            for (lbp, lop, hip, rbp), (lbg, log_, hig, rbg) in zip(vp, vg):
                if lbp != lbg or rbp != rbg:
                    return False
                if not (_exprs_equal(lop, log_) and _exprs_equal(hip, hig)):
                    return False
            return True
        return False
    except Exception:
        return False


# ---------------- pool with hard timeout ----------------

_EXECUTOR = None


def _executor():
    global _EXECUTOR
    if _EXECUTOR is None:
        import atexit
        import multiprocessing
        from concurrent.futures import ProcessPoolExecutor

        _EXECUTOR = ProcessPoolExecutor(
            max_workers=1, mp_context=multiprocessing.get_context("fork")
        )
        atexit.register(_kill_executor)
    return _EXECUTOR


def _kill_executor():
    global _EXECUTOR
    if _EXECUTOR is not None:
        ex, _EXECUTOR = _EXECUTOR, None
        procs = list((getattr(ex, "_processes", None) or {}).values())
        ex.shutdown(wait=False, cancel_futures=True)
        for p in procs:
            try:
                p.kill()
            except Exception:
                pass


def answers_match_sympy(pred: str, gold: str, timeout: float = 3.0) -> bool:
    """Symbolic equivalence with a hard per-call timeout; the worker process
    is killed and replaced on timeout (sympy.simplify can hang)."""
    from concurrent.futures import TimeoutError as FuturesTimeout

    try:
        fut = _executor().submit(sympy_match_worker, pred, gold)
        return bool(fut.result(timeout=timeout))
    except FuturesTimeout:
        _kill_executor()
        return False
    except Exception:
        _kill_executor()
        return False
