"""Allocation auto-search: pick (device mesh, layout) per MFC.

Capability parity: realhf/search_engine/search.py `search_rpc_allocations`
(profile -> estimate -> multi_mcmc_search -> RPCAllocation list) — the
estimator is the TPU roofline (estimate.py) and the combinatorial search is
the C++ library (csrc/search/mdm_search.cpp, ctypes via native.py).

Device-mesh candidates over an n-chip slice: the full slice and its two
contiguous halves (the reference's disjoint gen/train split,
`sglang.d64p1m1+d32p2m1`).  Layout candidates per mesh: every
(data, fsdp, model[, pipe]) factorization that divides the model's head
counts/layers.  The first option of every MFC is the most
memory-conservative (max sharding) so the search always has a feasible
fallback.
"""

import dataclasses
import itertools
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from areal_tpu.api.config import ModelInterfaceType
from areal_tpu.base import logging
from areal_tpu.base.topology import ParallelConfig
from areal_tpu.models.config import ModelConfig
from areal_tpu.search_engine import estimate, native
from areal_tpu.search_engine.spec import CHIPS, TPUChipSpec

logger = logging.getLogger("search")


@dataclasses.dataclass
class RPCAllocation:
    """Result per MFC (reference: api/quickstart/device_mesh.py:317)."""

    rpc_name: str
    device_range: Tuple[int, int]  # [start, end) chip indices in the slice
    parallel: ParallelConfig
    est_time: float


@dataclasses.dataclass
class MFCSpec:
    name: str
    model_key: str               # MFCs sharing a model get sync edges
    interface_type: ModelInterfaceType
    config: ModelConfig
    stats: estimate.MFCStats
    trainable: bool = False      # holds optimizer state


def _factorizations(n: int, cfg: ModelConfig, allow_pipe: bool):
    """(data, fsdp, model, pipe) tuples with product n, honoring the model's
    divisibility limits."""
    out = []
    for m in (x for x in range(1, n + 1) if n % x == 0):
        if cfg.n_kv_heads % m or cfg.n_q_heads % m:
            continue
        for p in (x for x in range(1, n // m + 1) if (n // m) % x == 0):
            if p > 1 and (not allow_pipe or cfg.n_layers % p):
                continue
            rem = n // m // p
            for f in (x for x in range(1, rem + 1) if rem % x == 0):
                if cfg.hidden_dim % f:
                    continue
                d = rem // f
                out.append(ParallelConfig(data=d, fsdp=f, model=m, pipe=p))
    return out


def _mesh_candidates(n_devices: int) -> List[Tuple[int, int]]:
    meshes = [(0, n_devices)]
    if n_devices >= 2 and n_devices % 2 == 0:
        meshes += [(0, n_devices // 2), (n_devices // 2, n_devices)]
    return meshes


def _option_time(spec: MFCSpec, pc: ParallelConfig, chip: TPUChipSpec) -> float:
    if spec.interface_type == ModelInterfaceType.TRAIN_STEP:
        return estimate.train_time(spec.config, spec.stats, pc, chip)
    if spec.interface_type == ModelInterfaceType.GENERATE:
        return estimate.generate_time(spec.config, spec.stats, pc, chip)
    return estimate.inference_time(spec.config, spec.stats, pc, chip)


def _option_mems(
    spec: MFCSpec, pc: ParallelConfig, max_tokens_per_mb: int
) -> Tuple[float, float]:
    if spec.trainable:
        persist = estimate.train_persist_mem(spec.config, pc)
    elif spec.interface_type == ModelInterfaceType.GENERATE:
        persist = estimate.gen_persist_mem(spec.config, spec.stats, pc)
    else:
        persist = 2.0 * estimate.n_params(spec.config) / (
            pc.fsdp * pc.model * pc.pipe
        )
    exec_mem = estimate.act_mem(spec.config, spec.stats, pc, max_tokens_per_mb)
    return exec_mem, persist


def search_rpc_allocations(
    mfcs: Sequence[MFCSpec],
    deps: Sequence[Tuple[int, int]],
    n_devices: int,
    chip: "TPUChipSpec | str" = "v5e",
    max_tokens_per_mb: int = 16384,
    iters: int = 20000,
    seed: int = 1,
    mem_headroom: float = 0.9,
) -> List[RPCAllocation]:
    """Search (mesh, layout) per MFC minimizing simulated step makespan."""
    if isinstance(chip, str):
        chip = CHIPS[chip]

    meshes = _mesh_candidates(n_devices)
    overlap = native.ranges_overlap_matrix(meshes)

    times, exec_mems, persist_mems, mesh_ids = [], [], [], []
    options: List[List[Tuple[int, ParallelConfig]]] = []
    for spec in mfcs:
        opts, t, em, pm, mi = [], [], [], [], []
        allow_pipe = spec.interface_type != ModelInterfaceType.GENERATE
        for mesh_id, (lo, hi) in enumerate(meshes):
            for pc in _factorizations(hi - lo, spec.config, allow_pipe):
                opts.append((mesh_id, pc))
                t.append(_option_time(spec, pc, chip))
                e, p = _option_mems(spec, pc, max_tokens_per_mb)
                em.append(e)
                pm.append(p)
                mi.append(mesh_id)
        if not opts:
            raise ValueError(
                f"no feasible layout for MFC {spec.name} on {n_devices} chips"
            )
        # Most-memory-conservative option first: the C++ search restarts
        # from all-zeros if the greedy init is infeasible.
        order = np.argsort(
            [pm[i] + em[i] for i in range(len(opts))], kind="stable"
        )
        opts = [opts[i] for i in order]
        options.append(opts)
        times.append([t[i] for i in order])
        exec_mems.append([em[i] for i in order])
        persist_mems.append([pm[i] for i in order])
        mesh_ids.append([mi[i] for i in order])

    # Param-sync tables between MFCs sharing a model.
    syncs = []
    for i, a in enumerate(mfcs):
        for j, b in enumerate(mfcs):
            if i >= j or a.model_key != b.model_key:
                continue
            table = np.zeros((len(options[i]), len(options[j])))
            for oi, (ma, pa) in enumerate(options[i]):
                for oj, (mb, pb) in enumerate(options[j]):
                    table[oi, oj] = estimate.realloc_cost(
                        a.config, pa, pb, same_mesh=bool(overlap[ma, mb]),
                        chip=chip,
                    )
            syncs.append((i, j, table))

    inst = native.Instance(
        times=times,
        exec_mems=exec_mems,
        persist_mems=persist_mems,
        mesh_ids=mesh_ids,
        mesh_ranges=meshes,
        deps=deps,
        syncs=syncs,
        mem_cap=chip.hbm_bytes * mem_headroom,
    )
    assign, cost = inst.search(iters=iters, seed=seed)
    if cost >= native.INFEASIBLE:
        raise RuntimeError(
            f"no feasible allocation under {chip.hbm_bytes * mem_headroom:.1e}"
            f" bytes/chip for {n_devices} chips"
        )

    out = []
    for i, spec in enumerate(mfcs):
        mesh_id, pc = options[i][assign[i]]
        lo, hi = meshes[mesh_id]
        out.append(
            RPCAllocation(
                rpc_name=spec.name,
                device_range=(lo, hi),
                parallel=pc,
                est_time=times[i][assign[i]],
            )
        )
        logger.info(
            f"alloc {spec.name}: chips [{lo},{hi}) layout {pc.to_str()} "
            f"(~{times[i][assign[i]]:.3f}s/step)"
        )
    logger.info(f"simulated step makespan: {cost:.3f}s")
    return out


def search_ppo_math_allocations(
    model_cfg: ModelConfig,
    n_prompts: int,
    group_size: int,
    max_new_tokens: int,
    n_devices: int,
    chip: "TPUChipSpec | str" = "v5e",
    prompt_len: int = 512,
    has_ref: bool = False,
    max_tokens_per_mb: int = 16384,
    iters: int = 20000,
    seed: int = 1,
) -> Dict[str, RPCAllocation]:
    """Search allocations for the quickstart ppo-math DFG (actor_gen ->
    [ref_inf] -> actor_train).  Returns {rpc_name: RPCAllocation}; the
    quickstart `--allocation search` path translates these into
    (parallel, device_offset) per shard (reference: apps/main.py:104-107
    caching search_rpc_allocations results into the experiment setup)."""
    n_seqs = n_prompts * group_size
    avg_len = prompt_len + max_new_tokens // 2
    mfcs = [
        MFCSpec(
            "actor_gen", "actor", ModelInterfaceType.GENERATE, model_cfg,
            estimate.MFCStats(
                n_seqs=n_seqs, avg_seqlen=avg_len, gen_tokens=max_new_tokens
            ),
        ),
    ]
    deps = []
    if has_ref:
        mfcs.append(
            MFCSpec(
                "ref_inf", "ref", ModelInterfaceType.INFERENCE, model_cfg,
                estimate.MFCStats(n_seqs=n_seqs, avg_seqlen=avg_len),
            )
        )
        deps.append((0, 1))
    train_idx = len(mfcs)
    mfcs.append(
        MFCSpec(
            "actor_train", "actor", ModelInterfaceType.TRAIN_STEP, model_cfg,
            estimate.MFCStats(n_seqs=n_seqs, avg_seqlen=avg_len),
            trainable=True,
        )
    )
    deps += [(i, train_idx) for i in range(train_idx)]
    allocs = search_rpc_allocations(
        mfcs, deps, n_devices, chip=chip,
        max_tokens_per_mb=max_tokens_per_mb, iters=iters, seed=seed,
    )
    return {a.rpc_name: a for a in allocs}
