"""ctypes bindings for the C++ MCMC allocation search (csrc/search/).

Builds the shared library on demand with `make` (g++), mirroring the
reference's compiled mdm_search extension (csrc/search/search.cpp:706,
driven from realhf/search_engine/search.py).  A pure-python fallback
implements the same simulate() semantics for environments without a
toolchain (and doubles as the parity oracle in tests).
"""

import ctypes
import os
import subprocess
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from areal_tpu.base import logging

logger = logging.getLogger("mdm_search")

_CSRC = os.path.join(os.path.dirname(__file__), "..", "..", "csrc")
_LIB_PATH = os.path.abspath(os.path.join(_CSRC, "build", "libmdm_search.so"))
_lib = None

INFEASIBLE = 1e30


def ranges_overlap_matrix(mesh_ranges) -> np.ndarray:
    """[n, n] bool: do chip ranges [lo, hi) intersect (mirrors the C++
    ranges_overlap)."""
    lo = np.array([r[0] for r in mesh_ranges])
    hi = np.array([r[1] for r in mesh_ranges])
    return ~((hi[:, None] <= lo[None, :]) | (hi[None, :] <= lo[:, None]))


def _stale() -> bool:
    """True when any csrc/search source is newer than the built library.
    Missing sources (prebuilt-only deployment) never mark the lib stale."""
    if not os.path.exists(_LIB_PATH):
        return True
    lib_mtime = os.path.getmtime(_LIB_PATH)
    src_dir = os.path.join(os.path.abspath(_CSRC), "search")
    sources = [os.path.join(os.path.abspath(_CSRC), "Makefile")]
    if os.path.isdir(src_dir):
        sources += [
            os.path.join(src_dir, f)
            for f in os.listdir(src_dir)
            if f.endswith((".cpp", ".h"))
        ]
    return any(
        os.path.exists(s) and os.path.getmtime(s) > lib_mtime for s in sources
    )


def _load() -> Optional[ctypes.CDLL]:
    global _lib
    if _lib is not None:
        return _lib
    try:
        if _stale():
            # `make` only replaces the target on success, so a failed
            # rebuild leaves any previous (stale but loadable) binary.
            subprocess.run(
                ["make", "-B"], cwd=os.path.abspath(_CSRC), check=True,
                capture_output=True,
            )
        lib = ctypes.CDLL(_LIB_PATH)
    except (OSError, subprocess.CalledProcessError) as e:
        logger.warning(f"cannot load mdm_search ({e!r}); python fallback")
        return None
    i32p = np.ctypeslib.ndpointer(np.int32, flags="C_CONTIGUOUS")
    f64p = np.ctypeslib.ndpointer(np.float64, flags="C_CONTIGUOUS")
    common = [
        ctypes.c_int, i32p, i32p, f64p, f64p, f64p, i32p,
        ctypes.c_int, i32p, i32p,
        ctypes.c_int, i32p, i32p,
        ctypes.c_int, i32p, i32p, f64p, i32p,
        ctypes.c_double,
    ]
    lib.mdm_simulate.restype = ctypes.c_double
    lib.mdm_simulate.argtypes = common + [i32p]
    lib.mdm_search.restype = ctypes.c_double
    lib.mdm_search.argtypes = common + [
        ctypes.c_int64, ctypes.c_uint64, ctypes.c_double, ctypes.c_double,
        i32p,
    ]
    _lib = lib
    return _lib


class Instance:
    """Flattened search problem (mirrors csrc/search/mdm_search.cpp)."""

    def __init__(
        self,
        times: List[List[float]],          # [mfc][option] seconds
        exec_mems: List[List[float]],      # [mfc][option] bytes
        persist_mems: List[List[float]],   # [mfc][option] bytes
        mesh_ids: List[List[int]],         # [mfc][option]
        mesh_ranges: Sequence[Tuple[int, int]],  # [n_meshes] chip [lo, hi)
        deps: Sequence[Tuple[int, int]],   # (src, dst) MFC indices
        syncs: Sequence[Tuple[int, int, np.ndarray]],  # (a, b, cost[na, nb])
        mem_cap: float,
    ):
        self.n_mfcs = len(times)
        self.n_options = np.array([len(t) for t in times], np.int32)
        self.opt_offset = np.zeros(self.n_mfcs, np.int32)
        np.cumsum(self.n_options[:-1], out=self.opt_offset[1:])
        self.time = np.concatenate([np.asarray(t, np.float64) for t in times])
        self.exec_mem = np.concatenate(
            [np.asarray(t, np.float64) for t in exec_mems]
        )
        self.persist_mem = np.concatenate(
            [np.asarray(t, np.float64) for t in persist_mems]
        )
        self.mesh_of = np.concatenate(
            [np.asarray(t, np.int32) for t in mesh_ids]
        )
        self.mesh_lo = np.array([r[0] for r in mesh_ranges], np.int32)
        self.mesh_hi = np.array([r[1] for r in mesh_ranges], np.int32)
        self.n_meshes = len(mesh_ranges)
        self.mesh_overlap = ranges_overlap_matrix(mesh_ranges)
        self.dep_src = np.array([d[0] for d in deps], np.int32)
        self.dep_dst = np.array([d[1] for d in deps], np.int32)
        self.sync_a = np.array([s[0] for s in syncs], np.int32)
        self.sync_b = np.array([s[1] for s in syncs], np.int32)
        tables = [np.asarray(s[2], np.float64).ravel() for s in syncs]
        self.sync_cost = (
            np.concatenate(tables) if tables else np.zeros(0, np.float64)
        )
        self.sync_offset = np.zeros(len(syncs), np.int32)
        off = 0
        for i, t in enumerate(tables):
            self.sync_offset[i] = off
            off += t.size
        self.mem_cap = float(mem_cap)

    def _args(self):
        return (
            self.n_mfcs, self.n_options, self.opt_offset, self.time,
            self.exec_mem, self.persist_mem, self.mesh_of,
            self.n_meshes, self.mesh_lo, self.mesh_hi,
            len(self.dep_src), self.dep_src, self.dep_dst,
            len(self.sync_a), self.sync_a, self.sync_b,
            self.sync_cost, self.sync_offset,
            self.mem_cap,
        )

    # ---------------- native ----------------

    def simulate(self, assign: Sequence[int]) -> float:
        a = np.asarray(assign, np.int32)
        lib = _load()
        if lib is not None:
            return float(lib.mdm_simulate(*self._args(), a))
        return self.simulate_py(a)

    def search(
        self,
        iters: int = 20000,
        seed: int = 0,
        beta0: float = 0.1,
        beta1: float = 50.0,
    ) -> Tuple[np.ndarray, float]:
        lib = _load()
        best = np.zeros(self.n_mfcs, np.int32)
        if lib is not None:
            cost = float(
                lib.mdm_search(
                    *self._args(), iters, seed, beta0, beta1, best
                )
            )
            return best, cost
        return self.search_py(iters, seed, beta0, beta1)

    # ---------------- pure-python mirror ----------------

    def simulate_py(self, assign: Sequence[int]) -> float:
        # Per-chip memory: residents of every mesh covering a chip stack;
        # transient peak is the largest exec allocation among MFCs on it
        # (mirrors csrc/search/mdm_search.cpp simulate()).
        n_chips = int(self.mesh_hi.max(initial=0))
        chip_persist = np.zeros(n_chips)
        chip_exec = np.zeros(n_chips)
        for i in range(self.n_mfcs):
            o = self.opt_offset[i] + assign[i]
            m = self.mesh_of[o]
            lo, hi = self.mesh_lo[m], self.mesh_hi[m]
            chip_persist[lo:hi] += self.persist_mem[o]
            chip_exec[lo:hi] = np.maximum(chip_exec[lo:hi], self.exec_mem[o])
        if np.any(chip_persist + chip_exec > self.mem_cap):
            return INFEASIBLE

        sync_delay = np.zeros(self.n_mfcs)
        for s in range(len(self.sync_a)):
            a, b = self.sync_a[s], self.sync_b[s]
            nb = self.n_options[b]
            sync_delay[b] += self.sync_cost[
                self.sync_offset[s] + assign[a] * nb + assign[b]
            ]

        # Kahn topological order over dep edges, like the C++.
        indeg = np.zeros(self.n_mfcs, np.int32)
        for d in self.dep_dst:
            indeg[d] += 1
        order = [i for i in range(self.n_mfcs) if indeg[i] == 0]
        h = 0
        while h < len(order):
            i = order[h]
            h += 1
            for s, d in zip(self.dep_src, self.dep_dst):
                if s == i:
                    indeg[d] -= 1
                    if indeg[d] == 0:
                        order.append(int(d))
        if len(order) != self.n_mfcs:
            return INFEASIBLE  # dependency cycle

        finish = np.zeros(self.n_mfcs)
        mesh_free = np.zeros(self.n_meshes)
        for i in order:
            o = self.opt_offset[i] + assign[i]
            m = self.mesh_of[o]
            start = 0.0
            for s, d in zip(self.dep_src, self.dep_dst):
                if d == i:
                    start = max(start, finish[s])
            for m2 in range(self.n_meshes):
                if self.mesh_overlap[m, m2]:
                    start = max(start, mesh_free[m2])
            start += sync_delay[i]
            finish[i] = start + self.time[o]
            mesh_free[m] = finish[i]
        return float(finish.max(initial=0.0))

    def search_py(self, iters, seed, beta0, beta1):
        rng = np.random.default_rng(seed)
        cur = np.array(
            [int(np.argmin(t)) for t in np.split(self.time, self.opt_offset[1:])],
            np.int32,
        )
        cost = self.simulate_py(cur)
        if cost >= INFEASIBLE:
            cur = np.zeros(self.n_mfcs, np.int32)
            cost = self.simulate_py(cur)
        best, best_cost = cur.copy(), cost
        for it in range(iters):
            beta = beta0 + (beta1 - beta0) * it / max(iters - 1, 1)
            i = int(rng.integers(self.n_mfcs))
            if self.n_options[i] <= 1:
                continue
            old = cur[i]
            prop = int(rng.integers(self.n_options[i]))
            if prop == old:
                prop = (prop + 1) % self.n_options[i]
            cur[i] = prop
            c = self.simulate_py(cur)
            if c <= cost or (
                c < INFEASIBLE
                and rng.random() < np.exp(-beta * (c - cost))
            ):
                cost = c
                if c < best_cost:
                    best_cost, best = c, cur.copy()
            else:
                cur[i] = old
        return best, float(best_cost)
