"""TPU chip/interconnect specs for the allocation-search cost model.

Capability parity: the reference's cluster spec + profiled GPU cost tables
(realhf/search_engine/estimate.py reads profiled layer stats); on TPU the
roofline numbers are stable enough to parameterize directly.  Numbers are
public datasheet values derated by an empirical MFU/utilization factor.
"""

import dataclasses


@dataclasses.dataclass(frozen=True)
class TPUChipSpec:
    name: str
    bf16_flops: float        # peak bf16 FLOP/s per chip
    hbm_bytes: float         # HBM capacity per chip
    hbm_bw: float            # HBM bandwidth bytes/s
    ici_bw: float            # per-link ICI bandwidth bytes/s (one direction)
    dcn_bw: float = 25e9 / 8  # host NIC, bytes/s
    mfu: float = 0.4         # achievable fraction of peak on matmul-heavy work
    comm_eff: float = 0.7    # achieved fraction of ICI peak on collectives


V5E = TPUChipSpec(
    name="v5e",
    bf16_flops=197e12,
    hbm_bytes=16e9,
    hbm_bw=819e9,
    ici_bw=1600e9 / 8 / 2,  # 1.6 Tbps total over links -> per-direction bytes
)

V5P = TPUChipSpec(
    name="v5p",
    bf16_flops=459e12,
    hbm_bytes=95e9,
    hbm_bw=2765e9,
    ici_bw=4800e9 / 8 / 2,
)

CHIPS = {"v5e": V5E, "v5p": V5P}
