"""Roofline cost/memory estimation per (MFC, mesh, layout) option.

Capability parity: realhf/search_engine/estimate.py (op/comm time + memory
estimation feeding mdm_search) — re-parameterized for the TPU roofline:
MXU-bound matmul time, HBM-bound decode, ICI-bound collectives, instead of
profiled CUDA layer tables.

All estimates are per training step of one MFC, in seconds / bytes
per device.  Coarse by design: the search only needs correct *ordering*
between candidate layouts, and the reference likewise searches on a
simulator, not measurements.
"""

import dataclasses

from areal_tpu.base.topology import ParallelConfig
from areal_tpu.models.config import ModelConfig
from areal_tpu.search_engine.spec import TPUChipSpec


@dataclasses.dataclass
class MFCStats:
    """Workload of one MFC per step."""

    n_seqs: int              # sequences per step
    avg_seqlen: int          # average total length (prompt + generated)
    gen_tokens: int = 0      # decoded tokens per sequence (generate MFCs)


def n_params(cfg: ModelConfig) -> float:
    d, f, L, v = cfg.hidden_dim, cfg.intermediate_dim, cfg.n_layers, cfg.vocab_size
    attn = d * (cfg.q_dim + 2 * cfg.kv_dim) + cfg.q_dim * d
    if cfg.is_moe:
        mlp = 3 * d * cfg.moe_intermediate_dim * cfg.n_experts + d * cfg.n_experts
    else:
        mlp = 3 * d * f
    embed = v * d * (1 if cfg.tied_embeddings else 2)
    return float(L * (attn + mlp) + embed)


def fwd_flops(cfg: ModelConfig, tokens: float, avg_seqlen: float) -> float:
    """2*N per token matmul flops + quadratic attention term."""
    quad = 4.0 * cfg.n_layers * cfg.q_dim * avg_seqlen * tokens
    return 2.0 * n_params(cfg) * tokens + quad


def _shard(parallel: ParallelConfig) -> float:
    """Degree over which params shard (fsdp x model x pipe)."""
    return float(parallel.fsdp * parallel.model * parallel.pipe)


def train_time(
    cfg: ModelConfig, st: MFCStats, parallel: ParallelConfig, chip: TPUChipSpec
) -> float:
    tokens = st.n_seqs * st.avg_seqlen
    flops = 3.0 * fwd_flops(cfg, tokens, st.avg_seqlen)  # fwd + bwd
    compute = flops / (parallel.world_size * chip.bf16_flops * chip.mfu)
    pbytes = 2.0 * n_params(cfg)  # bf16
    comm = 0.0
    if parallel.model > 1:
        # 4 all-reduces of activations per layer (fwd+bwd), ring cost.
        act = tokens * cfg.hidden_dim * 2.0 / (parallel.data * parallel.fsdp * parallel.seq)
        comm += (
            4.0 * cfg.n_layers * act
            * (parallel.model - 1) / parallel.model
            / (chip.ici_bw * chip.comm_eff)
        )
    if parallel.fsdp > 1:
        # all-gather params (fwd+bwd) + reduce-scatter grads.
        comm += 3.0 * (pbytes / parallel.model / parallel.pipe) * (
            (parallel.fsdp - 1) / parallel.fsdp
        ) / (chip.ici_bw * chip.comm_eff)
    if parallel.pipe > 1:
        # GPipe bubble: (P-1)/(M+P-1) with M=4P microbatches.
        P = parallel.pipe
        compute *= 1.0 + (P - 1) / (4.0 * P + P - 1)
    return compute + comm


def inference_time(
    cfg: ModelConfig, st: MFCStats, parallel: ParallelConfig, chip: TPUChipSpec
) -> float:
    tokens = st.n_seqs * st.avg_seqlen
    compute = fwd_flops(cfg, tokens, st.avg_seqlen) / (
        parallel.world_size * chip.bf16_flops * chip.mfu
    )
    return compute


def generate_time(
    cfg: ModelConfig, st: MFCStats, parallel: ParallelConfig, chip: TPUChipSpec
) -> float:
    """Prefill (MXU-bound) + decode (HBM-bound weight streaming)."""
    prompt_len = max(st.avg_seqlen - st.gen_tokens, 1)
    prefill = fwd_flops(cfg, st.n_seqs * prompt_len, prompt_len) / (
        parallel.world_size * chip.bf16_flops * chip.mfu
    )
    pbytes_dev = 2.0 * n_params(cfg) / _shard(parallel)
    batch_per_dev = max(st.n_seqs / (parallel.data * parallel.fsdp), 1.0)
    per_step_compute = 2.0 * n_params(cfg) * batch_per_dev / (
        _shard(parallel) * chip.bf16_flops * chip.mfu
    )
    per_step = max(pbytes_dev / chip.hbm_bw, per_step_compute)
    return prefill + st.gen_tokens * per_step


def train_persist_mem(cfg: ModelConfig, parallel: ParallelConfig) -> float:
    """fp32 master + Adam(mu,nu) + bf16 compute copy + fp32 grads."""
    return n_params(cfg) * (4.0 + 8.0 + 2.0 + 4.0) / _shard(parallel)


def gen_persist_mem(
    cfg: ModelConfig, st: MFCStats, parallel: ParallelConfig
) -> float:
    pbytes = 2.0 * n_params(cfg) / _shard(parallel)
    kv = (
        2.0 * st.n_seqs * st.avg_seqlen * cfg.n_layers * cfg.kv_dim * 2.0
        / (parallel.data * parallel.fsdp * parallel.model)
    )
    return pbytes + kv


def act_mem(
    cfg: ModelConfig, st: MFCStats, parallel: ParallelConfig, max_tokens_per_mb: int
) -> float:
    """Transient activation memory with remat: one layer's activations plus
    the per-layer residual stream, and the fp32 logits of one micro-batch."""
    tok_dev = max_tokens_per_mb / (parallel.data * parallel.fsdp * parallel.seq)
    resid = tok_dev * cfg.hidden_dim * 4.0 * cfg.n_layers / parallel.pipe * 0.1
    layer = tok_dev * (cfg.hidden_dim * 8.0 + cfg.intermediate_dim * 2.0) / parallel.model
    logits = tok_dev * cfg.vocab_size * 4.0 * 3.0 / parallel.model
    return resid + layer + logits


def realloc_cost(
    cfg: ModelConfig,
    src: ParallelConfig,
    dst: ParallelConfig,
    same_mesh: bool,
    chip: TPUChipSpec,
) -> float:
    """Reshard cost between two layouts of the same model's params."""
    if same_mesh and src == dst:
        return 0.0
    pbytes = 2.0 * n_params(cfg)
    bw = (chip.ici_bw if same_mesh else chip.dcn_bw) * chip.comm_eff
    # Each device receives its destination shard; approximate total moved
    # bytes as one full param set over the aggregate bandwidth of the
    # destination's sharding degree.
    return pbytes / _shard(dst) / bw * max(_shard(dst) / _shard(src), 1.0)
