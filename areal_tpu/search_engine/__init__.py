from areal_tpu.search_engine.search import (  # noqa: F401
    RPCAllocation,
    search_rpc_allocations,
)
