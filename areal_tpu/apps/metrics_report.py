"""Fleet-health poller + SLO watchdog over the live metrics plane.

    python -m areal_tpu.apps.metrics_report --experiment e --trial t \
        [--count 5] [--interval 2] \
        [--slo "crit: staleness_p99 <= 4"] \
        [--slo "warn: drop(goodput) < 0.2 over 5"]

Discovers every process role of a trial via ``name_resolve`` (each role
announces its ``/metrics`` base URL under ``names.metrics_root``;
``--url role=http://host:port`` adds/overrides endpoints statically),
scrapes them on an interval, renders one fleet-health table per scrape
(per-server goodput, staleness p50/p99, idle fraction, weight-version
skew), and evaluates declarative SLO rules against the scrape history,
emitting ``WARN``/``CRIT`` lines — the watchdog signal a fleet
controller (ROADMAP item 2) subscribes to.

SLO rule grammar (one rule per ``--slo`` / per line of ``--slo-file``;
``#`` comments and blank lines ignored)::

    [warn:|crit:] SIGNAL OP VALUE          # threshold on the latest scrape
    [warn:|crit:] drop(SIGNAL) OP FRAC over N   # relative drop over a window

``OP`` is one of ``<= < >= > == !=``.  The rule states the REQUIREMENT;
a violation fires at the rule's severity (default ``crit``).  Threshold
rules read the newest scrape; ``drop(s) < f over N`` requires the
relative drop of ``s`` from its max over the last ``N`` scrapes to stay
under ``f`` (e.g. ``drop(goodput) < 0.2 over 5`` = goodput must not
fall more than 20% below its recent peak).

Fleet signals available to rules: ``goodput`` (tokens/s summed over gen
servers, rate of ``areal_gen_tokens_total`` between scrapes),
``staleness_p50`` / ``staleness_p99`` (from the
``areal_replay_staleness`` histogram), ``sample_e2e_p50`` /
``sample_e2e_p99`` / ``sample_admit_p99`` (per-sample causal-lineage
latencies: dispatch → train consumption and dispatch → replay
admission, from the ``areal_sample_e2e_seconds`` /
``areal_sample_admit_seconds`` histograms — e.g. ``warn:
sample_e2e_p99 <= 30`` alerts when the slowest samples take more than
30 s dispatch-to-train), ``queue_depth``,
``kv_utilization``, ``idle_frac``, ``version_skew`` (max-min serving
weight version across gen servers), ``backpressure`` (rate of
``areal_rollout_backpressure_total``), ``in_flight``,
``pipeline_fill`` / ``pipeline_bubble`` (pipelined-step occupancy: the
busiest stage's ``areal_master_pipeline_fill_ratio`` and the summed
``areal_master_pipeline_bubble_seconds`` over stages — e.g.
``warn: pipeline_fill >= 0.6`` alerts when the overlapped step leaves
the dominant stage mostly idle), ``ckpt_age`` (seconds since the last
committed recover checkpoint — ``crit: ckpt_age < 900`` requires a
crash to lose at most 15 minutes of work), ``anomalies`` /
``quarantine_streak`` / ``push_rejected`` (numerical-integrity guard
plane: sentinel trips summed over kinds, the master's live run of
consecutive quarantined steps, and checksum-rejected weight pushes —
e.g. ``crit: quarantine_streak <= 2`` pages one step before the
escalation ladder rolls the trial back to the last good checkpoint),
``weight_version_skew`` / ``push_p99`` (parameter distribution fabric,
system/paramstore.py: max-min serving weight version across gen servers
— an alias of ``version_skew`` named for fabric SLOs — and the p99 of
``areal_param_push_seconds``, one observation per whole-fleet broadcast
or cross-set realloc push — e.g. ``warn: weight_version_skew <= 1``
requires laggards to stay within the v-1 staleness bound the store's
refcounts guarantee, and ``crit: push_p99 <= 30`` pages when weight
distribution is eating the training step), ``advisor_pred_err`` /
``mfc_mfu_min`` / ``mfc_mfu_max`` (placement-advisor plane,
apps/advisor.py: the master's online cost-model residual
``areal_master_advisor_pred_err_ratio`` and the min/max of the labeled
per-MFC MFU gauges — e.g. ``warn: advisor_pred_err <= 0.5`` flags when
the DFG-composed prediction stops tracking the measured step, so the
advisor's offline rankings are running on stale physics, and ``warn:
mfc_mfu_min >= 0.02`` surfaces an MFC whose current placement is
starving it), ``grade_latency_p99`` / ``verifier_queue_depth`` /
``verifier_servers`` / ``verifier_breaker_open`` (verifier fleet,
system/verifier_pool.py: the p99 of ``areal_verifier_grade_seconds``
over all backends, the pool client's in-flight grade items, live
members, and open breakers — e.g. ``crit: grade_latency_p99 <= 5``
tells the supervisor's verifier lane to spawn a worker when sandboxed
grading starts eating the sample pipeline, and ``crit:
verifier_queue_depth <= 64`` catches a backed-up pool before episode
completion stalls on rewards), ``task_reward_min`` (task-mixture
curriculum, data/mixture.py: the min over the labeled per-task reward
EMAs ``areal_mixture_task_reward`` — e.g. ``warn: task_reward_min >=
0.2`` pages when any task stream's reward collapses), plus any raw
unlabeled series name.

Exit status: 0 if no CRIT fired over the run, 1 otherwise (``--count``
bounds the run; without it the poller runs until interrupted).
"""

import argparse
import dataclasses
import json
import math
import re
import sys
import time
import urllib.request
from typing import Dict, List, Optional, Tuple

from areal_tpu.base import name_resolve, names
from areal_tpu.base.metrics import parse_prometheus_text, quantile_from_buckets

_OPS = {
    "<=": lambda a, b: a <= b,
    ">=": lambda a, b: a >= b,
    "<": lambda a, b: a < b,
    ">": lambda a, b: a > b,
    "==": lambda a, b: a == b,
    "!=": lambda a, b: a != b,
}

_RULE_RE = re.compile(
    r"^\s*(?:(warn|crit)\s*:\s*)?"
    r"(?:drop\(\s*(?P<dsig>[a-zA-Z_][a-zA-Z0-9_]*)\s*\)"
    r"|(?P<sig>[a-zA-Z_][a-zA-Z0-9_]*))"
    r"\s*(?P<op><=|>=|==|!=|<|>)\s*(?P<val>[-+0-9.eE%]+)"
    r"(?:\s+over\s+(?P<win>\d+))?\s*$"
)


@dataclasses.dataclass
class SLORule:
    severity: str  # "warn" | "crit"
    signal: str
    op: str
    value: float
    window: Optional[int] = None  # set => drop(signal) rule
    is_drop: bool = False
    text: str = ""

    def evaluate(self, history: List[Dict[str, float]]) -> Optional[str]:
        """Return a violation message, or None when the rule holds.
        A signal absent from the scrape is not a violation (the role may
        not have started yet) — the watchdog reports coverage separately."""
        if not history:
            return None
        if self.is_drop:
            win = history[-(self.window or 1):]
            vals = [h[self.signal] for h in win if self.signal in h]
            if len(vals) < 2:
                return None
            peak, cur = max(vals), vals[-1]
            if peak <= 0:
                return None
            drop = (peak - cur) / peak
            if not _OPS[self.op](drop, self.value):
                return (
                    f"{self.text}: {self.signal} dropped "
                    f"{100 * drop:.1f}% from its window peak "
                    f"({peak:.4g} -> {cur:.4g} over last {len(vals)} scrapes)"
                )
            return None
        cur = history[-1].get(self.signal)
        if cur is None or (isinstance(cur, float) and math.isnan(cur)):
            return None
        if not _OPS[self.op](cur, self.value):
            return f"{self.text}: {self.signal}={cur:.4g} (want {self.op} {self.value:g})"
        return None


def parse_slo_rule(text: str) -> SLORule:
    m = _RULE_RE.match(text)
    if not m:
        raise ValueError(
            f"unparseable SLO rule {text!r} (grammar: "
            f"'[warn:|crit:] SIGNAL OP VALUE [over N]' or "
            f"'[warn:|crit:] drop(SIGNAL) OP FRAC over N')"
        )
    raw = m.group("val")
    value = float(raw[:-1]) / 100.0 if raw.endswith("%") else float(raw)
    is_drop = m.group("dsig") is not None
    win = m.group("win")
    if is_drop and win is None:
        raise ValueError(f"drop() rule needs an 'over N' window: {text!r}")
    return SLORule(
        severity=m.group(1) or "crit",
        signal=m.group("dsig") or m.group("sig"),
        op=m.group("op"),
        value=value,
        window=int(win) if win else None,
        is_drop=is_drop,
        text=text.strip(),
    )


# ---------------------------------------------------------------------------
# Scraping


def scrape_url(url: str, timeout: float = 5.0) -> Tuple[
        List[Tuple[str, Dict[str, str], float]], Dict[str, str]]:
    target = url if url.endswith("/metrics") else url.rstrip("/") + "/metrics"
    with urllib.request.urlopen(target, timeout=timeout) as r:
        return parse_prometheus_text(r.read().decode())


def discover(experiment: str, trial: str) -> Dict[str, str]:
    """role -> base URL, from the trial's announced metrics subtree."""
    root = names.metrics_root(experiment, trial)
    out: Dict[str, str] = {}
    for key in sorted(name_resolve.find_subtree(root)):
        role = key[len(root) + 1:]
        try:
            out[role] = name_resolve.get(key)
        except Exception:
            continue
    return out


def _series_sum(samples, name: str) -> Optional[float]:
    vals = [v for n, _, v in samples if n == name]
    return sum(vals) if vals else None


def _hist_quantile(samples, series: str, q: float) -> float:
    pts = [
        (float(labels["le"]), v)
        for n, labels, v in samples
        if n == f"{series}_bucket" and "le" in labels
    ]
    return quantile_from_buckets(pts, q)


def _staleness_quantile(samples, q: float) -> float:
    return _hist_quantile(samples, "areal_replay_staleness", q)


@dataclasses.dataclass
class RoleScrape:
    role: str
    t: float
    samples: list
    ok: bool = True
    error: str = ""

    def value(self, name: str) -> Optional[float]:
        return _series_sum(self.samples, name)


def scrape_fleet(endpoints: Dict[str, str]) -> List[RoleScrape]:
    out = []
    for role, url in endpoints.items():
        t = time.monotonic()
        try:
            samples, _ = scrape_url(url)
            out.append(RoleScrape(role, t, samples))
        except Exception as e:  # noqa: BLE001 — a dead role is a finding
            out.append(RoleScrape(role, t, [], ok=False, error=repr(e)))
    return out


def _rate(cur: RoleScrape, prev: Optional[RoleScrape], name: str) -> float:
    """Per-second rate of a counter between two scrapes of one role."""
    if prev is None or not prev.ok or not cur.ok:
        return 0.0
    c, p = cur.value(name), prev.value(name)
    if c is None or p is None:
        return 0.0
    dt = cur.t - prev.t
    return max(c - p, 0.0) / dt if dt > 0 else 0.0


def fleet_signals(
    roles: List[RoleScrape],
    prev: Optional[Dict[str, RoleScrape]],
) -> Tuple[Dict[str, float], List[Dict[str, object]]]:
    """(fleet-level signal dict, per-role table rows) for one scrape."""
    signals: Dict[str, float] = {}
    rows: List[Dict[str, object]] = []
    all_samples = [s for r in roles if r.ok for s in r.samples]
    gen_roles = [
        r for r in roles
        if r.ok and any(n.startswith("areal_gen_") for n, _, _ in r.samples)
    ]
    goodput_total = 0.0
    versions: List[float] = []
    idle_fracs: List[float] = []
    for r in roles:
        p = prev.get(r.role) if prev else None
        row: Dict[str, object] = {"role": r.role, "ok": r.ok}
        if not r.ok:
            row["error"] = r.error
            rows.append(row)
            continue
        if r in gen_roles:
            gp = _rate(r, p, "areal_gen_tokens_total")
            if gp == 0.0:
                gp = r.value("areal_gen_goodput_tokens_per_second") or 0.0
            goodput_total += gp
            live = r.value("areal_gen_live_slots") or 0.0
            cap = r.value("areal_gen_capacity_slots") or 0.0
            idle = 1.0 - (live / cap) if cap > 0 else 1.0
            idle_fracs.append(idle)
            v = r.value("areal_gen_weight_version")
            if v is not None:
                versions.append(v)
            row.update(
                goodput=round(gp, 2),
                queue_depth=r.value("areal_gen_queue_depth") or 0.0,
                kv_util=round(
                    r.value("areal_gen_kv_utilization_ratio") or 0.0, 3
                ),
                live_slots=live,
                idle_frac=round(idle, 3),
                version=v,
            )
        steps = r.value("areal_master_steps_total")
        if steps is not None:
            row["steps"] = steps
        rows.append(row)
    signals["goodput"] = goodput_total
    signals["queue_depth"] = _series_sum(
        all_samples, "areal_gen_queue_depth"
    ) or 0.0
    kv = [
        r.value("areal_gen_kv_utilization_ratio") or 0.0 for r in gen_roles
    ]
    signals["kv_utilization"] = sum(kv) / len(kv) if kv else 0.0
    signals["idle_frac"] = (
        sum(idle_fracs) / len(idle_fracs) if idle_fracs else 0.0
    )
    signals["version_skew"] = (
        max(versions) - min(versions) if versions else 0.0
    )
    # Fabric alias: the same spread, named for parameter-distribution
    # SLOs (``warn: weight_version_skew <= 1`` asserts the store's
    # staleness bound — orphaned subtrees serve head-1, never head-2).
    signals["weight_version_skew"] = signals["version_skew"]
    p50 = _staleness_quantile(all_samples, 0.50)
    p99 = _staleness_quantile(all_samples, 0.99)
    if not math.isnan(p50):
        signals["staleness_p50"] = p50
    if not math.isnan(p99):
        signals["staleness_p99"] = p99
    # Per-sample lineage latencies (seconds): dispatch -> train
    # consumption and dispatch -> replay admission, from the replay
    # buffer's stage histograms.  Absent until the first sample trains.
    for sig, series, q in (
        ("sample_e2e_p50", "areal_sample_e2e_seconds", 0.50),
        ("sample_e2e_p99", "areal_sample_e2e_seconds", 0.99),
        ("sample_admit_p99", "areal_sample_admit_seconds", 0.99),
    ):
        v = _hist_quantile(all_samples, series, q)
        if not math.isnan(v):
            signals[sig] = v
    bp = _series_sum(all_samples, "areal_rollout_backpressure_total")
    if bp is not None:
        signals["backpressure"] = bp
    inf = _series_sum(all_samples, "areal_rollout_in_flight")
    if inf is not None:
        signals["in_flight"] = inf
    # Elastic-fleet health: total prompt re-dispatches (all failure
    # reasons) and currently-open circuit breakers — a rising redispatch
    # rate or any stuck-open breaker is a capacity/SLO signal the fleet
    # supervisor and watchdog can alert or scale on.
    rd = _series_sum(all_samples, "areal_rollout_redispatch_total")
    if rd is not None:
        signals["redispatch"] = rd
    bo = _series_sum(all_samples, "areal_rollout_breaker_open")
    if bo is not None:
        signals["breaker_open"] = bo
    # Pipelined-step occupancy (labeled per-stage gauges -> computed
    # fleet signals): wall-clock of an overlapped step ~= the busiest
    # stage, so that stage's fill approaching 1.0 means the pipeline is
    # tight; the summed per-stage bubble seconds is the idle the
    # overlap exists to shrink.  Absent when pipeline_overlap is off.
    fills = [
        v for n, labels, v in all_samples
        if n == "areal_master_pipeline_fill_ratio"
    ]
    if fills:
        signals["pipeline_fill"] = max(fills)
    bubs = [
        v for n, labels, v in all_samples
        if n == "areal_master_pipeline_bubble_seconds"
    ]
    if bubs:
        signals["pipeline_bubble"] = sum(bubs)
    # Checkpoint freshness: seconds since the master last committed a
    # recover checkpoint (the atomic flip stamps
    # areal_ckpt_last_success_timestamp_seconds).  A rule like
    # ``crit: ckpt_age < 900`` requires recoverability to stay under 15
    # minutes of lost work.  Absent until the first flip.
    ts = [
        v for n, labels, v in all_samples
        if n == "areal_ckpt_last_success_timestamp_seconds" and v > 0
    ]
    if ts:
        signals["ckpt_age"] = max(0.0, time.time() - max(ts))
    # Numerical-integrity guard plane: sentinel trips summed over kinds
    # (the raw series is labeled, so rules can't address it directly),
    # the master's live quarantine streak, and checksum-rejected weight
    # pushes.  ``warn: anomalies <= 0`` surfaces the first quarantined
    # step; ``crit: quarantine_streak <= 2`` pages one step before the
    # escalation ladder rolls the trial back; ``crit: push_rejected == 0``
    # means a generation server saw a corrupt weight payload.
    an = _series_sum(all_samples, "areal_train_anomaly_total")
    if an is not None:
        signals["anomalies"] = an
    qs = _series_sum(all_samples, "areal_master_consecutive_quarantines")
    if qs is not None:
        signals["quarantine_streak"] = qs
    pr = _series_sum(all_samples, "areal_gen_weight_push_rejected_total")
    if pr is not None:
        signals["push_rejected"] = pr
    # Parameter distribution fabric: whole-push latency p99 (one
    # areal_param_push_seconds observation per fleet broadcast or
    # cross-set realloc push).  ``crit: push_p99 <= 30`` pages when
    # weight distribution starts eating the training step.  Absent
    # until the first push.
    pp = _hist_quantile(all_samples, "areal_param_push_seconds", 0.99)
    if not math.isnan(pp):
        signals["push_p99"] = pp
    # Verifier fleet (system/verifier_pool.py): grade round-trip p99
    # over all backends and the pool client's in-flight item count —
    # the capacity signals the supervisor's verifier lane scales on.
    # ``crit: grade_latency_p99 <= 5`` spawns a worker when sandboxed
    # grading starts eating the sample pipeline; ``crit:
    # verifier_queue_depth <= 64`` catches a backed-up pool before
    # episode completion stalls on rewards.  Absent until the first
    # pooled grade.
    gl = _hist_quantile(all_samples, "areal_verifier_grade_seconds", 0.99)
    if not math.isnan(gl):
        signals["grade_latency_p99"] = gl
    vq = _series_sum(all_samples, "areal_verifier_queue_depth")
    if vq is not None:
        signals["verifier_queue_depth"] = vq
    vs = _series_sum(all_samples, "areal_verifier_pool_servers")
    if vs is not None:
        signals["verifier_servers"] = vs
    vb = _series_sum(all_samples, "areal_verifier_breaker_open")
    if vb is not None:
        signals["verifier_breaker_open"] = vb
    # Per-task reward curves (labeled areal_mixture_task_reward gauges
    # -> computed min): ``warn: task_reward_min >= 0.2`` pages when any
    # task stream's reward EMA collapses — the curriculum's floor.
    trs = [
        v for n, labels, v in all_samples
        if n == "areal_mixture_task_reward"
    ]
    if trs:
        signals["task_reward_min"] = min(trs)
    # Placement-advisor health: the master's online cost-model residual
    # (DFG-composed per-MFC walls vs the measured step,
    # areal_master_advisor_pred_err_ratio) and the spread of per-MFC MFU
    # (the labeled areal_mfc_mfu_ratio gauges -> computed min/max).
    # ``warn: advisor_pred_err <= 0.5`` flags when the advisor's
    # composition stops tracking reality (its rankings are then stale);
    # ``warn: mfc_mfu_min >= 0.02`` surfaces an MFC whose placement is
    # starving it.  Absent until the first completed step.
    ae = [
        v for n, labels, v in all_samples
        if n == "areal_master_advisor_pred_err_ratio"
    ]
    if ae:
        signals["advisor_pred_err"] = max(ae)
    mfus = [
        v for n, labels, v in all_samples
        if n == "areal_mfc_mfu_ratio" and labels.get("mfc") != "all"
    ]
    if mfus:
        signals["mfc_mfu_min"] = min(mfus)
        signals["mfc_mfu_max"] = max(mfus)
    # Raw unlabeled series become rule-addressable too (last wins on
    # duplicates; labeled series need the computed signals above).
    for n, labels, v in all_samples:
        if not labels and n not in signals:
            signals[n] = v
    return signals, rows


# ---------------------------------------------------------------------------
# Rendering


_COLS = (
    ("role", 24), ("ok", 3), ("goodput", 9), ("queue_depth", 11),
    ("kv_util", 8), ("live_slots", 10), ("idle_frac", 9),
    ("version", 8), ("steps", 6),
)


def render_table(rows: List[Dict[str, object]],
                 signals: Dict[str, float]) -> str:
    lines = []
    hdr = "  ".join(name.ljust(w) for name, w in _COLS)
    lines.append(hdr)
    lines.append("-" * len(hdr))
    for row in rows:
        cells = []
        for name, w in _COLS:
            v = row.get(name, "")
            if isinstance(v, bool):
                v = "y" if v else "N"
            elif isinstance(v, float) and v == int(v):
                v = int(v)
            cells.append(str(v).ljust(w))
        lines.append("  ".join(cells).rstrip())
        if row.get("error"):
            lines.append(f"    !! {row['error']}")
    keys = (
        "goodput", "staleness_p50", "staleness_p99", "sample_e2e_p50",
        "sample_e2e_p99", "sample_admit_p99", "queue_depth",
        "kv_utilization", "idle_frac", "version_skew", "backpressure",
        "pipeline_fill", "pipeline_bubble", "anomalies",
        "quarantine_streak", "push_rejected", "weight_version_skew",
        "push_p99", "grade_latency_p99", "verifier_queue_depth",
        "verifier_servers", "verifier_breaker_open", "task_reward_min",
    )
    fleet = ", ".join(
        f"{k}={signals[k]:.4g}" for k in keys if k in signals
    )
    lines.append(f"fleet: {fleet}")
    return "\n".join(lines)


# ---------------------------------------------------------------------------
# Driver


def run_watchdog(
    endpoints: Dict[str, str],
    rules: List[SLORule],
    count: Optional[int],
    interval: float,
    as_json: bool = False,
    out=sys.stdout,
) -> int:
    """Poll, render, evaluate.  Returns the number of CRIT violations."""
    history: List[Dict[str, float]] = []
    prev: Optional[Dict[str, RoleScrape]] = None
    crits = 0
    i = 0
    while count is None or i < count:
        if i > 0:
            time.sleep(interval)
        roles = scrape_fleet(endpoints)
        signals, rows = fleet_signals(roles, prev)
        history.append(signals)
        prev = {r.role: r for r in roles}
        violations = []
        for rule in rules:
            msg = rule.evaluate(history)
            if msg is not None:
                violations.append((rule.severity, msg))
                if rule.severity == "crit":
                    crits += 1
        if as_json:
            print(json.dumps({
                "scrape": i,
                "signals": signals,
                "roles": rows,
                "violations": [
                    {"severity": s, "message": m} for s, m in violations
                ],
            }), file=out)
        else:
            print(f"--- scrape {i} ---", file=out)
            print(render_table(rows, signals), file=out)
            for sev, msg in violations:
                print(f"{sev.upper()}: {msg}", file=out)
        i += 1
    return crits


def main(argv: Optional[List[str]] = None) -> int:
    p = argparse.ArgumentParser(prog="areal_tpu.apps.metrics_report")
    p.add_argument("--experiment", default="")
    p.add_argument("--trial", default="trial")
    p.add_argument(
        "--url", action="append", default=[],
        metavar="ROLE=URL",
        help="static endpoint (repeatable); bare URLs get role names "
             "server0, server1, ...",
    )
    p.add_argument("--interval", type=float, default=2.0)
    p.add_argument("--count", type=int, default=None,
                   help="scrapes to run (default: until interrupted)")
    p.add_argument("--slo", action="append", default=[],
                   help="SLO rule (repeatable); see module docstring")
    p.add_argument("--slo-file", default=None)
    p.add_argument("--json", action="store_true",
                   help="one JSON object per scrape instead of tables")
    args = p.parse_args(argv)

    endpoints: Dict[str, str] = {}
    if args.experiment:
        endpoints.update(discover(args.experiment, args.trial))
    for j, spec in enumerate(args.url):
        if "=" in spec and not spec.split("=", 1)[0].startswith("http"):
            role, url = spec.split("=", 1)
        else:
            role, url = f"server{j}", spec
        endpoints[role] = url
    if not endpoints:
        print("no endpoints: pass --experiment (announced roles) or --url",
              file=sys.stderr)
        return 2

    rule_texts = list(args.slo)
    if args.slo_file:
        with open(args.slo_file) as f:
            rule_texts += [
                ln for ln in (l.strip() for l in f)
                if ln and not ln.startswith("#")
            ]
    rules = [parse_slo_rule(t) for t in rule_texts]

    crits = run_watchdog(
        endpoints, rules, args.count, args.interval, as_json=args.json
    )
    return 1 if crits else 0


if __name__ == "__main__":
    raise SystemExit(main())
