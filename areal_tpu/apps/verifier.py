"""Verifier fleet worker entrypoint.

Runs one grading server (``system/verifier_pool.VerifierWorker``) and
joins it to a trial's verifier fleet: announced under
``names.verifier_servers`` with a keepalive TTL (so a crash expires out
of the pool without deregistration) and under the metrics subtree (so
``metrics_report`` / the fleet supervisor scrape its ``/metrics``).

    python -m areal_tpu.apps.verifier --experiment e --trial t --port 8201

The supervisor's verifier lane spawns exactly this argv (with ``{port}``
/ ``{experiment}`` / ``{trial}`` substituted) when grade-latency or
queue-depth SLOs go critical; chaos legs break it via ``AREAL_FAULTS``
(e.g. ``kill@t=2s`` preempts it mid-grade, ``slow@ms=500&point=grade``
inflates its grade latency) with no test-only code paths.

Code grading EXECUTES submitted programs: the default bind is loopback,
and any non-loopback deployment should set a shared token
(--token / AREAL_REWARD_TOKEN; clients send X-Areal-Token).
"""

import argparse
import os
import time

from areal_tpu.base import logging
from areal_tpu.system.verifier_pool import VerifierWorker

logger = logging.getLogger("verifier_app")


def main(argv=None):
    p = argparse.ArgumentParser(
        prog="areal_tpu.apps.verifier",
        description="announced reward-verification worker "
                    "(one member of the autoscaled verifier fleet)",
    )
    p.add_argument("--host", default="127.0.0.1",
                   help="bind address; non-loopback binds should set --token")
    p.add_argument("--port", type=int, default=0,
                   help="0 picks an ephemeral port")
    p.add_argument("--experiment", required=True)
    p.add_argument("--trial", required=True)
    p.add_argument("--server-id", default="",
                   help="fleet identity (default: port-stable v<port>)")
    p.add_argument("--ttl", type=float, default=10.0,
                   help="keepalive TTL for the fleet announcement")
    p.add_argument("--token", default="",
                   help="shared secret (or AREAL_REWARD_TOKEN)")
    p.add_argument("--max-workers", type=int, default=8,
                   help="grading threads per batch")
    args = p.parse_args(argv)

    worker = VerifierWorker(
        args.host,
        args.port,
        token=args.token or os.environ.get("AREAL_REWARD_TOKEN", ""),
        max_workers=args.max_workers,
    )
    sid = worker.announce(
        args.experiment, args.trial, args.server_id or None, ttl=args.ttl
    )
    worker.announce_metrics(args.experiment, args.trial, sid)
    logger.info(f"verifier {sid} serving at {worker.url}")
    try:
        while not worker._stop.is_set():
            time.sleep(0.5)
    except KeyboardInterrupt:
        pass
    worker.close()


if __name__ == "__main__":
    main()
