"""Worker-process bootstrap: load a pickled WorkerConfig, serve the stream.

Capability parity: realhf/apps/remote.py (re-register experiment from cached
config, run the worker poll loop).  Launched by the scheduler as

    python -m areal_tpu.apps.worker --config <plan_dir> --index <i> \
        --experiment <name> --trial <name>

Discovery/config env: AREAL_NAME_RESOLVE(=file) + AREAL_NAME_RESOLVE_ROOT
must point at the trial's shared store (set by apps/main.py).
"""

import argparse
import os
import pickle


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--config", required=True, help="plan directory")
    p.add_argument("--index", type=int, required=True)
    p.add_argument("--experiment", required=True)
    p.add_argument("--trial", required=True)
    args = p.parse_args()

    # Workers colocated on one host run on CPU devices unless told otherwise
    # (one process owns the TPU runtime; see scheduler/local.py).
    if os.environ.get("AREAL_WORKER_PLATFORM"):
        import jax

        jax.config.update(
            "jax_platforms", os.environ["AREAL_WORKER_PLATFORM"]
        )

    from areal_tpu.base import (
        compilation_cache,
        logging,
        metrics,
        seeding,
        tracer,
    )

    compilation_cache.enable()
    # Shard name: trace_worker_<index>.jsonl (dir comes from
    # AREAL_TRACE_DIR, exported by the launcher when tracing is on).
    tracer.configure(role="worker", rank=args.index)
    # Live metrics plane: every role exposes /metrics and announces the
    # URL under the trial's metrics subtree for apps/metrics_report.py.
    metrics_server = metrics.MetricsServer(
        announce=(args.experiment, args.trial, f"model_worker/{args.index}")
    )
    from areal_tpu.system.stream import run_worker_stream
    from areal_tpu.system.transfer import ZMQTransfer
    from areal_tpu.system.worker import ModelWorker

    logger = logging.getLogger(f"worker{args.index}")
    with open(
        os.path.join(args.config, f"worker_{args.index}.pkl"), "rb"
    ) as f:
        config = pickle.load(f)
    seeding.set_random_seed(config.seed, config.worker_index)
    if config.dist_num_processes > 1:
        from areal_tpu.base import distributed

        distributed.initialize(
            args.experiment,
            args.trial,
            process_id=config.dist_process_id,
            num_processes=config.dist_num_processes,
        )
    # Lifecycle side channel (ping/pause/resume/exit + TTL keepalive) —
    # reference: worker_base.py WorkerServer, bound before the model build
    # so the controller can see the worker during its (slow) setup.
    from areal_tpu.system.worker_control import WorkerServer, WorkerState

    control = WorkerServer(
        args.experiment, args.trial, f"model_worker/{args.index}"
    )
    # Bulk worker-to-worker plane (data/param transfers planned by the
    # master); bound before model build so peers can connect early.
    transfer = ZMQTransfer(args.experiment, args.trial, args.index)
    worker = ModelWorker(config, transfer=transfer)
    control.state = WorkerState.RUNNING
    logger.info(f"worker {args.index} ready, serving stream")
    try:
        run_worker_stream(
            worker, args.experiment, args.trial, control=control
        )
    finally:
        tracer.flush()
        metrics_server.close()
        transfer.close()
        control.stop()
    logger.info(f"worker {args.index} exiting")


if __name__ == "__main__":
    main()
