"""arealint CLI: TPU-hot-path static analysis over the areal_tpu tree.

    python -m areal_tpu.apps.lint [paths...] [--json] [--rules a,b]
                                  [--strict] [--min-severity LEVEL]

Exit status: 0 when no gating findings, 1 when errors exist (or warnings
under ``--strict``), 2 on usage errors.  Importing jax is deliberately
avoided: the linter must run on a bare CPU CI box in milliseconds.

Rule families (see areal_tpu/analysis/rules/): host-sync,
retrace-hazard, async-blocking, sharding, stats-keys,
metrics-names.  Suppress a finding
with ``# arealint: ignore[rule] -- reason`` on the offending line or the
line directly above; reasonless suppressions are themselves errors.
"""

import argparse
import os
import sys

from areal_tpu.analysis import (
    Severity,
    analyze_paths,
    get_rules,
    render_human,
    render_json,
)
from areal_tpu.analysis.rules import RULE_NAMES

_LEVELS = {"info": Severity.INFO, "warning": Severity.WARNING,
           "error": Severity.ERROR}


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="python -m areal_tpu.apps.lint",
        description="arealint: TPU-hot-path static analysis",
    )
    p.add_argument("paths", nargs="*", default=None,
                   help="files/directories to lint (default: areal_tpu/)")
    p.add_argument("--json", action="store_true",
                   help="machine-readable output (stable schema, v1)")
    p.add_argument("--rules", default=None,
                   help=f"comma-separated subset of: {', '.join(RULE_NAMES)}")
    p.add_argument("--min-severity", default="info",
                   choices=sorted(_LEVELS),
                   help="hide findings below this level (default: info)")
    p.add_argument("--strict", action="store_true",
                   help="warnings also gate (nonzero exit)")
    p.add_argument("--list-rules", action="store_true",
                   help="print rule names and exit")
    args = p.parse_args(argv)

    if args.list_rules:
        for name in RULE_NAMES:
            print(name)
        return 0

    paths = args.paths or ["areal_tpu"]
    try:
        rules = get_rules(
            [r.strip() for r in args.rules.split(",") if r.strip()]
            if args.rules else None
        )
    except KeyError as e:
        print(f"arealint: {e.args[0]}", file=sys.stderr)
        return 2
    try:
        findings = analyze_paths(paths, rules, relative_to=os.getcwd())
    except FileNotFoundError as e:
        print(f"arealint: {e}", file=sys.stderr)
        return 2

    floor = _LEVELS[args.min_severity]
    shown = [f for f in findings if f.severity >= floor]
    if args.json:
        print(render_json(shown))
    else:
        print(render_human(shown))

    gate = Severity.WARNING if args.strict else Severity.ERROR
    return 1 if any(f.severity >= gate for f in findings) else 0


if __name__ == "__main__":
    sys.exit(main())
