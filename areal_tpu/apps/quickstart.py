"""Quickstart CLI: `python -m areal_tpu.apps.quickstart <exp> [options]`.

Capability parity: realhf/apps/quickstart.py (hydra CLI over registered
experiment configs) — argparse-based (the config tree is small dataclasses;
a YAML file via --config covers the reference's prologue path).

Experiments:
    sft       — supervised fine-tuning (experiments/common.py build_sft)
    ppo-math  — PPO/GRPO with math-verified rewards (build_ppo_math)

Examples:
    python -m areal_tpu.apps.quickstart sft \
        --model.path /ckpts/qwen2-1.5b --dataset.path data.jsonl \
        --allocation d1f4m2 --batch-size 32 --epochs 1
    python -m areal_tpu.apps.quickstart ppo-math \
        --model.path /ckpts/qwen2-1.5b --dataset.path prompts.jsonl \
        --group-size 8 --workers 1
"""

import argparse
import json
import os
from typing import Optional

from areal_tpu.api.config import ModelAbstraction
from areal_tpu.api.data_api import DatasetAbstraction, MicroBatchSpec
from areal_tpu.api.model_api import GenerationHyperparameters, OptimizerConfig
from areal_tpu.base import logging
from areal_tpu.base.topology import ParallelConfig
from areal_tpu.experiments import common as exps
from areal_tpu.system.master import ExperimentSaveEvalControl

logger = logging.getLogger("quickstart")


def _eval_protocol_arg(value: str) -> str:
    """Reject a malformed protocol at PARSE time — a typo must not
    surface as a crash only after the multi-hour trial finishes."""
    from areal_tpu.scheduler.evaluator import parse_protocol

    try:
        parse_protocol(value)
    except ValueError as e:
        raise argparse.ArgumentTypeError(str(e)) from None
    return value


def _add_common(p: argparse.ArgumentParser):
    p.add_argument("--config", default=None,
                   help="YAML file of option defaults (keys = flag names, "
                        "e.g. 'model.path:'); CLI flags override it — the "
                        "reference's prologue path (realhf/apps/main.py "
                        "--config)")
    p.add_argument("--model.path", dest="model_path", required=True,
                   help="HF checkpoint dir")
    p.add_argument("--dataset.path", dest="dataset_path", required=True,
                   help="jsonl dataset path")
    p.add_argument("--allocation", default="d1",
                   help="parallel layout, e.g. d2f2m2 / p2f2m2 / d1s4; "
                        "'search' runs the MCMC allocation search (ppo-math)")
    p.add_argument("--chip", default="v5e",
                   help="TPU chip spec for the allocation search (v5e/v5p)")
    p.add_argument("--search-devices", type=int, default=None,
                   help="chip count for --allocation search (required with "
                        "--multiprocess so the launcher never touches the "
                        "TPU runtime)")
    p.add_argument("--tokenizer-path", default=None,
                   help="tokenizer dir (default: model path); 'char:<n>' "
                        "loads the hermetic char tokenizer")
    p.add_argument("--batch-size", type=int, default=8)
    p.add_argument("--epochs", type=int, default=1)
    p.add_argument("--max-tokens-per-mb", type=int, default=16384)
    p.add_argument("--lr", type=float, default=2e-5)
    p.add_argument("--seed", type=int, default=1)
    p.add_argument("--experiment-name", default=None)
    p.add_argument("--trial-name", default="trial0")
    p.add_argument("--fileroot", default="/tmp/areal_tpu")
    p.add_argument("--save-freq-steps", type=int, default=None)
    p.add_argument("--ckpt-freq-steps", type=int, default=None)
    p.add_argument("--benchmark-steps", type=int, default=None)
    p.add_argument("--launcher", default="local",
                   choices=("local", "slurm", "tpu-pod"),
                   help="where workers run: this host (local), sbatch jobs "
                        "(slurm), or one process per TPU-VM host via "
                        "gcloud ssh (tpu-pod; needs a shared --fileroot, "
                        "e.g. GCS fuse)")
    p.add_argument("--tpu-name", default=None,
                   help="tpu-pod: TPU VM / pod-slice name")
    p.add_argument("--tpu-zone", default=None)
    p.add_argument("--tpu-project", default=None)
    p.add_argument("--tpu-num-hosts", type=int, default=1,
                   help="tpu-pod: hosts in the slice (worker i runs on "
                        "host i %% num-hosts)")
    p.add_argument("--multiprocess", action="store_true",
                   help="spawn workers as subprocesses over ZMQ (default: "
                        "in-process)")
    p.add_argument("--recover-retries", type=int, default=0)
    p.add_argument("--mfc-timeout-s", type=float, default=None,
                   help="per-MFC deadline; a worker that misses it AND "
                        "stops heartbeating is declared dead and the "
                        "master rolls back to the recover checkpoint "
                        "(default: no deadline)")
    p.add_argument("--worker-heartbeat-s", type=float, default=5.0,
                   help="worker liveness beat period (ZMQ runtime); long "
                        "MFCs stay alive by beating, so --mfc-timeout-s "
                        "distinguishes slow from dead")
    p.add_argument("--max-recoveries", type=int, default=3,
                   help="worker deaths the master absorbs by restoring "
                        "the recover checkpoint before exiting non-zero")
    p.add_argument("--anomaly-grad-norm-mult", type=float, default=0.0,
                   help="quarantine a train step whose grad norm exceeds "
                        "this multiple of the engine's running EWMA "
                        "(must be > 1; 0 = sentinel off; non-finite "
                        "loss/grads always quarantine)")
    p.add_argument("--anomaly-update-norm-max", type=float, default=0.0,
                   help="quarantine a train step whose optimizer update "
                        "norm exceeds this absolute ceiling (0 = off)")
    p.add_argument("--max-consecutive-quarantines", type=int, default=3,
                   help="consecutive quarantined steps before the master "
                        "rolls the fleet back to the last recover "
                        "checkpoint (0 = never escalate)")
    p.add_argument("--no-weight-push-checksum", action="store_true",
                   help="skip the per-leaf-norm content checksum "
                        "receivers verify on cross-worker weight pushes")
    p.add_argument("--eval-data", default=None,
                   help="held-out jsonl; after the trial, every saved "
                        "checkpoint is graded (pass@1) by the automatic "
                        "evaluator")
    p.add_argument("--eval-max-new-tokens", type=int, default=256)
    p.add_argument("--eval-protocol", default="greedy",
                   type=_eval_protocol_arg,
                   help="'greedy', 'avg@K' (avg@32 = the AIME avg-of-32 "
                        "pass@1 protocol at temperature 1.0), or 'maj@K' "
                        "(majority voting over K samples)")


def _apply_yaml_config(parser: argparse.ArgumentParser, argv):
    """Pre-read --config <yaml> and install its values as parser defaults
    (CLI flags still win).  YAML keys use the flag spelling ('model.path',
    'batch-size') or the python dest ('model_path')."""
    pre = argparse.ArgumentParser(add_help=False)
    pre.add_argument("--config", default=None)
    known, _ = pre.parse_known_args(argv)
    if not known.config:
        return
    import yaml

    with open(known.config) as f:
        raw = yaml.safe_load(f) or {}
    dests = {a.dest for a in parser._actions}
    mapped = {}
    for key, val in raw.items():
        dest = key.replace("-", "_")
        if dest not in dests:
            dest = key.replace(".", "_").replace("-", "_")
        if dest not in dests:
            raise SystemExit(f"--config: unknown option {key!r}")
        mapped[dest] = val
    parser.set_defaults(**mapped)
    # YAML-provided values satisfy required flags.
    for a in parser._actions:
        if a.dest in mapped and a.required:
            a.required = False


def _maybe_eval(args, plan):
    if not args.eval_data:
        return
    from areal_tpu.scheduler.evaluator import AutomaticEvaluator, EvalConfig

    exp, trial = plan.experiment_name, plan.trial_name
    for node in plan.dfg.nodes:
        from areal_tpu.api.config import ModelInterfaceType

        if node.interface_type != ModelInterfaceType.TRAIN_STEP:
            continue
        ckpt_root = os.path.join(
            args.fileroot, "checkpoints", exp, trial, str(node.model_name)
        )
        if not os.path.isdir(ckpt_root):
            continue
        ev = AutomaticEvaluator(
            ckpt_root,
            os.path.join(args.fileroot, "eval", exp, trial),
            EvalConfig(
                data_path=args.eval_data,
                tokenizer_path=args.tokenizer_path or args.model_path,
                max_new_tokens=args.eval_max_new_tokens,
                protocol=args.eval_protocol,
            ),
        )
        steps = ev.step()
        logger.info(f"evaluated checkpoints at steps {steps}")


def _ctrl(args) -> ExperimentSaveEvalControl:
    return ExperimentSaveEvalControl(
        total_train_epochs=args.epochs,
        save_freq_steps=args.save_freq_steps,
        ckpt_freq_steps=args.ckpt_freq_steps,
        benchmark_steps=args.benchmark_steps,
    )


def _run(plan, args):
    # Deferred here so `--help`/arg errors never pay the jax import.
    from areal_tpu.base import compilation_cache

    compilation_cache.enable()
    from areal_tpu.apps import main as runner

    if args.multiprocess or args.launcher != "local":
        kwargs = {}
        if args.launcher == "tpu-pod":
            if not args.tpu_name:
                raise SystemExit("--launcher tpu-pod needs --tpu-name")
            kwargs = dict(
                tpu_name=args.tpu_name,
                zone=args.tpu_zone,
                project=args.tpu_project,
                num_hosts=args.tpu_num_hosts,
            )
        return runner.run_experiment(
            plan,
            recover_retries=args.recover_retries,
            scheduler_mode=args.launcher,
            scheduler_kwargs=kwargs,
        )
    return runner.run_experiment_inproc(plan)


def cmd_sft(args):
    cfg = exps.SFTConfig(
        model=ModelAbstraction("hf", {"path": args.model_path}),
        dataset=DatasetAbstraction(
            "prompt_answer", {"dataset_path": args.dataset_path,
                              "max_length": args.max_seqlen}
        ),
        parallel=ParallelConfig.from_str(args.allocation),
        optimizer=OptimizerConfig(lr=args.lr),
        batch_size=args.batch_size,
        total_train_epochs=args.epochs,
        mb_spec=MicroBatchSpec(max_tokens_per_mb=args.max_tokens_per_mb),
        ctrl=_ctrl(args),
        seed=args.seed,
        experiment_name=args.experiment_name or "sft",
        trial_name=args.trial_name,
        fileroot=args.fileroot,
        mfc_timeout_s=args.mfc_timeout_s,
        worker_heartbeat_s=args.worker_heartbeat_s,
        max_recoveries=args.max_recoveries,
        anomaly_grad_norm_mult=args.anomaly_grad_norm_mult,
        anomaly_update_norm_max=args.anomaly_update_norm_max,
        max_consecutive_quarantines=args.max_consecutive_quarantines,
        weight_push_checksum=not args.no_weight_push_checksum,
    )
    plan = exps.build_sft(cfg)
    for wc in plan.worker_configs:
        wc.tokenizer_path = args.tokenizer_path or args.model_path
    stats = _run(plan, args)
    _maybe_eval(args, plan)
    print(json.dumps(stats[-1] if stats else {}))


def _searched_ppo_allocation(args):
    """`--allocation search`: pick (mesh, layout) per MFC with the C++ MCMC
    search over the TPU roofline estimator (reference: apps/main.py:104-107
    driving search_rpc_allocations)."""
    import jax

    from areal_tpu.models.hf import registry as hf
    from areal_tpu.search_engine.search import search_ppo_math_allocations

    if args.multiprocess and not args.search_devices:
        # jax.device_count() would initialize the TPU runtime in THIS
        # launcher process, stealing the chips from the spawned workers.
        raise SystemExit(
            "--allocation search with --multiprocess needs an explicit "
            "--search-devices N (the launcher must not initialize the TPU "
            "runtime itself)"
        )
    n_devices = args.search_devices or jax.device_count()
    hf_cfg = hf.load_hf_config(args.model_path)
    model_cfg = hf.HF_FAMILIES[hf_cfg["model_type"]].config_from_hf(hf_cfg)
    allocs = search_ppo_math_allocations(
        model_cfg,
        n_prompts=args.batch_size,
        group_size=args.group_size,
        max_new_tokens=args.max_new_tokens,
        n_devices=n_devices,
        chip=args.chip,
        max_tokens_per_mb=args.max_tokens_per_mb,
        seed=args.seed,
    )
    train = allocs["actor_train"]
    gen = allocs["actor_gen"]
    logger.info(
        f"searched allocation: train {train.parallel.to_str()} on chips "
        f"{train.device_range}, gen {gen.parallel.to_str()} on chips "
        f"{gen.device_range}"
    )
    return train, gen


def _parse_mixture_weights(specs):
    """'task=weight' CLI pairs -> {task: float} for PPOMathConfig."""
    weights = {}
    for spec in specs:
        task, sep, w = spec.partition("=")
        if not sep or not task:
            raise SystemExit(
                f"--mixture-weight wants TASK=WEIGHT, got {spec!r}"
            )
        try:
            weights[task] = float(w)
        except ValueError:
            raise SystemExit(
                f"--mixture-weight {spec!r}: weight must be a number"
            )
    return weights


def cmd_ppo_math(args):
    searched = None
    if args.allocation == "search":
        if args.gen_allocation:
            raise SystemExit(
                "--gen-allocation conflicts with --allocation search "
                "(the search chooses the generation layout)"
            )
        searched = _searched_ppo_allocation(args)
    ppo_kwargs = {}
    if args.kl_ctl:
        if not args.ref_path:
            raise SystemExit(
                "--kl-ctl needs --ref-path: the KL penalty is computed "
                "against a reference policy's logprobs"
            )
        ppo_kwargs["kl_ctl"] = args.kl_ctl
    if args.kl_adaptive:
        if not args.kl_ctl:
            # The controller is multiplicative: a 0.0 start can never
            # leave 0, so silently "enabling" it would do nothing.
            raise SystemExit(
                "--kl-adaptive needs a nonzero --kl-ctl as the initial "
                "coefficient"
            )
        ppo_kwargs["kl_adaptive"] = True
        ppo_kwargs["adaptive_kl_target"] = args.adaptive_kl_target
        ppo_kwargs["adaptive_kl_horizon"] = args.adaptive_kl_horizon
    if args.generation_size is not None:
        ppo_kwargs["generation_size"] = args.generation_size
    if args.early_stop_imp_ratio is not None:
        ppo_kwargs["early_stop_imp_ratio"] = args.early_stop_imp_ratio
    if args.early_stop_kl is not None:
        ppo_kwargs["early_stop_kl"] = args.early_stop_kl
    cfg = exps.PPOMathConfig(
        actor=ModelAbstraction("hf", {"path": args.model_path}),
        ref=(
            ModelAbstraction("hf", {"path": args.ref_path})
            if args.ref_path else None
        ),
        ppo_kwargs=ppo_kwargs,
        ref_ema_eta=args.ref_ema_eta,
        fuse_rew_ref=args.fuse_rew_ref,
        offload_ref=args.offload_ref,
        gen_server_url=args.gen_server_url,
        rollout_ahead=args.rollout_ahead,
        max_head_offpolicyness=args.max_head_offpolicyness,
        replay_capacity=args.replay_capacity,
        pipeline_overlap=args.pipeline_overlap,
        overlap_window=args.overlap_window,
        pipeline_chunk_seqs=args.pipeline_chunk_seqs,
        inmem_weight_sync=args.inmem_weight_sync,
        param_push_tree=args.param_push_tree,
        param_push_fanout=args.param_push_fanout,
        gen_backend_args=(
            {"kv_cache_dtype": args.kv_cache_dtype}
            if args.kv_cache_dtype != "auto" else {}
        ),
        kv_paged=False if args.no_paged_kv else None,
        kv_page_size=args.kv_page_size,
        kv_pool_pages=args.kv_pool_pages,
        prefill_chunk_tokens=args.prefill_chunk_tokens,
        kv_share_prefix=False if args.no_kv_share_prefix else None,
        train_backend_args={
            k: v
            for k, v in (
                ("master_dtype", args.master_dtype),
                ("remat_policy", args.remat),
            )
            if v is not None
        },
        dataset=DatasetAbstraction(
            "math_code_prompt", {"dataset_path": args.dataset_path}
        ),
        actor_parallel=(
            searched[0].parallel
            if searched
            else ParallelConfig.from_str(args.allocation)
        ),
        gen_parallel=(
            searched[1].parallel
            if searched
            else ParallelConfig.from_str(args.gen_allocation)
            if args.gen_allocation
            else None
        ),
        actor_device_offset=searched[0].device_range[0] if searched else None,
        gen_device_offset=searched[1].device_range[0] if searched else None,
        optimizer=OptimizerConfig(lr=args.lr),
        gconfig=GenerationHyperparameters(
            n=args.group_size,
            max_new_tokens=args.max_new_tokens,
            temperature=args.temperature,
            spec_decode_k=args.spec_decode_k,
        ),
        batch_size=args.batch_size,
        total_train_epochs=args.epochs,
        mb_spec=MicroBatchSpec(max_tokens_per_mb=args.max_tokens_per_mb),
        ctrl=_ctrl(args),
        seed=args.seed,
        experiment_name=args.experiment_name or "ppo-math",
        trial_name=args.trial_name,
        fileroot=args.fileroot,
        mfc_timeout_s=args.mfc_timeout_s,
        worker_heartbeat_s=args.worker_heartbeat_s,
        max_recoveries=args.max_recoveries,
        anomaly_grad_norm_mult=args.anomaly_grad_norm_mult,
        anomaly_update_norm_max=args.anomaly_update_norm_max,
        anomaly_kl_max=args.anomaly_kl_max,
        max_consecutive_quarantines=args.max_consecutive_quarantines,
        weight_push_checksum=not args.no_weight_push_checksum,
        episode_max_turns=args.episode_max_turns,
        episode_token_budget=args.episode_token_budget,
        tool_timeout_s=args.tool_timeout_s,
        reward_backend=args.reward_backend,
        verifier_pool=args.verifier_pool,
        mixture_weights=_parse_mixture_weights(args.mixture_weight),
        mixture_adaptive=args.mixture_adaptive,
    )
    plan = exps.build_ppo_math(cfg)
    for wc in plan.worker_configs:
        wc.tokenizer_path = args.tokenizer_path or args.model_path
    stats = _run(plan, args)
    _maybe_eval(args, plan)
    print(json.dumps(stats[-1] if stats else {}))


def main(argv=None):
    p = argparse.ArgumentParser(prog="areal_tpu.apps.quickstart")
    sub = p.add_subparsers(dest="exp", required=True)

    ps = sub.add_parser("sft", help="supervised fine-tuning")
    _add_common(ps)
    ps.add_argument("--max-seqlen", type=int, default=4096)
    ps.set_defaults(fn=cmd_sft)

    pp = sub.add_parser("ppo-math", help="PPO/GRPO with verified rewards")
    _add_common(pp)
    pp.add_argument("--group-size", type=int, default=4)
    pp.add_argument("--max-new-tokens", type=int, default=1024)
    pp.add_argument("--temperature", type=float, default=1.0)
    pp.add_argument("--gen-allocation", default=None,
                    help="separate layout for generation (decoupled meshes)")
    pp.add_argument("--gen-server-url", default=None,
                    help="decoupled serving: URL(s) of running "
                         "areal_tpu.system.gen_server instances, comma-"
                         "separated for one server per DP rank (actor_gen "
                         "becomes a weightless client; weight sync ships "
                         "checkpoints to every rank)")
    pp.add_argument("--ref-path", default=None,
                    help="reference policy checkpoint (enables KL control)")
    pp.add_argument("--kl-ctl", type=float, default=0.0)
    pp.add_argument("--kl-adaptive", action="store_true",
                    help="adapt the KL coefficient to hold the measured "
                         "policy-ref KL at --adaptive-kl-target "
                         "(Ziegler controller; --kl-ctl is the initial "
                         "value)")
    pp.add_argument("--adaptive-kl-target", type=float, default=6.0)
    pp.add_argument("--adaptive-kl-horizon", type=float, default=10000.0)
    pp.add_argument("--generation-size", type=int, default=None,
                    help="best-of-k: sample this many responses per prompt "
                         "but train on only the top --group-size by reward")
    pp.add_argument("--early-stop-imp-ratio", type=float, default=None,
                    help="skip remaining minibatches of a step once the "
                         "mean importance ratio exceeds this (e.g. 10.0)")
    pp.add_argument("--early-stop-kl", type=float, default=None,
                    help="skip remaining minibatches once |approx_kl| "
                         "exceeds this (e.g. 0.1)")
    pp.add_argument("--ref-ema-eta", type=float, default=None,
                    help="EMA-update the ref toward the actor each step")
    pp.add_argument("--kv-cache-dtype", default="auto",
                    choices=("auto", "int8"),
                    help="int8 halves KV HBM per generated token (the "
                         "capacity bound for 16k+ decodes)")
    pp.add_argument("--no-paged-kv", action="store_true",
                    help="use the dense grow-by-doubling KV window "
                         "instead of the paged pool (parity/debug)")
    pp.add_argument("--kv-page-size", type=int, default=128,
                    help="tokens per KV page in the paged decode pool")
    pp.add_argument("--kv-pool-pages", type=int, default=0,
                    help="fixed KV pool size in pages (0 = auto-size "
                         "for the worst case; positive caps KV HBM and "
                         "bounds concurrent admissions)")
    pp.add_argument("--prefill-chunk-tokens", type=int, default=None,
                    help="serving plane: prompt tokens forwarded per "
                         "decode step inside the unified chunk (0 = "
                         "legacy two-program admit; default from "
                         "AREAL_PREFILL_CHUNK_TOKENS)")
    pp.add_argument("--no-kv-share-prefix", action="store_true",
                    help="disable copy-on-write prompt page sharing "
                         "across a sampling group (parity/debug)")
    pp.add_argument("--master-dtype", default=None,
                    choices=(None, "float32", "bfloat16"),
                    help="optimizer master/Adam dtype; bfloat16 halves "
                         "optimizer memory (the single-chip 1.5B fit)")
    pp.add_argument("--remat", default=None,
                    choices=(None, "full", "dots_small", "dots", "none"),
                    help="activation rematerialization policy for training")
    pp.add_argument("--fuse-rew-ref", action="store_true",
                    help="one fused MFC for reward grading + ref inference")
    pp.add_argument("--offload-ref", action="store_true",
                    help="host-offload ref params between steps")
    pp.add_argument("--spec-decode-k", type=int, default=0,
                    help="speculative decoding drafts per step (0 = off)")
    pp.add_argument("--rollout-ahead", type=int, default=0, choices=(0, 1),
                    help="1 = generate step t+1's rollouts while step t "
                         "trains (one-step-stale async rollout)")
    pp.add_argument("--max-head-offpolicyness", type=int, default=None,
                    help="enable the async-RL replay pipeline: keep up to "
                         "N+1 rollout batches in flight and train only on "
                         "batches whose head weight version lags the "
                         "trainer by <= N (0 = bounded pipeline that "
                         "degrades to synchronous numerics; mutually "
                         "exclusive with --rollout-ahead)")
    pp.add_argument("--replay-capacity", type=int, default=4,
                    help="async RL: max resident rollout batches in the "
                         "replay buffer (puts at capacity evict oldest)")
    pp.add_argument("--inmem-weight-sync", action="store_true",
                    help="decoupled serving: pause/resume generation "
                         "around weight pushes (in-flight decodes halt at "
                         "a chunk boundary and resume on their KV pages) "
                         "instead of draining the server")
    pp.add_argument("--param-push-tree", action="store_true",
                    help="decoupled serving: distribute weight pushes "
                         "down a broadcast tree over the gen-server "
                         "fleet (serialize once, servers relay to their "
                         "children before applying; O(log N) push "
                         "wall-time) instead of N serial point-to-point "
                         "pushes; requires --gen-server-url")
    pp.add_argument("--param-push-fanout", type=int, default=2,
                    help="broadcast-tree fan-out per relay server "
                         "(with --param-push-tree; depth ~ "
                         "log_fanout(N))")
    pp.add_argument("--pipeline-overlap", action="store_true",
                    help="overlap the stages INSIDE a step: slice the "
                         "batch into rollout-group chunks and stream each "
                         "through gen -> ref/reward -> train "
                         "forward-backward while later chunks still "
                         "decode; one optimizer step per global step "
                         "(mutually exclusive with --rollout-ahead and "
                         "--max-head-offpolicyness)")
    pp.add_argument("--overlap-window", type=int, default=2,
                    help="pipeline overlap: max chunks in flight at once "
                         "(1 = serial dispatch, bit-exact vs the barrier "
                         "scheduler)")
    pp.add_argument("--pipeline-chunk-seqs", type=int, default=1,
                    help="pipeline overlap: rollout groups per chunk")
    pp.add_argument("--anomaly-kl-max", type=float, default=None,
                    help="quarantine a batch whose mean |policy-ref KL| "
                         "exceeds this before it ever reaches the train "
                         "engine (needs --ref-path; omit to disable)")
    pp.add_argument("--episode-max-turns", type=int, default=0,
                    help="agent-serving runtime: >0 turns rollout into "
                         "multi-turn tool-use episodes parked on "
                         "persistent KV slots (0 = single-shot)")
    pp.add_argument("--episode-token-budget", type=int, default=0,
                    help="agent episodes: total transcript token cap per "
                         "episode (0 = engine default)")
    pp.add_argument("--tool-timeout-s", type=float, default=10.0,
                    help="agent episodes: wall-clock bound on each tool "
                         "call before it degrades to an error observation")
    pp.add_argument("--reward-backend", default="",
                    help="force one reward-fabric verifier backend (math, "
                         "code, judge, or a registered name) for every "
                         "sample instead of routing by per-row task")
    pp.add_argument("--verifier-pool", action="store_true",
                    help="route grading through the trial's announced "
                         "verifier-worker fleet (areal_tpu.apps.verifier) "
                         "instead of grading in-process")
    pp.add_argument("--mixture-weight", action="append", default=[],
                    metavar="TASK=WEIGHT",
                    help="task-mixture curriculum weight, e.g. "
                         "'math=3' 'code=1'; repeatable")
    pp.add_argument("--mixture-adaptive", action="store_true",
                    help="adaptively upweight tasks whose reward EMA is "
                         "below their watermark")
    pp.set_defaults(fn=cmd_ppo_math)

    # Install YAML defaults on whichever subcommand was chosen.
    import sys as _sys

    raw_argv = list(argv if argv is not None else _sys.argv[1:])
    if raw_argv and raw_argv[0] in ("sft", "ppo-math"):
        sub_parser = {"sft": ps, "ppo-math": pp}[raw_argv[0]]
        _apply_yaml_config(sub_parser, raw_argv[1:])
    args = p.parse_args(argv)
    args.fn(args)


if __name__ == "__main__":
    main()
