"""Stall attribution over a merged trace: where did each step's wall-clock
go, per process?

    python -m areal_tpu.apps.trace_report <trace_dir | trace.json> [--top N]

Given a directory, first merges the ``trace_*.jsonl`` shards into
``trace.json`` (tracer.merge_shards), then walks each process track and
buckets every step's wall-clock into compute / comms / host / idle:

- step windows come from the master's ``step`` spans (the whole trace is
  one step when absent — e.g. a bare gen_server capture);
- category time is the union of that process's categorized spans clipped
  to the window, with precedence comms > compute > host (a compute span
  nested inside a transfer wait counts once, as comms);
- idle is the uncovered remainder — the bubbles future overlap PRs exist
  to shrink.  The top-N bubble intervals are printed with the spans that
  bound them, which is the artifact a perf PR cites before/after.

Uncategorized spans (request lifetimes, dispatch waits) shape the
timeline but never count toward a bucket.

``--json`` emits the report as one JSON object with a stable schema
(``json_report``) instead of the human tables, for dashboards and the
regression tooling:

    {"version": 1,
     "rows": [{"step", "pid", "process", "window_us", "compute_us",
               "comms_us", "host_us", "idle_us"}, ...],
     "bubbles": [{"process", "step", "start_us", "dur_us",
                  "after_span", "before_span"}, ...]}

``version`` bumps on any breaking change; consumers must reject
versions they don't know.
"""

import argparse
import json
import os
import sys
from collections import defaultdict
from typing import Any, Dict, List, Optional, Tuple

from areal_tpu.base import tracer

Interval = Tuple[int, int]  # [start_us, end_us)

# Attribution precedence: a span overlapped by a higher category yields
# to it so nested spans never double-count.
CATEGORIES = ("comms", "compute", "host")


def _union(intervals: List[Interval]) -> List[Interval]:
    if not intervals:
        return []
    intervals = sorted(intervals)
    out = [list(intervals[0])]
    for s, e in intervals[1:]:
        if s <= out[-1][1]:
            out[-1][1] = max(out[-1][1], e)
        else:
            out.append([s, e])
    return [(s, e) for s, e in out]


def _subtract(base: List[Interval], cut: List[Interval]) -> List[Interval]:
    """base minus cut; both must be sorted unions."""
    out: List[Interval] = []
    ci = 0
    for s, e in base:
        cur = s
        while ci < len(cut) and cut[ci][1] <= cur:
            ci += 1
        j = ci
        while j < len(cut) and cut[j][0] < e:
            cs, ce = cut[j]
            if cs > cur:
                out.append((cur, cs))
            cur = max(cur, ce)
            if cur >= e:
                break
            j += 1
        if cur < e:
            out.append((cur, e))
    return out


def _clip(intervals: List[Interval], lo: int, hi: int) -> List[Interval]:
    return [
        (max(s, lo), min(e, hi))
        for s, e in intervals
        if min(e, hi) > max(s, lo)
    ]


def _total(intervals: List[Interval]) -> int:
    return sum(e - s for s, e in intervals)


def load_trace(path: str) -> Dict[str, Any]:
    with open(path) as f:
        return json.load(f)


def _spans_by_pid(trace) -> Dict[int, List[Dict]]:
    by_pid: Dict[int, List[Dict]] = defaultdict(list)
    for e in trace.get("traceEvents", []):
        if e.get("ph") == "X":
            by_pid[int(e.get("pid", 0))].append(e)
    return by_pid


def _proc_names(trace) -> Dict[int, str]:
    names = {}
    for e in trace.get("traceEvents", []):
        if e.get("ph") == "M" and e.get("name") == "process_name":
            names[int(e["pid"])] = e.get("args", {}).get("name", "?")
    return names


def _step_windows(trace) -> List[Tuple[Optional[int], int, int]]:
    """[(step_number, start_us, end_us)] from ``step`` spans; the whole
    trace as one anonymous window when no step spans exist."""
    steps = []
    for e in trace.get("traceEvents", []):
        if e.get("ph") == "X" and e.get("name") == "step":
            num = (e.get("args") or {}).get("step")
            steps.append(
                (
                    int(num) if num is not None else None,
                    int(e["ts"]),
                    int(e["ts"]) + int(e["dur"]),
                )
            )
    if steps:
        return sorted(steps, key=lambda t: t[1])
    spans = [e for e in trace.get("traceEvents", []) if e.get("ph") == "X"]
    if not spans:
        return []
    lo = min(int(e["ts"]) for e in spans)
    hi = max(int(e["ts"]) + int(e["dur"]) for e in spans)
    return [(None, lo, hi)]


def attribute(trace) -> List[Dict[str, Any]]:
    """-> one row per (step, process): {step, process, window_us,
    compute_us, comms_us, host_us, idle_us}."""
    by_pid = _spans_by_pid(trace)
    names = _proc_names(trace)
    rows = []
    for step, lo, hi in _step_windows(trace):
        for pid, spans in sorted(by_pid.items()):
            cat_iv: Dict[str, List[Interval]] = {c: [] for c in CATEGORIES}
            for e in spans:
                c = e.get("cat")
                if c in cat_iv:
                    cat_iv[c].append(
                        (int(e["ts"]), int(e["ts"]) + int(e["dur"]))
                    )
            covered: List[Interval] = []
            row = {
                "step": step,
                "pid": pid,
                "process": names.get(pid, str(pid)),
                "window_us": hi - lo,
            }
            for c in CATEGORIES:
                u = _subtract(_union(_clip(cat_iv[c], lo, hi)), covered)
                row[f"{c}_us"] = _total(u)
                covered = _union(covered + u)
            row["idle_us"] = (hi - lo) - _total(covered)
            row["_covered"] = covered
            rows.append(row)
    return rows


def bubbles(trace, top: int = 5) -> List[Dict[str, Any]]:
    """Largest uncovered (idle) intervals per process across all step
    windows, with the categorized spans bounding each gap."""
    by_pid = _spans_by_pid(trace)
    names = _proc_names(trace)
    windows = _step_windows(trace)
    out = []
    for pid, spans in by_pid.items():
        cat_spans = [e for e in spans if e.get("cat") in CATEGORIES]
        covered = _union(
            [
                (int(e["ts"]), int(e["ts"]) + int(e["dur"]))
                for e in cat_spans
            ]
        )
        for step, lo, hi in windows:
            for gs, ge in _subtract([(lo, hi)], _clip(covered, lo, hi)):
                before = after = None
                for e in cat_spans:
                    s, ee = int(e["ts"]), int(e["ts"]) + int(e["dur"])
                    if ee <= gs and (
                        before is None
                        or ee > int(before["ts"]) + int(before["dur"])
                    ):
                        before = e
                    if s >= ge and (
                        after is None or s < int(after["ts"])
                    ):
                        after = e
                out.append(
                    {
                        "process": names.get(pid, str(pid)),
                        "step": step,
                        "start_us": gs,
                        "dur_us": ge - gs,
                        "after_span": before["name"] if before else None,
                        "before_span": after["name"] if after else None,
                    }
                )
    out.sort(key=lambda b: -b["dur_us"])
    return out[:top]


def format_report(trace, top: int = 5) -> str:
    rows = attribute(trace)
    lines = []
    ms = lambda us: f"{us / 1000.0:9.1f}"  # noqa: E731
    lines.append(
        f"{'step':>5} {'process':<16} {'window_ms':>9} {'compute':>9} "
        f"{'comms':>9} {'host':>9} {'idle':>9} {'idle%':>6}"
    )
    for r in rows:
        step = "-" if r["step"] is None else str(r["step"])
        idle_pct = 100.0 * r["idle_us"] / max(r["window_us"], 1)
        lines.append(
            f"{step:>5} {r['process']:<16} {ms(r['window_us'])} "
            f"{ms(r['compute_us'])} {ms(r['comms_us'])} {ms(r['host_us'])} "
            f"{ms(r['idle_us'])} {idle_pct:5.1f}%"
        )
    bubs = bubbles(trace, top=top)
    if bubs:
        lines.append("")
        lines.append(f"top {len(bubs)} bubbles (uncovered intervals):")
        for b in bubs:
            step = "-" if b["step"] is None else str(b["step"])
            lines.append(
                f"  {b['dur_us'] / 1000.0:8.1f} ms  step {step:>3}  "
                f"{b['process']:<16} between "
                f"{b['after_span'] or '<window start>'} and "
                f"{b['before_span'] or '<window end>'}"
            )
    return "\n".join(lines)


JSON_VERSION = 1


def json_report(trace, top: int = 5) -> Dict[str, Any]:
    """Machine-readable report, schema v1 (see module docstring).  The
    internal ``_covered`` interval list is stripped from rows — it is an
    implementation detail of the precedence subtraction, not contract."""
    rows = [
        {k: v for k, v in r.items() if not k.startswith("_")}
        for r in attribute(trace)
    ]
    return {
        "version": JSON_VERSION,
        "rows": rows,
        "bubbles": bubbles(trace, top=top),
    }


def main(argv: Optional[List[str]] = None) -> int:
    p = argparse.ArgumentParser(prog="areal_tpu.apps.trace_report")
    p.add_argument(
        "path",
        help="trace dir (shards are merged into trace.json) or a merged "
        "trace.json",
    )
    p.add_argument("--top", type=int, default=5, help="bubbles to print")
    p.add_argument(
        "--out", default=None,
        help="where to write the merged trace.json (dir input only)",
    )
    p.add_argument(
        "--json", action="store_true",
        help="emit the stable v1 JSON report instead of tables",
    )
    args = p.parse_args(argv)
    if os.path.isdir(args.path):
        out = args.out or os.path.join(args.path, "trace.json")
        trace = tracer.merge_shards(args.path, out_path=out)
        if not args.json:
            print(f"merged {args.path} -> {out}")
    else:
        trace = load_trace(args.path)
    errors = tracer.validate_trace(trace)
    if errors:
        print("trace schema problems:", file=sys.stderr)
        for e in errors:
            print(f"  - {e}", file=sys.stderr)
        return 1
    if args.json:
        print(json.dumps(json_report(trace, top=args.top)))
    else:
        print(format_report(trace, top=args.top))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
