"""Stall attribution over a merged trace: where did each step's wall-clock
go, per process?

    python -m areal_tpu.apps.trace_report <trace_dir | trace.json> [--top N]

Given a directory, first merges the ``trace_*.jsonl`` shards into
``trace.json`` (tracer.merge_shards), then walks each process track and
buckets every step's wall-clock into compute / comms / host / idle:

- step windows come from the master's ``step`` spans (the whole trace is
  one step when absent — e.g. a bare gen_server capture);
- category time is the union of that process's categorized spans clipped
  to the window, with precedence comms > compute > host (a compute span
  nested inside a transfer wait counts once, as comms);
- idle is the uncovered remainder — the bubbles future overlap PRs exist
  to shrink.  The top-N bubble intervals are printed with the spans that
  bound them, which is the artifact a perf PR cites before/after.

Uncategorized spans (request lifetimes, dispatch waits) shape the
timeline but never count toward a bucket.

``--pipeline`` switches the human view to the pipelined-step report:
one row per (step, stage) over the master's ``pipe:<stage>`` dispatch
spans, with each stage's busy time (interval union of its chunk
dispatches), fill fraction of the step window, and intra-stage bubble,
plus a per-step overlap fraction (how much of the stages' summed busy
time ran concurrently — 0 under the barrier scheduler, > 0 once chunks
of different stages execute at the same time).

``--lineage`` switches to the causal-lineage view: joins the merged
shards by ``trace_id`` (the ``lineage:*`` instant events every stage of
the async-RL pipeline stamps) and renders one end-to-end timeline per
sample — dispatched → first-token → generated → graded → admitted →
trained — plus stage-transition p50/p99 and a staleness-vs-latency
breakdown keyed on the admission-time weight-version lag.

``--flight`` renders the flight-recorder dumps
(``flightrec_<role>_<rank>.json``, written next to the shards when a
fault trips) as one cross-process timeline of the last ``--window``
seconds before the fault instant.  It reads the dumps directly — no
merge, no validation — because the trace may be torn at exactly the
moment you need this view.

``--json`` emits the report as one JSON object with a stable schema
(``json_report``) instead of the human tables, for dashboards and the
regression tooling:

    {"version": 4,
     "rows": [{"step", "pid", "process", "window_us", "compute_us",
               "comms_us", "host_us", "idle_us"}, ...],
     "bubbles": [{"process", "step", "start_us", "dur_us",
                  "after_span", "before_span"}, ...],
     "pipeline": [{"step", "window_us", "overlap_frac",
                   "stages": [{"stage", "n_chunks", "busy_us", "fill",
                               "bubble_us"}, ...]}, ...],
     "lineage": {"summary": {"n", "complete", "in_flight", "failed",
                             "rejected_stale", "orphans", "e2e_p50_us",
                             "e2e_p99_us", "transitions", "staleness"},
                 "traces": [{"trace_id", "qid", "root", "complete",
                             "e2e_us", "version_lag", "stages"}, ...]},
     "profile": [<analysis/profile.py harvest_trace entries: per-MFC
                  records keyed (mfc, model_shape, layout, batch_shape),
                  per-step walls, inferred topology levels>]}

``version`` bumps on any breaking change; consumers must reject
versions they don't know.  v2 was additive over v1 (``pipeline``); v3
was additive over v2 (``lineage``, empty traces/zero counts when the
trace carries no ``lineage:*`` events); v4 is additive over v3:
``profile`` is new — the placement advisor's profile-store entries
harvested from this trace (empty list when no MFC spans carry profile
args, i.e. any pre-advisor run).
"""

import argparse
import json
import os
import sys
from collections import defaultdict
from typing import Any, Dict, List, Optional, Tuple

from areal_tpu.base import tracer

Interval = Tuple[int, int]  # [start_us, end_us)

# Attribution precedence: a span overlapped by a higher category yields
# to it so nested spans never double-count.
CATEGORIES = ("comms", "compute", "host")


def _union(intervals: List[Interval]) -> List[Interval]:
    if not intervals:
        return []
    intervals = sorted(intervals)
    out = [list(intervals[0])]
    for s, e in intervals[1:]:
        if s <= out[-1][1]:
            out[-1][1] = max(out[-1][1], e)
        else:
            out.append([s, e])
    return [(s, e) for s, e in out]


def _subtract(base: List[Interval], cut: List[Interval]) -> List[Interval]:
    """base minus cut; both must be sorted unions."""
    out: List[Interval] = []
    ci = 0
    for s, e in base:
        cur = s
        while ci < len(cut) and cut[ci][1] <= cur:
            ci += 1
        j = ci
        while j < len(cut) and cut[j][0] < e:
            cs, ce = cut[j]
            if cs > cur:
                out.append((cur, cs))
            cur = max(cur, ce)
            if cur >= e:
                break
            j += 1
        if cur < e:
            out.append((cur, e))
    return out


def _clip(intervals: List[Interval], lo: int, hi: int) -> List[Interval]:
    return [
        (max(s, lo), min(e, hi))
        for s, e in intervals
        if min(e, hi) > max(s, lo)
    ]


def _total(intervals: List[Interval]) -> int:
    return sum(e - s for s, e in intervals)


def load_trace(path: str) -> Dict[str, Any]:
    with open(path) as f:
        return json.load(f)


def _spans_by_pid(trace) -> Dict[int, List[Dict]]:
    by_pid: Dict[int, List[Dict]] = defaultdict(list)
    for e in trace.get("traceEvents", []):
        if e.get("ph") == "X":
            by_pid[int(e.get("pid", 0))].append(e)
    return by_pid


def _proc_names(trace) -> Dict[int, str]:
    names = {}
    for e in trace.get("traceEvents", []):
        if e.get("ph") == "M" and e.get("name") == "process_name":
            names[int(e["pid"])] = e.get("args", {}).get("name", "?")
    return names


def _step_windows(trace) -> List[Tuple[Optional[int], int, int]]:
    """[(step_number, start_us, end_us)] from ``step`` spans; the whole
    trace as one anonymous window when no step spans exist."""
    steps = []
    for e in trace.get("traceEvents", []):
        if e.get("ph") == "X" and e.get("name") == "step":
            num = (e.get("args") or {}).get("step")
            steps.append(
                (
                    int(num) if num is not None else None,
                    int(e["ts"]),
                    int(e["ts"]) + int(e["dur"]),
                )
            )
    if steps:
        return sorted(steps, key=lambda t: t[1])
    spans = [e for e in trace.get("traceEvents", []) if e.get("ph") == "X"]
    if not spans:
        return []
    lo = min(int(e["ts"]) for e in spans)
    hi = max(int(e["ts"]) + int(e["dur"]) for e in spans)
    return [(None, lo, hi)]


def attribute(trace) -> List[Dict[str, Any]]:
    """-> one row per (step, process): {step, process, window_us,
    compute_us, comms_us, host_us, idle_us}."""
    by_pid = _spans_by_pid(trace)
    names = _proc_names(trace)
    rows = []
    for step, lo, hi in _step_windows(trace):
        for pid, spans in sorted(by_pid.items()):
            cat_iv: Dict[str, List[Interval]] = {c: [] for c in CATEGORIES}
            for e in spans:
                c = e.get("cat")
                if c in cat_iv:
                    cat_iv[c].append(
                        (int(e["ts"]), int(e["ts"]) + int(e["dur"]))
                    )
            covered: List[Interval] = []
            row = {
                "step": step,
                "pid": pid,
                "process": names.get(pid, str(pid)),
                "window_us": hi - lo,
            }
            for c in CATEGORIES:
                u = _subtract(_union(_clip(cat_iv[c], lo, hi)), covered)
                row[f"{c}_us"] = _total(u)
                covered = _union(covered + u)
            row["idle_us"] = (hi - lo) - _total(covered)
            row["_covered"] = covered
            rows.append(row)
    return rows


def bubbles(trace, top: int = 5) -> List[Dict[str, Any]]:
    """Largest uncovered (idle) intervals per process across all step
    windows, with the categorized spans bounding each gap."""
    by_pid = _spans_by_pid(trace)
    names = _proc_names(trace)
    windows = _step_windows(trace)
    out = []
    for pid, spans in by_pid.items():
        cat_spans = [e for e in spans if e.get("cat") in CATEGORIES]
        covered = _union(
            [
                (int(e["ts"]), int(e["ts"]) + int(e["dur"]))
                for e in cat_spans
            ]
        )
        for step, lo, hi in windows:
            for gs, ge in _subtract([(lo, hi)], _clip(covered, lo, hi)):
                before = after = None
                for e in cat_spans:
                    s, ee = int(e["ts"]), int(e["ts"]) + int(e["dur"])
                    if ee <= gs and (
                        before is None
                        or ee > int(before["ts"]) + int(before["dur"])
                    ):
                        before = e
                    if s >= ge and (
                        after is None or s < int(after["ts"])
                    ):
                        after = e
                out.append(
                    {
                        "process": names.get(pid, str(pid)),
                        "step": step,
                        "start_us": gs,
                        "dur_us": ge - gs,
                        "after_span": before["name"] if before else None,
                        "before_span": after["name"] if after else None,
                    }
                )
    out.sort(key=lambda b: -b["dur_us"])
    return out[:top]


def pipeline_rows(trace) -> List[Dict[str, Any]]:
    """Per-step occupancy of the pipelined executor, from the master's
    ``pipe:<stage>`` dispatch spans.

    For each step window and each stage (DFG node): ``busy_us`` is the
    interval union of that stage's chunk dispatches clipped to the
    window, ``fill`` = busy / window, ``bubble_us`` = idle time strictly
    inside the stage's own active span (last end - first start - busy).
    The per-step ``overlap_frac`` = 1 - union(all stages) / sum(stages):
    0 when stages run strictly one after another (the barrier
    scheduler), approaching 1 - 1/n_stages as they fully overlap.
    Steps without pipe spans (non-pipelined runs) produce no rows.
    """
    events = [
        e
        for e in trace.get("traceEvents", [])
        if e.get("ph") == "X" and str(e.get("name", "")).startswith("pipe:")
    ]
    out: List[Dict[str, Any]] = []
    for step, lo, hi in _step_windows(trace):
        window = hi - lo
        stages: Dict[str, List[Interval]] = {}
        for e in events:
            s, ee = int(e["ts"]), int(e["ts"]) + int(e["dur"])
            if ee <= lo or s >= hi:
                continue
            stage = (e.get("args") or {}).get("stage") or e["name"][
                len("pipe:"):
            ]
            stages.setdefault(str(stage), []).append(
                (max(s, lo), min(ee, hi))
            )
        if not stages:
            continue
        srows = []
        busy_all: List[Interval] = []
        sum_busy = 0
        for stage, iv in sorted(stages.items()):
            u = _union(iv)
            busy = _total(u)
            srows.append(
                {
                    "stage": stage,
                    "n_chunks": len(iv),
                    "busy_us": busy,
                    "fill": busy / max(window, 1),
                    "bubble_us": max((u[-1][1] - u[0][0]) - busy, 0),
                }
            )
            busy_all.extend(u)
            sum_busy += busy
        union_all = _total(_union(busy_all))
        out.append(
            {
                "step": step,
                "window_us": window,
                "overlap_frac": (
                    1.0 - union_all / sum_busy if sum_busy else 0.0
                ),
                "stages": srows,
            }
        )
    return out


def format_pipeline(trace) -> str:
    steps = pipeline_rows(trace)
    if not steps:
        return (
            "no pipe:* spans in this trace (pipeline_overlap off, or the "
            "master was not traced)"
        )
    lines = [
        f"{'step':>5} {'stage':<16} {'chunks':>6} {'busy_ms':>9} "
        f"{'fill%':>6} {'bubble_ms':>9}"
    ]
    for st in steps:
        step = "-" if st["step"] is None else str(st["step"])
        for r in st["stages"]:
            lines.append(
                f"{step:>5} {r['stage']:<16} {r['n_chunks']:>6} "
                f"{r['busy_us'] / 1000.0:9.1f} {100.0 * r['fill']:5.1f}% "
                f"{r['bubble_us'] / 1000.0:9.1f}"
            )
        lines.append(
            f"{step:>5} {'(step)':<16} window "
            f"{st['window_us'] / 1000.0:.1f} ms, overlap "
            f"{100.0 * st['overlap_frac']:.1f}%"
        )
    return "\n".join(lines)


def format_report(trace, top: int = 5) -> str:
    rows = attribute(trace)
    lines = []
    ms = lambda us: f"{us / 1000.0:9.1f}"  # noqa: E731
    lines.append(
        f"{'step':>5} {'process':<16} {'window_ms':>9} {'compute':>9} "
        f"{'comms':>9} {'host':>9} {'idle':>9} {'idle%':>6}"
    )
    for r in rows:
        step = "-" if r["step"] is None else str(r["step"])
        idle_pct = 100.0 * r["idle_us"] / max(r["window_us"], 1)
        lines.append(
            f"{step:>5} {r['process']:<16} {ms(r['window_us'])} "
            f"{ms(r['compute_us'])} {ms(r['comms_us'])} {ms(r['host_us'])} "
            f"{ms(r['idle_us'])} {idle_pct:5.1f}%"
        )
    bubs = bubbles(trace, top=top)
    if bubs:
        lines.append("")
        lines.append(f"top {len(bubs)} bubbles (uncovered intervals):")
        for b in bubs:
            step = "-" if b["step"] is None else str(b["step"])
            lines.append(
                f"  {b['dur_us'] / 1000.0:8.1f} ms  step {step:>3}  "
                f"{b['process']:<16} between "
                f"{b['after_span'] or '<window start>'} and "
                f"{b['before_span'] or '<window end>'}"
            )
    return "\n".join(lines)


# ---------------------------------------------------------------------------
# causal lineage: join merged shards by trace_id into per-sample timelines
# ---------------------------------------------------------------------------

# The canonical stage order of the async-RL pipeline; transitions between
# adjacent present stages are what the p50/p99 table reports.
_LINEAGE_TRANSITIONS = (
    ("dispatch", "first_token"),
    ("first_token", "generated"),
    ("generated", "graded"),
    ("graded", "admitted"),
    ("admitted", "trained"),
)


def _pctl(vals: List[float], q: float) -> float:
    if not vals:
        return 0.0
    vals = sorted(vals)
    return float(vals[min(len(vals) - 1, int(round(q * (len(vals) - 1))))])


def lineage_rows(trace) -> List[Dict[str, Any]]:
    """-> one row per trace_id: {trace_id, qid, root, stages: {stage:
    first_ts_us}, complete, e2e_us, version_lag}.  ``complete`` means
    the sample's timeline runs dispatch → trained; ``version_lag`` is
    the admission-time staleness the replay buffer stamped."""
    by_tid: Dict[str, Dict[str, Any]] = {}
    for e in trace.get("traceEvents", []):
        if e.get("ph") != "i" or str(e.get("cat", "")) != "lineage":
            continue
        a = e.get("args") or {}
        tid = str(a.get("trace_id", ""))
        if not tid:
            continue
        row = by_tid.setdefault(
            tid,
            {
                "trace_id": tid,
                "qid": "",
                "task": "",
                "root": False,
                "stages": {},
                "version_lag": None,
            },
        )
        stage = str(a.get("stage", ""))
        ts = int(e.get("ts", 0))
        if stage and (
            stage not in row["stages"] or ts < row["stages"][stage]
        ):
            row["stages"][stage] = ts
        if a.get("root"):
            row["root"] = True
        if a.get("qid") and not row["qid"]:
            row["qid"] = str(a["qid"])
        # Task-mixture stamp (the dispatch root carries it; graders
        # echo it) — keys per-task e2e attribution.
        if a.get("task") and not row["task"]:
            row["task"] = str(a["task"])
        if stage == "admitted" and a.get("version_lag") is not None:
            row["version_lag"] = int(a["version_lag"])
    rows = []
    for tid in sorted(by_tid):
        row = by_tid[tid]
        st = row["stages"]
        row["complete"] = "dispatch" in st and "trained" in st
        row["e2e_us"] = (
            st["trained"] - st["dispatch"] if row["complete"] else None
        )
        rows.append(row)
    return rows


def _group_by_task(rows: List[Dict[str, Any]]) -> Dict[str, List[Dict]]:
    by_task: Dict[str, List[Dict]] = {}
    for r in rows:
        if r.get("task"):
            by_task.setdefault(r["task"], []).append(r)
    return by_task


def lineage_summary(trace) -> Dict[str, Any]:
    """Fleet view of the joined timelines: counts (complete / in-flight
    at shutdown / failed / rejected / orphaned), end-to-end and
    stage-transition p50/p99, and staleness-vs-latency keyed on the
    admission version lag."""
    rows = lineage_rows(trace)
    complete = [r for r in rows if r["complete"]]
    terminal = ("trained", "failed", "rejected_stale")
    in_flight = [
        r["trace_id"]
        for r in rows
        if r["root"] and not any(s in r["stages"] for s in terminal)
    ]
    transitions: Dict[str, Dict[str, float]] = {}
    for a, b in _LINEAGE_TRANSITIONS:
        deltas = [
            float(r["stages"][b] - r["stages"][a])
            for r in rows
            if a in r["stages"] and b in r["stages"]
        ]
        if deltas:
            transitions[f"{a}->{b}"] = {
                "n": len(deltas),
                "p50_us": _pctl(deltas, 0.5),
                "p99_us": _pctl(deltas, 0.99),
            }
    e2e = [float(r["e2e_us"]) for r in complete]
    by_lag: Dict[int, List[float]] = {}
    for r in complete:
        if r["version_lag"] is not None:
            by_lag.setdefault(r["version_lag"], []).append(
                float(r["e2e_us"])
            )
    return {
        "n": len(rows),
        "complete": len(complete),
        "in_flight": len(in_flight),
        "failed": sum(1 for r in rows if "failed" in r["stages"]),
        "rejected_stale": sum(
            1 for r in rows if "rejected_stale" in r["stages"]
        ),
        "orphans": [r["trace_id"] for r in rows if not r["root"]],
        "e2e_p50_us": _pctl(e2e, 0.5),
        "e2e_p99_us": _pctl(e2e, 0.99),
        "transitions": transitions,
        # Per-task e2e attribution (task-mixture trials): which task
        # stream the pipeline's latency is going to.  Empty-task rows
        # (single-stream trials) are omitted.
        "by_task": [
            {
                "task": task,
                "n": len(trs),
                "complete": sum(1 for r in trs if r["complete"]),
                "e2e_p50_us": _pctl(
                    [float(r["e2e_us"]) for r in trs if r["complete"]],
                    0.5,
                ),
                "e2e_p99_us": _pctl(
                    [float(r["e2e_us"]) for r in trs if r["complete"]],
                    0.99,
                ),
            }
            for task, trs in sorted(_group_by_task(rows).items())
        ],
        "staleness": [
            {
                "version_lag": lag,
                "n": len(v),
                "p50_us": _pctl(v, 0.5),
                "p99_us": _pctl(v, 0.99),
            }
            for lag, v in sorted(by_lag.items())
        ],
    }


def format_lineage(trace) -> str:
    rows = lineage_rows(trace)
    if not rows:
        return (
            "no lineage:* events in this trace (pre-lineage run, or the "
            "dispatcher was not traced)"
        )
    s = lineage_summary(trace)
    lines = [
        f"{'trace_id':<22} {'qid':<14} {'lag':>3} {'e2e_ms':>9}  timeline"
    ]
    for r in rows:
        order = sorted(r["stages"].items(), key=lambda kv: kv[1])
        t0 = order[0][1]
        tl = " -> ".join(
            f"{st}@{(ts - t0) / 1000.0:.1f}ms" for st, ts in order
        )
        e2e = (
            f"{r['e2e_us'] / 1000.0:9.1f}" if r["complete"] else
            f"{'-':>9}"
        )
        lag = "-" if r["version_lag"] is None else str(r["version_lag"])
        lines.append(
            f"{r['trace_id']:<22} {r['qid']:<14} {lag:>3} {e2e}  {tl}"
        )
    lines.append("")
    lines.append(
        f"{s['n']} traces: {s['complete']} complete, "
        f"{s['in_flight']} in-flight, {s['failed']} failed, "
        f"{s['rejected_stale']} rejected stale, "
        f"{len(s['orphans'])} orphaned; e2e p50 "
        f"{s['e2e_p50_us'] / 1000.0:.1f} ms, p99 "
        f"{s['e2e_p99_us'] / 1000.0:.1f} ms"
    )
    for name, t in s["transitions"].items():
        lines.append(
            f"  {name:<24} n={t['n']:<4} p50 {t['p50_us'] / 1000.0:8.1f} "
            f"ms  p99 {t['p99_us'] / 1000.0:8.1f} ms"
        )
    for b in s["by_task"]:
        lines.append(
            f"  task={b['task']:<12} n={b['n']:<4} "
            f"complete={b['complete']:<4} e2e p50 "
            f"{b['e2e_p50_us'] / 1000.0:8.1f} ms  p99 "
            f"{b['e2e_p99_us'] / 1000.0:8.1f} ms"
        )
    for b in s["staleness"]:
        lines.append(
            f"  lag={b['version_lag']:<2} n={b['n']:<4} e2e p50 "
            f"{b['p50_us'] / 1000.0:8.1f} ms  p99 "
            f"{b['p99_us'] / 1000.0:8.1f} ms"
        )
    return "\n".join(lines)


# ---------------------------------------------------------------------------
# flight recorder: cross-process timeline around the fault instant
# ---------------------------------------------------------------------------


def format_flight(trace_dir: str, window_s: float = 10.0) -> str:
    """Render every flightrec dump in ``trace_dir`` as one merged
    timeline of the last ``window_s`` seconds before the latest fault.
    Reads the dumps directly — the trace itself may be torn at exactly
    the moment this view matters."""
    dumps = tracer.read_flight_dumps(trace_dir)
    if not dumps:
        return f"no flightrec_*.json dumps in {trace_dir}"
    fault_us = max(int(d.get("t_dump_us", 0)) for d in dumps)
    lo_us = fault_us - int(window_s * 1e6)
    lines = [
        f"{len(dumps)} flight dump(s); fault window: last "
        f"{window_s:.1f}s before t={fault_us}us"
    ]
    for d in sorted(dumps, key=lambda d: int(d.get("t_dump_us", 0))):
        lines.append(
            f"  {d.get('role', '?')}_{d.get('rank', '?')} "
            f"(pid {d.get('pid', '?')}): {d.get('reason', '?')} with "
            f"{len(d.get('events', []))} ring events"
        )
    merged = []
    for d in dumps:
        who = f"{d.get('role', '?')}_{d.get('rank', '?')}"
        for ev in d.get("events", []):
            t = int(ev.get("t_us", 0))
            if t >= lo_us:
                merged.append((t, who, ev))
    merged.sort(key=lambda x: x[0])
    for t, who, ev in merged:
        rest = {
            k: v for k, v in ev.items() if k not in ("t_us", "kind")
        }
        detail = " ".join(f"{k}={v}" for k, v in sorted(rest.items()))
        lines.append(
            f"  {(t - fault_us) / 1e6:+9.3f}s {who:<16} "
            f"{ev.get('kind', '?'):<10} {detail}"
        )
    return "\n".join(lines)


# v4 is additive over v3: rows/bubbles/pipeline/lineage unchanged,
# "profile" added (see module docstring).
JSON_VERSION = 4


def json_report(trace, top: int = 5) -> Dict[str, Any]:
    """Machine-readable report, schema v4 (see module docstring).  The
    internal ``_covered`` interval list is stripped from rows — it is an
    implementation detail of the precedence subtraction, not contract."""
    from areal_tpu.analysis import profile as _profile

    rows = [
        {k: v for k, v in r.items() if not k.startswith("_")}
        for r in attribute(trace)
    ]
    return {
        "version": JSON_VERSION,
        "rows": rows,
        "bubbles": bubbles(trace, top=top),
        "pipeline": pipeline_rows(trace),
        "lineage": {
            "summary": lineage_summary(trace),
            "traces": lineage_rows(trace),
        },
        "profile": _profile.harvest_trace(trace),
    }


def main(argv: Optional[List[str]] = None) -> int:
    p = argparse.ArgumentParser(prog="areal_tpu.apps.trace_report")
    p.add_argument(
        "path",
        help="trace dir (shards are merged into trace.json) or a merged "
        "trace.json",
    )
    p.add_argument("--top", type=int, default=5, help="bubbles to print")
    p.add_argument(
        "--out", default=None,
        help="where to write the merged trace.json (dir input only)",
    )
    p.add_argument(
        "--json", action="store_true",
        help="emit the stable v3 JSON report instead of tables",
    )
    p.add_argument(
        "--pipeline", action="store_true",
        help="per-stage fill/overlap of the pipelined step executor "
        "(from pipe:* spans) instead of the stall tables",
    )
    p.add_argument(
        "--lineage", action="store_true",
        help="per-sample causal timelines joined by trace_id "
        "(dispatch -> ... -> trained) instead of the stall tables",
    )
    p.add_argument(
        "--flight", action="store_true",
        help="render flightrec_*.json dumps around the fault instant "
        "(skips merge + validation: the trace may be torn)",
    )
    p.add_argument(
        "--window", type=float, default=10.0,
        help="seconds of flight-recorder history to render (--flight)",
    )
    args = p.parse_args(argv)
    if args.flight:
        d = (
            args.path
            if os.path.isdir(args.path)
            else os.path.dirname(os.path.abspath(args.path))
        )
        print(format_flight(d, window_s=args.window))
        return 0
    if os.path.isdir(args.path):
        out = args.out or os.path.join(args.path, "trace.json")
        trace = tracer.merge_shards(args.path, out_path=out)
        if not args.json:
            print(f"merged {args.path} -> {out}")
    else:
        trace = load_trace(args.path)
    errors = tracer.validate_trace(trace)
    if errors:
        print("trace schema problems:", file=sys.stderr)
        for e in errors:
            print(f"  - {e}", file=sys.stderr)
        return 1
    if args.json:
        print(json.dumps(json_report(trace, top=args.top)))
    elif args.pipeline:
        print(format_pipeline(trace))
    elif args.lineage:
        print(format_lineage(trace))
    else:
        print(format_report(trace, top=args.top))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
