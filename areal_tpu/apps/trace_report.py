"""Stall attribution over a merged trace: where did each step's wall-clock
go, per process?

    python -m areal_tpu.apps.trace_report <trace_dir | trace.json> [--top N]

Given a directory, first merges the ``trace_*.jsonl`` shards into
``trace.json`` (tracer.merge_shards), then walks each process track and
buckets every step's wall-clock into compute / comms / host / idle:

- step windows come from the master's ``step`` spans (the whole trace is
  one step when absent — e.g. a bare gen_server capture);
- category time is the union of that process's categorized spans clipped
  to the window, with precedence comms > compute > host (a compute span
  nested inside a transfer wait counts once, as comms);
- idle is the uncovered remainder — the bubbles future overlap PRs exist
  to shrink.  The top-N bubble intervals are printed with the spans that
  bound them, which is the artifact a perf PR cites before/after.

Uncategorized spans (request lifetimes, dispatch waits) shape the
timeline but never count toward a bucket.

``--pipeline`` switches the human view to the pipelined-step report:
one row per (step, stage) over the master's ``pipe:<stage>`` dispatch
spans, with each stage's busy time (interval union of its chunk
dispatches), fill fraction of the step window, and intra-stage bubble,
plus a per-step overlap fraction (how much of the stages' summed busy
time ran concurrently — 0 under the barrier scheduler, > 0 once chunks
of different stages execute at the same time).

``--json`` emits the report as one JSON object with a stable schema
(``json_report``) instead of the human tables, for dashboards and the
regression tooling:

    {"version": 2,
     "rows": [{"step", "pid", "process", "window_us", "compute_us",
               "comms_us", "host_us", "idle_us"}, ...],
     "bubbles": [{"process", "step", "start_us", "dur_us",
                  "after_span", "before_span"}, ...],
     "pipeline": [{"step", "window_us", "overlap_frac",
                   "stages": [{"stage", "n_chunks", "busy_us", "fill",
                               "bubble_us"}, ...]}, ...]}

``version`` bumps on any breaking change; consumers must reject
versions they don't know.  v2 is additive over v1: every v1 field is
unchanged, ``pipeline`` is new (empty list when the trace has no
``pipe:*`` spans, i.e. any non-pipelined run).
"""

import argparse
import json
import os
import sys
from collections import defaultdict
from typing import Any, Dict, List, Optional, Tuple

from areal_tpu.base import tracer

Interval = Tuple[int, int]  # [start_us, end_us)

# Attribution precedence: a span overlapped by a higher category yields
# to it so nested spans never double-count.
CATEGORIES = ("comms", "compute", "host")


def _union(intervals: List[Interval]) -> List[Interval]:
    if not intervals:
        return []
    intervals = sorted(intervals)
    out = [list(intervals[0])]
    for s, e in intervals[1:]:
        if s <= out[-1][1]:
            out[-1][1] = max(out[-1][1], e)
        else:
            out.append([s, e])
    return [(s, e) for s, e in out]


def _subtract(base: List[Interval], cut: List[Interval]) -> List[Interval]:
    """base minus cut; both must be sorted unions."""
    out: List[Interval] = []
    ci = 0
    for s, e in base:
        cur = s
        while ci < len(cut) and cut[ci][1] <= cur:
            ci += 1
        j = ci
        while j < len(cut) and cut[j][0] < e:
            cs, ce = cut[j]
            if cs > cur:
                out.append((cur, cs))
            cur = max(cur, ce)
            if cur >= e:
                break
            j += 1
        if cur < e:
            out.append((cur, e))
    return out


def _clip(intervals: List[Interval], lo: int, hi: int) -> List[Interval]:
    return [
        (max(s, lo), min(e, hi))
        for s, e in intervals
        if min(e, hi) > max(s, lo)
    ]


def _total(intervals: List[Interval]) -> int:
    return sum(e - s for s, e in intervals)


def load_trace(path: str) -> Dict[str, Any]:
    with open(path) as f:
        return json.load(f)


def _spans_by_pid(trace) -> Dict[int, List[Dict]]:
    by_pid: Dict[int, List[Dict]] = defaultdict(list)
    for e in trace.get("traceEvents", []):
        if e.get("ph") == "X":
            by_pid[int(e.get("pid", 0))].append(e)
    return by_pid


def _proc_names(trace) -> Dict[int, str]:
    names = {}
    for e in trace.get("traceEvents", []):
        if e.get("ph") == "M" and e.get("name") == "process_name":
            names[int(e["pid"])] = e.get("args", {}).get("name", "?")
    return names


def _step_windows(trace) -> List[Tuple[Optional[int], int, int]]:
    """[(step_number, start_us, end_us)] from ``step`` spans; the whole
    trace as one anonymous window when no step spans exist."""
    steps = []
    for e in trace.get("traceEvents", []):
        if e.get("ph") == "X" and e.get("name") == "step":
            num = (e.get("args") or {}).get("step")
            steps.append(
                (
                    int(num) if num is not None else None,
                    int(e["ts"]),
                    int(e["ts"]) + int(e["dur"]),
                )
            )
    if steps:
        return sorted(steps, key=lambda t: t[1])
    spans = [e for e in trace.get("traceEvents", []) if e.get("ph") == "X"]
    if not spans:
        return []
    lo = min(int(e["ts"]) for e in spans)
    hi = max(int(e["ts"]) + int(e["dur"]) for e in spans)
    return [(None, lo, hi)]


def attribute(trace) -> List[Dict[str, Any]]:
    """-> one row per (step, process): {step, process, window_us,
    compute_us, comms_us, host_us, idle_us}."""
    by_pid = _spans_by_pid(trace)
    names = _proc_names(trace)
    rows = []
    for step, lo, hi in _step_windows(trace):
        for pid, spans in sorted(by_pid.items()):
            cat_iv: Dict[str, List[Interval]] = {c: [] for c in CATEGORIES}
            for e in spans:
                c = e.get("cat")
                if c in cat_iv:
                    cat_iv[c].append(
                        (int(e["ts"]), int(e["ts"]) + int(e["dur"]))
                    )
            covered: List[Interval] = []
            row = {
                "step": step,
                "pid": pid,
                "process": names.get(pid, str(pid)),
                "window_us": hi - lo,
            }
            for c in CATEGORIES:
                u = _subtract(_union(_clip(cat_iv[c], lo, hi)), covered)
                row[f"{c}_us"] = _total(u)
                covered = _union(covered + u)
            row["idle_us"] = (hi - lo) - _total(covered)
            row["_covered"] = covered
            rows.append(row)
    return rows


def bubbles(trace, top: int = 5) -> List[Dict[str, Any]]:
    """Largest uncovered (idle) intervals per process across all step
    windows, with the categorized spans bounding each gap."""
    by_pid = _spans_by_pid(trace)
    names = _proc_names(trace)
    windows = _step_windows(trace)
    out = []
    for pid, spans in by_pid.items():
        cat_spans = [e for e in spans if e.get("cat") in CATEGORIES]
        covered = _union(
            [
                (int(e["ts"]), int(e["ts"]) + int(e["dur"]))
                for e in cat_spans
            ]
        )
        for step, lo, hi in windows:
            for gs, ge in _subtract([(lo, hi)], _clip(covered, lo, hi)):
                before = after = None
                for e in cat_spans:
                    s, ee = int(e["ts"]), int(e["ts"]) + int(e["dur"])
                    if ee <= gs and (
                        before is None
                        or ee > int(before["ts"]) + int(before["dur"])
                    ):
                        before = e
                    if s >= ge and (
                        after is None or s < int(after["ts"])
                    ):
                        after = e
                out.append(
                    {
                        "process": names.get(pid, str(pid)),
                        "step": step,
                        "start_us": gs,
                        "dur_us": ge - gs,
                        "after_span": before["name"] if before else None,
                        "before_span": after["name"] if after else None,
                    }
                )
    out.sort(key=lambda b: -b["dur_us"])
    return out[:top]


def pipeline_rows(trace) -> List[Dict[str, Any]]:
    """Per-step occupancy of the pipelined executor, from the master's
    ``pipe:<stage>`` dispatch spans.

    For each step window and each stage (DFG node): ``busy_us`` is the
    interval union of that stage's chunk dispatches clipped to the
    window, ``fill`` = busy / window, ``bubble_us`` = idle time strictly
    inside the stage's own active span (last end - first start - busy).
    The per-step ``overlap_frac`` = 1 - union(all stages) / sum(stages):
    0 when stages run strictly one after another (the barrier
    scheduler), approaching 1 - 1/n_stages as they fully overlap.
    Steps without pipe spans (non-pipelined runs) produce no rows.
    """
    events = [
        e
        for e in trace.get("traceEvents", [])
        if e.get("ph") == "X" and str(e.get("name", "")).startswith("pipe:")
    ]
    out: List[Dict[str, Any]] = []
    for step, lo, hi in _step_windows(trace):
        window = hi - lo
        stages: Dict[str, List[Interval]] = {}
        for e in events:
            s, ee = int(e["ts"]), int(e["ts"]) + int(e["dur"])
            if ee <= lo or s >= hi:
                continue
            stage = (e.get("args") or {}).get("stage") or e["name"][
                len("pipe:"):
            ]
            stages.setdefault(str(stage), []).append(
                (max(s, lo), min(ee, hi))
            )
        if not stages:
            continue
        srows = []
        busy_all: List[Interval] = []
        sum_busy = 0
        for stage, iv in sorted(stages.items()):
            u = _union(iv)
            busy = _total(u)
            srows.append(
                {
                    "stage": stage,
                    "n_chunks": len(iv),
                    "busy_us": busy,
                    "fill": busy / max(window, 1),
                    "bubble_us": max((u[-1][1] - u[0][0]) - busy, 0),
                }
            )
            busy_all.extend(u)
            sum_busy += busy
        union_all = _total(_union(busy_all))
        out.append(
            {
                "step": step,
                "window_us": window,
                "overlap_frac": (
                    1.0 - union_all / sum_busy if sum_busy else 0.0
                ),
                "stages": srows,
            }
        )
    return out


def format_pipeline(trace) -> str:
    steps = pipeline_rows(trace)
    if not steps:
        return (
            "no pipe:* spans in this trace (pipeline_overlap off, or the "
            "master was not traced)"
        )
    lines = [
        f"{'step':>5} {'stage':<16} {'chunks':>6} {'busy_ms':>9} "
        f"{'fill%':>6} {'bubble_ms':>9}"
    ]
    for st in steps:
        step = "-" if st["step"] is None else str(st["step"])
        for r in st["stages"]:
            lines.append(
                f"{step:>5} {r['stage']:<16} {r['n_chunks']:>6} "
                f"{r['busy_us'] / 1000.0:9.1f} {100.0 * r['fill']:5.1f}% "
                f"{r['bubble_us'] / 1000.0:9.1f}"
            )
        lines.append(
            f"{step:>5} {'(step)':<16} window "
            f"{st['window_us'] / 1000.0:.1f} ms, overlap "
            f"{100.0 * st['overlap_frac']:.1f}%"
        )
    return "\n".join(lines)


def format_report(trace, top: int = 5) -> str:
    rows = attribute(trace)
    lines = []
    ms = lambda us: f"{us / 1000.0:9.1f}"  # noqa: E731
    lines.append(
        f"{'step':>5} {'process':<16} {'window_ms':>9} {'compute':>9} "
        f"{'comms':>9} {'host':>9} {'idle':>9} {'idle%':>6}"
    )
    for r in rows:
        step = "-" if r["step"] is None else str(r["step"])
        idle_pct = 100.0 * r["idle_us"] / max(r["window_us"], 1)
        lines.append(
            f"{step:>5} {r['process']:<16} {ms(r['window_us'])} "
            f"{ms(r['compute_us'])} {ms(r['comms_us'])} {ms(r['host_us'])} "
            f"{ms(r['idle_us'])} {idle_pct:5.1f}%"
        )
    bubs = bubbles(trace, top=top)
    if bubs:
        lines.append("")
        lines.append(f"top {len(bubs)} bubbles (uncovered intervals):")
        for b in bubs:
            step = "-" if b["step"] is None else str(b["step"])
            lines.append(
                f"  {b['dur_us'] / 1000.0:8.1f} ms  step {step:>3}  "
                f"{b['process']:<16} between "
                f"{b['after_span'] or '<window start>'} and "
                f"{b['before_span'] or '<window end>'}"
            )
    return "\n".join(lines)


# v2 is additive over v1: rows/bubbles unchanged, "pipeline" added.
JSON_VERSION = 2


def json_report(trace, top: int = 5) -> Dict[str, Any]:
    """Machine-readable report, schema v2 (see module docstring).  The
    internal ``_covered`` interval list is stripped from rows — it is an
    implementation detail of the precedence subtraction, not contract."""
    rows = [
        {k: v for k, v in r.items() if not k.startswith("_")}
        for r in attribute(trace)
    ]
    return {
        "version": JSON_VERSION,
        "rows": rows,
        "bubbles": bubbles(trace, top=top),
        "pipeline": pipeline_rows(trace),
    }


def main(argv: Optional[List[str]] = None) -> int:
    p = argparse.ArgumentParser(prog="areal_tpu.apps.trace_report")
    p.add_argument(
        "path",
        help="trace dir (shards are merged into trace.json) or a merged "
        "trace.json",
    )
    p.add_argument("--top", type=int, default=5, help="bubbles to print")
    p.add_argument(
        "--out", default=None,
        help="where to write the merged trace.json (dir input only)",
    )
    p.add_argument(
        "--json", action="store_true",
        help="emit the stable v2 JSON report instead of tables",
    )
    p.add_argument(
        "--pipeline", action="store_true",
        help="per-stage fill/overlap of the pipelined step executor "
        "(from pipe:* spans) instead of the stall tables",
    )
    args = p.parse_args(argv)
    if os.path.isdir(args.path):
        out = args.out or os.path.join(args.path, "trace.json")
        trace = tracer.merge_shards(args.path, out_path=out)
        if not args.json:
            print(f"merged {args.path} -> {out}")
    else:
        trace = load_trace(args.path)
    errors = tracer.validate_trace(trace)
    if errors:
        print("trace schema problems:", file=sys.stderr)
        for e in errors:
            print(f"  - {e}", file=sys.stderr)
        return 1
    if args.json:
        print(json.dumps(json_report(trace, top=args.top)))
    elif args.pipeline:
        print(format_pipeline(trace))
    else:
        print(format_report(trace, top=args.top))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
