"""Experiment launcher: master in this process, workers via the scheduler.

Capability parity: realhf/apps/main.py (`main_start` submit + wait + recover
retry loop) and system/controller.py (worker configure/start) — condensed:
the ExperimentPlan already carries fully-resolved WorkerConfigs, so
"configuring" a worker is shipping it a pickle, and the master runs in the
launcher process (the reference's separate master worker process exists to
survive launcher death under slurm; the local/TPU-pod launcher supervises
directly).

Two execution modes:
- run_experiment_inproc(plan): workers in-process (tests, single-host
  trials, and the bench path) — no subprocesses, no sockets.
- run_experiment(plan): ZMQ multi-process — one subprocess per
  WorkerConfig, file-backed name-resolve for discovery, recover retry loop
  re-submitting everything on failure (reference recover mode "auto").
"""

import asyncio
import os
import pickle
import sys
from typing import Dict, List, Optional

from areal_tpu.base import logging, metrics, name_resolve, tracer
from areal_tpu.experiments.common import ExperimentPlan
from areal_tpu.scheduler import JobException, make_scheduler
from areal_tpu.system.master import MasterWorker
from areal_tpu.system.stream import ZMQWorkerPool

logger = logging.getLogger("main")


def _make_master(plan: ExperimentPlan, pool) -> MasterWorker:
    return MasterWorker(
        dfg=plan.dfg,
        pool=pool,
        model_placement=plan.model_placement,
        data_worker_ids=plan.data_worker_ids,
        ctrl=plan.ctrl,
        fileroot=plan.fileroot,
        experiment_name=plan.experiment_name,
        trial_name=plan.trial_name,
        model_groups=plan.model_groups,
        model_replicas=plan.model_replicas,
        difficulty_filter=plan.difficulty_filter,
        rollout_ahead=plan.rollout_ahead,
        max_recoveries=plan.max_recoveries,
    )


def run_experiment_inproc(plan: ExperimentPlan, tokenizer=None):
    """All workers in this process — delegates to the canonical in-process
    runner (areal_tpu/experiments/common.py run_experiment)."""
    from areal_tpu.experiments.common import run_experiment as _run_inproc

    _, stats = _run_inproc(plan, tokenizer=tokenizer)
    return stats


async def _watch_jobs(sched):
    """Fail fast if any worker process dies while the master is running."""
    from areal_tpu.scheduler import JobState
    from areal_tpu.scheduler.client import read_log_tail

    while True:
        for info in sched.find_all():
            if info.state in (JobState.FAILED, JobState.CANCELLED):
                raise JobException(
                    "trial", info.name, info.host or "?", info.state
                ) from RuntimeError(
                    f"worker log tail:\n{read_log_tail(info.log_path)}"
                )
        await asyncio.sleep(1.0)


async def _run_master_zmq(plan: ExperimentPlan, n_workers: int, sched):
    pool = ZMQWorkerPool(
        plan.experiment_name,
        plan.trial_name,
        n_workers,
        mfc_timeout_s=plan.mfc_timeout_s,
        worker_heartbeat_s=plan.worker_heartbeat_s,
    )
    watchdog = asyncio.get_running_loop().create_task(_watch_jobs(sched))
    try:
        master_task = asyncio.get_running_loop().create_task(
            _drive_master(plan, pool)
        )
        done, _ = await asyncio.wait(
            {master_task, watchdog}, return_when=asyncio.FIRST_COMPLETED
        )
        if watchdog in done:  # worker died -> propagate
            master_task.cancel()
            watchdog.result()
        return master_task.result()
    finally:
        watchdog.cancel()
        pool.close()


async def _drive_master(plan: ExperimentPlan, pool: ZMQWorkerPool):
    await pool.wait_workers()
    master = _make_master(plan, pool)
    # Resume step counters / freq-ctl state from a recover checkpoint if one
    # exists (written every ckpt_freq; no-op on fresh trials).
    master.load_recover_info()
    stats = await master.run()
    await pool.broadcast({"type": "exit"})
    return stats


def run_experiment(
    plan: ExperimentPlan,
    recover_retries: int = 0,
    name_resolve_root: Optional[str] = None,
    scheduler_mode: str = "local",
    worker_env: Optional[Dict[str, str]] = None,
    scheduler_kwargs: Optional[Dict] = None,
):
    """Multi-process trial: spawn workers, run the master, wait, recover."""
    root = name_resolve_root or os.path.join(
        plan.fileroot, "name_resolve", plan.experiment_name, plan.trial_name
    )
    os.makedirs(root, exist_ok=True)
    os.environ["AREAL_NAME_RESOLVE"] = "file"
    os.environ["AREAL_NAME_RESOLVE_ROOT"] = root
    name_resolve.set_default(name_resolve.FileNameResolveRepository(root))

    plan_dir = os.path.join(
        plan.fileroot, "plans", plan.experiment_name, plan.trial_name
    )
    os.makedirs(plan_dir, exist_ok=True)
    for wc in plan.worker_configs:
        with open(
            os.path.join(plan_dir, f"worker_{wc.worker_index}.pkl"), "wb"
        ) as f:
            pickle.dump(wc, f)

    last_err = None
    for attempt in range(recover_retries + 1):
        # Workers must import areal_tpu regardless of the launcher's cwd
        # (the package is not pip-installed; reference relies on install).
        pkg_root = os.path.dirname(
            os.path.dirname(os.path.abspath(__file__))
        )
        pkg_root = os.path.dirname(pkg_root)  # dir containing areal_tpu/
        pythonpath = os.environ.get("PYTHONPATH", "")
        if pkg_root not in pythonpath.split(os.pathsep):
            pythonpath = (
                f"{pkg_root}{os.pathsep}{pythonpath}" if pythonpath
                else pkg_root
            )
        env = {
            "PYTHONPATH": pythonpath,
            "AREAL_NAME_RESOLVE": "file",
            "AREAL_NAME_RESOLVE_ROOT": root,
            # Liveness lane: workers beat this often so the master's MFC
            # deadline distinguishes slow (alive, still beating) from
            # dead (no beats past the grace window).
            "AREAL_WORKER_HEARTBEAT_S": str(plan.worker_heartbeat_s),
        }
        # Trace shards from every process must land in ONE dir; the
        # explicit env dict ships it to schedulers that don't inherit
        # our environ (the master configures itself in MasterWorker).
        trace_dir = tracer.default_dir(
            plan.fileroot, plan.experiment_name, plan.trial_name
        )
        if trace_dir:
            env["AREAL_TRACE"] = os.environ.get("AREAL_TRACE", "1")
            env["AREAL_TRACE_DIR"] = trace_dir
        if scheduler_mode != "tpu-pod":
            # Colocated workers default to CPU: one process owns the TPU
            # runtime (apps/worker.py applies this via jax.config, since
            # a site PJRT plugin may ignore JAX_PLATFORMS).  On a TPU pod
            # each worker runs on its OWN host and must claim its chips.
            env["AREAL_WORKER_PLATFORM"] = "cpu"
        env.update(worker_env or {})
        sched = make_scheduler(
            scheduler_mode,
            plan.experiment_name,
            plan.trial_name,
            env=env,
            **(scheduler_kwargs or {}),
        )
        # Live metrics plane for the master (which runs in THIS process):
        # serve the default registry and announce the URL so
        # apps/metrics_report.py finds the trainer role next to the
        # workers' own servers (apps/worker.py announces those).
        metrics_server = metrics.MetricsServer(
            announce=(plan.experiment_name, plan.trial_name, "master")
        )
        sched.submit_array(
            "model_worker",
            lambda i: [
                sys.executable, "-m", "areal_tpu.apps.worker",
                "--config", plan_dir, "--index", str(i),
                "--experiment", plan.experiment_name,
                "--trial", plan.trial_name,
            ],
            count=len(plan.worker_configs),
        )
        try:
            stats = asyncio.run(
                _run_master_zmq(plan, len(plan.worker_configs), sched)
            )
            sched.wait(timeout=60.0)
            return stats
        except (JobException, RuntimeError, TimeoutError) as e:
            last_err = e
            logger.error(f"trial attempt {attempt} failed: {e!r}")
            sched.stop_all()
            if attempt >= recover_retries:
                raise
            logger.info(f"recovering (attempt {attempt + 1})...")
        finally:
            metrics_server.close()
            sched.stop_all()
    raise last_err  # pragma: no cover
