"""Offline placement advisor: rank candidate parallelism/placement plans
against a calibrated cost model (ROADMAP item 3's search stage).

    python -m areal_tpu.apps.advisor <profiles.jsonl | trace dir/json>
        [--devices N] [--mem-budget-gb G] [--windows 1,2,4]
        [--chunk-seqs 0,2,4] [--split] [--top K] [--json]

Input is a profile store (``analysis/profile.py`` JSONL) or a trace —
a merged ``trace.json`` / shard dir is harvested in-memory first.  The
advisor then:

1. calibrates a roofline from the measured records
   (``costmodel.calibrate``): achieved FLOP/s per device per MFC,
   constant walls for FLOP-less MFCs;
2. scores the CURRENT layout: per-MFC predicted wall vs measured, and
   the DFG-composed predicted step vs the measured step walls — the
   predicted-vs-measured error every placement PR must cite (PERF.md);
3. enumerates candidate plans — every (data, fsdp, model) factorization
   of ``--devices`` for gen and train layouts, colocated and (with
   ``--split``) disaggregated gen/train with per-step weight-realloc
   cost, crossed with ``overlap_window`` x ``pipeline_chunk_seqs`` —
   filters them by the device/memory budget, and ranks by predicted
   step time (``costmodel.predict_plan`` / ``rank_plans``).

``--json`` emits one stable JSON object (schema below) instead of the
human table.  ``ADVISOR_JSON_VERSION`` bumps on any breaking change;
consumers must reject versions they don't know:

    {"version": 1,
     "store": {"n_records", "skipped_newer"},
     "roofline": {"eff_flops_per_dev", "fixed_wall_s",
                  "xfer_bytes_per_s", "overhead_s", ...},
     "levels": [["actor:generate"], ...],
     "current": {"layouts": {mfc: layout}, "measured_step_s",
                 "predicted_step_s", "pred_err",
                 "per_mfc": [{"mfc", "layout", "batch_shape",
                              "measured_wall_s", "predicted_wall_s",
                              "err", "compute_bound"}, ...]},
     "candidates": [{"name", "gen_layout", "train_layout", "colocated",
                     "overlap_window", "pipeline_chunk_seqs",
                     "predicted_step_s", "predicted_mem_gb", "feasible",
                     "per_mfc": [...]}, ...],   # ranked, top K
     "n_enumerated": int}

Stdlib-only end to end (profile + costmodel are jax-free): runs on a
laptop against a store scp'd off the cluster.
"""

import argparse
import json
import os
import statistics
import sys
from typing import Any, Dict, List, Optional

from areal_tpu.analysis import costmodel
from areal_tpu.analysis.profile import (
    ProfileKey,
    ProfileStore,
    harvest_trace,
)

ADVISOR_JSON_VERSION = 1


def _load_entries(path: str) -> List[Dict[str, Any]]:
    """Profile entries from a store file, a merged trace.json, or a
    trace shard dir (harvested in-memory — nothing is written)."""
    if os.path.isdir(path):
        from areal_tpu.base import tracer

        return harvest_trace(tracer.merge_shards(path))
    if path.endswith(".jsonl"):
        return ProfileStore(path).load()
    with open(path) as f:
        doc = json.load(f)
    if isinstance(doc, dict) and "traceEvents" in doc:
        return harvest_trace(doc)
    raise SystemExit(f"unrecognized input {path!r}: expected a profile "
                     "store (.jsonl), a merged trace.json, or a shard dir")


class _MemStore(ProfileStore):
    """A ProfileStore over in-memory entries (trace inputs)."""

    def __init__(self, entries: List[Dict[str, Any]]):
        super().__init__(path="<memory>")
        self._entries = entries

    def load(self) -> List[Dict[str, Any]]:
        return list(self._entries)


def current_report(
    store: ProfileStore, rf: costmodel.Roofline,
    levels: List[List[str]],
) -> Dict[str, Any]:
    """Predicted-vs-measured for the measured layout: the calibration
    residual a placement PR cites, and the fleet `advisor_pred_err`
    signal's offline twin."""
    latest = store.latest()
    per_mfc = []
    walls: Dict[str, float] = {}
    layouts: Dict[str, str] = {}
    for key, m in sorted(latest.items(), key=lambda kv: kv[0].mfc):
        p = costmodel.predict_mfc(key, m, rf)
        measured = float(m.get("wall_s_mean", 0.0))
        err = (
            abs(p.wall_s - measured) / measured if measured > 0 else 0.0
        )
        per_mfc.append(
            {
                "mfc": key.mfc,
                "layout": key.layout,
                "batch_shape": key.batch_shape,
                "measured_wall_s": round(measured, 6),
                "predicted_wall_s": round(p.wall_s, 6),
                "err": round(err, 6),
                "compute_bound": p.compute_bound,
            }
        )
        walls[key.mfc] = max(walls.get(key.mfc, 0.0), measured)
        layouts.setdefault(key.mfc, key.layout)
    step_walls = store.step_walls()
    measured_step = (
        statistics.median(step_walls) if step_walls else 0.0
    )
    predicted_step = costmodel.compose_step(levels, walls)
    pred_err = (
        abs(predicted_step - measured_step) / measured_step
        if measured_step > 0
        else 0.0
    )
    return {
        "layouts": layouts,
        "measured_step_s": round(measured_step, 6),
        "predicted_step_s": round(predicted_step, 6),
        "pred_err": round(pred_err, 6),
        "per_mfc": per_mfc,
    }


def enumerate_plans(
    devices: int,
    latest: Dict[ProfileKey, Dict[str, float]],
    windows: List[int],
    chunk_seqs: List[int],
    include_split: bool = False,
) -> List[costmodel.CandidatePlan]:
    """The candidate grid: colocated plans pair every gen layout with
    every train layout over the full device pool; split plans give each
    side half the pool and pay the gen weights over the fabric every
    step."""
    gen_param_bytes = max(
        (
            float(m.get("param_bytes") or 0.0)
            for k, m in latest.items()
            if k.mfc.endswith(":generate")
        ),
        default=0.0,
    )
    plans: List[costmodel.CandidatePlan] = []
    full = costmodel.enumerate_layouts(devices)
    halves = (
        costmodel.enumerate_layouts(devices // 2)
        if include_split and devices >= 2
        else []
    )
    for w in windows:
        for cs in chunk_seqs:
            for g in full:
                for t in full:
                    plans.append(
                        costmodel.CandidatePlan(
                            name=f"co:{g}|{t}:w{w}c{cs}",
                            gen_layout=g,
                            train_layout=t,
                            colocated=True,
                            overlap_window=w,
                            pipeline_chunk_seqs=cs,
                        )
                    )
            for g in halves:
                for t in halves:
                    plans.append(
                        costmodel.CandidatePlan(
                            name=f"split:{g}|{t}:w{w}c{cs}",
                            gen_layout=g,
                            train_layout=t,
                            colocated=False,
                            overlap_window=w,
                            pipeline_chunk_seqs=cs,
                            realloc_bytes=gen_param_bytes,
                        )
                    )
    return plans


def advise(
    store: ProfileStore,
    devices: int,
    mem_budget_gb: float = 0.0,
    windows: Optional[List[int]] = None,
    chunk_seqs: Optional[List[int]] = None,
    include_split: bool = False,
    top: int = 10,
) -> Dict[str, Any]:
    """The full advisor pass as one JSON-ready dict (schema v1)."""
    records = store.records()
    rf = costmodel.calibrate(records)
    levels = store.levels()
    latest = store.latest()
    if not levels:
        # No measured topology: every MFC its own level (serial).
        levels = [[k.mfc] for k in sorted(latest, key=lambda k: k.mfc)]
        seen = set()
        levels = [
            lv for lv in levels
            if lv[0] not in seen and not seen.add(lv[0])
        ]
    batch_seqs = int(
        max(
            (
                float(m.get("seqs_mean") or 0.0)
                for k, m in latest.items()
                if k.mfc.endswith(":train_step")
            ),
            default=0.0,
        )
    )
    plans = enumerate_plans(
        devices,
        latest,
        windows=windows or [1, 2, 4],
        chunk_seqs=chunk_seqs or [0, 2, 4],
        include_split=include_split,
    )
    preds = [
        costmodel.predict_plan(
            plan,
            latest,
            levels,
            rf,
            batch_seqs=batch_seqs,
            mem_budget_bytes=mem_budget_gb * 1e9,
        )
        for plan in plans
    ]
    ranked = costmodel.rank_plans(preds)
    return {
        "version": ADVISOR_JSON_VERSION,
        "store": {
            "n_records": len(records),
            "skipped_newer": store.skipped_newer,
        },
        "roofline": rf.to_dict(),
        "levels": [list(lv) for lv in levels],
        "current": current_report(store, rf, levels),
        "candidates": [p.to_dict() for p in ranked[: max(top, 1)]],
        "n_enumerated": len(plans),
    }


def format_table(report: Dict[str, Any]) -> str:
    cur = report["current"]
    lines = [
        f"profile store: {report['store']['n_records']} records "
        f"({report['store']['skipped_newer']} newer-version skipped)",
        f"current layout(s): "
        + (
            ", ".join(
                f"{m}={l}" for m, l in sorted(cur["layouts"].items())
            )
            or "(none)"
        ),
        f"measured step {cur['measured_step_s']:.4f}s, composed "
        f"prediction {cur['predicted_step_s']:.4f}s "
        f"(err {cur['pred_err']:.1%})",
        "",
        "per-MFC predicted vs measured:",
        f"  {'mfc':<28} {'layout':<10} {'measured':>10} {'predicted':>10}"
        f" {'err':>7} bound",
    ]
    for r in cur["per_mfc"]:
        lines.append(
            f"  {r['mfc']:<28} {r['layout']:<10} "
            f"{r['measured_wall_s']:>9.4f}s {r['predicted_wall_s']:>9.4f}s"
            f" {r['err']:>6.1%} "
            f"{'compute' if r['compute_bound'] else 'other'}"
        )
    lines += [
        "",
        f"top candidate plans ({report['n_enumerated']} enumerated):",
        f"  {'#':>3} {'plan':<28} {'step_s':>9} {'mem_gb':>8} feasible",
    ]
    for i, c in enumerate(report["candidates"], 1):
        lines.append(
            f"  {i:>3} {c['name']:<28} {c['predicted_step_s']:>9.4f} "
            f"{c['predicted_mem_gb']:>8.3f} "
            f"{'yes' if c['feasible'] else 'NO'}"
        )
    return "\n".join(lines)


def main(argv: Optional[List[str]] = None) -> int:
    p = argparse.ArgumentParser(prog="areal_tpu.apps.advisor")
    p.add_argument(
        "path",
        help="profiles.jsonl store, merged trace.json, or trace shard dir",
    )
    p.add_argument(
        "--devices", type=int, default=8,
        help="device budget for candidate layouts",
    )
    p.add_argument(
        "--mem-budget-gb", type=float, default=0.0,
        help="per-device HBM budget; 0 disables the feasibility filter",
    )
    p.add_argument(
        "--windows", default="1,2,4",
        help="overlap_window values to enumerate (comma-separated)",
    )
    p.add_argument(
        "--chunk-seqs", default="0,2,4",
        help="pipeline_chunk_seqs values to enumerate (0 = unchunked)",
    )
    p.add_argument(
        "--split", action="store_true",
        help="also enumerate disaggregated gen/train plans (half the "
        "device pool each + per-step weight realloc cost)",
    )
    p.add_argument("--top", type=int, default=10, help="plans to emit")
    p.add_argument(
        "--harvest-to", default=None,
        help="also append harvested/loaded entries to this store path",
    )
    p.add_argument(
        "--json", action="store_true",
        help="emit the stable v1 JSON report instead of tables",
    )
    args = p.parse_args(argv)
    if args.path.endswith(".jsonl") and not os.path.isdir(args.path):
        # A real store: keep it, so skipped_newer reflects the file.
        store: ProfileStore = ProfileStore(args.path)
        entries = store.load()
    else:
        entries = _load_entries(args.path)
        store = _MemStore(entries)
    if args.harvest_to:
        n = ProfileStore(args.harvest_to).append(entries)
        if not args.json:
            print(f"appended {n} entries -> {args.harvest_to}")
    if not store.records():
        print(
            f"no MFC profile records in {args.path!r} (need a traced "
            "run with profile-stamped spans)",
            file=sys.stderr,
        )
        return 1
    report = advise(
        store,
        devices=args.devices,
        mem_budget_gb=args.mem_budget_gb,
        windows=[int(x) for x in args.windows.split(",") if x],
        chunk_seqs=[int(x) for x in args.chunk_seqs.split(",") if x],
        include_split=args.split,
        top=args.top,
    )
    if args.json:
        print(json.dumps(report, sort_keys=True))
    else:
        print(format_table(report))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
