"""Fleet supervisor entrypoint: SLO-driven autoscaling of gen servers.

Runs :class:`areal_tpu.system.fleet.FleetSupervisor` against a trial's
live metrics plane, spawning/draining LOCAL gen-server processes via
:class:`~areal_tpu.system.fleet.LocalProcessFleet`::

    python -m areal_tpu.apps.fleet \\
        --experiment exp0 --trial t0 \\
        --slo "crit: staleness_p99 <= 4" \\
        --spawn-cmd "python -m areal_tpu.system.gen_server \\
                     --path /ckpt --port {port} \\
                     --experiment {experiment} --trial {trial}" \\
        --min-servers 1 --max-servers 4

A CRIT violation on a capacity signal (staleness_p99 / queue_depth /
backpressure) adds one server; a sustained idle window (goodput ~0,
fleet idle) drains one.  Membership epochs persist through the trial's
``RecoverInfo`` when ``--recover-root`` is given, so a restarted
supervisor resumes its epoch counter instead of re-counting from 0.

``--verifier-spawn-cmd`` adds a second, independently-scaled **verifier
lane** (:class:`~areal_tpu.system.fleet.SupervisorLane` over
``python -m areal_tpu.apps.verifier`` workers): grade-latency /
queue-depth CRITs (``--verifier-slo``, default scale-up signals
``grade_latency_p99`` and ``verifier_queue_depth``) spawn a grading
worker, an idle pool drains one, and a TTL-evicted crash is refilled
back to ``--verifier-min-servers`` without waiting out the cooldown.
"""

import argparse
import shlex
import sys
from typing import List, Optional

from areal_tpu.base import logging, names
from areal_tpu.system.fleet import (
    FleetSupervisor, LocalProcessFleet, SupervisorLane,
)

logger = logging.getLogger("fleet")


def main(argv: Optional[List[str]] = None) -> int:
    p = argparse.ArgumentParser(prog="areal_tpu.apps.fleet")
    p.add_argument("--experiment", required=True)
    p.add_argument("--trial", default="trial")
    p.add_argument("--slo", action="append", default=[],
                   help="SLO rule (metrics_report grammar; the rule "
                        "states the invariant that must HOLD), e.g. "
                        "'crit: staleness_p99 <= 4'; repeatable")
    p.add_argument("--slo-file", default=None,
                   help="file of SLO rules, one per line (# comments)")
    p.add_argument("--spawn-cmd", default="",
                   help="gen-server launch command; {port}/{experiment}/"
                        "{trial} are substituted per spawn")
    p.add_argument("--base-port", type=int, default=8101)
    p.add_argument("--min-servers", type=int, default=1)
    p.add_argument("--max-servers", type=int, default=8)
    p.add_argument("--interval", type=float, default=2.0,
                   help="seconds between scrape/evaluate rounds")
    p.add_argument("--count", type=int, default=None,
                   help="rounds to run (default: forever)")
    p.add_argument("--action-cooldown", type=float, default=30.0,
                   help="minimum seconds between scale actions")
    p.add_argument("--idle-rounds", type=int, default=3,
                   help="consecutive idle scrapes before a drain")
    p.add_argument("--recover-root", default=None,
                   help="trial recover dir: persists membership epochs "
                        "through RecoverInfo.fleet_state")
    p.add_argument("--verifier-spawn-cmd", default="",
                   help="verifier-worker launch command "
                        "({port}/{experiment}/{trial} substituted); "
                        "enables the verifier lane")
    p.add_argument("--verifier-slo", action="append", default=[],
                   help="verifier-lane SLO rule, e.g. "
                        "'crit: grade_latency_p99 <= 5' or "
                        "'crit: verifier_queue_depth <= 64'; repeatable")
    p.add_argument("--verifier-base-port", type=int, default=8201)
    p.add_argument("--verifier-min-servers", type=int, default=1)
    p.add_argument("--verifier-max-servers", type=int, default=4)
    args = p.parse_args(argv)

    from areal_tpu.apps.metrics_report import parse_slo_rule

    rule_texts = list(args.slo)
    if args.slo_file:
        with open(args.slo_file) as f:
            rule_texts += [
                ln.strip() for ln in f
                if ln.strip() and not ln.lstrip().startswith("#")
            ]
    rules = [parse_slo_rule(t) for t in rule_texts]

    procs = None
    spawn = drain = None
    if args.spawn_cmd:
        procs = LocalProcessFleet(
            shlex.split(args.spawn_cmd),
            experiment=args.experiment,
            trial=args.trial,
            base_port=args.base_port,
        )
        spawn, drain = procs.spawn, procs.drain

    lanes = []
    verifier_procs = None
    if args.verifier_spawn_cmd:
        from areal_tpu.system.verifier_pool import list_verifiers

        verifier_procs = LocalProcessFleet(
            shlex.split(args.verifier_spawn_cmd),
            experiment=args.experiment,
            trial=args.trial,
            base_port=args.verifier_base_port,
            name_key=names.verifier_server,
            sid_prefix="v",
        )
        lanes.append(
            SupervisorLane(
                name="verifier",
                list_servers=lambda: list_verifiers(
                    args.experiment, args.trial
                ),
                rules=[parse_slo_rule(t) for t in args.verifier_slo],
                spawn=verifier_procs.spawn,
                drain=verifier_procs.drain,
                min_servers=args.verifier_min_servers,
                max_servers=args.verifier_max_servers,
                action_cooldown_s=args.action_cooldown,
                idle_rounds=args.idle_rounds,
            )
        )

    sup = FleetSupervisor(
        experiment=args.experiment,
        trial=args.trial,
        rules=rules,
        spawn=spawn,
        drain=drain,
        min_servers=args.min_servers,
        max_servers=args.max_servers,
        action_cooldown_s=args.action_cooldown,
        idle_rounds=args.idle_rounds,
        recover_root=args.recover_root,
        lanes=lanes,
    )
    logger.info(
        f"fleet supervisor: {len(rules)} SLO rule(s), "
        f"servers in [{args.min_servers}, {args.max_servers}], "
        f"epoch {sup.membership_epoch}"
    )
    try:
        actions = sup.run(count=args.count, interval=args.interval)
    except KeyboardInterrupt:
        actions = []
    finally:
        if procs is not None:
            procs.shutdown()
        if verifier_procs is not None:
            verifier_procs.shutdown()
    for a in actions:
        logger.info(f"action taken: {a.action} {a.victim} ({a.reason})")
    return 0


if __name__ == "__main__":
    sys.exit(main())
