"""Per-layer profiling: measured forward/backward/decode costs vs the
roofline estimator.

Capability parity: realhf/apps/profile_layers.py + profile_exp (per-layer
op timing used to calibrate the allocation search) — TPU version: times one
transformer block, the full stack, the LM head, and a decode step on the
live chip across sequence lengths, and prints a JSON table next to the
analytic FLOPs/MFU so the search estimator can be sanity-checked against
hardware.

Usage:
    python -m areal_tpu.apps.profile_layers --size 1.5b \
        --seqlens 512,2048 --batch 8
    python -m areal_tpu.apps.profile_layers --model.path /ckpts/qwen2-7b
"""

import argparse
import json
import time


def _timeit(fn, *args, iters=10):
    import jax

    out = fn(*args)
    jax.block_until_ready(out)
    t0 = time.perf_counter()
    for _ in range(iters):
        out = fn(*args)
    jax.block_until_ready(out)
    return (time.perf_counter() - t0) / iters


def profile(cfg, batch: int, seqlens, decode_batch: int = 32):
    import jax
    import jax.numpy as jnp
    import numpy as np

    from areal_tpu.base import monitor
    from areal_tpu.models import transformer as tfm

    params = tfm.init_params(cfg, jax.random.PRNGKey(0))
    rows = []
    for s in seqlens:
        toks = jnp.asarray(
            np.random.default_rng(0).integers(
                0, cfg.vocab_size, size=(batch, s)
            ),
            jnp.int32,
        )
        seg = jnp.ones((batch, s), jnp.int32)

        # arealint: ignore[retrace-hazard] -- profiling sweep: a fresh jit
        # per seqlen is the measurement (compile cost is timed separately).
        fwd = jax.jit(lambda p, t, sg: tfm.hidden_states(p, cfg, t, sg)[0])
        t_fwd = _timeit(fwd, params, toks, seg)

        def loss(p, t, sg):
            x, aux = tfm.hidden_states(p, cfg, t, sg, remat=True)
            out = tfm.per_token_output(p, cfg, x, t, sg)
            return jnp.sum(out) * 1e-6 + aux

        # arealint: ignore[retrace-hazard] -- profiling sweep: per-shape
        # jit is intentional here, same as fwd above.
        bwd = jax.jit(jax.grad(loss))
        t_bwd = _timeit(bwd, params, toks, seg, iters=5)

        n_tok = batch * s
        sum_sq = float(batch * s * s)
        fl_fwd = monitor.flops_forward(cfg, n_tok, sum_sq)
        fl_train = monitor.flops_train(cfg, n_tok, sum_sq)
        rows.append(
            {
                "seqlen": s,
                "batch": batch,
                "fwd_ms": round(t_fwd * 1e3, 3),
                "fwd_bwd_ms": round(t_bwd * 1e3, 3),
                "fwd_mfu": monitor.mfu(fl_fwd, t_fwd, 1),
                "train_mfu": monitor.mfu(fl_train, t_bwd, 1),
                "fwd_tflops": round(fl_fwd / 1e12, 4),
            }
        )

    # Decode step at a mid window.
    s_max = max(seqlens)
    cache = tfm.init_kv_cache(cfg, decode_batch, s_max, dtype=cfg.dtype)
    toks = jnp.ones((decode_batch,), jnp.int32)
    pos = jnp.full((decode_batch,), s_max // 2, jnp.int32)
    vf = jnp.zeros((decode_batch,), jnp.int32)
    step = jax.jit(
        lambda p, t, po, c: tfm.decode_step(
            p, cfg, t, po, c, jnp.int32(s_max // 2), vf
        )
    )
    t_dec = _timeit(step, params, toks, pos, cache)
    decode = {
        "decode_batch": decode_batch,
        "window": s_max,
        "decode_step_ms": round(t_dec * 1e3, 3),
        "decode_tokens_per_sec": round(decode_batch / t_dec, 1),
    }
    return {"layers": cfg.n_layers, "per_seqlen": rows, "decode": decode}


def main(argv=None):
    p = argparse.ArgumentParser(prog="areal_tpu.apps.profile_layers")
    p.add_argument("--model.path", dest="model_path", default=None)
    p.add_argument("--size", default="1.5b", help="qwen2 preset when no path")
    p.add_argument("--batch", type=int, default=8)
    p.add_argument("--decode-batch", type=int, default=32)
    p.add_argument("--seqlens", default="512,2048")
    args = p.parse_args(argv)

    if args.model_path:
        from areal_tpu.models.hf import registry as hf

        hf_cfg = hf.load_hf_config(args.model_path)
        cfg = hf.HF_FAMILIES[hf_cfg["model_type"]].config_from_hf(hf_cfg)
    elif args.size == "tiny":
        from areal_tpu.models.config import tiny_config

        cfg = tiny_config()
    else:
        from areal_tpu.models.config import qwen2_config

        cfg = qwen2_config(args.size, param_dtype="bfloat16")
    seqlens = [int(s) for s in args.seqlens.split(",")]
    print(
        json.dumps(
            profile(cfg, args.batch, seqlens, args.decode_batch), indent=2
        )
    )


if __name__ == "__main__":
    main()
