"""The TPU-native transformer: pure-functional, scan-over-layers, packed rows.

Capability parity: realhf/impl/model/nn/real_llm_api.py (`ReaLModel`) +
real_llm_base.py (blocks, heads) — re-designed for XLA:

- Parameters are a plain pytree with per-layer tensors STACKED on a leading
  axis, so the forward pass is one `lax.scan` over layers: O(1) compile time
  in depth, and the natural substrate for pipeline stages.
- Batches are packed rows [B, S]: each row concatenates sequences, delimited
  by `segment_ids` (0 = pad).  Static shapes; attention is causal-within-
  segment (see areal_tpu/ops/attention.py).
- No device/layout logic here: sharding is applied by the engines via
  `jax.sharding` rules over this pytree (areal_tpu/parallel/sharding.py).
- `is_critic` swaps the LM head for a scalar value head
  (reference: real_llm_base.py:358-453).

Functions:
    init_params(cfg, key)                                  -> params
    forward(params, cfg, tokens, segment_ids[, positions]) -> logits/values
    init_kv_cache(cfg, b, s_max)                           -> cache
    prefill(params, cfg, tokens, segment_ids, cache)
    decode_step(params, cfg, tokens, positions, cache, slot, valid_from)
"""

import dataclasses
import functools
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
from jax.ad_checkpoint import checkpoint_name

from areal_tpu.models.config import ModelConfig
from areal_tpu.ops.attention import (
    decode_attention,
    decode_attention_chunk,
    packed_attention,
    paged_decode_attention,
    paged_decode_attention_chunk,
    ragged_paged_attention,
    repeat_kv,
)
from areal_tpu.ops.norms import apply_rotary, rms_norm, rope_cos_sin

Params = Dict[str, Any]


# --------------------------------------------------------------------------
# Init
# --------------------------------------------------------------------------


def init_params(cfg: ModelConfig, key: jax.Array) -> Params:
    """Random init (truncated-normal fan-in scaling), layer-stacked."""
    dtype = cfg.dtype
    k_embed, k_blocks, k_head = jax.random.split(key, 3)

    def dense(key, shape, fan_in):
        return (
            jax.random.truncated_normal(key, -2, 2, shape, jnp.float32)
            * (fan_in**-0.5)
        ).astype(dtype)

    L, D, F = cfg.n_layers, cfg.hidden_dim, cfg.intermediate_dim
    ks = jax.random.split(k_blocks, 8)
    blocks = {
        "ln1": jnp.ones((L, D), dtype),
        "wq": dense(ks[0], (L, D, cfg.q_dim), D),
        "wk": dense(ks[1], (L, D, cfg.kv_dim), D),
        "wv": dense(ks[2], (L, D, cfg.kv_dim), D),
        "wo": dense(ks[3], (L, cfg.q_dim, D), cfg.q_dim),
        "ln2": jnp.ones((L, D), dtype),
    }
    if cfg.qkv_bias:
        blocks["bq"] = jnp.zeros((L, cfg.q_dim), dtype)
        blocks["bk"] = jnp.zeros((L, cfg.kv_dim), dtype)
        blocks["bv"] = jnp.zeros((L, cfg.kv_dim), dtype)
    if cfg.norm_type == "layernorm":
        blocks["ln1_b"] = jnp.zeros((L, D), dtype)
        blocks["ln2_b"] = jnp.zeros((L, D), dtype)
    if cfg.proj_bias:
        blocks["bo"] = jnp.zeros((L, D), dtype)
        blocks["bproj"] = jnp.zeros((L, D), dtype)
        if not cfg.mlp_gated:
            blocks["bfc"] = jnp.zeros((L, F), dtype)
    if cfg.is_moe:
        E, FM = cfg.n_experts, cfg.moe_intermediate_dim
        km = jax.random.split(ks[4], 4)
        blocks["router"] = dense(km[0], (L, D, E), D)
        blocks["wg"] = dense(km[1], (L, E, D, FM), D)
        blocks["wu"] = dense(km[2], (L, E, D, FM), D)
        blocks["wd"] = dense(km[3], (L, E, FM, D), FM)
    else:
        km = jax.random.split(ks[4], 3)
        blocks["wg"] = dense(km[0], (L, D, F), D)
        if cfg.mlp_gated:
            blocks["wu"] = dense(km[1], (L, D, F), D)
        blocks["wd"] = dense(km[2], (L, F, D), F)

    params: Params = {
        "embed": dense(k_embed, (cfg.vocab_size, D), D),
        "blocks": blocks,
        "final_ln": jnp.ones((D,), dtype),
    }
    if cfg.norm_type == "layernorm":
        params["final_ln_b"] = jnp.zeros((D,), dtype)
    if cfg.pos_emb == "learned":
        params["pos_embed"] = dense(
            jax.random.fold_in(k_embed, 1),
            (cfg.max_position_embeddings, D),
            D,
        )
    if cfg.is_critic:
        params["value_head"] = dense(k_head, (D, 1), D)
    elif not cfg.tied_embeddings:
        params["lm_head"] = dense(k_head, (D, cfg.vocab_size), D)
    return params


# --------------------------------------------------------------------------
# Forward
# --------------------------------------------------------------------------


def _act(x: jax.Array, cfg: ModelConfig) -> jax.Array:
    if cfg.hidden_act == "silu":
        return jax.nn.silu(x)
    if cfg.hidden_act == "gelu":
        return jax.nn.gelu(x, approximate=False)
    if cfg.hidden_act == "gelu_tanh":
        return jax.nn.gelu(x, approximate=True)
    raise ValueError(f"unknown hidden_act {cfg.hidden_act!r}")


def _norm(
    x: jax.Array, w: jax.Array, b: Optional[jax.Array], cfg: ModelConfig
) -> jax.Array:
    if cfg.norm_type == "rms":
        scale = w.astype(jnp.float32) + 1.0 if cfg.rms_norm_offset else w
        return rms_norm(x, scale, cfg.rms_norm_eps)
    # LayerNorm (gpt2): mean-centered, with bias, fp32 accumulation.
    dtype = x.dtype
    xf = x.astype(jnp.float32)
    mean = jnp.mean(xf, axis=-1, keepdims=True)
    var = jnp.mean(jnp.square(xf - mean), axis=-1, keepdims=True)
    out = (xf - mean) * jax.lax.rsqrt(var + cfg.rms_norm_eps)
    out = out * w.astype(jnp.float32)
    if b is not None:
        out = out + b.astype(jnp.float32)
    return out.astype(dtype)


def _embed(
    params: Params, cfg: ModelConfig, tokens: jax.Array, positions: jax.Array
) -> jax.Array:
    # mode="clip", not the jit default "fill": out-of-vocab ids (the pad /
    # eos sentinels sit past the table in some configs) must embed to
    # FINITE garbage.  A NaN here is not locally harmless — pad lanes write
    # their k/v into cache pages, and masked attention still reads them as
    # weight*NaN = NaN, poisoning every later query on the page.
    x = jnp.take(params["embed"], tokens, axis=0, mode="clip")
    if cfg.embed_scale:  # gemma normalizer, computed in fp32
        x = (x.astype(jnp.float32) * (cfg.hidden_dim**0.5)).astype(x.dtype)
    if cfg.pos_emb == "learned":
        x = x + jnp.take(params["pos_embed"], positions, axis=0, mode="clip")
    return x


def positions_from_segments(segment_ids: jax.Array) -> jax.Array:
    """Within-segment positions for packed rows.

    Segments are contiguous runs in each row; position resets to 0 at every
    segment boundary.  [B, S] int32.
    """
    s = segment_ids.shape[-1]
    idx = jnp.arange(s, dtype=jnp.int32)
    prev = jnp.pad(segment_ids[..., :-1], ((0, 0), (1, 0)), constant_values=-1)
    is_start = segment_ids != prev
    start_idx = jnp.where(is_start, idx, 0)
    seg_start = jax.lax.associative_scan(jnp.maximum, start_idx, axis=-1)
    return idx - seg_start


def _mlp_dense(h: jax.Array, blk: Params, cfg: ModelConfig) -> jax.Array:
    if cfg.mlp_gated:
        gate = _act(h @ blk["wg"], cfg)
        out = (gate * (h @ blk["wu"])) @ blk["wd"]
        if cfg.proj_bias:
            out = out + blk["bproj"]
        return out
    # Plain fc -> act -> proj (gpt2).
    hmid = h @ blk["wg"]
    if cfg.proj_bias:
        hmid = hmid + blk["bfc"]
    out = _act(hmid, cfg) @ blk["wd"]
    if cfg.proj_bias:
        out = out + blk["bproj"]
    return out


def _moe_route(x: jax.Array, blk: Params, cfg: ModelConfig):
    """Router: top-k weights/indices + switch-style load-balancing aux."""
    router_logits = (x.astype(jnp.float32)) @ blk["router"].astype(jnp.float32)  # [T, E]
    probs = jax.nn.softmax(router_logits, axis=-1)
    top_w, top_idx = jax.lax.top_k(probs, cfg.n_experts_per_tok)  # [T, k]
    top_w = top_w / jnp.sum(top_w, axis=-1, keepdims=True)
    one_hot = jax.nn.one_hot(top_idx, cfg.n_experts, dtype=probs.dtype)  # [T,k,E]
    # Load-balancing aux loss (switch-style): E * sum_e f_e * P_e.
    load = jnp.mean(one_hot.sum(axis=1), axis=0)  # fraction routed per expert
    importance = jnp.mean(probs, axis=0)
    aux = cfg.n_experts * jnp.sum(load * importance)
    return top_w, top_idx, one_hot, aux


def _mlp_moe_dense(h: jax.Array, blk: Params, cfg: ModelConfig) -> Tuple[jax.Array, jax.Array]:
    """Numerics-oracle MoE: full expert compute + weight masking.

    Every token runs through a dense einsum over ALL experts, then results
    are combined with the (sparse) router weights — E/k times the FLOPs of
    real dispatch, but perfectly static and exactly equal to un-dropped
    top-k routing.  Reference semantics: realhf/impl/model/modules/moe/.
    """
    b, s, d = h.shape
    x = h.reshape(-1, d)  # [T, D]
    top_w, _, one_hot, aux = _moe_route(x, blk, cfg)
    comb = jnp.einsum("tk,tke->te", top_w, one_hot)  # [T, E]
    # All-expert compute: [E, T, F] einsums.
    gate = jax.nn.silu(jnp.einsum("td,edf->etf", x, blk["wg"]))
    up = jnp.einsum("td,edf->etf", x, blk["wu"])
    expert_out = jnp.einsum("etf,efd->etd", gate * up, blk["wd"])  # [E,T,D]
    out = jnp.einsum("te,etd->td", comb.astype(expert_out.dtype), expert_out)
    return out.reshape(b, s, d), aux


def _mlp_moe_topk(h: jax.Array, blk: Params, cfg: ModelConfig) -> Tuple[jax.Array, jax.Array]:
    """Capacity-based top-k dispatch (GShard-style): expert matmuls run on
    [E, C, D] gathered slots, C = ceil(T*k/E * capacity_factor), so FLOPs
    scale with top-k rather than E.  First-choice assignments claim
    capacity before second choices; tokens over capacity are dropped
    (their combine weight is zero), matching the reference's token-choice
    router with capacity (realhf/impl/model/modules/moe/experts.py).  The
    expert axis of the dispatch einsums shards over the mesh (see
    parallel/sharding.py moe rules) — GSPMD inserts the all-to-alls.
    """
    import math

    b, s, d = h.shape
    x = h.reshape(-1, d)  # [T, D]
    T = x.shape[0]
    E, k = cfg.n_experts, cfg.n_experts_per_tok
    top_w, _, one_hot, aux = _moe_route(x, blk, cfg)
    cap = max(int(math.ceil(T * k / E * cfg.moe_capacity_factor)), 1)

    # Queue position of each (choice slot, token) in its expert, choice-
    # slot-major so first choices win capacity.
    sel = one_hot.transpose(1, 0, 2).reshape(k * T, E)  # [k*T, E]
    pos = jnp.cumsum(sel, axis=0) - sel  # position BEFORE this entry
    keep = sel * (pos < cap)
    slot = jax.nn.one_hot(pos.astype(jnp.int32), cap, dtype=x.dtype)  # [kT,E,C]
    disp_k = keep[..., None] * slot  # [k*T, E, C]
    disp = disp_k.reshape(k, T, E, cap)
    dispatch = disp.sum(axis=0)  # [T, E, C] 0/1
    combine = jnp.einsum(
        "tk,ktec->tec", top_w.astype(x.dtype), disp.astype(x.dtype)
    )  # [T, E, C]

    xe = jnp.einsum("tec,td->ecd", dispatch.astype(x.dtype), x)  # [E, C, D]
    gate = jax.nn.silu(jnp.einsum("ecd,edf->ecf", xe, blk["wg"]))
    up = jnp.einsum("ecd,edf->ecf", xe, blk["wu"])
    ye = jnp.einsum("ecf,efd->ecd", gate * up, blk["wd"])  # [E, C, D]
    out = jnp.einsum("tec,ecd->td", combine, ye)
    return out.reshape(b, s, d), aux


def _mlp_moe_grouped(h: jax.Array, blk: Params, cfg: ModelConfig) -> Tuple[jax.Array, jax.Array]:
    """Dropless grouped-GEMM dispatch (the default): tokens sorted by
    expert, expert matmuls ride `jax.lax.ragged_dot` — XLA:TPU's native
    megablox-style ragged kernel, which tiles each expert's contiguous
    row group onto the MXU without materializing per-expert buffers.

    Expert FLOPs are exactly 3·T·k·D·F — proportional to TOKENS, where
    the dense oracle pays E/k× that and capacity dispatch pays
    capacity_factor× plus GShard's one-hot dispatch einsums (T·E·C·D
    each, quadratic in T).  No token is ever dropped, so this matches
    the dense oracle bit-for-bit up to matmul rounding.  The TPU
    equivalent of the reference's grouped GEMM
    (realhf/impl/model/utils/moe.py, tests/cpp_extensions/
    test_grouped_gemm.py:149).

    Under expert-parallel meshes the stacked expert weights are sharded
    over fsdp (parallel/sharding.py moe rules); GSPMD resolves
    ragged_dot by gathering the expert dim — ZeRO-style weight
    gathering, the right trade below ~100B total expert bytes.  True
    token all-to-all EP stays on `moe_dispatch="topk"`.
    """
    b, s, d = h.shape
    x = h.reshape(-1, d)  # [T, D]
    T = x.shape[0]
    k = cfg.n_experts_per_tok
    top_w, top_idx, one_hot, aux = _moe_route(x, blk, cfg)
    flat_e = top_idx.reshape(-1)  # [T*k], token-major
    order = jnp.argsort(flat_e, stable=True)
    group_sizes = jnp.sum(one_hot, axis=(0, 1)).astype(jnp.int32)  # [E]
    tok_of = order // k
    xs = x[tok_of]  # [T*k, D] sorted by expert
    gate = jax.nn.silu(jax.lax.ragged_dot(xs, blk["wg"], group_sizes))
    up = jax.lax.ragged_dot(xs, blk["wu"], group_sizes)
    ys = jax.lax.ragged_dot(gate * up, blk["wd"], group_sizes)  # [T*k, D]
    w_sorted = top_w.reshape(-1)[order].astype(ys.dtype)
    out = jnp.zeros_like(x).at[tok_of].add(ys * w_sorted[:, None])
    return out.reshape(b, s, d), aux


def _mlp_moe(h: jax.Array, blk: Params, cfg: ModelConfig) -> Tuple[jax.Array, jax.Array]:
    if cfg.moe_dispatch == "dense":
        return _mlp_moe_dense(h, blk, cfg)
    if cfg.moe_dispatch == "grouped":
        return _mlp_moe_grouped(h, blk, cfg)
    return _mlp_moe_topk(h, blk, cfg)


def _block_forward(
    x: jax.Array,
    blk: Params,
    cfg: ModelConfig,
    segment_ids: jax.Array,
    cos: jax.Array,
    sin: jax.Array,
    use_flash: "bool | None" = None,
    cp_mesh=None,
    cp_manual: "Optional[Tuple[str, int]]" = None,
    cp_zigzag: bool = False,
) -> Tuple[jax.Array, jax.Array]:
    b, s, d = x.shape
    h = _norm(x, blk["ln1"], blk.get("ln1_b"), cfg)
    q = h @ blk["wq"]
    k = h @ blk["wk"]
    v = h @ blk["wv"]
    if cfg.qkv_bias:
        q, k, v = q + blk["bq"], k + blk["bk"], v + blk["bv"]
    q = q.reshape(b, s, cfg.n_q_heads, cfg.head_dim)
    k = k.reshape(b, s, cfg.n_kv_heads, cfg.head_dim)
    v = v.reshape(b, s, cfg.n_kv_heads, cfg.head_dim)
    if cfg.pos_emb == "rope":
        q, k = apply_rotary(q, k, cos, sin)
    if cp_manual is not None:
        # Already inside a manual region that includes the seq axis (the
        # CP+PP pipeline): run the ring body DIRECTLY on this shard's
        # chunk — nesting another shard_map over auto axes is not
        # expressible once operands vary over the outer manual axis.
        from areal_tpu.ops.ring_attention import _ring_shard

        axis_name, axis_size, *my_idx = cp_manual
        attn = _ring_shard(
            q, k, v, segment_ids, axis_name, axis_size, causal=True,
            my_index=my_idx[0] if my_idx else None,
        )
    elif cp_mesh is not None:
        if cp_zigzag:
            # Inputs already zigzag-permuted by _backbone (ONCE per
            # forward, not per layer).
            from areal_tpu.ops.ring_attention import (
                zigzag_ring_packed_attention_prepermuted,
            )

            attn = zigzag_ring_packed_attention_prepermuted(
                q, k, v, segment_ids, cp_mesh, causal=True
            )
        else:
            from areal_tpu.ops.ring_attention import ring_packed_attention

            attn = ring_packed_attention(
                q, k, v, segment_ids, cp_mesh, causal=True
            )
    else:
        attn = packed_attention(
            q, k, v, segment_ids, causal=True, use_flash=use_flash
        )
    attn_out = attn.reshape(b, s, cfg.q_dim) @ blk["wo"]
    if cfg.proj_bias:
        attn_out = attn_out + blk["bo"]
    # Named checkpoints for remat="dots_small" (see _backbone): the
    # attention output and the MLP down-projection output are the SMALL
    # per-token dots ([*, D]) whose saving lets backward skip only the
    # fat gate/up recompute candidates' DOWNSTREAM — memory ~2x "full"
    # remat instead of the ~7x of "dots".
    attn_out = checkpoint_name(attn_out, "attn_out")
    x = x + attn_out
    h2 = _norm(x, blk["ln2"], blk.get("ln2_b"), cfg)
    if cfg.is_moe:
        mlp_out, aux = _mlp_moe(h2, blk, cfg)
    else:
        mlp_out, aux = _mlp_dense(h2, blk, cfg), jnp.zeros((), jnp.float32)
    mlp_out = checkpoint_name(mlp_out, "mlp_out")
    return x + mlp_out, aux


_ZIGZAG_SNAPSHOT: "Optional[bool]" = None


def _zigzag_enabled() -> bool:
    """AREAL_RING_ZIGZAG, read ONCE: the value is baked into traced
    programs, and jit caches do not key on it — honoring later toggles
    only sometimes (cache misses) would make layout comparisons silently
    measure the same variant twice.  Set the env var before first use."""
    global _ZIGZAG_SNAPSHOT
    if _ZIGZAG_SNAPSHOT is None:
        import os

        _ZIGZAG_SNAPSHOT = os.environ.get("AREAL_RING_ZIGZAG") == "1"
    return _ZIGZAG_SNAPSHOT


def _backbone(
    params: Params,
    cfg: ModelConfig,
    tokens: jax.Array,
    segment_ids: jax.Array,
    positions: jax.Array,
    remat: bool,
    use_flash: "bool | None" = None,
    cp_mesh=None,
    pp_mesh=None,
    pp_microbatches: int = 4,
) -> Tuple[jax.Array, jax.Array]:
    x = _embed(params, cfg, tokens, positions)
    cos, sin = rope_cos_sin(positions, cfg.head_dim, cfg.rope_theta)

    if pp_mesh is not None:
        from areal_tpu.parallel.pipeline import pipelined_blocks

        if cp_mesh is not None and _zigzag_enabled():
            from areal_tpu.base import logging as _logging

            # The CP+PP schedule keeps the contiguous layout: zigzag
            # there needs the permutation threaded through the tick
            # schedule's position bookkeeping — not built yet.  Say so
            # instead of silently ignoring the knob.
            _logging.getLogger("transformer").warning(
                "AREAL_RING_ZIGZAG has no effect under combined CP+PP; "
                "running the contiguous ring"
            )
        # The pipeline checkpoints each stage tick internally.  CP + PP
        # compose by manualizing BOTH axes in the pipeline's shard_map
        # (see pipelined_blocks: nesting a fresh seq shard_map per stage
        # is rejected by jax once operands vary over the manual pipe
        # axis, and silently mistrains under check_vma=False).
        x, aux = pipelined_blocks(
            params["blocks"], cfg, x, segment_ids, cos, sin,
            pp_mesh, pp_microbatches, use_flash,
            cp=cp_mesh is not None,
        )
        x = _norm(x, params["final_ln"], params.get("final_ln_b"), cfg)
        return x, aux

    # Zigzag ring layout: permute the token order ONCE for the whole
    # layer stack (every other op is per-token; attention sees original
    # positions via cos/sin + segment ids traveling with the tokens) and
    # invert after the final norm.
    from areal_tpu.base.topology import SEQ_AXIS as _SEQ

    zz_inv = None
    if cp_mesh is not None and _zigzag_enabled():
        if x.shape[1] % (2 * cp_mesh.shape[_SEQ]) == 0:
            from areal_tpu.ops.ring_attention import zigzag_indices

            idx, zz_inv = zigzag_indices(x.shape[1], cp_mesh.shape[_SEQ])
            x = jnp.take(x, idx, axis=1)
            segment_ids = jnp.take(segment_ids, idx, axis=1)
            cos = jnp.take(cos, idx, axis=1)
            sin = jnp.take(sin, idx, axis=1)
        else:
            from areal_tpu.base import logging as _logging

            # Never let a benchmark believe it measured zigzag when the
            # shape quietly fell back to the contiguous ring.
            _logging.getLogger("transformer").warning(
                f"AREAL_RING_ZIGZAG ignored: row length {x.shape[1]} not "
                f"divisible by 2*seq={2 * cp_mesh.shape[_SEQ]}"
            )

    def body(carry, blk):
        y, aux = _block_forward(
            carry, blk, cfg, segment_ids, cos, sin, use_flash, cp_mesh,
            cp_zigzag=zz_inv is not None,
        )
        return y, aux

    # Remat policy per scanned layer (HBM vs recompute-FLOPs tradeoff):
    #   "full"/True — save nothing, recompute the whole layer in backward
    #     (minimum activation memory; ~1/3 extra forward FLOPs);
    #   "dots" — save matmul outputs, recompute elementwise/norms only
    #     (more memory, near-zero recompute — the right default when the
    #     activations fit);
    #   "dots_small" — save only the per-layer residual-branch outputs
    #     (attn_out, mlp_out): ~1/8 the memory of "dots", recomputes
    #     most of the layer — for models where "dots" overflows HBM;
    #   "none"/False — plain autodiff residuals.
    if remat is True or remat == "full":
        body = jax.checkpoint(
            body, policy=jax.checkpoint_policies.nothing_saveable
        )
    elif remat == "dots":
        body = jax.checkpoint(
            body,
            policy=jax.checkpoint_policies.dots_with_no_batch_dims_saveable,
        )
    elif remat == "dots_small":
        # Middle ground when "dots" (~46 KB/token/layer of saved matmul
        # outputs at 1.5B) overflows HBM but "full" recompute caps MFU:
        # save only the two [*, D] residual-branch outputs per layer
        # (~6 KB/token/layer) — backward recomputes qkv/attention and
        # the fat gate/up matmuls, but the residual stream itself is
        # never recomputed.
        body = jax.checkpoint(
            body,
            policy=jax.checkpoint_policies.save_only_these_names(
                "attn_out", "mlp_out"
            ),
        )
    elif remat not in (False, None, "none"):
        raise ValueError(f"unknown remat policy {remat!r}")
    x, auxes = jax.lax.scan(body, x, params["blocks"])
    x = _norm(x, params["final_ln"], params.get("final_ln_b"), cfg)
    if zz_inv is not None:
        x = jnp.take(x, zz_inv, axis=1)
    return x, jnp.sum(auxes)


def _head(params: Params, cfg: ModelConfig, x: jax.Array) -> jax.Array:
    if cfg.is_critic:
        v = jnp.einsum(
            "bsd,dk->bsk", x, params["value_head"],
            preferred_element_type=jnp.float32,
        )
        return v[..., 0]  # [B, S] fp32 values
    head = params["embed"].T if cfg.tied_embeddings else params["lm_head"]
    return jnp.einsum(
        "bsd,dv->bsv", x, head, preferred_element_type=jnp.float32
    )  # [B, S, V] fp32 logits


def forward(
    params: Params,
    cfg: ModelConfig,
    tokens: jax.Array,  # [B, S] int32
    segment_ids: jax.Array,  # [B, S] int32, 0 = pad
    positions: Optional[jax.Array] = None,
    remat: bool = False,
    use_flash: "bool | None" = None,
    cp_mesh=None,
    pp_mesh=None,
    pp_microbatches: int = 4,
) -> jax.Array:
    """Full forward over packed rows -> fp32 logits [B,S,V] (or values [B,S]
    for critics).  Also returns MoE aux loss via `forward_with_aux`.

    `cp_mesh`: pass the engine's Mesh to route attention through ring
    context parallelism over its `seq` axis (areal_tpu/ops/ring_attention).
    `pp_mesh`: pass the Mesh to microbatch-pipeline the block stack over its
    `pipe` axis (areal_tpu/parallel/pipeline).
    """
    out, _ = forward_with_aux(
        params, cfg, tokens, segment_ids, positions, remat, use_flash,
        cp_mesh, pp_mesh, pp_microbatches,
    )
    return out


def hidden_states(
    params: Params,
    cfg: ModelConfig,
    tokens: jax.Array,
    segment_ids: jax.Array,
    positions: Optional[jax.Array] = None,
    remat: bool = False,
    use_flash: "bool | None" = None,
    cp_mesh=None,
    pp_mesh=None,
    pp_microbatches: int = 4,
) -> Tuple[jax.Array, jax.Array]:
    """Backbone only: final-layernormed hidden states [B, S, D] (+ MoE aux
    loss), WITHOUT the LM head.  Lets engines fuse the head into a chunked
    loss (ops/functional.fused_next_token_logprobs) instead of materializing
    [B, S, V] logits."""
    if positions is None:
        positions = positions_from_segments(segment_ids)
    return _backbone(
        params, cfg, tokens, segment_ids, positions, remat, use_flash,
        cp_mesh, pp_mesh, pp_microbatches,
    )


def head_weights(params: Params, cfg: ModelConfig) -> jax.Array:
    """[D, V] LM-head matrix (transposed embedding when tied)."""
    return params["embed"].T if cfg.tied_embeddings else params["lm_head"]


def per_token_output(
    params: Params,
    cfg: ModelConfig,
    x: jax.Array,  # [B, S, D] from hidden_states()
    tokens: jax.Array,
    segment_ids: jax.Array,
    chunk_size: int = 512,
) -> jax.Array:
    """The engine-facing per-token model output [B, S] fp32: critic values
    (via the value head) or fused chunked next-token logprobs for LMs —
    never [B, S, V] logits."""
    if cfg.is_critic:
        return _head(params, cfg, x)
    from areal_tpu.ops.functional import fused_next_token_logprobs

    return fused_next_token_logprobs(
        x, head_weights(params, cfg), tokens, segment_ids, chunk_size
    )


def forward_with_aux(
    params: Params,
    cfg: ModelConfig,
    tokens: jax.Array,
    segment_ids: jax.Array,
    positions: Optional[jax.Array] = None,
    remat: bool = False,
    use_flash: "bool | None" = None,
    cp_mesh=None,
    pp_mesh=None,
    pp_microbatches: int = 4,
) -> Tuple[jax.Array, jax.Array]:
    if positions is None:
        positions = positions_from_segments(segment_ids)
    x, aux = _backbone(
        params, cfg, tokens, segment_ids, positions, remat, use_flash,
        cp_mesh, pp_mesh, pp_microbatches,
    )
    return _head(params, cfg, x), aux


# --------------------------------------------------------------------------
# KV-cache generation path
# --------------------------------------------------------------------------


@dataclasses.dataclass
class KVCache:
    """Dense per-layer KV cache: k/v [L, B, S_max, n_kv, head_dim].

    int8 mode (k/v int8 + per-(layer,row,slot,head) bf16 scales in
    k_scale/v_scale): HALVES the HBM bytes per cached token.  At long
    context the decode batch × window product is capacity-bound — a 1.5B
    model's bf16 cache at batch 32 × 16k window is ~15 GB and does not
    fit a 16 GB chip at all; int8 does.  Scales add 1/head_dim overhead.
    (Bandwidth parity, not win: without a fused dequant-attention kernel
    the read path materializes a bf16 layer view — the saving is
    capacity and the cache WRITE stream.)  Reference role: KV-cache
    quantization knobs in serving engines (sglang).
    """

    k: jax.Array
    v: jax.Array
    k_scale: "jax.Array | None" = None  # [L, B, S_max, n_kv] bf16
    v_scale: "jax.Array | None" = None

    @property
    def s_max(self) -> int:
        return self.k.shape[2]

    @property
    def quantized(self) -> bool:
        return self.k_scale is not None


jax.tree_util.register_dataclass(
    KVCache, data_fields=["k", "v", "k_scale", "v_scale"], meta_fields=[]
)


# Canonical implementations live in ops/quant.py (shared with the
# attention paths); re-exported here for the cache-facing API.
from areal_tpu.ops.quant import kv_dequant, kv_quant  # noqa: E402,F401


def _cache_update_read(
    kc, vc, ksc, vsc, k, v, li, idx, quant: bool, read_dtype,
    dequant: bool = True,
):
    """Shared cache write + layer read for the decode steps: scatter the
    new K/V entries at `(li, *idx)` (quantizing when the cache is int8)
    and return the layer's K/V views.  One implementation for the plain
    and speculative paths so a quantization change can never silently
    diverge their distributions.

    dequant=True materializes bf16/f32 layer views (consumers that only
    take dense operands); dequant=False returns the RAW views plus the
    layer's scales (or None) — for `decode_attention`, which dequantizes
    itself (in-kernel under AREAL_DECODE_KERNEL=1, saving the extra
    bf16 window materialization where bandwidth is the bottleneck).

    Out-of-range indices are DROPPED (the paged path writes through a
    page table whose unmapped entries are the sentinel `n_pages`; the
    dense paths always index in bounds, where `mode="drop"` is a
    no-op)."""
    if quant:
        kq, ks = kv_quant(k)
        vq, vs = kv_quant(v)
        kc = kc.at[(li, *idx)].set(kq, mode="drop")
        vc = vc.at[(li, *idx)].set(vq, mode="drop")
        ksc = ksc.at[(li, *idx)].set(ks, mode="drop")
        vsc = vsc.at[(li, *idx)].set(vs, mode="drop")
        ks_l = jax.lax.dynamic_index_in_dim(ksc, li, axis=0, keepdims=False)
        vs_l = jax.lax.dynamic_index_in_dim(vsc, li, axis=0, keepdims=False)
        k_raw = jax.lax.dynamic_index_in_dim(kc, li, axis=0, keepdims=False)
        v_raw = jax.lax.dynamic_index_in_dim(vc, li, axis=0, keepdims=False)
        if dequant:
            k_layer = kv_dequant(k_raw, ks_l, read_dtype)
            v_layer = kv_dequant(v_raw, vs_l, read_dtype)
            return kc, vc, ksc, vsc, k_layer, v_layer, None, None
        return kc, vc, ksc, vsc, k_raw, v_raw, ks_l, vs_l
    kc = kc.at[(li, *idx)].set(k.astype(kc.dtype), mode="drop")
    vc = vc.at[(li, *idx)].set(v.astype(vc.dtype), mode="drop")
    k_layer = jax.lax.dynamic_index_in_dim(kc, li, axis=0, keepdims=False)
    v_layer = jax.lax.dynamic_index_in_dim(vc, li, axis=0, keepdims=False)
    return kc, vc, ksc, vsc, k_layer, v_layer, None, None


def init_kv_cache(
    cfg: ModelConfig, batch: int, s_max: int, dtype=None
) -> KVCache:
    shape = (cfg.n_layers, batch, s_max, cfg.n_kv_heads, cfg.head_dim)
    dtype = dtype or cfg.dtype
    if dtype in (jnp.int8, "int8"):
        return KVCache(
            k=jnp.zeros(shape, jnp.int8),
            v=jnp.zeros(shape, jnp.int8),
            k_scale=jnp.zeros(shape[:-1], jnp.bfloat16),
            v_scale=jnp.zeros(shape[:-1], jnp.bfloat16),
        )
    return KVCache(k=jnp.zeros(shape, dtype), v=jnp.zeros(shape, dtype))


def _block_kv(
    h: jax.Array, blk: Params, cfg: ModelConfig, cos: jax.Array, sin: jax.Array
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    b, s, _ = h.shape
    q = h @ blk["wq"]
    k = h @ blk["wk"]
    v = h @ blk["wv"]
    if cfg.qkv_bias:
        q, k, v = q + blk["bq"], k + blk["bk"], v + blk["bv"]
    q = q.reshape(b, s, cfg.n_q_heads, cfg.head_dim)
    k = k.reshape(b, s, cfg.n_kv_heads, cfg.head_dim)
    v = v.reshape(b, s, cfg.n_kv_heads, cfg.head_dim)
    if cfg.pos_emb == "rope":
        q, k = apply_rotary(q, k, cos, sin)
    return q, k, v


def prefill(
    params: Params,
    cfg: ModelConfig,
    tokens: jax.Array,  # [B, S] one sequence per row (left-aligned)
    segment_ids: jax.Array,  # [B, S] 1 where valid, 0 pad (single segment/row)
    cache: KVCache,
    use_flash: "bool | None" = None,
    quantize_kv: bool = False,
) -> Tuple[jax.Array, KVCache]:
    """Run the prompt through the model, filling cache[:, :, :S] and
    returning fp32 logits [B, V] at each row's LAST VALID position (the
    distribution over the first generated token).  Computing the head only
    there keeps prefill memory at [B, V] instead of [B, S, V] — at a 152k
    vocab that is the difference between 40 MB and 10 GB.

    quantize_kv=True (requires an int8 `cache` with scales) quantizes each
    layer's fresh K/V ONCE and attends over the DEQUANTIZED values —
    "quantize once, attend dequantized".  That makes prefill numerically
    identical to feeding the same tokens through the chunked decode path
    (which always reads its just-written quantized pool): every attention
    read anywhere sees dequant(quant(fresh)), so int8 generation is
    chunk-boundary-invariant instead of depending on how much of the
    prompt was prefilled in one shot."""
    positions = positions_from_segments(segment_ids)
    x = _embed(params, cfg, tokens, positions)
    cos, sin = rope_cos_sin(positions, cfg.head_dim, cfg.rope_theta)

    def body(carry, layer_in):
        blk = layer_in
        h = _norm(carry, blk["ln1"], blk.get("ln1_b"), cfg)
        q, k, v = _block_kv(h, blk, cfg, cos, sin)
        if quantize_kv:
            kq, ksc = kv_quant(k)
            vq, vsc = kv_quant(v)
            k_at = kv_dequant(kq, ksc, k.dtype)
            v_at = kv_dequant(vq, vsc, v.dtype)
            out = (kq, ksc, vq, vsc)
        else:
            k_at, v_at = k, v
            out = (k, v)
        attn = packed_attention(
            q, k_at, v_at, segment_ids, causal=True, use_flash=use_flash
        )
        y = attn.reshape(*carry.shape[:2], cfg.q_dim) @ blk["wo"]
        if cfg.proj_bias:
            y = y + blk["bo"]
        y = carry + y
        h2 = _norm(y, blk["ln2"], blk.get("ln2_b"), cfg)
        y = y + (_mlp_moe(h2, blk, cfg)[0] if cfg.is_moe else _mlp_dense(h2, blk, cfg))
        return y, out

    if quantize_kv:
        x, (kq, ksc, vq, vsc) = jax.lax.scan(body, x, params["blocks"])
        # Emit int8 + scales DIRECTLY: re-quantizing a dequantized value
        # is not idempotent (round(126*s/127 / s') flips codes), so the
        # codes produced here are the ones every later read must see.
        new_cache = KVCache(
            k=jax.lax.dynamic_update_slice(
                cache.k, kq.astype(cache.k.dtype), (0, 0, 0, 0, 0)
            ),
            v=jax.lax.dynamic_update_slice(
                cache.v, vq.astype(cache.v.dtype), (0, 0, 0, 0, 0)
            ),
            k_scale=jax.lax.dynamic_update_slice(
                cache.k_scale, ksc.astype(cache.k_scale.dtype), (0, 0, 0, 0)
            ),
            v_scale=jax.lax.dynamic_update_slice(
                cache.v_scale, vsc.astype(cache.v_scale.dtype), (0, 0, 0, 0)
            ),
        )
    else:
        x, (ks, vs) = jax.lax.scan(body, x, params["blocks"])
        new_cache = KVCache(
            k=jax.lax.dynamic_update_slice(
                cache.k, ks.astype(cache.k.dtype), (0, 0, 0, 0, 0)
            ),
            v=jax.lax.dynamic_update_slice(
                cache.v, vs.astype(cache.v.dtype), (0, 0, 0, 0, 0)
            ),
        )
    x = _norm(x, params["final_ln"], params.get("final_ln_b"), cfg)
    # Gather each row's last valid hidden state before the (huge) head matmul.
    # (index of the last nonzero segment: works for left- and right-aligned
    # prompt layouts alike)
    idx = jnp.arange(segment_ids.shape[-1])
    last = jnp.max(jnp.where(segment_ids > 0, idx, 0), axis=-1)  # [B]
    x_last = jnp.take_along_axis(x, last[:, None, None], axis=1)  # [B,1,D]
    return _head(params, cfg, x_last)[:, 0], new_cache


def decode_step(
    params: Params,
    cfg: ModelConfig,
    tokens: jax.Array,  # [B] int32 — current token per row
    positions: jax.Array,  # [B] int32 — its RoPE position per row
    cache: KVCache,
    slot: jax.Array,  # scalar int32 — cache slot written for ALL rows
    valid_from: jax.Array,  # [B] int32 — first valid cache slot per row
) -> Tuple[jax.Array, KVCache]:
    """One decode step: write the new token's k/v at cache slot `slot`
    (shared by every row — the right-aligned prompt layout makes the write a
    single `dynamic_update_slice`, not a per-row scatter), attend over the
    live window `[valid_from, slot]`, return fp32 logits [B, V] and the
    updated cache.

    The cache rides the layer scan as CARRY (updated in place by XLA), so
    per-token HBM traffic is one (B, n_kv, d) write + one window read per
    layer instead of a full-cache rewrite (the fix for the one-hot scatter
    this replaces).  Reference semantics: the fused decode step replayed via
    CUDA graphs, realhf/impl/model/nn/real_llm_generate.py:336-368.
    """
    b = tokens.shape[0]
    x = _embed(params, cfg, tokens, positions)[:, None, :]  # [B,1,D]
    cos, sin = rope_cos_sin(positions[:, None], cfg.head_dim, cfg.rope_theta)
    slot = jnp.asarray(slot, jnp.int32)

    def body(carry, blk):
        y, kc, vc, li = carry
        h = _norm(y, blk["ln1"], blk.get("ln1_b"), cfg)
        q, k, v = _block_kv(h, blk, cfg, cos, sin)  # q/k/v [B,1,h,d]
        # k/v [B,1,h,d] -> [1,B,1,h,d] written at (layer, :, slot).
        kc = jax.lax.dynamic_update_slice(
            kc, k.astype(kc.dtype)[None], (li, 0, slot, 0, 0)
        )
        vc = jax.lax.dynamic_update_slice(
            vc, v.astype(vc.dtype)[None], (li, 0, slot, 0, 0)
        )
        k_layer = jax.lax.dynamic_index_in_dim(kc, li, axis=0, keepdims=False)
        v_layer = jax.lax.dynamic_index_in_dim(vc, li, axis=0, keepdims=False)
        attn = decode_attention(q, k_layer, v_layer, valid_from, slot + 1)
        ao = attn.reshape(b, 1, cfg.q_dim) @ blk["wo"]
        if cfg.proj_bias:
            ao = ao + blk["bo"]
        y = y + ao
        h2 = _norm(y, blk["ln2"], blk.get("ln2_b"), cfg)
        y = y + (_mlp_moe(h2, blk, cfg)[0] if cfg.is_moe else _mlp_dense(h2, blk, cfg))
        return (y, kc, vc, li + 1), None

    (x, kc, vc, _), _ = jax.lax.scan(
        body, (x, cache.k, cache.v, jnp.int32(0)), params["blocks"]
    )
    x = _norm(x, params["final_ln"], params.get("final_ln_b"), cfg)
    logits = _head(params, cfg, x)[:, 0]  # [B, V]
    return logits, KVCache(k=kc, v=vc)


def decode_step_inflight(
    params: Params,
    cfg: ModelConfig,
    tokens: jax.Array,  # [B] int32
    positions: jax.Array,  # [B] int32 RoPE positions
    cache: KVCache,
    slots: jax.Array,  # [B] int32 — per-row cache write slot
    valid_to: jax.Array,  # [B] int32 — one past the last valid slot (incl. new)
    unroll: bool = False,
) -> Tuple[jax.Array, KVCache]:
    """Decode step with PER-ROW write slots (left-aligned rows), for the
    continuous-batching generator where rows start/stop independently and
    therefore sit at different cache depths.  The per-row write is a vmapped
    `dynamic_update_slice` (a small scatter — [B, n_kv, d] per layer), not a
    full-cache rewrite.  Reference: InflightBatchingGenerator's per-slot
    cache bookkeeping (realhf/impl/model/nn/real_llm_generate.py:670).

    unroll=True trades compile time for HBM traffic: the scan's dynamic
    per-layer cache read (`dynamic_index_in_dim` with a traced index)
    cannot fuse into the attention dot on TPU, so every layer's K and V
    windows are materialized as full HLO temps EVERY step — at 1.5B/b=32
    that extra write+read is comparable to streaming the weights and is
    the measured gap between decode and its roofline.  A python-level
    layer loop with STATIC indices lets XLA read the cache windows in
    place (leading-axis static slices alias) and update them in place."""
    b = tokens.shape[0]
    x = _embed(params, cfg, tokens, positions)[:, None, :]
    cos, sin = rope_cos_sin(positions[:, None], cfg.head_dim, cfg.rope_theta)
    zero_from = jnp.zeros((b,), jnp.int32)

    rows = jnp.arange(b)
    quant = cache.quantized  # trace-time static

    def body(carry, blk, li=None):
        y, kc, vc, ksc, vsc, dyn_li = carry
        li_ = dyn_li if li is None else li
        h = _norm(y, blk["ln1"], blk.get("ln1_b"), cfg)
        q, k, v = _block_kv(h, blk, cfg, cos, sin)
        # Direct scatter of the B new entries at (layer, row, slots[row]) —
        # in place on the scan carry.  The earlier formulation materialized
        # and wrote back a WHOLE [B, S, h, d] layer per token (~GBs/token
        # of pure HBM traffic at 1.5B scale).
        kc, vc, ksc, vsc, k_layer, v_layer, ks_l, vs_l = (
            _cache_update_read(
                kc, vc, ksc, vsc, k[:, 0], v[:, 0], li_, (rows, slots),
                quant, q.dtype, dequant=False,
            )
        )
        attn = decode_attention(
            q, k_layer, v_layer, zero_from, valid_to,
            k_scale=ks_l, v_scale=vs_l,
        )
        ao = attn.reshape(b, 1, cfg.q_dim) @ blk["wo"]
        if cfg.proj_bias:
            ao = ao + blk["bo"]
        y = y + ao
        h2 = _norm(y, blk["ln2"], blk.get("ln2_b"), cfg)
        y = y + (_mlp_moe(h2, blk, cfg)[0] if cfg.is_moe else _mlp_dense(h2, blk, cfg))
        return (y, kc, vc, ksc, vsc, dyn_li + 1), None

    # Scale carries: zero-size placeholders when unquantized keep ONE
    # carry structure for both modes.
    ksc0 = cache.k_scale if quant else jnp.zeros((0,), jnp.bfloat16)
    vsc0 = cache.v_scale if quant else jnp.zeros((0,), jnp.bfloat16)
    if unroll:
        carry = (x, cache.k, cache.v, ksc0, vsc0, jnp.int32(0))
        for li in range(cfg.n_layers):
            blk = jax.tree.map(lambda a: a[li], params["blocks"])
            carry, _ = body(carry, blk, li=li)
        x, kc, vc, ksc, vsc, _ = carry
    else:
        (x, kc, vc, ksc, vsc, _), _ = jax.lax.scan(
            body,
            (x, cache.k, cache.v, ksc0, vsc0, jnp.int32(0)),
            params["blocks"],
        )
    x = _norm(x, params["final_ln"], params.get("final_ln_b"), cfg)
    logits = _head(params, cfg, x)[:, 0]
    return logits, KVCache(
        k=kc, v=vc,
        k_scale=ksc if quant else None,
        v_scale=vsc if quant else None,
    )


def decode_step_spec(
    params: Params,
    cfg: ModelConfig,
    tokens: jax.Array,  # [B, Q] int32 — pending token + Q-1 drafts per row
    positions: jax.Array,  # [B, Q] int32 — RoPE positions
    cache: KVCache,
    slots0: jax.Array,  # [B] int32 — write slot of tokens[:, 0]
) -> Tuple[jax.Array, KVCache]:
    """Speculative decode step: consume Q consecutive tokens per row in ONE
    forward, writing their k/v at slots0..slots0+Q-1 and returning fp32
    logits [B, Q, V] (logits[:, j] = next-token distribution after
    tokens[:, :j+1]).  The Q-1 drafted inputs amortize a full weight stream
    over up to Q accepted tokens — the decode-bandwidth win speculative
    decoding exists for.  Rejected drafts leave stale cache entries past
    the accepted prefix; they are overwritten when those positions are
    consumed for real (left-aligned per-row layout, as
    `decode_step_inflight`)."""
    b, q_len = tokens.shape
    x = _embed(params, cfg, tokens.reshape(-1), positions.reshape(-1))
    x = x.reshape(b, q_len, cfg.hidden_dim)
    cos, sin = rope_cos_sin(positions, cfg.head_dim, cfg.rope_theta)
    rows = jnp.arange(b)
    col_idx = slots0[:, None] + jnp.arange(q_len)[None, :]  # [B, Q]
    quant = cache.quantized  # int8 is SOUND here: drafts and exact
    # verification both score against the quantized-cache model, so the
    # emitted distribution equals plain decoding with the same cache.

    def body(carry, blk):
        y, kc, vc, ksc, vsc, li = carry
        h = _norm(y, blk["ln1"], blk.get("ln1_b"), cfg)
        q, k, v = _block_kv(h, blk, cfg, cos, sin)  # [B, Q, h, d]
        kc, vc, ksc, vsc, k_layer, v_layer, ks_l, vs_l = (
            _cache_update_read(
                kc, vc, ksc, vsc, k, v, li, (rows[:, None], col_idx),
                quant, q.dtype, dequant=False,
            )
        )
        attn = decode_attention_chunk(
            q, k_layer, v_layer,
            jnp.zeros((b,), jnp.int32), slots0 + 1,
            k_scale=ks_l, v_scale=vs_l,
        )
        ao = attn.reshape(b, q_len, cfg.q_dim) @ blk["wo"]
        if cfg.proj_bias:
            ao = ao + blk["bo"]
        y = y + ao
        h2 = _norm(y, blk["ln2"], blk.get("ln2_b"), cfg)
        y = y + (
            _mlp_moe(h2, blk, cfg)[0] if cfg.is_moe else _mlp_dense(h2, blk, cfg)
        )
        return (y, kc, vc, ksc, vsc, li + 1), None

    ksc0 = cache.k_scale if quant else jnp.zeros((0,), jnp.bfloat16)
    vsc0 = cache.v_scale if quant else jnp.zeros((0,), jnp.bfloat16)
    (x, kc, vc, ksc, vsc, _), _ = jax.lax.scan(
        body,
        (x, cache.k, cache.v, ksc0, vsc0, jnp.int32(0)),
        params["blocks"],
    )
    x = _norm(x, params["final_ln"], params.get("final_ln_b"), cfg)
    logits = _head(params, cfg, x)  # [B, Q, V]
    return logits, KVCache(
        k=kc, v=vc,
        k_scale=ksc if quant else None,
        v_scale=vsc if quant else None,
    )


def prefill_into_slots(
    params: Params,
    cfg: ModelConfig,
    tokens: jax.Array,  # [M, SP] left-aligned prompts (padding right)
    prompt_lens: jax.Array,  # [M] int32
    cache: KVCache,  # [L, n_slots, s_max, h, d]
    slot_rows: jax.Array,  # [M] int32 — target cache row per prompt
    use_flash: "bool | None" = None,
) -> Tuple[jax.Array, KVCache]:
    """Prefill M requests into their cache rows in ONE forward; returns fp32
    logits [M, V] at each row's last prompt token.  The inflight generator
    admits every freed slot of a refill cycle through one call here instead
    of M serial batch-1 prefills (the reference batches admissions the same
    way inside SGLang's scheduler, sglang.py:267-352).  Rows whose
    `slot_rows` entry is out of range (>= n_slots) are compile-shape padding:
    their cache/notebook scatters are dropped (`mode="drop"`) and their
    logits are garbage the caller ignores."""
    m, sp = tokens.shape
    seg = (
        jnp.arange(sp)[None, :] < prompt_lens[:, None]
    ).astype(jnp.int32)
    row_cache = _prefill_row_cache(cfg, m, sp, cache)
    logits, row_cache = prefill(
        params, cfg, tokens, seg, row_cache, use_flash=use_flash,
        quantize_kv=cache.quantized,
    )
    if cache.quantized:
        # The prefill already quantized once and attended dequantized —
        # scatter its CODES as-is (re-quantizing here would flip codes
        # and break parity with the chunked serving admission).
        return logits, KVCache(
            k=cache.k.at[:, slot_rows, :sp].set(row_cache.k, mode="drop"),
            v=cache.v.at[:, slot_rows, :sp].set(row_cache.v, mode="drop"),
            k_scale=cache.k_scale.at[:, slot_rows, :sp].set(
                row_cache.k_scale, mode="drop"
            ),
            v_scale=cache.v_scale.at[:, slot_rows, :sp].set(
                row_cache.v_scale, mode="drop"
            ),
        )
    new_k = cache.k.at[:, slot_rows, :sp].set(row_cache.k, mode="drop")
    new_v = cache.v.at[:, slot_rows, :sp].set(row_cache.v, mode="drop")
    return logits, KVCache(k=new_k, v=new_v)


def _prefill_row_cache(cfg: ModelConfig, m: int, sp: int, cache) -> KVCache:
    """Scratch per-row dense cache for a batched admission prefill,
    matching the target cache's quantization (int8 codes + scales when
    the target pool is int8, so the scatters move codes verbatim)."""
    shape = (cfg.n_layers, m, sp, cfg.n_kv_heads, cfg.head_dim)
    if cache.quantized:
        return KVCache(
            k=jnp.zeros(shape, jnp.int8),
            v=jnp.zeros(shape, jnp.int8),
            k_scale=jnp.zeros(shape[:-1], jnp.bfloat16),
            v_scale=jnp.zeros(shape[:-1], jnp.bfloat16),
        )
    return KVCache(
        k=jnp.zeros(shape, cache.k.dtype), v=jnp.zeros(shape, cache.k.dtype)
    )


# --------------------------------------------------------------------------
# Paged KV-cache generation path
# --------------------------------------------------------------------------


@dataclasses.dataclass
class PagedKVCache:
    """Block-paged KV pool: k/v [L, n_pages, page_size, n_kv, head_dim].

    The dense inflight cache (`KVCache` at [L, n_slots, s_max, ...])
    couples every slot to the batch-max window: growth is a full-cache
    `jnp.pad` copy plus a decode recompile per bucket, and a finished
    short row keeps holding s_max worth of HBM until the batch drains.
    Paging breaks the coupling: the pool is allocated ONCE per generate
    call, each slot owns an ordered list of pages (the host-side page
    table), growth appends a page index, and a retired slot's pages are
    recycled into new admits — fixed memory, fixed shapes, one decode
    compilation.  Reference: TPU ragged paged attention / vLLM
    PagedAttention block tables.

    Page index `n_pages` is the UNMAPPED sentinel: writes through it are
    dropped (`mode="drop"`), reads clamp and are masked by `valid_to`
    (pages are mapped contiguously from position 0, so any position
    beyond the mapped prefix is also beyond the live window).

    int8 mode mirrors `KVCache`: int8 k/v + bf16 per-(layer,page,pos,
    head) scales — same capacity halving, same quantizer
    (`ops/quant.py`), so paged and dense int8 cannot diverge.
    """

    k: jax.Array
    v: jax.Array
    k_scale: "jax.Array | None" = None  # [L, n_pages, page_size, n_kv] bf16
    v_scale: "jax.Array | None" = None
    page_size: int = 128  # static metadata (pytree aux)

    @property
    def n_pages(self) -> int:
        return self.k.shape[1]

    @property
    def quantized(self) -> bool:
        return self.k_scale is not None


jax.tree_util.register_dataclass(
    PagedKVCache,
    data_fields=["k", "v", "k_scale", "v_scale"],
    meta_fields=["page_size"],
)


def init_paged_kv_cache(
    cfg: ModelConfig, n_pages: int, page_size: int, dtype=None
) -> PagedKVCache:
    shape = (cfg.n_layers, n_pages, page_size, cfg.n_kv_heads, cfg.head_dim)
    dtype = dtype or cfg.dtype
    if dtype in (jnp.int8, "int8"):
        return PagedKVCache(
            k=jnp.zeros(shape, jnp.int8),
            v=jnp.zeros(shape, jnp.int8),
            k_scale=jnp.zeros(shape[:-1], jnp.bfloat16),
            v_scale=jnp.zeros(shape[:-1], jnp.bfloat16),
            page_size=page_size,
        )
    return PagedKVCache(
        k=jnp.zeros(shape, dtype), v=jnp.zeros(shape, dtype),
        page_size=page_size,
    )


def _page_of(page_table: jax.Array, pos: jax.Array, page_size: int):
    """Per-row (page, offset) write coordinates for flat positions `pos`
    ([B] or [B, Q]) through `page_table` [B, max_pages]."""
    pos2 = pos if pos.ndim == 2 else pos[:, None]
    pages = jnp.take_along_axis(
        page_table, pos2 // page_size, axis=1, mode="clip"
    )
    # Positions addressing beyond the table width must DROP, not alias
    # the clipped last entry (2**30 is out of range of any pool axis).
    oob = pos2 // page_size >= page_table.shape[1]
    pages = jnp.where(oob, jnp.int32(2**30), pages)
    pages = pages if pos.ndim == 2 else pages[:, 0]
    return pages.astype(jnp.int32), (pos % page_size).astype(jnp.int32)


def decode_step_paged(
    params: Params,
    cfg: ModelConfig,
    tokens: jax.Array,  # [B] int32
    positions: jax.Array,  # [B] int32 RoPE positions
    cache: PagedKVCache,
    page_table: jax.Array,  # [B, max_pages] int32, sentinel = n_pages
    write_pos: jax.Array,  # [B] int32 — flat cache position to write
    valid_to: jax.Array,  # [B] int32 — one past the last valid position
) -> Tuple[jax.Array, PagedKVCache]:
    """`decode_step_inflight` over a paged pool: identical math, but the
    per-row write lands at (page_table[row, pos // ps], pos % ps) in the
    shared pool and the read side attends through the page table
    (`paged_decode_attention`: Pallas ragged kernel or XLA gather
    fallback).  The pool shape never changes during a generate call, so
    the enclosing program compiles exactly once."""
    b = tokens.shape[0]
    x = _embed(params, cfg, tokens, positions)[:, None, :]
    cos, sin = rope_cos_sin(positions[:, None], cfg.head_dim, cfg.rope_theta)
    wp_page, wp_off = _page_of(page_table, write_pos, cache.page_size)
    quant = cache.quantized

    def body(carry, blk):
        y, kc, vc, ksc, vsc, li = carry
        h = _norm(y, blk["ln1"], blk.get("ln1_b"), cfg)
        q, k, v = _block_kv(h, blk, cfg, cos, sin)
        kc, vc, ksc, vsc, k_pool_l, v_pool_l, ks_l, vs_l = (
            _cache_update_read(
                kc, vc, ksc, vsc, k[:, 0], v[:, 0], li, (wp_page, wp_off),
                quant, q.dtype, dequant=False,
            )
        )
        attn = paged_decode_attention(
            q, k_pool_l, v_pool_l, page_table, valid_to,
            k_scale=ks_l, v_scale=vs_l,
        )
        ao = attn.reshape(b, 1, cfg.q_dim) @ blk["wo"]
        if cfg.proj_bias:
            ao = ao + blk["bo"]
        y = y + ao
        h2 = _norm(y, blk["ln2"], blk.get("ln2_b"), cfg)
        y = y + (_mlp_moe(h2, blk, cfg)[0] if cfg.is_moe else _mlp_dense(h2, blk, cfg))
        return (y, kc, vc, ksc, vsc, li + 1), None

    ksc0 = cache.k_scale if quant else jnp.zeros((0,), jnp.bfloat16)
    vsc0 = cache.v_scale if quant else jnp.zeros((0,), jnp.bfloat16)
    (x, kc, vc, ksc, vsc, _), _ = jax.lax.scan(
        body,
        (x, cache.k, cache.v, ksc0, vsc0, jnp.int32(0)),
        params["blocks"],
    )
    x = _norm(x, params["final_ln"], params.get("final_ln_b"), cfg)
    logits = _head(params, cfg, x)[:, 0]
    return logits, PagedKVCache(
        k=kc, v=vc,
        k_scale=ksc if quant else None,
        v_scale=vsc if quant else None,
        page_size=cache.page_size,
    )


def decode_step_spec_paged(
    params: Params,
    cfg: ModelConfig,
    tokens: jax.Array,  # [B, Q] int32 — pending token + Q-1 drafts per row
    positions: jax.Array,  # [B, Q] int32 — RoPE positions
    cache: PagedKVCache,
    page_table: jax.Array,  # [B, max_pages] int32, sentinel = n_pages
    write_pos0: jax.Array,  # [B] int32 — flat position of tokens[:, 0]
    q_lens: "jax.Array | None" = None,  # [B] int32 — live queries per row
) -> Tuple[jax.Array, PagedKVCache]:
    """`decode_step_spec` over a paged pool: Q consecutive tokens per row
    in one forward, k/v written at flat positions write_pos0..+Q-1
    through the page table, fp32 logits [B, Q, V].  Same exact-
    verification semantics (quantized cache included) as the dense
    speculative step.

    `q_lens` makes the step RAGGED — the unified serving chunk's mixed
    prefill+decode forward: row b's queries i >= q_lens[b] are dead
    (their cache writes DROP and their attention is fully masked), so a
    decoding row contributes 1 query, an admitting row a prompt slice of
    up to Q, and a parked row 0, all in one compiled program.  Dead-
    query logits are garbage the caller ignores, exactly like padding
    rows in `prefill_into_pages`."""
    b, q_len = tokens.shape
    x = _embed(params, cfg, tokens.reshape(-1), positions.reshape(-1))
    x = x.reshape(b, q_len, cfg.hidden_dim)
    cos, sin = rope_cos_sin(positions, cfg.head_dim, cfg.rope_theta)
    col = write_pos0[:, None] + jnp.arange(q_len)[None, :]  # [B, Q]
    wp_page, wp_off = _page_of(page_table, col, cache.page_size)
    if q_lens is not None:
        # Dead queries must not scatter: route their page index out of
        # range (2**30, the `_page_of` OOB convention) so mode="drop"
        # discards them — this is what keeps garbage lanes from ever
        # touching pool pages (shared ones included).
        dead = jnp.arange(q_len)[None, :] >= q_lens[:, None]
        wp_page = jnp.where(dead, jnp.int32(2**30), wp_page)
    quant = cache.quantized

    def body(carry, blk):
        y, kc, vc, ksc, vsc, li = carry
        h = _norm(y, blk["ln1"], blk.get("ln1_b"), cfg)
        q, k, v = _block_kv(h, blk, cfg, cos, sin)  # [B, Q, h, d]
        kc, vc, ksc, vsc, k_pool_l, v_pool_l, ks_l, vs_l = (
            _cache_update_read(
                kc, vc, ksc, vsc, k, v, li, (wp_page, wp_off),
                quant, q.dtype, dequant=False,
            )
        )
        attn = paged_decode_attention_chunk(
            q, k_pool_l, v_pool_l, page_table, write_pos0 + 1,
            k_scale=ks_l, v_scale=vs_l, q_lens=q_lens,
        )
        ao = attn.reshape(b, q_len, cfg.q_dim) @ blk["wo"]
        if cfg.proj_bias:
            ao = ao + blk["bo"]
        y = y + ao
        h2 = _norm(y, blk["ln2"], blk.get("ln2_b"), cfg)
        y = y + (
            _mlp_moe(h2, blk, cfg)[0] if cfg.is_moe else _mlp_dense(h2, blk, cfg)
        )
        return (y, kc, vc, ksc, vsc, li + 1), None

    ksc0 = cache.k_scale if quant else jnp.zeros((0,), jnp.bfloat16)
    vsc0 = cache.v_scale if quant else jnp.zeros((0,), jnp.bfloat16)
    (x, kc, vc, ksc, vsc, _), _ = jax.lax.scan(
        body,
        (x, cache.k, cache.v, ksc0, vsc0, jnp.int32(0)),
        params["blocks"],
    )
    x = _norm(x, params["final_ln"], params.get("final_ln_b"), cfg)
    logits = _head(params, cfg, x)  # [B, Q, V]
    return logits, PagedKVCache(
        k=kc, v=vc,
        k_scale=ksc if quant else None,
        v_scale=vsc if quant else None,
        page_size=cache.page_size,
    )


def decode_step_ragged_paged(
    params: Params,
    cfg: ModelConfig,
    tokens: jax.Array,  # [T] int32 — PACKED token stream
    positions: jax.Array,  # [T] int32 — flat cache position (== RoPE pos)
    cache: PagedKVCache,
    page_table: jax.Array,  # [B, max_pages] int32, sentinel = n_pages
    row_of: jax.Array,  # [T] int32 — owning slot per token; >= B = dead lane
) -> Tuple[jax.Array, PagedKVCache]:
    """The megakernel forward: one packed [T] stream of query lanes with
    per-token windows, instead of a [B, Q] slab with per-row q_lens.

    `decode_step_spec_paged(q_lens=...)` pays B*Q query lanes of embed /
    QKV / MLP / head compute per step and MASKS the dead ones; here the
    serving chunk packs only live lanes (decode rows contribute 1,
    chunked-prefill / episode-observation rows their granted slice,
    spec-verify rows pending+drafts) so the whole transformer stack —
    not just attention — runs at ∝ T.  Token t writes its K/V at flat
    position `positions[t]` of slot `row_of[t]` and attends
    [0, positions[t]] through that slot's page-table row
    (`ragged_paged_attention`: Pallas stream kernel or XLA per-token
    gather).  Dead lanes (row_of >= B, the stream's slack) drop their
    cache writes, emit zero attention, and produce garbage logits the
    caller never reads.  Same pool-in/pool-out single-compilation
    contract as `decode_step_paged`."""
    t = tokens.shape[0]
    b = page_table.shape[0]
    live = row_of < b
    rid = jnp.minimum(row_of.astype(jnp.int32), b - 1)
    pt_tok = jnp.take(page_table, rid, axis=0)  # [T, max_pages]
    positions = jnp.where(live, positions, 0).astype(jnp.int32)
    x = _embed(params, cfg, tokens, positions)[:, None, :]  # [T, 1, D]
    cos, sin = rope_cos_sin(positions[:, None], cfg.head_dim, cfg.rope_theta)
    wp_page, wp_off = _page_of(pt_tok, positions, cache.page_size)
    # Dead lanes must not scatter (2**30 = the `_page_of` OOB drop).
    wp_page = jnp.where(live, wp_page, jnp.int32(2**30))
    valid_to = jnp.where(live, positions + 1, 0).astype(jnp.int32)
    quant = cache.quantized

    def body(carry, blk):
        y, kc, vc, ksc, vsc, li = carry
        h = _norm(y, blk["ln1"], blk.get("ln1_b"), cfg)
        q, k, v = _block_kv(h, blk, cfg, cos, sin)  # [T, 1, h, d]
        kc, vc, ksc, vsc, k_pool_l, v_pool_l, ks_l, vs_l = (
            _cache_update_read(
                kc, vc, ksc, vsc, k[:, 0], v[:, 0], li, (wp_page, wp_off),
                quant, q.dtype, dequant=False,
            )
        )
        attn = ragged_paged_attention(
            q[:, 0], k_pool_l, v_pool_l, pt_tok, valid_to,
            k_scale=ks_l, v_scale=vs_l,
        )
        ao = attn.reshape(t, 1, cfg.q_dim) @ blk["wo"]
        if cfg.proj_bias:
            ao = ao + blk["bo"]
        y = y + ao
        h2 = _norm(y, blk["ln2"], blk.get("ln2_b"), cfg)
        y = y + (
            _mlp_moe(h2, blk, cfg)[0] if cfg.is_moe else _mlp_dense(h2, blk, cfg)
        )
        return (y, kc, vc, ksc, vsc, li + 1), None

    ksc0 = cache.k_scale if quant else jnp.zeros((0,), jnp.bfloat16)
    vsc0 = cache.v_scale if quant else jnp.zeros((0,), jnp.bfloat16)
    (x, kc, vc, ksc, vsc, _), _ = jax.lax.scan(
        body,
        (x, cache.k, cache.v, ksc0, vsc0, jnp.int32(0)),
        params["blocks"],
    )
    x = _norm(x, params["final_ln"], params.get("final_ln_b"), cfg)
    logits = _head(params, cfg, x)[:, 0]  # [T, V]
    return logits, PagedKVCache(
        k=kc, v=vc,
        k_scale=ksc if quant else None,
        v_scale=vsc if quant else None,
        page_size=cache.page_size,
    )


def prefill_into_pages(
    params: Params,
    cfg: ModelConfig,
    tokens: jax.Array,  # [M, SP] left-aligned prompts (SP % page_size == 0)
    prompt_lens: jax.Array,  # [M] int32
    cache: PagedKVCache,
    page_rows: jax.Array,  # [M, SP // page_size] int32 pool page ids
    use_flash: "bool | None" = None,
) -> Tuple[jax.Array, PagedKVCache]:
    """`prefill_into_slots` for the paged pool: one batched forward for M
    admitted prompts, then the dense per-row caches are reshaped into
    page_size chunks and scattered at their assigned pool pages in one
    op.  `page_rows` entries >= n_pages (the sentinel) are compile-shape
    padding — those chunks drop, exactly like out-of-range `slot_rows`
    in the dense path.  The tail of a prompt's last page holds garbage
    past `prompt_lens`; it is overwritten by decode writes and masked by
    `valid_to` until then."""
    m, sp = tokens.shape
    ps = cache.page_size
    if sp % ps:
        raise ValueError(f"prefill width {sp} not a multiple of page_size {ps}")
    n_chunks = sp // ps
    seg = (
        jnp.arange(sp)[None, :] < prompt_lens[:, None]
    ).astype(jnp.int32)
    row_cache = _prefill_row_cache(cfg, m, sp, cache)
    logits, row_cache = prefill(
        params, cfg, tokens, seg, row_cache, use_flash=use_flash,
        quantize_kv=cache.quantized,
    )

    def chunked(a):  # [L, M, SP, ...] -> [L, M * n_chunks, ps, ...]
        return a.reshape(a.shape[0], m * n_chunks, ps, *a.shape[3:])

    flat = page_rows.reshape(-1)
    if cache.quantized:
        # Codes + scales scatter verbatim (quantized once inside the
        # prefill, attended dequantized there — see `prefill`).
        return logits, PagedKVCache(
            k=cache.k.at[:, flat].set(chunked(row_cache.k), mode="drop"),
            v=cache.v.at[:, flat].set(chunked(row_cache.v), mode="drop"),
            k_scale=cache.k_scale.at[:, flat].set(
                chunked(row_cache.k_scale), mode="drop"
            ),
            v_scale=cache.v_scale.at[:, flat].set(
                chunked(row_cache.v_scale), mode="drop"
            ),
            page_size=ps,
        )
    return logits, PagedKVCache(
        k=cache.k.at[:, flat].set(chunked(row_cache.k), mode="drop"),
        v=cache.v.at[:, flat].set(chunked(row_cache.v), mode="drop"),
        page_size=ps,
    )


def copy_pages(
    cache: PagedKVCache,
    src_pages: jax.Array,  # [N] int32 pool page ids (sentinel = padding)
    dst_pages: jax.Array,  # [N] int32 pool page ids (sentinel = padding)
) -> PagedKVCache:
    """Copy whole KV pages src -> dst inside the pool in one gather +
    scatter per tensor — the device half of copy-on-write (the allocator
    hands out the (src, dst) pairs, `PageAllocator.ensure_writable`).
    Padding pairs use the sentinel (>= n_pages): their gather clamps to
    a legal page and the scatter DROPS, so one compiled shape serves any
    number of live copies up to N."""
    n = cache.n_pages
    src = jnp.minimum(src_pages.astype(jnp.int32), n - 1)
    dst = jnp.where(
        dst_pages.astype(jnp.int32) >= n,
        jnp.int32(2**30),
        dst_pages.astype(jnp.int32),
    )
    new = PagedKVCache(
        k=cache.k.at[:, dst].set(cache.k[:, src], mode="drop"),
        v=cache.v.at[:, dst].set(cache.v[:, src], mode="drop"),
        page_size=cache.page_size,
    )
    if cache.quantized:
        new = dataclasses.replace(
            new,
            k_scale=cache.k_scale.at[:, dst].set(
                cache.k_scale[:, src], mode="drop"
            ),
            v_scale=cache.v_scale.at[:, dst].set(
                cache.v_scale[:, src], mode="drop"
            ),
        )
    return new
