"""Model architecture config.

Capability parity: realhf/api/core/model_api.py `ReaLModelConfig` (:210-340)
— one config dataclass covering the llama/qwen2/mistral/gemma family plus
MoE and critic variants.
"""

import dataclasses
from typing import Optional

import jax.numpy as jnp

_DTYPES = {
    "bfloat16": jnp.bfloat16,
    "float32": jnp.float32,
    "float16": jnp.float16,
}


@dataclasses.dataclass(frozen=True)
class ModelConfig:
    n_layers: int
    hidden_dim: int
    n_q_heads: int
    n_kv_heads: int
    head_dim: int
    intermediate_dim: int
    vocab_size: int
    max_position_embeddings: int = 32768
    rope_theta: float = 10000.0
    rms_norm_eps: float = 1e-6
    qkv_bias: bool = False  # qwen2-style attention bias
    tied_embeddings: bool = False
    is_critic: bool = False
    param_dtype: str = "bfloat16"
    # MoE (0 experts = dense MLP)
    n_experts: int = 0
    n_experts_per_tok: int = 2
    moe_intermediate_dim: int = 0
    # Router aux loss coefficient (reference: modules/moe/router.py)
    moe_aux_loss_coef: float = 0.001
    # "grouped" (default): dropless grouped-GEMM over expert-sorted
    # tokens via jax.lax.ragged_dot (megablox-style) — expert FLOPs
    # exactly proportional to tokens, numerics equal to the oracle.
    # "topk": capacity-based dispatch — FLOPs scale with top-k times the
    # capacity factor, tokens over capacity are dropped (GShard-style);
    # the true-EP path (all-to-all over the expert axis).  "dense":
    # every expert computes every token then results are weight-masked —
    # E/k times the FLOPs, kept as the numerics oracle.
    moe_dispatch: str = "grouped"
    # Expert capacity = ceil(T * k / E * this); 1.0 = perfectly balanced.
    moe_capacity_factor: float = 1.25
    # ---- architecture family switches (reference: api/from_hf/*) ----
    hidden_act: str = "silu"  # silu | gelu | gelu_tanh
    norm_type: str = "rms"  # rms | layernorm (layernorm adds bias params)
    rms_norm_offset: bool = False  # gemma: scale by (1 + w)
    embed_scale: bool = False  # gemma: embeddings scaled by sqrt(hidden)
    pos_emb: str = "rope"  # rope | learned (gpt2 wpe)
    mlp_gated: bool = True  # False = plain fc/act/proj (gpt2)
    proj_bias: bool = False  # biases on attn-out + mlp matmuls (gpt2)

    @property
    def dtype(self):
        return _DTYPES[self.param_dtype]

    @property
    def is_moe(self) -> bool:
        return self.n_experts > 0

    @property
    def q_dim(self) -> int:
        return self.n_q_heads * self.head_dim

    @property
    def kv_dim(self) -> int:
        return self.n_kv_heads * self.head_dim

    def as_critic(self) -> "ModelConfig":
        return dataclasses.replace(self, is_critic=True, tied_embeddings=False)


def tiny_config(
    vocab_size: int = 512,
    is_critic: bool = False,
    n_experts: int = 0,
    param_dtype: str = "float32",
) -> ModelConfig:
    """8-layer/64-hidden test model (mirrors the reference's tiny test
    constants, realhf/base/testing.py:36-44)."""
    return ModelConfig(
        n_layers=4,
        hidden_dim=64,
        n_q_heads=4,
        n_kv_heads=2,
        head_dim=16,
        intermediate_dim=128,
        vocab_size=vocab_size,
        max_position_embeddings=1024,
        qkv_bias=True,
        is_critic=is_critic,
        param_dtype=param_dtype,
        n_experts=n_experts,
        moe_intermediate_dim=64 if n_experts else 0,
    )


# Published architecture presets (values from the public model cards).
def qwen2_config(size: str, param_dtype: str = "bfloat16") -> ModelConfig:
    presets = {
        # R1-Distill-Qwen uses the qwen2 architecture.
        "1.5b": dict(
            n_layers=28, hidden_dim=1536, n_q_heads=12, n_kv_heads=2,
            head_dim=128, intermediate_dim=8960, vocab_size=151936,
            rope_theta=10000.0, tied_embeddings=True,
        ),
        "7b": dict(
            n_layers=28, hidden_dim=3584, n_q_heads=28, n_kv_heads=4,
            head_dim=128, intermediate_dim=18944, vocab_size=152064,
            rope_theta=10000.0,
        ),
        "32b": dict(
            n_layers=64, hidden_dim=5120, n_q_heads=40, n_kv_heads=8,
            head_dim=128, intermediate_dim=27648, vocab_size=152064,
            rope_theta=1000000.0,
        ),
    }
    return ModelConfig(
        qkv_bias=True,
        rms_norm_eps=1e-6,
        max_position_embeddings=131072,
        param_dtype=param_dtype,
        **presets[size.lower()],
    )


def llama_config(size: str, param_dtype: str = "bfloat16") -> ModelConfig:
    presets = {
        "7b": dict(
            n_layers=32, hidden_dim=4096, n_q_heads=32, n_kv_heads=32,
            head_dim=128, intermediate_dim=11008, vocab_size=32000,
        ),
        "8b": dict(
            n_layers=32, hidden_dim=4096, n_q_heads=32, n_kv_heads=8,
            head_dim=128, intermediate_dim=14336, vocab_size=128256,
            rope_theta=500000.0,
        ),
    }
    return ModelConfig(
        qkv_bias=False,
        rms_norm_eps=1e-5,
        max_position_embeddings=8192,
        param_dtype=param_dtype,
        **presets[size.lower()],
    )
