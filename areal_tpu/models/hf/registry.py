"""HuggingFace checkpoint conversion registry.

Capability parity: realhf/api/from_hf/* + realhf/impl/model/conversion/
hf_registry.py — config⇄config and state-dict⇄state-dict converters per model
family, used for loading pretrained checkpoints and saving HF-format outputs
(so downstream eval harnesses can consume them directly).

Families here: llama, qwen2 (identical tensor naming; qwen2 adds qkv bias).
The reference additionally registers gpt2/gemma/mistral/mixtral — same
registry mechanism, added as needed.
"""

import json
import os
from typing import Any, Callable, Dict, Optional

import numpy as np

from areal_tpu.base import logging
from areal_tpu.models.config import ModelConfig

logger = logging.getLogger("hf_registry")


class HFFamily:
    def __init__(
        self,
        name: str,
        config_from_hf: Callable[[dict], ModelConfig],
        config_to_hf: Callable[[ModelConfig], dict],
    ):
        self.name = name
        self.config_from_hf = config_from_hf
        self.config_to_hf = config_to_hf


HF_FAMILIES: Dict[str, HFFamily] = {}


def register_hf_family(family: HFFamily) -> None:
    HF_FAMILIES[family.name] = family


# ---------------- llama / qwen2 ----------------


def _llama_like_config_from_hf(hf: dict) -> ModelConfig:
    head_dim = hf.get("head_dim") or hf["hidden_size"] // hf["num_attention_heads"]
    return ModelConfig(
        n_layers=hf["num_hidden_layers"],
        hidden_dim=hf["hidden_size"],
        n_q_heads=hf["num_attention_heads"],
        n_kv_heads=hf.get("num_key_value_heads", hf["num_attention_heads"]),
        head_dim=head_dim,
        intermediate_dim=hf["intermediate_size"],
        vocab_size=hf["vocab_size"],
        max_position_embeddings=hf.get("max_position_embeddings", 32768),
        rope_theta=hf.get("rope_theta", 10000.0),
        rms_norm_eps=hf.get("rms_norm_eps", 1e-6),
        qkv_bias=hf["model_type"] == "qwen2",
        tied_embeddings=hf.get("tie_word_embeddings", False),
    )


def _llama_like_config_to_hf(cfg: ModelConfig, model_type: str) -> dict:
    return {
        "model_type": model_type,
        "num_hidden_layers": cfg.n_layers,
        "hidden_size": cfg.hidden_dim,
        "num_attention_heads": cfg.n_q_heads,
        "num_key_value_heads": cfg.n_kv_heads,
        "head_dim": cfg.head_dim,
        "intermediate_size": cfg.intermediate_dim,
        "vocab_size": cfg.vocab_size,
        "max_position_embeddings": cfg.max_position_embeddings,
        "rope_theta": cfg.rope_theta,
        "rms_norm_eps": cfg.rms_norm_eps,
        "tie_word_embeddings": cfg.tied_embeddings,
        "torch_dtype": "bfloat16",
        "architectures": [
            "LlamaForCausalLM" if model_type == "llama" else "Qwen2ForCausalLM"
        ],
    }


register_hf_family(
    HFFamily(
        "llama",
        _llama_like_config_from_hf,
        lambda cfg: _llama_like_config_to_hf(cfg, "llama"),
    )
)
register_hf_family(
    HFFamily(
        "qwen2",
        _llama_like_config_from_hf,
        lambda cfg: _llama_like_config_to_hf(cfg, "qwen2"),
    )
)


# ---------------- state dict conversion (llama-like naming) ----------------


def params_from_hf_state_dict(
    cfg: ModelConfig, sd: Dict[str, np.ndarray], dtype=None
) -> Dict[str, Any]:
    """HF tensors -> layer-stacked pytree.  HF linears are [out, in]; ours
    are [in, out], so weights transpose."""
    import jax.numpy as jnp

    dtype = dtype or cfg.dtype

    def get(name):
        if name not in sd:
            raise KeyError(f"missing tensor {name!r} in checkpoint")
        return np.asarray(sd[name])

    def stack(fmt, transpose=False):
        ts = [get(fmt.format(i)) for i in range(cfg.n_layers)]
        arr = np.stack(
            [t.T if transpose else t for t in ts], axis=0
        )
        return jnp.asarray(arr, dtype=dtype)

    blocks = {
        "ln1": stack("model.layers.{}.input_layernorm.weight"),
        "wq": stack("model.layers.{}.self_attn.q_proj.weight", transpose=True),
        "wk": stack("model.layers.{}.self_attn.k_proj.weight", transpose=True),
        "wv": stack("model.layers.{}.self_attn.v_proj.weight", transpose=True),
        "wo": stack("model.layers.{}.self_attn.o_proj.weight", transpose=True),
        "ln2": stack("model.layers.{}.post_attention_layernorm.weight"),
        "wg": stack("model.layers.{}.mlp.gate_proj.weight", transpose=True),
        "wu": stack("model.layers.{}.mlp.up_proj.weight", transpose=True),
        "wd": stack("model.layers.{}.mlp.down_proj.weight", transpose=True),
    }
    if cfg.qkv_bias:
        blocks["bq"] = stack("model.layers.{}.self_attn.q_proj.bias")
        blocks["bk"] = stack("model.layers.{}.self_attn.k_proj.bias")
        blocks["bv"] = stack("model.layers.{}.self_attn.v_proj.bias")
    import jax.numpy as jnp

    params = {
        "embed": jnp.asarray(get("model.embed_tokens.weight"), dtype=dtype),
        "blocks": blocks,
        "final_ln": jnp.asarray(get("model.norm.weight"), dtype=dtype),
    }
    if cfg.is_critic:
        if "value_head.weight" in sd:
            # Our own critic checkpoints carry the trained head.
            params["value_head"] = jnp.asarray(
                get("value_head.weight"), dtype=dtype
            )
        else:
            # Critic-from-actor init: fresh value head (reference:
            # conversion/hf_registry.py critic init path).
            params["value_head"] = jnp.zeros((cfg.hidden_dim, 1), dtype=dtype)
    elif not cfg.tied_embeddings:
        params["lm_head"] = jnp.asarray(get("lm_head.weight").T, dtype=dtype)
    return params


def params_to_hf_state_dict(
    cfg: ModelConfig, params: Dict[str, Any]
) -> Dict[str, np.ndarray]:
    from areal_tpu.base.distributed import to_host

    out: Dict[str, np.ndarray] = {}
    out["model.embed_tokens.weight"] = to_host(params["embed"]).astype(
        np.float32, copy=False
    )
    out["model.norm.weight"] = to_host(params["final_ln"]).astype(
        np.float32, copy=False
    )
    if cfg.is_critic:
        # Not an HF key — preserved so our critic checkpoints roundtrip
        # (recover would otherwise zero the trained value head).
        out["value_head.weight"] = to_host(params["value_head"]).astype(
            np.float32, copy=False
        )
    elif not cfg.tied_embeddings:
        # ascontiguousarray: safetensors serializes the raw buffer, so a
        # transposed VIEW would be written in untransposed memory order.
        out["lm_head.weight"] = np.ascontiguousarray(
            to_host(params["lm_head"]).astype(np.float32, copy=False).T
        )
    blocks = params["blocks"]

    def unstack(name, arr, transpose=False):
        arr = to_host(arr).astype(np.float32, copy=False)
        for i in range(cfg.n_layers):
            t = arr[i]
            # ascontiguousarray: see lm_head note — safetensors writes the
            # raw buffer and would silently drop the transpose.
            out[name.format(i)] = (
                np.ascontiguousarray(t.T) if transpose else t
            )

    unstack("model.layers.{}.input_layernorm.weight", blocks["ln1"])
    unstack("model.layers.{}.self_attn.q_proj.weight", blocks["wq"], True)
    unstack("model.layers.{}.self_attn.k_proj.weight", blocks["wk"], True)
    unstack("model.layers.{}.self_attn.v_proj.weight", blocks["wv"], True)
    unstack("model.layers.{}.self_attn.o_proj.weight", blocks["wo"], True)
    unstack("model.layers.{}.post_attention_layernorm.weight", blocks["ln2"])
    unstack("model.layers.{}.mlp.gate_proj.weight", blocks["wg"], True)
    unstack("model.layers.{}.mlp.up_proj.weight", blocks["wu"], True)
    unstack("model.layers.{}.mlp.down_proj.weight", blocks["wd"], True)
    if cfg.qkv_bias:
        unstack("model.layers.{}.self_attn.q_proj.bias", blocks["bq"])
        unstack("model.layers.{}.self_attn.k_proj.bias", blocks["bk"])
        unstack("model.layers.{}.self_attn.v_proj.bias", blocks["bv"])
    return out


# ---------------- checkpoint IO ----------------


def load_hf_config(path: str) -> dict:
    with open(os.path.join(path, "config.json")) as f:
        return json.load(f)


def load_hf_checkpoint(
    path: str, is_critic: bool = False, dtype=None
) -> "tuple[ModelConfig, Dict[str, Any]]":
    """Load an HF checkpoint dir (safetensors or torch .bin shards)."""
    hf_cfg = load_hf_config(path)
    family = HF_FAMILIES[hf_cfg["model_type"]]
    cfg = family.config_from_hf(hf_cfg)
    if is_critic:
        cfg = cfg.as_critic()
    sd: Dict[str, np.ndarray] = {}
    st_files = sorted(
        f for f in os.listdir(path) if f.endswith(".safetensors")
    )
    if st_files:
        from safetensors.numpy import load_file

        for f in st_files:
            sd.update(load_file(os.path.join(path, f)))
    else:
        import torch

        bins = sorted(f for f in os.listdir(path) if f.endswith(".bin"))
        if not bins:
            raise FileNotFoundError(f"no safetensors/bin shards in {path}")
        for f in bins:
            t = torch.load(
                os.path.join(path, f), map_location="cpu", weights_only=True
            )
            sd.update({k: v.float().numpy() for k, v in t.items()})
    params = params_from_hf_state_dict(cfg, sd, dtype=dtype)
    logger.info(f"loaded HF checkpoint from {path} ({hf_cfg['model_type']})")
    return cfg, params


def save_hf_checkpoint(
    path: str,
    cfg: ModelConfig,
    params: Dict[str, Any],
    model_type: str = "qwen2",
    tokenizer=None,
) -> None:
    """Write an HF-format checkpoint dir (safetensors + config.json) so the
    reference's eval tooling / vLLM / SGLang can consume our outputs."""
    from areal_tpu.base.distributed import is_primary

    # Host-gathering a process-spanning param tree is collective: every
    # group member computes the state dict, only jax process 0 writes.
    sd = params_to_hf_state_dict(cfg, params)
    if not is_primary():
        return
    os.makedirs(path, exist_ok=True)
    from safetensors.numpy import save_file

    save_file(sd, os.path.join(path, "model.safetensors"))
    with open(os.path.join(path, "config.json"), "w") as f:
        json.dump(HF_FAMILIES[model_type].config_to_hf(cfg), f, indent=2)
    if tokenizer is not None and hasattr(tokenizer, "save_pretrained"):
        tokenizer.save_pretrained(path)
