"""HuggingFace checkpoint conversion registry.

Capability parity: realhf/api/from_hf/* + realhf/impl/model/conversion/
hf_registry.py — config⇄config and state-dict⇄state-dict converters per model
family, used for loading pretrained checkpoints and saving HF-format outputs
(so downstream eval harnesses can consume them directly).

Families here (full reference parity, api/from_hf/*): llama, qwen2
(identical tensor naming; qwen2 adds qkv bias), mistral, gemma (gelu_tanh +
(1+w) rms offset + scaled embeddings), mixtral (MoE expert stacking), gpt2
(learned positions, LayerNorm+bias, fused c_attn, non-gated gelu MLP).
"""

import dataclasses
import json
import os
from typing import Any, Callable, Dict, Optional

import jax
import numpy as np

from areal_tpu.base import logging
from areal_tpu.models.config import ModelConfig

logger = logging.getLogger("hf_registry")


class HFFamily:
    def __init__(
        self,
        name: str,
        config_from_hf: Callable[[dict], ModelConfig],
        config_to_hf: Callable[[ModelConfig], dict],
        # State-dict converters; default = the llama-like tensor naming
        # shared by llama/qwen2/mistral/gemma.
        params_from_sd: Optional[Callable] = None,
        params_to_sd: Optional[Callable] = None,
    ):
        self.name = name
        self.config_from_hf = config_from_hf
        self.config_to_hf = config_to_hf
        # None -> resolved to the llama-like default at use (the functions
        # are defined below the early family registrations).
        self._params_from_sd = params_from_sd
        self._params_to_sd = params_to_sd

    def params_from_sd(self, cfg, sd, dtype=None):
        fn = self._params_from_sd or params_from_hf_state_dict
        return fn(cfg, sd, dtype=dtype)

    def params_to_sd(self, cfg, params):
        fn = self._params_to_sd or params_to_hf_state_dict
        return fn(cfg, params)


HF_FAMILIES: Dict[str, HFFamily] = {}


def register_hf_family(family: HFFamily) -> None:
    HF_FAMILIES[family.name] = family


# ---------------- llama / qwen2 ----------------


def _llama_like_config_from_hf(hf: dict) -> ModelConfig:
    head_dim = hf.get("head_dim") or hf["hidden_size"] // hf["num_attention_heads"]
    return ModelConfig(
        n_layers=hf["num_hidden_layers"],
        hidden_dim=hf["hidden_size"],
        n_q_heads=hf["num_attention_heads"],
        n_kv_heads=hf.get("num_key_value_heads", hf["num_attention_heads"]),
        head_dim=head_dim,
        intermediate_dim=hf["intermediate_size"],
        vocab_size=hf["vocab_size"],
        max_position_embeddings=hf.get("max_position_embeddings", 32768),
        rope_theta=hf.get("rope_theta", 10000.0),
        rms_norm_eps=hf.get("rms_norm_eps", 1e-6),
        qkv_bias=hf["model_type"] == "qwen2",
        tied_embeddings=hf.get("tie_word_embeddings", False),
    )


def _llama_like_config_to_hf(cfg: ModelConfig, model_type: str) -> dict:
    return {
        "model_type": model_type,
        "num_hidden_layers": cfg.n_layers,
        "hidden_size": cfg.hidden_dim,
        "num_attention_heads": cfg.n_q_heads,
        "num_key_value_heads": cfg.n_kv_heads,
        "head_dim": cfg.head_dim,
        "intermediate_size": cfg.intermediate_dim,
        "vocab_size": cfg.vocab_size,
        "max_position_embeddings": cfg.max_position_embeddings,
        "rope_theta": cfg.rope_theta,
        "rms_norm_eps": cfg.rms_norm_eps,
        "tie_word_embeddings": cfg.tied_embeddings,
        "torch_dtype": "bfloat16",
        "architectures": [
            "LlamaForCausalLM" if model_type == "llama" else "Qwen2ForCausalLM"
        ],
    }


register_hf_family(
    HFFamily(
        "llama",
        _llama_like_config_from_hf,
        lambda cfg: _llama_like_config_to_hf(cfg, "llama"),
    )
)
register_hf_family(
    HFFamily(
        "qwen2",
        _llama_like_config_from_hf,
        lambda cfg: _llama_like_config_to_hf(cfg, "qwen2"),
    )
)


# ---------------- state dict conversion (llama-like naming) ----------------


def params_from_hf_state_dict(
    cfg: ModelConfig, sd: Dict[str, np.ndarray], dtype=None,
    skip_mlp: bool = False,
) -> Dict[str, Any]:
    """HF tensors -> layer-stacked pytree.  HF linears are [out, in]; ours
    are [in, out], so weights transpose."""
    import jax.numpy as jnp

    dtype = dtype or cfg.dtype

    def get(name):
        if name not in sd:
            raise KeyError(f"missing tensor {name!r} in checkpoint")
        return np.asarray(sd[name])

    def stack(fmt, transpose=False):
        ts = [get(fmt.format(i)) for i in range(cfg.n_layers)]
        arr = np.stack(
            [t.T if transpose else t for t in ts], axis=0
        )
        return jnp.asarray(arr, dtype=dtype)

    blocks = {
        "ln1": stack("model.layers.{}.input_layernorm.weight"),
        "wq": stack("model.layers.{}.self_attn.q_proj.weight", transpose=True),
        "wk": stack("model.layers.{}.self_attn.k_proj.weight", transpose=True),
        "wv": stack("model.layers.{}.self_attn.v_proj.weight", transpose=True),
        "wo": stack("model.layers.{}.self_attn.o_proj.weight", transpose=True),
        "ln2": stack("model.layers.{}.post_attention_layernorm.weight"),
    }
    if not skip_mlp:  # mixtral routes its MoE tensors separately
        blocks["wg"] = stack("model.layers.{}.mlp.gate_proj.weight", transpose=True)
        blocks["wu"] = stack("model.layers.{}.mlp.up_proj.weight", transpose=True)
        blocks["wd"] = stack("model.layers.{}.mlp.down_proj.weight", transpose=True)
    if cfg.qkv_bias:
        blocks["bq"] = stack("model.layers.{}.self_attn.q_proj.bias")
        blocks["bk"] = stack("model.layers.{}.self_attn.k_proj.bias")
        blocks["bv"] = stack("model.layers.{}.self_attn.v_proj.bias")
    import jax.numpy as jnp

    params = {
        "embed": jnp.asarray(get("model.embed_tokens.weight"), dtype=dtype),
        "blocks": blocks,
        "final_ln": jnp.asarray(get("model.norm.weight"), dtype=dtype),
    }
    if cfg.is_critic:
        if "value_head.weight" in sd:
            # Our own critic checkpoints carry the trained head.
            params["value_head"] = jnp.asarray(
                get("value_head.weight"), dtype=dtype
            )
        else:
            # Critic-from-actor init: fresh value head (reference:
            # conversion/hf_registry.py critic init path).
            params["value_head"] = jnp.zeros((cfg.hidden_dim, 1), dtype=dtype)
    elif not cfg.tied_embeddings:
        params["lm_head"] = jnp.asarray(get("lm_head.weight").T, dtype=dtype)
    return params


def params_to_hf_state_dict(
    cfg: ModelConfig, params: Dict[str, Any], skip_mlp: bool = False
) -> Dict[str, np.ndarray]:
    from areal_tpu.base.distributed import to_host

    out: Dict[str, np.ndarray] = {}
    out["model.embed_tokens.weight"] = to_host(params["embed"]).astype(
        np.float32, copy=False
    )
    out["model.norm.weight"] = to_host(params["final_ln"]).astype(
        np.float32, copy=False
    )
    if cfg.is_critic:
        # Not an HF key — preserved so our critic checkpoints roundtrip
        # (recover would otherwise zero the trained value head).
        out["value_head.weight"] = to_host(params["value_head"]).astype(
            np.float32, copy=False
        )
    elif not cfg.tied_embeddings:
        # ascontiguousarray: safetensors serializes the raw buffer, so a
        # transposed VIEW would be written in untransposed memory order.
        out["lm_head.weight"] = np.ascontiguousarray(
            to_host(params["lm_head"]).astype(np.float32, copy=False).T
        )
    blocks = params["blocks"]

    def unstack(name, arr, transpose=False):
        arr = to_host(arr).astype(np.float32, copy=False)
        for i in range(cfg.n_layers):
            t = arr[i]
            # ascontiguousarray: see lm_head note — safetensors writes the
            # raw buffer and would silently drop the transpose.
            out[name.format(i)] = (
                np.ascontiguousarray(t.T) if transpose else t
            )

    unstack("model.layers.{}.input_layernorm.weight", blocks["ln1"])
    unstack("model.layers.{}.self_attn.q_proj.weight", blocks["wq"], True)
    unstack("model.layers.{}.self_attn.k_proj.weight", blocks["wk"], True)
    unstack("model.layers.{}.self_attn.v_proj.weight", blocks["wv"], True)
    unstack("model.layers.{}.self_attn.o_proj.weight", blocks["wo"], True)
    unstack("model.layers.{}.post_attention_layernorm.weight", blocks["ln2"])
    if not skip_mlp:  # mixtral writes its MoE tensors separately
        unstack("model.layers.{}.mlp.gate_proj.weight", blocks["wg"], True)
        unstack("model.layers.{}.mlp.up_proj.weight", blocks["wu"], True)
        unstack("model.layers.{}.mlp.down_proj.weight", blocks["wd"], True)
    if cfg.qkv_bias:
        unstack("model.layers.{}.self_attn.q_proj.bias", blocks["bq"])
        unstack("model.layers.{}.self_attn.k_proj.bias", blocks["bk"])
        unstack("model.layers.{}.self_attn.v_proj.bias", blocks["bv"])
    return out


# ---------------- mistral ----------------
# Llama tensor naming; sliding-window attention is NOT modeled (full causal
# attention — exact for sequences within the window, reference api/from_hf/
# mistral.py maps the same fields).


def _mistral_config_from_hf(hf: dict) -> ModelConfig:
    cfg = _llama_like_config_from_hf(hf)
    return dataclasses.replace(cfg, qkv_bias=False)


register_hf_family(
    HFFamily(
        "mistral",
        _mistral_config_from_hf,
        lambda cfg: {
            **_llama_like_config_to_hf(cfg, "mistral"),
            "model_type": "mistral",
            "architectures": ["MistralForCausalLM"],
            "sliding_window": None,
        },
    )
)


# ---------------- gemma ----------------


def _gemma_config_from_hf(hf: dict) -> ModelConfig:
    return ModelConfig(
        n_layers=hf["num_hidden_layers"],
        hidden_dim=hf["hidden_size"],
        n_q_heads=hf["num_attention_heads"],
        n_kv_heads=hf.get("num_key_value_heads", hf["num_attention_heads"]),
        head_dim=hf["head_dim"],
        intermediate_dim=hf["intermediate_size"],
        vocab_size=hf["vocab_size"],
        max_position_embeddings=hf.get("max_position_embeddings", 8192),
        rope_theta=hf.get("rope_theta", 10000.0),
        rms_norm_eps=hf.get("rms_norm_eps", 1e-6),
        tied_embeddings=True,  # gemma always ties
        hidden_act="gelu_tanh",  # gelu_pytorch_tanh
        rms_norm_offset=True,  # norm scales by (1 + w)
        embed_scale=True,  # embeddings scaled by sqrt(hidden)
    )


def _gemma_config_to_hf(cfg: ModelConfig) -> dict:
    return {
        "model_type": "gemma",
        "num_hidden_layers": cfg.n_layers,
        "hidden_size": cfg.hidden_dim,
        "num_attention_heads": cfg.n_q_heads,
        "num_key_value_heads": cfg.n_kv_heads,
        "head_dim": cfg.head_dim,
        "intermediate_size": cfg.intermediate_dim,
        "vocab_size": cfg.vocab_size,
        "max_position_embeddings": cfg.max_position_embeddings,
        "rope_theta": cfg.rope_theta,
        "rms_norm_eps": cfg.rms_norm_eps,
        "tie_word_embeddings": True,
        "hidden_act": "gelu_pytorch_tanh",
        "hidden_activation": "gelu_pytorch_tanh",
        "torch_dtype": "bfloat16",
        "architectures": ["GemmaForCausalLM"],
    }


register_hf_family(
    HFFamily("gemma", _gemma_config_from_hf, _gemma_config_to_hf)
)


# ---------------- mixtral ----------------


def _mixtral_config_from_hf(hf: dict) -> ModelConfig:
    base = _llama_like_config_from_hf(hf)
    return dataclasses.replace(
        base,
        qkv_bias=False,
        n_experts=hf["num_local_experts"],
        n_experts_per_tok=hf["num_experts_per_tok"],
        moe_intermediate_dim=hf["intermediate_size"],
    )


def _mixtral_config_to_hf(cfg: ModelConfig) -> dict:
    out = _llama_like_config_to_hf(cfg, "mixtral")
    out.update(
        model_type="mixtral",
        architectures=["MixtralForCausalLM"],
        num_local_experts=cfg.n_experts,
        num_experts_per_tok=cfg.n_experts_per_tok,
        intermediate_size=cfg.moe_intermediate_dim or cfg.intermediate_dim,
    )
    return out


def _mixtral_params_from_sd(cfg, sd, dtype=None):
    """Attention/norms via the llama-like path; MoE tensors
    (block_sparse_moe.gate + experts.{e}.w1/w2/w3) stacked over (L, E)."""
    import jax.numpy as jnp

    params = params_from_hf_state_dict(cfg, sd, dtype=dtype, skip_mlp=True)
    dtype = dtype or cfg.dtype

    def stack_experts(fmt, transpose):
        layers = []
        for i in range(cfg.n_layers):
            experts = [
                np.asarray(sd[fmt.format(i, e)])
                for e in range(cfg.n_experts)
            ]
            layers.append(
                np.stack([t.T if transpose else t for t in experts], axis=0)
            )
        return jnp.asarray(np.stack(layers, axis=0), dtype=dtype)

    blocks = params["blocks"]
    blocks["router"] = jnp.asarray(
        np.stack(
            [
                np.asarray(
                    sd[f"model.layers.{i}.block_sparse_moe.gate.weight"]
                ).T
                for i in range(cfg.n_layers)
            ],
            axis=0,
        ),
        dtype=dtype,
    )
    moe = "model.layers.{}.block_sparse_moe.experts.{}"
    blocks["wg"] = stack_experts(moe + ".w1.weight", True)  # [L,E,D,F]
    blocks["wd"] = stack_experts(moe + ".w2.weight", True)  # [L,E,F,D]
    blocks["wu"] = stack_experts(moe + ".w3.weight", True)  # [L,E,D,F]
    return params


def _mixtral_params_to_sd(cfg, params):
    from areal_tpu.base.distributed import to_host

    out = params_to_hf_state_dict(cfg, params, skip_mlp=True)
    blocks = params["blocks"]
    router = to_host(blocks["router"]).astype(np.float32, copy=False)
    wg = to_host(blocks["wg"]).astype(np.float32, copy=False)
    wu = to_host(blocks["wu"]).astype(np.float32, copy=False)
    wd = to_host(blocks["wd"]).astype(np.float32, copy=False)
    moe = "model.layers.{}.block_sparse_moe"
    for i in range(cfg.n_layers):
        out[moe.format(i) + ".gate.weight"] = np.ascontiguousarray(
            router[i].T
        )
        for e in range(cfg.n_experts):
            pre = moe.format(i) + f".experts.{e}"
            out[pre + ".w1.weight"] = np.ascontiguousarray(wg[i, e].T)
            out[pre + ".w2.weight"] = np.ascontiguousarray(wd[i, e].T)
            out[pre + ".w3.weight"] = np.ascontiguousarray(wu[i, e].T)
    return out


register_hf_family(
    HFFamily(
        "mixtral",
        _mixtral_config_from_hf,
        _mixtral_config_to_hf,
        params_from_sd=_mixtral_params_from_sd,
        params_to_sd=_mixtral_params_to_sd,
    )
)


# ---------------- gpt2 ----------------
# Different lineage: learned positions, LayerNorm with bias, fused c_attn,
# plain (non-gated) gelu MLP, biases everywhere, Conv1D weights stored
# [in, out] — which matches this codebase's convention directly.


def _gpt2_config_from_hf(hf: dict) -> ModelConfig:
    d = hf["n_embd"]
    heads = hf["n_head"]
    return ModelConfig(
        n_layers=hf["n_layer"],
        hidden_dim=d,
        n_q_heads=heads,
        n_kv_heads=heads,
        head_dim=d // heads,
        intermediate_dim=hf.get("n_inner") or 4 * d,
        vocab_size=hf["vocab_size"],
        max_position_embeddings=hf.get("n_positions", 1024),
        rms_norm_eps=hf.get("layer_norm_epsilon", 1e-5),
        qkv_bias=True,
        tied_embeddings=True,
        hidden_act="gelu_tanh",  # gelu_new
        norm_type="layernorm",
        pos_emb="learned",
        mlp_gated=False,
        proj_bias=True,
    )


def _gpt2_config_to_hf(cfg: ModelConfig) -> dict:
    return {
        "model_type": "gpt2",
        "n_layer": cfg.n_layers,
        "n_embd": cfg.hidden_dim,
        "n_head": cfg.n_q_heads,
        "n_inner": cfg.intermediate_dim,
        "vocab_size": cfg.vocab_size,
        "n_positions": cfg.max_position_embeddings,
        "n_ctx": cfg.max_position_embeddings,
        "layer_norm_epsilon": cfg.rms_norm_eps,
        "activation_function": "gelu_new",
        "tie_word_embeddings": True,
        "torch_dtype": "float32",
        "architectures": ["GPT2LMHeadModel"],
    }


def _gpt2_params_from_sd(cfg, sd, dtype=None):
    import jax.numpy as jnp

    dtype = dtype or cfg.dtype
    L, D = cfg.n_layers, cfg.hidden_dim

    def get(name):
        key = name if name in sd else "transformer." + name
        return np.asarray(sd[key])

    def stack(fmt):
        return np.stack([get(fmt.format(i)) for i in range(L)], axis=0)

    c_attn_w = stack("h.{}.attn.c_attn.weight")  # [L, D, 3D] (Conv1D: in,out)
    c_attn_b = stack("h.{}.attn.c_attn.bias")  # [L, 3D]
    blocks = {
        "ln1": stack("h.{}.ln_1.weight"),
        "ln1_b": stack("h.{}.ln_1.bias"),
        "wq": c_attn_w[:, :, :D],
        "wk": c_attn_w[:, :, D : 2 * D],
        "wv": c_attn_w[:, :, 2 * D :],
        "bq": c_attn_b[:, :D],
        "bk": c_attn_b[:, D : 2 * D],
        "bv": c_attn_b[:, 2 * D :],
        "wo": stack("h.{}.attn.c_proj.weight"),
        "bo": stack("h.{}.attn.c_proj.bias"),
        "ln2": stack("h.{}.ln_2.weight"),
        "ln2_b": stack("h.{}.ln_2.bias"),
        "wg": stack("h.{}.mlp.c_fc.weight"),
        "bfc": stack("h.{}.mlp.c_fc.bias"),
        "wd": stack("h.{}.mlp.c_proj.weight"),
        "bproj": stack("h.{}.mlp.c_proj.bias"),
    }
    params = {
        "embed": get("wte.weight"),
        "pos_embed": get("wpe.weight"),
        "blocks": blocks,
        "final_ln": get("ln_f.weight"),
        "final_ln_b": get("ln_f.bias"),
    }
    params = jax.tree.map(lambda x: jnp.asarray(x, dtype=dtype), params)
    if cfg.is_critic:
        params["value_head"] = jnp.zeros((D, 1), dtype=dtype)
    return params


def _gpt2_params_to_sd(cfg, params):
    from areal_tpu.base.distributed import to_host

    host = jax.tree.map(
        lambda x: to_host(x).astype(np.float32, copy=False), params
    )
    blocks = host["blocks"]
    out = {
        "wte.weight": host["embed"],
        "wpe.weight": host["pos_embed"],
        "ln_f.weight": host["final_ln"],
        "ln_f.bias": host["final_ln_b"],
    }
    for i in range(cfg.n_layers):
        pre = f"h.{i}."
        out[pre + "ln_1.weight"] = blocks["ln1"][i]
        out[pre + "ln_1.bias"] = blocks["ln1_b"][i]
        out[pre + "attn.c_attn.weight"] = np.ascontiguousarray(
            np.concatenate(
                [blocks["wq"][i], blocks["wk"][i], blocks["wv"][i]], axis=1
            )
        )
        out[pre + "attn.c_attn.bias"] = np.ascontiguousarray(
            np.concatenate(
                [blocks["bq"][i], blocks["bk"][i], blocks["bv"][i]]
            )
        )
        out[pre + "attn.c_proj.weight"] = blocks["wo"][i]
        out[pre + "attn.c_proj.bias"] = blocks["bo"][i]
        out[pre + "ln_2.weight"] = blocks["ln2"][i]
        out[pre + "ln_2.bias"] = blocks["ln2_b"][i]
        out[pre + "mlp.c_fc.weight"] = blocks["wg"][i]
        out[pre + "mlp.c_fc.bias"] = blocks["bfc"][i]
        out[pre + "mlp.c_proj.weight"] = blocks["wd"][i]
        out[pre + "mlp.c_proj.bias"] = blocks["bproj"][i]
    return {k: np.ascontiguousarray(v) for k, v in out.items()}


register_hf_family(
    HFFamily(
        "gpt2",
        _gpt2_config_from_hf,
        _gpt2_config_to_hf,
        params_from_sd=_gpt2_params_from_sd,
        params_to_sd=_gpt2_params_to_sd,
    )
)


def infer_model_type(cfg: ModelConfig) -> str:
    """Best-fit HF family for a ModelConfig — the save path's dispatcher
    when the caller didn't record where the weights came from."""
    if cfg.norm_type == "layernorm":
        return "gpt2"
    if cfg.is_moe:
        return "mixtral"
    if cfg.rms_norm_offset:
        return "gemma"
    if cfg.qkv_bias:
        return "qwen2"
    return "llama"


# ---------------- checkpoint IO ----------------


def load_hf_config(path: str) -> dict:
    with open(os.path.join(path, "config.json")) as f:
        return json.load(f)


def load_model_config(path: str, is_critic: bool = False) -> ModelConfig:
    """Config-only load (no weights) — e.g. remote-generator workers that
    hold no local params."""
    hf_cfg = load_hf_config(path)
    cfg = HF_FAMILIES[hf_cfg["model_type"]].config_from_hf(hf_cfg)
    return cfg.as_critic() if is_critic else cfg


def load_hf_checkpoint(
    path: str, is_critic: bool = False, dtype=None
) -> "tuple[ModelConfig, Dict[str, Any]]":
    """Load an HF checkpoint dir (safetensors or torch .bin shards)."""
    hf_cfg = load_hf_config(path)
    family = HF_FAMILIES[hf_cfg["model_type"]]
    cfg = family.config_from_hf(hf_cfg)
    if is_critic:
        cfg = cfg.as_critic()
    sd: Dict[str, np.ndarray] = {}
    st_files = sorted(
        f for f in os.listdir(path) if f.endswith(".safetensors")
    )
    if st_files:
        from safetensors.numpy import load_file

        for f in st_files:
            sd.update(load_file(os.path.join(path, f)))
    else:
        import torch

        bins = sorted(f for f in os.listdir(path) if f.endswith(".bin"))
        if not bins:
            raise FileNotFoundError(f"no safetensors/bin shards in {path}")
        for f in bins:
            t = torch.load(
                os.path.join(path, f), map_location="cpu", weights_only=True
            )
            sd.update({k: v.float().numpy() for k, v in t.items()})
    params = family.params_from_sd(cfg, sd, dtype=dtype)
    logger.info(f"loaded HF checkpoint from {path} ({hf_cfg['model_type']})")
    return cfg, params


def save_hf_checkpoint(
    path: str,
    cfg: ModelConfig,
    params: Dict[str, Any],
    model_type: str = "qwen2",
    tokenizer=None,
    max_shard_bytes: int = 5 * 1024**3,
) -> None:
    """Write an HF-format checkpoint dir (safetensors + config.json) so the
    reference's eval tooling / vLLM / SGLang can consume our outputs.
    State dicts over `max_shard_bytes` split into the standard
    model-XXXXX-of-YYYYY.safetensors shards + index json (the layout
    transformers/vLLM expect for large models)."""
    from areal_tpu.base.distributed import is_primary

    # Host-gathering a process-spanning param tree is collective: every
    # group member computes the state dict, only jax process 0 writes.
    sd = HF_FAMILIES[model_type].params_to_sd(cfg, params)
    if not is_primary():
        return
    os.makedirs(path, exist_ok=True)
    from safetensors.numpy import save_file

    total = sum(v.nbytes for v in sd.values())
    if total <= max_shard_bytes:
        save_file(sd, os.path.join(path, "model.safetensors"))
    else:
        shards: list = [[]]
        size = 0
        for k in sd:
            if size + sd[k].nbytes > max_shard_bytes and shards[-1]:
                shards.append([])
                size = 0
            shards[-1].append(k)
            size += sd[k].nbytes
        n = len(shards)
        weight_map = {}
        for i, keys in enumerate(shards):
            fname = f"model-{i + 1:05d}-of-{n:05d}.safetensors"
            save_file({k: sd[k] for k in keys}, os.path.join(path, fname))
            weight_map.update({k: fname for k in keys})
        with open(
            os.path.join(path, "model.safetensors.index.json"), "w"
        ) as f:
            json.dump(
                {
                    "metadata": {"total_size": total},
                    "weight_map": weight_map,
                },
                f,
                indent=2,
            )
    with open(os.path.join(path, "config.json"), "w") as f:
        json.dump(HF_FAMILIES[model_type].config_to_hf(cfg), f, indent=2)
    if tokenizer is not None and hasattr(tokenizer, "save_pretrained"):
        tokenizer.save_pretrained(path)
