"""Scheduler client API: submit/wait/stop worker jobs.

Capability parity: realhf/scheduler/client.py (`SchedulerClient`,
`JobState` lifecycle, `JobException`).  Backends: `local` (subprocesses on
this host, areal_tpu/scheduler/local.py); multi-host TPU-pod launchers (GKE
jobsets / ray) plug in through the same interface.
"""

import dataclasses
import enum
from typing import Dict, List, Optional


class JobState(str, enum.Enum):
    NOT_FOUND = "NOT_FOUND"
    PENDING = "PENDING"
    RUNNING = "RUNNING"
    COMPLETED = "COMPLETED"
    FAILED = "FAILED"
    CANCELLED = "CANCELLED"

    def active(self) -> bool:
        return self in (JobState.PENDING, JobState.RUNNING)


@dataclasses.dataclass
class JobInfo:
    name: str
    state: JobState
    host: Optional[str] = None
    pid: Optional[int] = None
    exit_code: Optional[int] = None
    log_path: Optional[str] = None


def read_log_tail(path: Optional[str], n: int = 2048) -> str:
    """Last `n` bytes of a log file (seeks, never reads the whole file)."""
    if not path:
        return ""
    try:
        with open(path, "rb") as f:
            f.seek(0, 2)
            size = f.tell()
            f.seek(max(0, size - n))
            return f.read().decode(errors="replace")
    except OSError:
        return ""


class JobException(Exception):
    def __init__(self, run_name: str, worker_type: str, host: str, reason: JobState):
        super().__init__(f"Job {run_name}:{worker_type} {reason} at {host}")
        self.run_name = run_name
        self.worker_type = worker_type
        self.host = host
        self.reason = reason


class SchedulerClient:
    def __init__(self, expr_name: str, trial_name: str):
        self.expr_name = expr_name
        self.trial_name = trial_name
        self.run_name = f"{expr_name}_{trial_name}"

    def submit(self, worker_type: str, cmd: List[str], **kwargs) -> None:
        raise NotImplementedError()

    def submit_array(
        self, worker_type: str, cmd_of_index, count: int, **kwargs
    ) -> None:
        """Submit `count` jobs; cmd_of_index(i) -> argv list."""
        for i in range(count):
            self.submit(f"{worker_type}/{i}", cmd_of_index(i), **kwargs)

    def stop(self, worker_type: str) -> None:
        raise NotImplementedError()

    def stop_all(self) -> None:
        raise NotImplementedError()

    def find(self, worker_type: str) -> JobInfo:
        raise NotImplementedError()

    def find_all(self, pattern: str = "") -> List[JobInfo]:
        raise NotImplementedError()

    def wait(
        self,
        timeout: Optional[float] = None,
        check_status=(JobState.FAILED, JobState.CANCELLED, JobState.NOT_FOUND),
        remove_status=(JobState.COMPLETED,),
        update: bool = False,
    ) -> None:
        """Block until all jobs leave active states; raise JobException on
        any state in `check_status`."""
        raise NotImplementedError()


def make_scheduler(
    mode: str, expr_name: str, trial_name: str, **kwargs
) -> SchedulerClient:
    if mode == "local":
        from areal_tpu.scheduler.local import LocalSchedulerClient

        return LocalSchedulerClient(expr_name, trial_name, **kwargs)
    if mode == "slurm":
        from areal_tpu.scheduler.slurm import SlurmSchedulerClient

        return SlurmSchedulerClient(expr_name, trial_name, **kwargs)
    if mode == "tpu-pod":
        from areal_tpu.scheduler.tpu_pod import TPUPodSchedulerClient

        return TPUPodSchedulerClient(expr_name, trial_name, **kwargs)
    raise ValueError(f"unknown scheduler mode {mode!r}")
