"""TPU-pod scheduler client: one worker process per TPU-VM host.

Capability parity: the reference's Ray controller
(realhf/system/controller.py:448-641 RayController — Ray actors placed
across cluster nodes; realhf/scheduler/client.py:51 mode routing) — built
the TPU way: a v4/v5 pod slice is N independent VM hosts that each own
their local chips, and `gcloud compute tpus tpu-vm ssh --worker=i` is the
fabric-provided way to start a process on host i.  No cluster runtime to
install (Ray head/object store have no role: bulk data rides the trial's
ZMQ planes and jax.distributed forms the ICI/DCN world).

Each submitted worker becomes a detached remote process:

    nohup sh -c 'env ... <cmd> >log 2>&1; echo $? >log.exit' & echo $! >pid

so the ssh session can exit immediately while liveness (`kill -0 $pid`)
and the exit code (`log.exit`) stay poll-able — the same
pid-file/exit-file protocol the local scheduler uses in-process, lifted
over ssh.  The launcher (running on host 0 or off-pod) needs:

- a SHARED fileroot (GCS fuse / NFS) across hosts: worker-config pickles,
  file name-resolve, and checkpoints all live there (SURVEY §7: file/GCS
  name-resolve is the TPU-pod idiom replacing redis/etcd);
- `gcloud` authenticated for the project/zone (or any ssh transport with
  the same argv contract — injectable for tests and for bare-metal pods).

Workers then form the multi-controller world via
areal_tpu/base/distributed.py (coordinator address through name-resolve),
exactly like the in-process and slurm paths, and the recover retry loop in
apps/main.py works unchanged: stop_all + resubmit.
"""

import os
import shlex
import subprocess
import time
from typing import Callable, Dict, List, Optional, Sequence, Tuple

from areal_tpu.base import logging
from areal_tpu.scheduler.client import (
    JobException,
    JobInfo,
    JobState,
    SchedulerClient,
)

logger = logging.getLogger("tpu_pod")

# transport(argv) -> (returncode, stdout).  Default shells out to gcloud;
# tests inject a recorder.
Transport = Callable[[Sequence[str]], Tuple[int, str]]


def _subprocess_transport(argv: Sequence[str]) -> Tuple[int, str]:
    try:
        out = subprocess.run(
            list(argv), capture_output=True, text=True, timeout=300
        )
    except subprocess.TimeoutExpired:
        # A hung gcloud ssh is a transient transport failure (find() maps
        # nonzero rc to PENDING), not a reason to crash the launcher.
        return 255, "ssh transport timeout"
    return out.returncode, out.stdout + out.stderr


class TPUPodSchedulerClient(SchedulerClient):
    """`gcloud compute tpus tpu-vm ssh`-backed scheduler.

    Worker index i runs on pod host `i % num_hosts` — the canonical
    layout is one model worker per host (each host drives its local
    chips; the jit'd program spans hosts via jax.distributed).
    """

    def __init__(
        self,
        expr_name: str,
        trial_name: str,
        tpu_name: str,
        zone: Optional[str] = None,
        project: Optional[str] = None,
        num_hosts: int = 1,
        log_root: str = "/tmp/areal_tpu/logs",
        remote_workdir: str = "",
        env: Optional[Dict[str, str]] = None,
        gcloud_bin: str = "gcloud",
        poll_interval: float = 10.0,
        transport: Optional[Transport] = None,
    ):
        super().__init__(expr_name, trial_name)
        self.tpu_name = tpu_name
        self.zone = zone
        self.project = project
        self.num_hosts = num_hosts
        self.log_root = os.path.join(log_root, self.run_name)
        self.remote_workdir = remote_workdir
        self.env = dict(env or {})
        self.gcloud_bin = gcloud_bin
        self.poll_interval = poll_interval
        self.transport = transport or _subprocess_transport
        # worker_type -> (host_index, log_path, pid_path)
        self._jobs: Dict[str, Tuple[int, str, str]] = {}

    # -------------- argv construction (exposed for tests) --------------

    def ssh_argv(self, host_index: int, remote_cmd: str) -> List[str]:
        argv = [
            self.gcloud_bin, "compute", "tpus", "tpu-vm", "ssh",
            self.tpu_name,
            f"--worker={host_index}",
            "--command", remote_cmd,
        ]
        if self.zone:
            argv += ["--zone", self.zone]
        if self.project:
            argv += ["--project", self.project]
        return argv

    def _paths(self, worker_type: str) -> Tuple[str, str]:
        stem = os.path.join(
            self.log_root, worker_type.replace("/", "_")
        )
        return stem + ".log", stem + ".pid"

    def host_of(self, worker_type: str) -> int:
        """worker_type 'name/i' runs on host i % num_hosts."""
        _, _, idx = worker_type.rpartition("/")
        return (int(idx) if idx.isdigit() else 0) % self.num_hosts

    def launch_cmd(self, worker_type: str, cmd: List[str]) -> str:
        """The remote shell line that detaches one worker."""
        log, pid = self._paths(worker_type)
        envs = " ".join(
            f"{k}={shlex.quote(str(v))}" for k, v in self.env.items()
        )
        payload = " ".join(shlex.quote(c) for c in cmd)
        if envs:
            payload = f"env {envs} {payload}"
        cd = f"cd {shlex.quote(self.remote_workdir)} && " if (
            self.remote_workdir
        ) else ""
        # The tag comment makes the process findable for pkill on stop.
        tag = f"AREAL_JOB={self.run_name}:{worker_type}"
        inner = (
            f"{cd}{payload} >{shlex.quote(log)} 2>&1; "
            f"echo $? >{shlex.quote(log)}.exit"
        )
        # The brace group is load-bearing: a bare `a && b && nohup ... &
        # echo $!` backgrounds the WHOLE and-list (shell grammar binds `&`
        # to the list), racing the pid-file write against mkdir and
        # swallowing mkdir/rm failures into rc=0.
        return (
            f"mkdir -p {shlex.quote(self.log_root)} && "
            f"rm -f {shlex.quote(log)}.exit && "
            f"{{ nohup sh -c {shlex.quote(inner)} >/dev/null 2>&1 & "
            f"echo $! >{shlex.quote(pid)}; }} # {tag}"
        )

    # -------------- SchedulerClient surface --------------

    def submit(self, worker_type: str, cmd: List[str], **kwargs) -> None:
        host = kwargs.get("host_index", self.host_of(worker_type))
        rc, out = self.transport(
            self.ssh_argv(host, self.launch_cmd(worker_type, cmd))
        )
        if rc != 0:
            raise JobException(
                self.run_name, worker_type, f"host{host}", JobState.FAILED
            )
        log, pid = self._paths(worker_type)
        self._jobs[worker_type] = (host, log, pid)
        logger.info(
            f"submitted {worker_type} to {self.tpu_name} host {host}"
        )

    def _probe_cmd(self, worker_type: str) -> str:
        log, pid = self._paths(worker_type)
        # Prints one token: EXIT:<code> | RUNNING | LOST.
        return (
            f"if [ -f {shlex.quote(log)}.exit ]; then "
            f"echo EXIT:$(cat {shlex.quote(log)}.exit); "
            f"elif [ -f {shlex.quote(pid)} ] && "
            f"kill -0 $(cat {shlex.quote(pid)}) 2>/dev/null; then "
            f"echo RUNNING; else echo LOST; fi"
        )

    @staticmethod
    def _extract_token(out: str) -> Optional[str]:
        """Last probe token in the output.  gcloud/ssh freely interleave
        stderr warnings ('Permanently added ... known hosts'), so scan for
        OUR tokens instead of trusting the last line."""
        token = None
        for line in out.splitlines():
            line = line.strip()
            if line in ("RUNNING", "LOST") or line.startswith("EXIT:"):
                token = line
        return token

    def _info_from_token(
        self, worker_type: str, token: Optional[str]
    ) -> JobInfo:
        host, log, _ = self._jobs[worker_type]
        state = JobState.PENDING  # transient ssh failure: stay optimistic
        exit_code = None
        if token and token.startswith("EXIT:"):
            try:
                exit_code = int(token.split(":", 1)[1])
            except ValueError:
                exit_code = -1
            state = (
                JobState.COMPLETED if exit_code == 0 else JobState.FAILED
            )
        elif token == "RUNNING":
            state = JobState.RUNNING
        elif token == "LOST":
            # pid gone with no exit file: killed hard (OOM/host reboot).
            state = JobState.FAILED
        return JobInfo(
            name=worker_type,
            state=state,
            host=f"{self.tpu_name}:{host}",
            exit_code=exit_code,
            log_path=log,
        )

    def find(self, worker_type: str) -> JobInfo:
        if worker_type not in self._jobs:
            return JobInfo(name=worker_type, state=JobState.NOT_FOUND)
        host, _, _ = self._jobs[worker_type]
        rc, out = self.transport(
            self.ssh_argv(host, self._probe_cmd(worker_type))
        )
        return self._info_from_token(
            worker_type, self._extract_token(out) if rc == 0 else None
        )

    def find_all(self, pattern: str = "") -> List[JobInfo]:
        """ONE ssh round trip per HOST per sweep (not per worker): each
        host probes all its jobs in a single remote command emitting
        '<worker_type> <token>' lines."""
        wts = [wt for wt in list(self._jobs) if pattern in wt]
        by_host: Dict[int, List[str]] = {}
        for wt in wts:
            by_host.setdefault(self._jobs[wt][0], []).append(wt)
        infos: Dict[str, JobInfo] = {}
        for host, group in by_host.items():
            cmd = "; ".join(
                f"printf '%s ' {shlex.quote(wt)}; {self._probe_cmd(wt)}"
                for wt in group
            )
            rc, out = self.transport(self.ssh_argv(host, cmd))
            tokens: Dict[str, str] = {}
            if rc == 0:
                for line in out.splitlines():
                    parts = line.strip().rsplit(" ", 1)
                    if len(parts) == 2 and self._extract_token(parts[1]):
                        tokens[parts[0]] = parts[1]
            for wt in group:
                infos[wt] = self._info_from_token(wt, tokens.get(wt))
        return [infos[wt] for wt in wts]

    def stop(self, worker_type: str) -> None:
        if worker_type not in self._jobs:
            return
        host, log, pid = self._jobs.pop(worker_type)
        # TERM first; then poll briefly and escalate to KILL.  A worker
        # that ignores TERM would otherwise survive stop_all() holding the
        # TPU chip lease, and the recover retry's resubmitted worker fails
        # to initialize against the still-held devices.
        p = shlex.quote(pid)
        self.transport(
            self.ssh_argv(
                host,
                f"if [ -f {p} ]; then w=$(cat {p}); "
                f"pkill -TERM -P $w 2>/dev/null; "
                f"kill -TERM $w 2>/dev/null; "
                "for i in 1 2 3 4 5 6 7 8 9 10; do "
                "kill -0 $w 2>/dev/null || break; sleep 0.5; done; "
                "if kill -0 $w 2>/dev/null; then "
                f"pkill -KILL -P $w 2>/dev/null; "
                "kill -KILL $w 2>/dev/null; fi; fi; true",
            )
        )

    def stop_all(self) -> None:
        for wt in list(self._jobs):
            self.stop(wt)

    def wait(
        self,
        timeout: Optional[float] = None,
        check_status=(JobState.FAILED, JobState.CANCELLED, JobState.NOT_FOUND),
        remove_status=(JobState.COMPLETED,),
        update: bool = False,
    ) -> None:
        deadline = time.time() + timeout if timeout else None
        while self._jobs:
            for info in self.find_all():
                if info.state in check_status:
                    raise JobException(
                        self.run_name, info.name, info.host or "?",
                        info.state,
                    )
                if info.state in remove_status:
                    self._jobs.pop(info.name, None)
                    if update:
                        logger.info(f"{info.name} finished")
            if not self._jobs:
                return
            if deadline and time.time() > deadline:
                raise TimeoutError(
                    f"jobs still active after {timeout}s: "
                    f"{sorted(self._jobs)}"
                )
            time.sleep(self.poll_interval)
