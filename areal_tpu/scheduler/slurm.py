"""Slurm scheduler client.

Capability parity: realhf/scheduler/slurm/client.py:32 (`SlurmSchedulerClient`
— sbatch submission, squeue/sacct state polling, scancel teardown) — slimmed
to the sbatch surface a TPU-pod slurm deployment exposes; GPU/gres types and
the pyxis container plumbing are replaced by plain `--wrap` launches with an
optional container prefix.

SCOPE (deliberate): the PRODUCTION launcher for this framework is
`tpu_pod.py` — TPU fleets are allocated as whole pod slices by the cloud
control plane, so the reference's fragmentation-aware per-GPU resource
arithmetic (realhf/scheduler/slurm/utils.py:64, 870 LoC of allocate+commit
bookkeeping over gres strings) has no TPU counterpart: there is nothing to
fragment — a trial gets a pod slice or it doesn't.  This client exists for
shops that front their TPU VMs with slurm as a queue, and intentionally
stays at the sbatch/squeue surface (validated against mocked slurm
binaries in tests/test_slurm.py; no real cluster in CI).
"""

import os
import re
import subprocess
import time
from typing import Dict, List, Optional, Sequence

from areal_tpu.base import logging
from areal_tpu.scheduler.client import (
    JobException,
    JobInfo,
    JobState,
    SchedulerClient,
)

logger = logging.getLogger("slurm")

# Slurm state -> JobState (reference: slurm/utils.py STATUS_MAPPING).
_STATE_MAP = {
    "PENDING": JobState.PENDING,
    "CONFIGURING": JobState.PENDING,
    "RUNNING": JobState.RUNNING,
    "COMPLETING": JobState.RUNNING,
    "COMPLETED": JobState.COMPLETED,
    "FAILED": JobState.FAILED,
    "OUT_OF_MEMORY": JobState.FAILED,
    "NODE_FAIL": JobState.FAILED,
    "TIMEOUT": JobState.FAILED,
    "PREEMPTED": JobState.CANCELLED,
    "CANCELLED": JobState.CANCELLED,
}


def _run(cmd: Sequence[str]) -> str:
    out = subprocess.run(
        list(cmd), capture_output=True, text=True, check=True
    )
    return out.stdout


class SlurmSchedulerClient(SchedulerClient):
    """sbatch/squeue/scancel-backed scheduler.

    Each worker is one sbatch job (`--wrap`).  Worker env vars ride
    `--export`; a `wrap_cmd_prefix` (e.g. a container runtime) prepends the
    payload command.
    """

    def __init__(
        self,
        expr_name: str,
        trial_name: str,
        log_root: str = "/tmp/areal_tpu/logs",
        env: Optional[Dict[str, str]] = None,
        partition: Optional[str] = None,
        account: Optional[str] = None,
        time_limit: Optional[str] = None,
        cpus_per_task: int = 8,
        mem_gb: int = 32,
        nodes_per_job: int = 1,
        wrap_cmd_prefix: str = "",
        extra_sbatch_args: Sequence[str] = (),
    ):
        super().__init__(expr_name, trial_name)
        self.log_root = os.path.join(log_root, self.run_name)
        os.makedirs(self.log_root, exist_ok=True)
        self.env = dict(env or {})
        self.partition = partition
        self.account = account
        self.time_limit = time_limit
        self.cpus_per_task = cpus_per_task
        self.mem_gb = mem_gb
        self.nodes_per_job = nodes_per_job
        self.wrap_cmd_prefix = wrap_cmd_prefix
        self.extra_sbatch_args = list(extra_sbatch_args)
        self._jobs: Dict[str, str] = {}  # worker_type -> slurm job id
        self._logs: Dict[str, str] = {}

    # -------------- submission --------------

    def sbatch_cmd(self, worker_type: str, cmd: List[str]) -> List[str]:
        """The sbatch argv for one worker (exposed for tests/dry runs)."""
        log = os.path.join(
            self.log_root, worker_type.replace("/", "_") + ".log"
        )
        self._logs[worker_type] = log
        payload = " ".join(cmd)
        if self.wrap_cmd_prefix:
            payload = f"{self.wrap_cmd_prefix} {payload}"
        if self.env:
            # Env rides the wrapped command line, not --export: slurm's
            # --export parser splits on commas inside VALUES (e.g.
            # LIBTPU_INIT_ARGS flag lists), silently truncating them.
            import shlex

            pairs = " ".join(
                f"{k}={shlex.quote(str(v))}" for k, v in self.env.items()
            )
            payload = f"env {pairs} {payload}"
        argv = [
            "sbatch",
            "--parsable",
            f"--job-name={self.run_name}:{worker_type}",
            f"--output={log}",
            "--error=" + log,
            f"--nodes={self.nodes_per_job}",
            "--ntasks-per-node=1",
            f"--cpus-per-task={self.cpus_per_task}",
            f"--mem={self.mem_gb}G",
        ]
        if self.partition:
            argv.append(f"--partition={self.partition}")
        if self.account:
            argv.append(f"--account={self.account}")
        if self.time_limit:
            argv.append(f"--time={self.time_limit}")
        argv.extend(self.extra_sbatch_args)
        argv.append(f"--wrap={payload}")
        return argv

    def submit(self, worker_type: str, cmd: List[str], **kwargs) -> None:
        out = _run(self.sbatch_cmd(worker_type, cmd)).strip()
        # --parsable prints "<jobid>[;cluster]".
        job_id = out.split(";")[0].strip()
        if not re.fullmatch(r"\d+", job_id):
            raise RuntimeError(f"unparsable sbatch output: {out!r}")
        self._jobs[worker_type] = job_id
        logger.info(f"submitted {worker_type} as slurm job {job_id}")

    # -------------- state --------------

    def _query_states(self) -> Dict[str, JobState]:
        if not self._jobs:
            return {}
        ids = ",".join(self._jobs.values())
        by_id: Dict[str, JobState] = {}
        try:
            out = _run(["squeue", "-h", "-j", ids, "-o", "%i %T"])
            for line in out.splitlines():
                parts = line.split()
                if len(parts) >= 2:
                    state = parts[1].split("+")[0]
                    by_id[parts[0]] = _STATE_MAP.get(
                        state, JobState.RUNNING
                    )
        except subprocess.CalledProcessError:
            pass  # all jobs already left the queue
        missing = [j for j in self._jobs.values() if j not in by_id]
        if missing:
            # Finished jobs drop out of squeue; sacct has the verdict.
            try:
                out = _run(
                    [
                        "sacct", "-n", "-P", "-j", ",".join(missing),
                        "-o", "JobID,State",
                    ]
                )
                for line in out.splitlines():
                    parts = line.split("|")
                    if len(parts) >= 2 and "." not in parts[0]:
                        state = parts[1].split()[0].split("+")[0]
                        by_id[parts[0]] = _STATE_MAP.get(
                            state, JobState.COMPLETED
                        )
            except (subprocess.CalledProcessError, FileNotFoundError):
                pass
            # Still unaccounted (accounting disabled returns zero rows, or
            # record lag right after dequeue): gone = finished, not fatal.
            for j in missing:
                by_id.setdefault(j, JobState.COMPLETED)
        return {
            wt: by_id.get(jid, JobState.NOT_FOUND)
            for wt, jid in self._jobs.items()
        }

    def find(self, worker_type: str) -> JobInfo:
        state = self._query_states().get(worker_type, JobState.NOT_FOUND)
        return JobInfo(
            name=worker_type,
            state=state,
            log_path=self._logs.get(worker_type),
        )

    def find_all(self, pattern: str = "") -> List[JobInfo]:
        states = self._query_states()
        return [
            JobInfo(name=wt, state=st, log_path=self._logs.get(wt))
            for wt, st in states.items()
            if pattern in wt
        ]

    # -------------- teardown / wait --------------

    def stop(self, worker_type: str) -> None:
        job_id = self._jobs.get(worker_type)
        if job_id:
            subprocess.run(["scancel", job_id], capture_output=True)

    def stop_all(self) -> None:
        if self._jobs:
            subprocess.run(
                ["scancel", *self._jobs.values()], capture_output=True
            )

    def wait(
        self,
        timeout: Optional[float] = None,
        check_status=(JobState.FAILED, JobState.CANCELLED, JobState.NOT_FOUND),
        remove_status=(JobState.COMPLETED,),
        update: bool = False,
        poll_interval: float = 10.0,
    ) -> None:
        deadline = None if timeout is None else time.monotonic() + timeout
        left = set(self._jobs)
        while left:
            states = self._query_states()
            for wt in list(left):
                st = states.get(wt, JobState.NOT_FOUND)
                if st in check_status:
                    raise JobException(self.run_name, wt, "slurm", st)
                if st in remove_status:
                    left.discard(wt)
                    if update:
                        self._jobs.pop(wt, None)
            if not left:
                return
            if deadline is not None and time.monotonic() >= deadline:
                raise TimeoutError(
                    f"slurm jobs still active after {timeout}s: {sorted(left)}"
                )
            time.sleep(poll_interval)
