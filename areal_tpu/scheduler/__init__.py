from areal_tpu.scheduler.client import (  # noqa: F401
    JobException,
    JobInfo,
    JobState,
    SchedulerClient,
    make_scheduler,
)
