"""Local scheduler: worker jobs as subprocesses with per-job logs.

Capability parity: realhf/scheduler/local/client.py (subprocess spawn with
GPU isolation + per-worker logs).  TPU note: on a single host there is one
TPU runtime owner, so colocated jobs default to CPU (`JAX_PLATFORMS=cpu`)
unless the caller passes env overrides — the multi-chip story is one worker
process per host anyway (XLA SPMD runs the mesh inside one process).
"""

import os
import signal
import subprocess
import time
from typing import Dict, List, Optional

from areal_tpu.base import logging
from areal_tpu.scheduler.client import (
    JobException,
    JobInfo,
    JobState,
    SchedulerClient,
    read_log_tail,
)

logger = logging.getLogger("local_sched")


class LocalSchedulerClient(SchedulerClient):
    def __init__(
        self,
        expr_name: str,
        trial_name: str,
        log_root: str = "/tmp/areal_tpu/logs",
        env: Optional[Dict[str, str]] = None,
    ):
        super().__init__(expr_name, trial_name)
        self.log_root = os.path.join(log_root, self.run_name)
        os.makedirs(self.log_root, exist_ok=True)
        self.base_env = dict(env or {})
        self._procs: Dict[str, subprocess.Popen] = {}
        self._logs: Dict[str, str] = {}

    def submit(self, worker_type: str, cmd: List[str], env=None, **kwargs):
        if worker_type in self._procs:
            raise ValueError(f"job {worker_type!r} already submitted")
        log_path = os.path.join(
            self.log_root, worker_type.replace("/", "-") + ".log"
        )
        full_env = {**os.environ, **self.base_env, **(env or {})}
        with open(log_path, "wb") as logf:
            proc = subprocess.Popen(
                cmd,
                stdout=logf,
                stderr=subprocess.STDOUT,
                env=full_env,
                start_new_session=True,
            )
        self._procs[worker_type] = proc
        self._logs[worker_type] = log_path
        logger.info(
            f"submitted {worker_type} (pid {proc.pid}), log: {log_path}"
        )

    def _state(self, proc: subprocess.Popen) -> JobState:
        rc = proc.poll()
        if rc is None:
            return JobState.RUNNING
        if rc == 0:
            return JobState.COMPLETED
        if rc < 0 and -rc in (signal.SIGTERM, signal.SIGKILL):
            return JobState.CANCELLED
        return JobState.FAILED

    def find(self, worker_type: str) -> JobInfo:
        proc = self._procs.get(worker_type)
        if proc is None:
            return JobInfo(worker_type, JobState.NOT_FOUND)
        return JobInfo(
            worker_type,
            self._state(proc),
            host="localhost",
            pid=proc.pid,
            exit_code=proc.poll(),
            log_path=self._logs[worker_type],
        )

    def find_all(self, pattern: str = "") -> List[JobInfo]:
        return [
            self.find(w) for w in self._procs if pattern in w
        ]

    def stop(self, worker_type: str, timeout: float = 10.0) -> None:
        proc = self._procs.get(worker_type)
        if proc is None or proc.poll() is not None:
            return
        proc.terminate()
        try:
            proc.wait(timeout)
        except subprocess.TimeoutExpired:
            proc.kill()
            proc.wait()

    def stop_all(self) -> None:
        for w in list(self._procs):
            self.stop(w)

    def wait(
        self,
        timeout: Optional[float] = None,
        check_status=(JobState.FAILED, JobState.CANCELLED, JobState.NOT_FOUND),
        remove_status=(JobState.COMPLETED,),
        update: bool = False,
        poll_interval: float = 0.5,
    ) -> None:
        deadline = None if timeout is None else time.monotonic() + timeout
        left = set(self._procs)
        while left:
            for w in list(left):
                info = self.find(w)
                if info.state in check_status:
                    logger.error(
                        f"job {w} {info.state}; log tail:\n"
                        f"{read_log_tail(info.log_path)}"
                    )
                    raise JobException(
                        self.run_name, w, "localhost", info.state
                    )
                if info.state in remove_status:
                    left.discard(w)
                    if update:
                        self._procs.pop(w, None)
            if left:
                if deadline is not None and time.monotonic() > deadline:
                    raise TimeoutError(f"jobs still active: {sorted(left)}")
                time.sleep(poll_interval)
