"""Automatic checkpoint evaluator.

Capability parity: realhf/scheduler/evaluator.py:28-306
(`AutomaticEvaluator`: watch the trial's checkpoint dir, launch one eval
job per new checkpoint, log pass rates per global step) — condensed for
this runtime: evaluation runs in-process with the repo's own
GeneratorEngine (no external vLLM container), grades with the sympy-backed
`verify_math`, and writes one `eval_step_{N}.json` per checkpoint into the
trial's eval dir.
"""

import dataclasses
import json
import os
import re
import time
from typing import Dict, List, Optional

from areal_tpu.base import logging

logger = logging.getLogger("evaluator")


@dataclasses.dataclass
class EvalConfig:
    """What to evaluate and how to decode (reference: cli_args
    AutomaticEvaluator config: data_names, max_gen_tokens, greedy...)."""

    data_path: str  # jsonl rows: {"prompt", "solutions" or "answers"}
    tokenizer_path: Optional[str] = None  # None -> load from the ckpt dir
    max_new_tokens: int = 256
    n_samples: int = 1  # sequences per prompt (pass@k needs k>1)
    greedy: bool = True
    temperature: float = 1.0
    max_prompts: Optional[int] = None
    parallel: str = "d1"
    batch_size: int = 64
    # Applied to each row's prompt before tokenization (the reference's
    # prompt_type templating, e.g. a chat wrapper):
    #   --prompt-template $'<|user|>\n{prompt}\n<|assistant|>\n'
    prompt_template: str = "{prompt}"
    # "greedy": one greedy sample per prompt (cheap smoke eval).
    # "avg@K" (e.g. "avg@32"): the reference's headline protocol — K
    # temperature-1.0 samples per prompt, score = pass@1 AVERAGED over all
    # K·P samples with boxed-answer extraction (AReaL README.md:46-55:
    # "32 answers ... average pass@1", realhf/scheduler/evaluator.py).
    protocol: str = "greedy"

    def __post_init__(self):
        # Validate at CONSTRUCTION (i.e. CLI parse time) — a typo must not
        # silently grade under the wrong protocol, or crash an eval hours
        # later at int() time.
        parse_protocol(self.protocol)


def parse_protocol(proto: str) -> Optional[int]:
    """'greedy' -> None; 'avg@K'/'maj@K' -> K.  Anything else raises."""
    if proto == "greedy":
        return None
    m = re.fullmatch(r"(avg|maj)@(\d+)", proto)
    if not m or int(m.group(2)) < 1:
        raise ValueError(
            f"unknown eval protocol {proto!r}: use 'greedy', 'avg@K', or "
            "'maj@K' (e.g. avg@32, maj@8)"
        )
    return int(m.group(2))


_GRADER = None


def _grader():
    """Shared grader instance: the SAME math/code verification used for
    training rewards (interfaces/reward.py), so offline scores and RL
    rewards can never disagree on what counts as correct."""
    global _GRADER
    if _GRADER is None:
        from areal_tpu.interfaces.reward import MultiTaskRewardInterface

        _GRADER = MultiTaskRewardInterface()
    return _GRADER


def _load_rows(path: str, limit: Optional[int]) -> List[Dict]:
    rows = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if line:
                rows.append(json.loads(line))
            if limit is not None and len(rows) >= limit:
                break
    return rows


def evaluate_checkpoint(
    ckpt_dir: str, config: EvalConfig, seed: int = 0
) -> Dict[str, float]:
    """Generate over the held-out set with the checkpoint's weights and
    grade with verify_math.  Returns {'pass@1': ..., 'pass@n': ..., ...}."""
    import jax
    import numpy as np

    from areal_tpu.base import compilation_cache

    compilation_cache.enable()

    from areal_tpu.api.model_api import GenerationHyperparameters
    from areal_tpu.base.topology import ParallelConfig, make_mesh
    from areal_tpu.data.tokenizer import load_hf_tokenizer
    from areal_tpu.engines.generator import GeneratorEngine
    from areal_tpu.models.hf import registry as hf

    cfg, params = hf.load_hf_checkpoint(ckpt_dir)
    tokenizer = load_hf_tokenizer(config.tokenizer_path or ckpt_dir)
    pc = ParallelConfig.from_str(config.parallel)
    mesh = make_mesh(pc, jax.devices()[: pc.world_size])
    engine = GeneratorEngine(
        cfg,
        params,
        mesh,
        eos_token_id=tokenizer.eos_token_id,
        pad_token_id=getattr(tokenizer, "pad_token_id", None),
    )
    n, greedy, temperature = (
        config.n_samples, config.greedy, config.temperature,
    )
    k = parse_protocol(config.protocol)
    majority = config.protocol.startswith("maj@")
    if k is not None:
        # avg@K: K independent temp-1.0 samples per prompt; greedy would
        # collapse them into K copies of one answer.
        n, greedy, temperature = k, False, 1.0
    gconfig = GenerationHyperparameters(
        n=n,
        max_new_tokens=config.max_new_tokens,
        greedy=greedy,
        temperature=temperature,
    )

    # Multiple benchmarks per checkpoint (reference: comma-separated
    # data_names shipped to its eval harness): per-dataset metrics are
    # prefixed "<name>/"; flat keys stay the single-dataset aggregate /
    # unweighted mean so existing consumers keep working.
    datasets = _parse_datasets(config.data_path)
    result: Dict[str, float] = {}
    total_s = 0.0
    for name, path in datasets:
        one = _eval_one_dataset(
            engine, tokenizer, config, gconfig, n, path, seed,
            majority=majority,
        )
        total_s += one["eval_seconds"]
        if len(datasets) == 1:
            return one
        for k_, v in one.items():
            result[f"{name}/{k_}"] = v
    agg_keys = ["pass@1", f"pass@{n}", "pass@1_prompt_std"]
    if majority:
        agg_keys.append(f"maj@{n}")
    for key in agg_keys:
        vals = [result[f"{nm}/{key}"] for nm, _ in datasets]
        result[key] = float(np.mean(vals))
    result["samples_per_prompt"] = float(n)
    result["n_prompts"] = float(
        sum(result[f"{nm}/n_prompts"] for nm, _ in datasets)
    )
    result["n_samples"] = float(
        sum(result[f"{nm}/n_samples"] for nm, _ in datasets)
    )
    result["eval_seconds"] = total_s
    return result


def _parse_datasets(data_path: str):
    """'aime=/d/aime.jsonl,/d/math500.jsonl' -> [(name, path), ...]
    (name defaults to the file stem)."""
    out = []
    for part in data_path.split(","):
        part = part.strip()
        if not part:
            continue
        # 'name=path' only when the prefix is a plain label — a '=' after
        # any '/' is part of the path (hive-style '/data/date=2024/x.jsonl').
        # Dotted labels ('v1.5=/d/aime.jsonl') are labels when what follows
        # '=' is an explicit path ('/', './'); a bare relative filename
        # containing '=' and a dotted prefix ('temp=0.7.jsonl') is
        # ambiguous and REJECTED rather than silently misparsed — write
        # './temp=0.7.jsonl' (path) or 'label=./temp=0.7.jsonl'.
        prefix, _, rest = part.partition("=")
        if "=" in part and "/" not in prefix:
            if "." not in prefix or rest.startswith(("/", "./", "~")):
                name, path = prefix, rest
            else:
                raise ValueError(
                    f"ambiguous dataset spec {part!r}: dotted prefix "
                    f"{prefix!r} could be a label or part of a filename; "
                    "use an explicit path ('./file') or 'label=./file'"
                )
        else:
            name = os.path.splitext(os.path.basename(part))[0]
            path = part
        out.append((name.strip(), path.strip()))
    if not out:
        raise ValueError(f"no datasets in data_path {data_path!r}")
    names = [n for n, _ in out]
    if len(set(names)) != len(names):
        raise ValueError(
            f"duplicate dataset names in data_path {data_path!r}: label "
            "them apart with name=path"
        )
    return out


def _eval_one_dataset(
    engine, tokenizer, config: EvalConfig, gconfig, n: int, data_path: str,
    seed: int, majority: bool = False,
) -> Dict[str, float]:
    import numpy as np

    from areal_tpu.api.data_api import MicroBatchSpec, SequenceSample

    rows = _load_rows(data_path, config.max_prompts)
    n_correct = 0
    n_total = 0
    n_any = 0
    n_maj = 0
    prompt_acc: List[float] = []  # per-prompt mean correctness
    t0 = time.monotonic()
    for start in range(0, len(rows), config.batch_size):
        chunk = rows[start : start + config.batch_size]
        parts = []
        for i, r in enumerate(chunk):
            prompt = r["prompt"]
            # GPQA/MMLU-style rows: render lettered options under the
            # question (reference: evaluation/data_loader.py choice rows
            # + parser.py choice extraction); grading then goes through
            # verify_math's multiple-choice path on the letter gold.
            choices = r.get("choices")
            if choices:
                from areal_tpu.interfaces.math_verify import CHOICE_LETTERS

                if len(choices) > len(CHOICE_LETTERS):
                    raise ValueError(
                        f"row {r.get('query_id')!r} has {len(choices)} "
                        f"choices; at most {len(CHOICE_LETTERS)} supported"
                    )
                prompt = prompt + "\n" + "\n".join(
                    f"({CHOICE_LETTERS[j]}) {c}"
                    for j, c in enumerate(choices)
                )
            toks = np.asarray(
                tokenizer.encode(
                    config.prompt_template.format(prompt=prompt)
                ),
                dtype=np.int32,
            )
            if len(toks) == 0:
                toks = np.asarray([tokenizer.eos_token_id], np.int32)
            parts.append(
                SequenceSample(
                    keys={"packed_prompts"},
                    ids=[str(r.get("query_id", start + i))],
                    seqlens={"packed_prompts": [[len(toks)]]},
                    data={"packed_prompts": toks},
                )
            )
        batch = SequenceSample.gather(parts)
        out = engine.generate(
            batch, MicroBatchSpec(), gconfig, seed=seed + start
        )
        for r, one in zip(chunk, out.unpack()):
            # Same task dispatch as training rewards: math rows grade via
            # boxed-answer sympy verification, code rows run their test
            # cases in the sandbox (interfaces/reward.py + sandbox.py) —
            # the evaluator covers both halves of the reference's
            # math+code evaluation surface.
            task = r.get("task", "math")
            sols = r.get("solutions") or r.get("answers") or []
            if not sols:
                # Letter golds of choice rows ("answer": "B" /
                # reference's "choice_answer").  HF-style INT golds are
                # option indices ("answer": 0 means choice A) — note 0 is
                # falsy, so no `or` chains here.
                letter = r.get("answer")
                if letter is None:
                    letter = r.get("choice_answer")
                if isinstance(letter, int) and r.get("choices"):
                    from areal_tpu.interfaces.math_verify import (
                        CHOICE_LETTERS,
                    )

                    letter = CHOICE_LETTERS[letter]
                if letter is not None:
                    sols = [str(letter)]
            info = {
                "solutions": sols,
                "input_output": r.get("input_output"),
                # Row-level evidence for is_multi_choice gating: rows
                # that rendered a choices block grade through choice
                # extraction; rows without one keep the gold-string
                # inference (None).
                "choices": r.get("choices"),
            }
            bounds = one.cu_seqlens("packed_input_ids")
            toks_all = np.asarray(one.data["packed_input_ids"])
            pmask = np.asarray(one.data["prompt_mask"])
            any_ok = False
            row_ok = 0
            row_n = 0
            texts = []
            for s in range(len(bounds) - 1):
                lo, hi = bounds[s], bounds[s + 1]
                resp = toks_all[lo:hi][~pmask[lo:hi].astype(bool)]
                text = tokenizer.decode(resp.tolist())
                texts.append(text)
                ok = bool(_grader().verify(task, text, info))
                n_correct += ok
                row_ok += ok
                row_n += 1
                n_total += 1
                any_ok = any_ok or ok
            n_any += any_ok
            if majority:
                n_maj += _majority_correct(task, texts, info)
            prompt_acc.append(row_ok / max(row_n, 1))
    # pass@1 is the SAMPLE mean — under avg@K this is exactly the
    # reference's "average pass@1 over K samples" headline number.
    acc = np.asarray(prompt_acc, np.float64)
    result = {
        "pass@1": n_correct / max(n_total, 1),
        f"pass@{n}": n_any / max(len(rows), 1),
        "pass@1_prompt_std": float(acc.std()) if len(acc) else 0.0,
        "samples_per_prompt": float(n),
        "n_prompts": float(len(rows)),
        "n_samples": float(n_total),
        "eval_seconds": time.monotonic() - t0,
    }
    if majority:
        result[f"maj@{n}"] = n_maj / max(len(rows), 1)
    return result


def _majority_correct(task: str, texts, info) -> bool:
    """maj@K (reference: evaluation/rm_maj_eval.py group_pred): cluster
    the K sampled answers by pairwise equivalence, grade the LARGEST
    cluster's representative.  Equivalence uses the same grading stack
    (each candidate answer treated as the gold for its peers), so
    '1/2' and '0.5' vote together.  The fast string/Fraction match
    decides most pairs; when it fails on two extractable math answers,
    the sympy grader breaks the tie so symbolically equivalent forms
    ('\\sqrt{2}/2' vs '0.7071') also share a cluster — the same
    two-tier stack verify_math grades with."""
    from areal_tpu.interfaces.math_verify import (
        answers_match,
        extract_answer,
    )

    def _equiv(p: str, rep: str) -> bool:
        if answers_match(p, rep):
            return True
        if task == "math" and p and rep:
            from areal_tpu.interfaces.math_sympy import answers_match_sympy

            return bool(answers_match_sympy(p, rep))
        return False

    preds = [extract_answer(t) or "" for t in texts]
    clusters: List[List[int]] = []
    reps: List[str] = []
    for i, p in enumerate(preds):
        placed = False
        for ci, rep in enumerate(reps):
            # Unextractable answers cluster TOGETHER ("" == ""): a
            # no-answer majority must be able to win (and then grade
            # wrong), as in the reference's equal-string grouping.
            if _equiv(p, rep):
                clusters[ci].append(i)
                placed = True
                break
        if not placed:
            clusters.append([i])
            reps.append(p)
    best = max(range(len(clusters)), key=lambda ci: len(clusters[ci]))
    winner = texts[clusters[best][0]]
    return bool(_grader().verify(task, winner, info))


_STEP_RE = re.compile(r"^(?:step_|epoch\w*_)(\d+)$")


class AutomaticEvaluator:
    """Watch a checkpoint root; evaluate each new step dir exactly once.

    Layout produced by the master (system/master.py save):
        <fileroot>/checkpoints/<exp>/<trial>/<model>/step_<N>/
    Eval outputs land in <fileroot>/eval/<exp>/<trial>/eval_step_<N>.json.
    """

    def __init__(
        self,
        ckpt_root: str,
        output_dir: str,
        config: EvalConfig,
    ):
        self.ckpt_root = ckpt_root
        self.output_dir = output_dir
        self.config = config
        os.makedirs(output_dir, exist_ok=True)

    def _done_steps(self) -> set:
        done = set()
        for f in os.listdir(self.output_dir):
            m = re.match(r"^eval_step_(\d+)\.json$", f)
            if m:
                done.add(int(m.group(1)))
        return done

    def pending(self) -> List[int]:
        """Step numbers with a complete checkpoint but no eval output."""
        if not os.path.isdir(self.ckpt_root):
            return []
        steps = []
        done = self._done_steps()
        for d in os.listdir(self.ckpt_root):
            m = _STEP_RE.match(d)
            if not m:
                continue
            step = int(m.group(1))
            if step in done:
                continue
            if os.path.exists(
                os.path.join(self.ckpt_root, d, "config.json")
            ):
                steps.append(step)
        return sorted(steps)

    def step(self) -> List[int]:
        """Evaluate every pending checkpoint; returns evaluated steps."""
        ran = []
        for step in self.pending():
            ckpt = None
            for d in os.listdir(self.ckpt_root):
                m = _STEP_RE.match(d)
                if m and int(m.group(1)) == step:
                    ckpt = os.path.join(self.ckpt_root, d)
                    break
            logger.info(f"evaluating checkpoint step {step}: {ckpt}")
            result = evaluate_checkpoint(ckpt, self.config)
            result["global_step"] = float(step)
            out = os.path.join(self.output_dir, f"eval_step_{step}.json")
            with open(out + ".tmp", "w") as f:
                json.dump(result, f, indent=2)
            os.replace(out + ".tmp", out)
            # Rolling per-checkpoint score series (one line per eval) —
            # the training-curve artifact the reference evaluator logs to
            # wandb/tensorboard.
            with open(
                os.path.join(self.output_dir, "score_series.jsonl"), "a"
            ) as f:
                f.write(json.dumps(result) + "\n")
            logger.info(
                f"step {step}: pass@1={result['pass@1']:.4f} "
                f"({int(result['n_samples'])} samples)"
            )
            ran.append(step)
        return ran

    def watch(self, interval: float = 10.0, until: Optional[float] = None):
        """Poll loop (reference evaluator's thread loop, evaluator.py:120)."""
        while True:
            self.step()
            if until is not None and time.time() >= until:
                return
            time.sleep(interval)


def main():
    import argparse

    p = argparse.ArgumentParser(
        description="Evaluate trial checkpoints (pass@1 on a jsonl set)"
    )
    p.add_argument("--ckpt-root", required=True)
    p.add_argument("--output-dir", required=True)
    p.add_argument("--data", required=True)
    p.add_argument("--tokenizer", default=None)
    p.add_argument("--max-new-tokens", type=int, default=256)
    p.add_argument("--n-samples", type=int, default=1)
    p.add_argument("--max-prompts", type=int, default=None)
    p.add_argument("--parallel", default="d1")
    p.add_argument("--prompt-template", default="{prompt}",
                   help="format string applied to each prompt before "
                        "tokenization (chat wrappers etc.)")
    p.add_argument("--protocol", default="greedy",
                   help="'greedy', 'avg@K' (e.g. avg@32: the AIME "
                        "avg-of-32 pass@1 protocol at temperature 1.0), "
                        "or 'maj@K' (majority voting over K samples)")
    p.add_argument("--watch", action="store_true")
    p.add_argument("--interval", type=float, default=10.0)
    args = p.parse_args()
    ev = AutomaticEvaluator(
        args.ckpt_root,
        args.output_dir,
        EvalConfig(
            data_path=args.data,
            tokenizer_path=args.tokenizer,
            max_new_tokens=args.max_new_tokens,
            n_samples=args.n_samples,
            max_prompts=args.max_prompts,
            parallel=args.parallel,
            protocol=args.protocol,
            prompt_template=args.prompt_template,
        ),
    )
    if args.watch:
        ev.watch(args.interval)
    else:
        ev.step()


if __name__ == "__main__":
    main()
