"""areal_tpu: a TPU-native distributed RL/RLHF training framework for LLMs.

Built from scratch for TPU (JAX/XLA/Pallas/pjit), with the capability surface of
AReaL (ReaLHF): RL algorithms expressed as dataflow graphs of model function
calls (generate / inference / train_step) over named models (actor, critic,
ref, reward), executed by a master/worker runtime with per-call parallel
layouts realized as `jax.sharding` meshes instead of NCCL process-group
surgery.

Package layout:
    base/        low-level utilities: name-resolve KV, mesh topology, FFD
                 packing, frequency control, logging, cluster spec
    api/         declarative core: config dataclasses, dataflow graph (DFG),
                 SequenceSample packed batches, engine/interface registries
    models/      JAX transformer (packed varlen, rotary, RMSNorm, MoE) +
                 HuggingFace checkpoint conversion (llama/qwen2 families)
    ops/         numerics: flash attention (Pallas), GAE scan, sampling
    parallel/    sharding rules, ring attention (context parallel), pipeline
    engines/     train (optax+FSDP), inference, generator (continuous
                 batching), mock (CPU tests)
    interfaces/  algorithms: SFT, PPO/GRPO actor+critic, reward verification
    data/        datasets (jsonl prompt / math-code), tokenizer utils
    system/      master/worker runtime, asyncio executor, buffers, streams
    scheduler/   job launch: local subprocess, TPU pod
"""

__version__ = "0.1.0"
