"""Autoscaled verifier service pool: horizontally-scaled reward grading.

The reference offloads grading to a remote FaaS (realhf/functioncall/) so
one slow sandboxed-code grade cannot backpressure the training loop; this
module gives the same property a fleet shape, reusing every elastic
primitive from ``system/fleet.py``:

- :class:`VerifierWorker` — one grading server.  Wraps the reward
  service's HTTP handler (``interfaces/reward_service.py``) with fleet
  membership: ``announce()`` registers the worker under
  ``names.verifier_servers`` with a keepalive TTL and a heartbeat thread,
  ``announce_metrics()`` joins the metrics plane so the supervisor can
  scrape it, and an ``AREAL_FAULTS`` kill crashes it WITHOUT
  deregistering (flight-recorder dump included) — exactly like a
  preempted node, leaving TTL expiry to evict it.

- :func:`verifier_discovery` — live membership ``{server_id: url}`` as a
  callable, the grading mirror of ``fleet.fleet_discovery``.

- :class:`VerifierPool` — the load-balancing client ``RewardFabric`` and
  ``MultiTaskRewardInterface`` plug in wherever a ``RemoteVerifier``
  fits (it exposes the same ``verify_batch``).  Each grade batch goes to
  the least-loaded live backend whose :class:`fleet.CircuitBreaker` is
  closed (an open breaker past cooldown admits the batch as its
  half-open probe); every attempt gets its own deadline; a failed
  attempt retries on a DIFFERENT server; when no backend remains the
  pool degrades to the in-process verifier registry — a dead fleet
  degrades throughput, never correctness.

The ``FleetSupervisor`` scales the pool through a ``SupervisorLane``
(``system/fleet.py``) keyed on the ``grade_latency_p99`` /
``verifier_queue_depth`` SLO signals from ``apps/metrics_report.py``.
"""

import json
import threading
import time
from http.server import ThreadingHTTPServer
from typing import Any, Callable, Dict, List, Optional

from areal_tpu.base import faults as faults_mod
from areal_tpu.base import logging, metrics, name_resolve, names, tracer
from areal_tpu.interfaces import reward_service
from areal_tpu.system.fleet import CircuitBreaker

logger = logging.getLogger("verifier_pool")

_REG = metrics.default_registry()

# Client-observed grade round-trip latency per backend; the fleet signal
# `grade_latency_p99` (apps/metrics_report.py) and the supervisor's
# verifier lane scale on its p99.
_M_GRADE_SECONDS = _REG.histogram(
    "areal_verifier_grade_seconds",
    "grade batch round-trip latency by backend server",
    ("server",),
    buckets=(0.01, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0, 120.0),
)
# Items the pool client currently has in flight across all backends —
# the `verifier_queue_depth` capacity signal.
_M_QUEUE_DEPTH = _REG.gauge(
    "areal_verifier_queue_depth",
    "grade items in flight across pool backends (client view)",
)
_M_POOL_SERVERS = _REG.gauge(
    "areal_verifier_pool_servers",
    "live verifier servers visible to the pool client",
)
_M_BREAKER_OPEN = _REG.gauge(
    "areal_verifier_breaker_open",
    "verifier backends currently circuit-broken open",
)
_M_BREAKER_TRANS = _REG.counter(
    "areal_verifier_breaker_transitions_total",
    "verifier breaker state transitions",
    ("state",),
)
_M_REDISPATCH = _REG.counter(
    "areal_verifier_redispatch_total",
    "grade batches retried on a different verifier server",
    ("reason",),
)
_M_GRADES = _REG.counter(
    "areal_verifier_grades_total",
    "items graded through the pool, by route",
    ("route",),  # pooled | local
)
# Worker-side signals (one per verifier process).
_M_WORKER_INFLIGHT = _REG.gauge(
    "areal_verifier_worker_inflight",
    "grade items currently being verified by this worker",
)
_M_WORKER_GRADED = _REG.counter(
    "areal_verifier_worker_graded_total",
    "items this worker graded, by task",
    ("task",),
)
_M_FAULTS = _REG.counter(
    "areal_verifier_faults_total",
    "chaos faults fired inside verifier workers",
    ("kind",),
)


def verifier_discovery(
    experiment: str, trial: str
) -> Callable[[], Dict[str, str]]:
    """``{server_id: url}`` of currently-announced verifier workers, as
    a closure the pool client polls at refresh time.  Expired keepalives
    (dead workers) drop out via the name_resolve TTL reaper, so a
    preempted worker leaves the pool without anyone deregistering it."""
    root = names.verifier_servers(experiment, trial)

    def discover() -> Dict[str, str]:
        out: Dict[str, str] = {}
        for key in name_resolve.find_subtree(root):
            sid = key[len(root) + 1:]
            try:
                out[sid] = name_resolve.get(key)
            except Exception:  # noqa: BLE001 — expired between list and get
                continue
        return out

    return discover


def list_verifiers(experiment: str, trial: str) -> List[str]:
    """Sorted live verifier server ids — the membership view the
    supervisor's verifier lane counts against its target size."""
    root = names.verifier_servers(experiment, trial)
    return sorted(
        key[len(root) + 1:] for key in name_resolve.find_subtree(root)
    )


class _WorkerHandler(reward_service._Handler):
    """The reward-service handler plus fleet-worker accounting: in-flight
    gauges, per-task graded counters, chaos injection at the ``grade``
    point, and a ``/metrics`` route for the supervisor's scrapes."""

    def do_GET(self):
        worker = getattr(self.server, "worker", None)
        path = self.path.split("?")[0]
        if path == "/health":
            inflight = worker.inflight if worker is not None else 0
            self._send(200, {"status": "ok", "inflight": inflight})
        elif path == "/metrics":
            body = metrics.default_registry().expose().encode()
            self.send_response(200)
            self.send_header("Content-Type", "text/plain; version=0.0.4")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)
        else:
            self._send(404, {"error": "unknown path"})

    def do_POST(self):
        if self.path != "/verify":
            self._send(404, {"error": "unknown path"})
            return
        token = getattr(self.server, "auth_token", None)
        if token and self.headers.get("X-Areal-Token") != token:
            self._send(403, {"error": "bad token"})
            return
        worker: "VerifierWorker" = self.server.worker
        try:
            n = int(self.headers.get("Content-Length", 0))
            req = json.loads(self.rfile.read(n))
            items = req["items"]
            results = worker.grade_batch(items)
            tracer.flush()
            self._send(200, {"results": results})
        except Exception as e:  # noqa: BLE001 — report to the client
            try:
                self._send(500, {"error": repr(e)})
            except Exception:  # noqa: BLE001 — crashed mid-reply
                pass


class VerifierWorker:
    """One grading server in the verifier fleet.

    Same graders and wire format as ``reward_service.serve`` (the
    verifier registry dispatches on the item's ``task`` key), plus fleet
    membership and chaos hooks.  A ``kill`` fault crashes the worker
    like a preemption: no deregistration, no draining — the flight
    recorder dumps its last grades and the TTL reaper evicts the
    announcement.  ``slow``/``error`` faults fire per grade batch at the
    ``grade`` injection point, so a chaos leg can inflate one backend's
    latency 10x without touching product code.
    """

    def __init__(
        self,
        host: str = "127.0.0.1",
        port: int = 0,
        token: str = "",
        max_workers: int = 8,
        faults: Optional[faults_mod.FaultInjector] = None,
    ):
        tracer.configure(role="verifier", rank=port)
        self.max_workers = max_workers
        self._stop = threading.Event()
        self._crashed = False
        self._announce_key: Optional[str] = None
        self.inflight = 0
        self.graded = 0
        self._lock = threading.Lock()
        self._faults = (
            faults
            if faults is not None
            else faults_mod.FaultInjector.from_env(
                on_fire=lambda kind: _M_FAULTS.labels(kind).inc()
            )
        )
        self.httpd = ThreadingHTTPServer((host, port), _WorkerHandler)
        self.httpd.auth_token = token
        self.httpd.worker = self
        self.port = self.httpd.server_port
        self.url = f"http://{host}:{self.port}"
        self._thread = threading.Thread(
            target=self.httpd.serve_forever, daemon=True
        )
        self._thread.start()
        if self._faults is not None and self._faults.kill_spec is not None:
            threading.Thread(target=self._kill_loop, daemon=True).start()
        logger.info(f"verifier worker at {self.url}")

    # ---------------- grading ----------------

    def grade_batch(self, items: List[Dict[str, Any]]) -> List[bool]:
        if self._faults is not None:
            self._faults.fire("grade")
        with self._lock:
            self.inflight += len(items)
            _M_WORKER_INFLIGHT.set(self.inflight)
        try:
            from concurrent.futures import ThreadPoolExecutor

            with tracer.span("verify", cat="host", n=len(items)):
                with ThreadPoolExecutor(self.max_workers) as ex:
                    results = list(
                        ex.map(reward_service.grade_item, items)
                    )
            for it in items:
                _M_WORKER_GRADED.labels(str(it.get("task", "math"))).inc()
            return results
        finally:
            with self._lock:
                self.inflight -= len(items)
                self.graded += len(items)
                _M_WORKER_INFLIGHT.set(self.inflight)

    # ---------------- chaos ----------------

    def _kill_loop(self) -> None:
        """Once the injector's `kill` fault is due, tear the worker down
        as a CRASH — no deregistration, the announcement expires by TTL,
        and the flight ring dumps the post-mortem."""
        while not self._stop.is_set():
            if self._faults.kill_due():
                logger.warning("FAULT kill: crashing the verifier worker")
                self._crashed = True
                tracer.flight_event("kill", port=self.port)
                tracer.flight_dump(
                    "fault_kill", role="verifier", rank=self.port
                )
                self.close()
                return
            self._stop.wait(0.05)

    # ---------------- fleet membership ----------------

    def announce(
        self,
        experiment: str,
        trial: str,
        server_id: Optional[str] = None,
        ttl: float = 10.0,
    ) -> str:
        """Join the verifier fleet under ``names.verifier_servers`` with
        a keepalive TTL and a heartbeat thread at ttl/3.  Default id is
        port-stable ``v<port>`` so a restart on the same port resumes
        the same fleet identity (and the pool's breaker probe re-closes
        it instead of treating it as a new member)."""
        sid = server_id or f"v{self.port}"
        key = names.verifier_server(experiment, trial, sid)
        name_resolve.add(
            key, self.url, keepalive_ttl=ttl, replace=True,
            delete_on_exit=True,
        )
        self._announce_key = key
        beat_s = max(ttl / 3.0, 0.05)

        def beat():
            repo = name_resolve.default()
            while not self._stop.wait(beat_s):
                try:
                    repo.touch(key)
                except Exception:  # noqa: BLE001 — key deleted: stop beating
                    return

        threading.Thread(target=beat, daemon=True).start()
        logger.info(f"announced verifier {sid} (ttl {ttl}s)")
        return sid

    def announce_metrics(
        self, experiment: str, trial: str, server_id: str
    ) -> None:
        """Join the metrics plane so metrics_report / the supervisor
        scrape this worker's /metrics alongside the rest of the trial."""
        name_resolve.add(
            names.metrics_endpoint(experiment, trial, f"verifier/{server_id}"),
            self.url,
            keepalive_ttl=30.0,
            replace=True,
            delete_on_exit=True,
        )

    def close(self) -> None:
        self._stop.set()
        if self._announce_key is not None and not self._crashed:
            try:
                name_resolve.delete(self._announce_key)
            except Exception:  # noqa: BLE001 — already expired is fine
                pass
        if self._faults is not None:
            self._faults.release()
        try:
            self.httpd.shutdown()
            self.httpd.server_close()
        except Exception:  # noqa: BLE001 — double-close on crash path
            pass


class VerifierPool:
    """Load-balancing client over the announced verifier fleet.

    Drop-in wherever a ``RemoteVerifier`` fits: ``verify_batch(items)``
    returns one bool per item, always.  Dispatch policy per batch:

    1. refresh membership (rate-limited to ``refresh_s``); joins get a
       breaker and start taking batches within one refresh, leaves stop
       receiving new batches (in-flight round-trips just fail over);
    2. pick the least-loaded backend whose breaker admits work — a
       closed breaker, or an open one past cooldown whose half-open
       probe rides this very batch;
    3. one POST with a per-attempt deadline (``attempt_timeout_s``);
    4. on failure: count the typed reason
       (``areal_reward_remote_errors_total{reason}`` — ``shape`` for a
       result-length mismatch), trip the backend's breaker, and retry
       the batch on a DIFFERENT server (``max_attempts`` total);
    5. exhausted or empty fleet: degrade to the in-process verifier
       registry (log-once), unless ``local_fallback=False``.

    Thread-safe — ``RewardFabric`` calls ``verify_batch`` from its
    grading pool threads.
    """

    def __init__(
        self,
        discovery: Optional[Callable[[], Dict[str, str]]] = None,
        servers: Optional[Dict[str, str]] = None,
        attempt_timeout_s: float = 60.0,
        max_attempts: int = 3,
        backoff_s: float = 0.05,
        refresh_s: float = 1.0,
        breaker_threshold: int = 3,
        breaker_cooldown_s: float = 5.0,
        token: str = "",
        local_fallback: bool = True,
        clock: Callable[[], float] = time.monotonic,
    ):
        if discovery is None and servers is None:
            raise ValueError("VerifierPool needs a discovery fn or servers")
        self.discovery = discovery or (lambda: dict(servers or {}))
        self.attempt_timeout_s = attempt_timeout_s
        self.max_attempts = max(1, int(max_attempts))
        self.backoff_s = backoff_s
        self.refresh_s = refresh_s
        self.breaker_threshold = breaker_threshold
        self.breaker_cooldown_s = breaker_cooldown_s
        self.token = token
        self.local_fallback = local_fallback
        self._clock = clock
        self._lock = threading.Lock()
        self._members: Dict[str, str] = {}  # sid -> url
        self._inflight: Dict[str, int] = {}  # sid -> items in flight
        # Breakers persist across leave/rejoin: a worker restarting on
        # the same port (same sid) is re-admitted via a half-open probe,
        # not treated as a pristine stranger.
        self.breakers: Dict[str, CircuitBreaker] = {}
        self._last_refresh: Optional[float] = None
        self._pending = 0
        self._degraded = False
        # Plain counters for harness assertions (metrics mirror them).
        self.graded_pooled = 0
        self.graded_local = 0
        self.redispatches = 0
        self._refresh(force=True)

    # ---------------- membership ----------------

    def _breaker(self, sid: str) -> CircuitBreaker:
        br = self.breakers.get(sid)
        if br is None:
            def on_transition(state: str, _sid: str = sid) -> None:
                _M_BREAKER_TRANS.labels(state).inc()
                logger.info(f"verifier breaker[{_sid}] -> {state}")

            br = CircuitBreaker(
                threshold=self.breaker_threshold,
                cooldown_s=self.breaker_cooldown_s,
                on_transition=on_transition,
                clock=self._clock,
            )
            self.breakers[sid] = br
        return br

    def _refresh(self, force: bool = False) -> None:
        with self._lock:
            now = self._clock()
            if (
                not force
                and self._last_refresh is not None
                and now - self._last_refresh < self.refresh_s
            ):
                return
            self._last_refresh = now
            try:
                live = dict(self.discovery())
            except Exception as e:  # noqa: BLE001 — registry hiccup
                logger.warning(f"verifier discovery failed: {e!r}")
                return
            joined = set(live) - set(self._members)
            left = set(self._members) - set(live)
            self._members = live
            for sid in joined:
                self._breaker(sid)
                self._inflight.setdefault(sid, 0)
                logger.info(f"verifier joined the pool: {sid}")
            for sid in left:
                logger.info(f"verifier left the pool: {sid}")
            _M_POOL_SERVERS.set(len(self._members))
            _M_BREAKER_OPEN.set(
                sum(
                    1
                    for sid in self._members
                    if self.breakers[sid].state == CircuitBreaker.OPEN
                )
            )

    def servers(self) -> Dict[str, str]:
        self._refresh()
        with self._lock:
            return dict(self._members)

    def _choose(self, exclude: set) -> Optional[str]:
        """Least-loaded live backend whose breaker admits work; an open
        breaker past cooldown is begun as a half-open probe — the probe
        IS the next grade batch, no separate health poll.  Probes take
        priority over healthy backends: a healed server must rejoin
        promptly even when the rest of the pool could absorb the load
        (a failed probe just re-opens and the batch retries elsewhere)."""
        with self._lock:
            for sid in sorted(self._members):
                if sid in exclude:
                    continue
                br = self.breakers[sid]
                if br.probe_due():
                    br.begin_probe()
                    return sid
            candidates = [
                sid
                for sid in self._members
                if sid not in exclude
                and self.breakers[sid].allow_dispatch()
            ]
            if not candidates:
                return None
            return min(
                candidates, key=lambda s: (self._inflight.get(s, 0), s)
            )

    # ---------------- grading ----------------

    def verify_batch(self, items: List[Dict[str, Any]]) -> List[bool]:
        self._refresh()
        with self._lock:
            self._pending += len(items)
            _M_QUEUE_DEPTH.set(self._pending)
        try:
            return self._verify_locked_out(items)
        finally:
            with self._lock:
                self._pending -= len(items)
                _M_QUEUE_DEPTH.set(self._pending)

    def _verify_locked_out(self, items: List[Dict[str, Any]]) -> List[bool]:
        exclude: set = set()
        last_err: Optional[BaseException] = None
        for attempt in range(1, self.max_attempts + 1):
            sid = self._choose(exclude)
            if sid is None:
                break
            with self._lock:
                url = self._members.get(sid)
                if url is None:
                    continue
                self._inflight[sid] = self._inflight.get(sid, 0) + 1
            t0 = time.monotonic()
            try:
                results = reward_service.post_verify(
                    url, items, self.attempt_timeout_s, self.token
                )
            except reward_service._RETRYABLE as e:
                last_err = e
                reason = reward_service._error_reason(e)
                reward_service._M_REMOTE_ERRORS.labels(reason).inc()
                br = self.breakers[sid]
                br.record_failure()
                _M_BREAKER_OPEN.set(
                    sum(
                        1
                        for b in self.breakers.values()
                        if b.state == CircuitBreaker.OPEN
                    )
                )
                exclude.add(sid)
                if attempt < self.max_attempts:
                    self.redispatches += 1
                    _M_REDISPATCH.labels(reason).inc()
                    logger.debug(
                        f"grade batch failed on {sid} ({reason}: {e!r}); "
                        f"retrying on a different server "
                        f"({attempt}/{self.max_attempts})"
                    )
                    if self.backoff_s > 0:
                        time.sleep(self.backoff_s)
                continue
            finally:
                with self._lock:
                    self._inflight[sid] = max(
                        0, self._inflight.get(sid, 1) - 1
                    )
            self.breakers[sid].record_success()
            _M_GRADE_SECONDS.labels(sid).observe(time.monotonic() - t0)
            _M_GRADES.labels("pooled").inc(len(items))
            with self._lock:
                self.graded_pooled += len(items)
            if self._degraded:
                self._degraded = False
                logger.info("verifier pool recovered from degradation")
            return results
        if not self.local_fallback:
            raise last_err if last_err is not None else RuntimeError(
                "verifier pool has no live backends"
            )
        log = logger.debug if self._degraded else logger.warning
        log(
            "verifier pool degraded to in-process grading "
            + (
                f"(last: {last_err!r})"
                if last_err is not None
                else "(no live backends)"
            )
        )
        self._degraded = True
        _M_GRADES.labels("local").inc(len(items))
        with self._lock:
            self.graded_local += len(items)
        return [reward_service.grade_item(it) for it in items]
