"""Agent-serving episodes: multi-turn tool use on persistent KV state.

Turns the generator from a one-shot sampler into an agent-serving
runtime (ROADMAP open item 5; the RLAX / Podracer agentic workload,
PAPERS.md arxiv 2512.06392 / 2104.06272).  An episode is a conversation
the serving side keeps HOT: each assistant turn decodes until it emits a
tool-call stop sequence (or EOS / a budget), the slot parks at a chunk
boundary with its KV pages intact, the tool result is appended as a
chunked-prefill admission onto the SAME pages, and decode resumes —
so turn N+1 prefills only the observation, never the transcript.

Layering:

- ``Turn`` / ``Episode`` — the state machine's record types.  An
  episode flattens to ONE replay :class:`~areal_tpu.system.replay.Trajectory`
  (version-stamped per turn, turn metadata in ``data``) so the training
  plane ingests agent episodes exactly like single-shot groups.
- ``ToolExecutor`` — a registry of named tools (calculator +
  sandboxed python-exec built in) with per-tool timeouts and
  fault-injection hooks (``AREAL_FAULTS="error@point=tool:calculator"``
  breaks exactly one tool), so the chaos harness can prove an episode
  survives a flaky environment.
- ``EpisodeController`` — drives the loop: start → parse tool call out
  of the stop-terminated turn → execute tool → extend with the
  observation → repeat until a terminal turn or the turn/token budget
  trips.  A continuation that hits a reclaimed slot raises the typed
  :class:`~areal_tpu.api.model_api.SlotGoneError`; the controller
  recovers by re-admitting the FULL conversation, which the transcript
  prefix cache turns into a tail re-prefill.

The controller is token-centric and transport-agnostic: it drives any
client exposing ``start/extend/release`` — :class:`EngineEpisodeClient`
(in-process engine; tests and check legs) or
:class:`~areal_tpu.api.model_api.LLMAPIClient` episode methods (HTTP
against a gen server).  Tool-call parsing and observation encoding are
injected callables, because what a "tool call" looks like is a property
of the model's chat template, not of the serving plane.
"""

import ast
import dataclasses
import threading
import time
from concurrent.futures import ThreadPoolExecutor
from concurrent.futures import TimeoutError as FuturesTimeout
from typing import Any, Callable, Dict, List, Optional, Sequence

from areal_tpu.api.model_api import (
    GenerationHyperparameters,
    SlotGoneError,
)
from areal_tpu.base import logging, metrics, tracer
from areal_tpu.base.faults import FaultError, FaultInjector
from areal_tpu.system.replay import Trajectory

logger = logging.getLogger("episode")

_reg = metrics.default_registry()
# Assistant turns completed, by how the turn ended — the fleet signal
# separating "agents are calling tools" (stop) from "agents are rambling
# into their budgets" (length/budget).
_M_TURNS = _reg.counter(
    "areal_episode_turns_total",
    "assistant turns completed, by stop reason",
    ("stop_reason",),
)
_M_ACTIVE = _reg.gauge(
    "areal_episode_active",
    "episodes currently running under a controller",
)
_M_TOOL_SECONDS = _reg.histogram(
    "areal_episode_tool_seconds",
    "tool execution latency, by tool",
    ("tool",),
)
_M_EPISODES = _reg.counter(
    "areal_episode_completed_total",
    "episodes finished, by terminal reason",
    ("reason",),
)
_M_TOOL_ERRORS = _reg.counter(
    "areal_episode_tool_errors_total",
    "tool executions that failed, by tool and error kind",
    ("tool", "kind"),
)


# ---------------------------------------------------------------------------
# state machine records
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class Turn:
    """One step of an episode: either an assistant decode (``role ==
    "assistant"``, carries logprobs + stop_reason) or a tool observation
    (``role == "tool"``, carries the tool name/latency/outcome).  Each
    turn is stamped with the weight version that produced it so replay
    admission can reason about mid-episode weight pushes."""

    index: int
    role: str  # "assistant" | "tool"
    tokens: List[int]
    logprobs: List[float] = dataclasses.field(default_factory=list)
    stop_reason: str = ""  # assistant: stop | eos | length | budget
    tool_name: str = ""
    tool_ok: bool = True
    tool_latency_s: float = 0.0
    version: int = 0  # weight version when this turn finished
    version_start: int = 0  # weight version when this turn started


@dataclasses.dataclass
class Episode:
    """A full multi-turn conversation and its terminal outcome."""

    episode_id: str
    prompt_ids: List[int]
    turns: List[Turn] = dataclasses.field(default_factory=list)
    status: str = "running"  # running | done
    stop_reason: str = ""  # eos | length | budget | max_turns | no_tool_call
    slot_lost: int = 0  # times the controller re-admitted after SlotGone
    reward: Optional[float] = None

    @property
    def assistant_turns(self) -> int:
        return sum(1 for t in self.turns if t.role == "assistant")

    def transcript(self) -> List[int]:
        """The full token transcript: prompt plus every turn in order —
        exactly the sequence sitting on the serving slot's KV pages."""
        out = list(self.prompt_ids)
        for t in self.turns:
            out.extend(t.tokens)
        return out

    def response_text_tokens(self) -> List[int]:
        """Everything after the prompt (assistant + tool tokens)."""
        out: List[int] = []
        for t in self.turns:
            out.extend(t.tokens)
        return out

    def to_trajectory(self, qid: str = "", birth_time: float = 0.0
                      ) -> Trajectory:
        """Flatten to ONE replay trajectory (group size 1): the prompt
        plus the concatenated turns, with tool-observation tokens carrying
        zero logprobs (they were injected, not sampled — the trainer masks
        them via the per-turn spans in ``data``).  ``version_start`` is the
        version the FIRST assistant turn started under and ``version_end``
        the version the LAST finished under, so bounded-staleness admission
        sees the episode's true age even across mid-episode pushes."""
        toks: List[int] = []
        lps: List[float] = []
        spans: List[Dict[str, Any]] = []
        for t in self.turns:
            spans.append(
                {
                    "index": t.index,
                    "role": t.role,
                    "start": len(toks),
                    "len": len(t.tokens),
                    "stop_reason": t.stop_reason,
                    "tool_name": t.tool_name,
                    "tool_ok": t.tool_ok,
                    "version": t.version,
                }
            )
            toks.extend(t.tokens)
            lps.extend(
                t.logprobs if t.role == "assistant" and t.logprobs
                else [0.0] * len(t.tokens)
            )
        a_turns = [t for t in self.turns if t.role == "assistant"]
        v0 = a_turns[0].version_start if a_turns else 0
        v1 = a_turns[-1].version if a_turns else 0
        last_reason = a_turns[-1].stop_reason if a_turns else ""
        return Trajectory(
            qid=qid or self.episode_id,
            prompt_ids=list(self.prompt_ids),
            output_ids=[toks],
            output_logprobs=[lps],
            no_eos=[last_reason != "eos"],
            version_start=v0,
            version_end=v1,
            birth_time=birth_time,
            data={
                "episode": {
                    "episode_id": self.episode_id,
                    "stop_reason": self.stop_reason,
                    "turns": spans,
                    "slot_lost": self.slot_lost,
                    "reward": self.reward,
                }
            },
        )


# ---------------------------------------------------------------------------
# tool executor registry
# ---------------------------------------------------------------------------


class ToolError(RuntimeError):
    """A tool execution failed; ``kind`` is the counter label
    (timeout | fault | error | unknown_tool)."""

    def __init__(self, kind: str, detail: str = ""):
        super().__init__(f"tool failed ({kind}): {detail}")
        self.kind = kind
        self.detail = detail


@dataclasses.dataclass(frozen=True)
class ToolCall:
    """A parsed tool invocation: a registry name plus a raw argument
    string (the tool decides how to interpret it)."""

    name: str
    args: str = ""


@dataclasses.dataclass
class _ToolSpec:
    fn: Callable[[str], str]
    timeout_s: float


def _calculator(args: str) -> str:
    """Arithmetic on a literal expression — numbers and ``+ - * / // %
    **`` with parentheses, evaluated over a parsed AST so no name lookup
    or call can ever run (``eval`` never sees the string)."""
    allowed_binops = (
        ast.Add, ast.Sub, ast.Mult, ast.Div, ast.FloorDiv, ast.Mod,
        ast.Pow,
    )

    def ev(node):
        if isinstance(node, ast.Expression):
            return ev(node.body)
        if isinstance(node, ast.Constant) and isinstance(
            node.value, (int, float)
        ):
            return node.value
        if isinstance(node, ast.UnaryOp) and isinstance(
            node.op, (ast.UAdd, ast.USub)
        ):
            v = ev(node.operand)
            return v if isinstance(node.op, ast.UAdd) else -v
        if isinstance(node, ast.BinOp) and isinstance(
            node.op, allowed_binops
        ):
            lhs, rhs = ev(node.left), ev(node.right)
            if isinstance(node.op, ast.Add):
                return lhs + rhs
            if isinstance(node.op, ast.Sub):
                return lhs - rhs
            if isinstance(node.op, ast.Mult):
                return lhs * rhs
            if isinstance(node.op, ast.Div):
                return lhs / rhs
            if isinstance(node.op, ast.FloorDiv):
                return lhs // rhs
            if isinstance(node.op, ast.Mod):
                return lhs % rhs
            return lhs ** rhs
        raise ValueError(f"disallowed expression node {type(node).__name__}")

    tree = ast.parse(args.strip(), mode="eval")
    val = ev(tree)
    # Render ints without a trailing .0 so observations stay compact.
    if isinstance(val, float) and val.is_integer() and abs(val) < 1e15:
        val = int(val)
    return str(val)


def _python_exec(args: str, timeout_s: float = 10.0) -> str:
    """Run a program in the OS sandbox (network-off when the kernel
    allows, rlimits always) and return its stdout; nonzero exit raises.
    The per-call wall clock is enforced by the ToolExecutor's timeout
    AND passed through so the sandbox reaps the process group itself."""
    from areal_tpu.interfaces.sandbox import run_sandboxed

    rc, out = run_sandboxed(
        ["python3", "-c", args], timeout_s=timeout_s
    )
    if rc != 0:
        raise ToolError("error", f"exit status {rc}: {out[-500:]}")
    return out


class ToolExecutor:
    """Registry of named tools with per-tool timeouts and fault hooks.

    ``run`` executes the tool on a worker thread bounded by the tool's
    timeout; before running it fires the injector at ``tool:<name>`` so a
    chaos spec (``AREAL_FAULTS="error@point=tool:python_exec&times=1"``)
    can break exactly one execution.  Failures come back as
    :class:`ToolError` with a typed ``kind`` — the controller turns them
    into an error observation instead of killing the episode, because an
    agent seeing "tool failed" is a training signal, not a crash.
    """

    def __init__(
        self,
        timeout_s: float = 10.0,
        faults: Optional[FaultInjector] = None,
        register_builtins: bool = True,
    ):
        self.default_timeout_s = float(timeout_s)
        self.faults = faults if faults is not None else FaultInjector.from_env()
        self._tools: Dict[str, _ToolSpec] = {}
        self._pool = ThreadPoolExecutor(
            max_workers=8, thread_name_prefix="tool"
        )
        if register_builtins:
            self.register("calculator", _calculator)
            # The sandbox tool reads its timeout from its own registry
            # entry at call time, so a later re-register with a custom
            # timeout applies to the subprocess reaper too.
            self.register(
                "python_exec",
                lambda a: _python_exec(
                    a, self._tools["python_exec"].timeout_s
                ),
            )

    def register(
        self,
        name: str,
        fn: Callable[[str], str],
        timeout_s: Optional[float] = None,
    ) -> None:
        self._tools[name] = _ToolSpec(
            fn=fn,
            timeout_s=(
                self.default_timeout_s if timeout_s is None
                else float(timeout_s)
            ),
        )

    def names(self) -> List[str]:
        return sorted(self._tools)

    def run(self, call: ToolCall) -> str:
        """Execute one tool call; returns its observation string or
        raises :class:`ToolError`.  Latency (success or failure) lands in
        ``areal_episode_tool_seconds{tool}``."""
        spec = self._tools.get(call.name)
        t0 = time.monotonic()
        try:
            if spec is None:
                raise ToolError("unknown_tool", call.name)
            if self.faults is not None:
                try:
                    self.faults.fire(f"tool:{call.name}")
                except FaultError as e:
                    raise ToolError("fault", repr(e)) from e
            fut = self._pool.submit(spec.fn, call.args)
            try:
                out = fut.result(timeout=spec.timeout_s + 1.0)
            except FuturesTimeout:
                fut.cancel()
                raise ToolError(
                    "timeout", f"{call.name} > {spec.timeout_s:.1f}s"
                ) from None
            except ToolError:
                raise
            except Exception as e:  # noqa: BLE001 — typed for the agent
                raise ToolError("error", repr(e)) from e
            return str(out)
        except ToolError as e:
            _M_TOOL_ERRORS.labels(call.name, e.kind).inc()
            raise
        finally:
            _M_TOOL_SECONDS.labels(call.name).observe(
                time.monotonic() - t0
            )


# ---------------------------------------------------------------------------
# episode clients (engine-backed; the HTTP client lives in model_api)
# ---------------------------------------------------------------------------


class EngineEpisodeClient:
    """Episode ops against an in-process GeneratorEngine.

    Mirrors the gen server's park loop: when a turn comes back parked
    (``None`` — a weight push interrupted mid-turn), wait for the pusher
    to clear the interrupt, then resume on the same pages.  Weight
    versions are stamped from ``version()`` when provided (the server
    tracks its own counter; in-process harnesses pass a lambda).
    """

    def __init__(
        self,
        engine: Any,
        gconfig: GenerationHyperparameters,
        token_budget: int = 0,
        seed: int = 0,
        version: Optional[Callable[[], int]] = None,
        lock: Optional[threading.Lock] = None,
    ):
        self.engine = engine
        self.gconfig = gconfig
        self.token_budget = int(token_budget)
        self.seed = int(seed)
        self._version = version or (lambda: 0)
        # Serializes episode ops against weight pushes, matching the gen
        # server's engine lock; release it while parked so the pusher can
        # take it.
        self._lock = lock if lock is not None else threading.Lock()

    def version(self) -> int:
        return int(self._version())

    def _drive(self, fn: Callable[[], Optional[Dict]], ep_id: str) -> Dict:
        with self._lock:
            out = fn()
        while out is None:
            while self.engine.interrupt_requested:
                time.sleep(0.005)
            with self._lock:
                out = self.engine.episode_resume(ep_id)
        return out

    def start(self, ep_id: str, prompt_ids: Sequence[int]) -> Dict:
        return self._drive(
            lambda: self.engine.episode_start(
                ep_id,
                list(prompt_ids),
                self.gconfig,
                token_budget=self.token_budget,
                seed=self.seed,
            ),
            ep_id,
        )

    def extend(self, ep_id: str, obs_ids: Sequence[int]) -> Dict:
        return self._drive(
            lambda: self.engine.episode_extend(ep_id, list(obs_ids)),
            ep_id,
        )

    def release(self, ep_id: str) -> None:
        with self._lock:
            self.engine.episode_release(ep_id)


class ServerEpisodeClient:
    """Episode ops over an :class:`~areal_tpu.api.model_api.LLMAPIClient`
    (the HTTP surface); SlotGoneError propagates from the client's typed
    409 handling.  The server parks/resumes internally, so responses are
    always complete turns."""

    def __init__(
        self,
        api_client: Any,
        gconfig: GenerationHyperparameters,
        token_budget: int = 0,
        seed: int = 0,
        trace_id: Optional[str] = None,
    ):
        self.api = api_client
        self.gconfig = gconfig
        self.token_budget = int(token_budget)
        self.seed = int(seed)
        # Rides every turn of the episode to the server (HTTP header /
        # ZMQ frame), keeping the whole multi-turn conversation on one
        # causal timeline.
        self.trace_id = trace_id
        self._last_version = 0

    def version(self) -> int:
        return self._last_version

    def _note_version(self, out: Dict) -> Dict:
        self._last_version = int(out.get("version", self._last_version))
        return out

    def start(self, ep_id: str, prompt_ids: Sequence[int]) -> Dict:
        kw: Dict[str, Any] = {}
        if self.trace_id:
            # Only plumbed when set — duck-typed clients predating the
            # lineage plane keep working without the kwarg.
            kw["trace_id"] = self.trace_id
        return self._note_version(
            self.api.episode_start(
                ep_id, prompt_ids, self.gconfig,
                token_budget=self.token_budget, seed=self.seed, **kw,
            )
        )

    def extend(self, ep_id: str, obs_ids: Sequence[int]) -> Dict:
        return self._note_version(self.api.episode_extend(ep_id, obs_ids))

    def release(self, ep_id: str) -> None:
        self.api.episode_release(ep_id)


# ---------------------------------------------------------------------------
# controller
# ---------------------------------------------------------------------------


class EpisodeController:
    """Drives ``Episode`` state machines over an episode client.

    ``parse_tool_call(tokens) -> Optional[ToolCall]`` inspects a finished
    assistant turn (the stop-sequence tokens are KEPT in the output, so
    the parser sees the full call); ``encode_observation(call, text,
    ok) -> tokens`` renders the tool result back into model tokens.
    Both are injected: the wire format of a tool call belongs to the
    chat template, not the serving plane.

    Terminal conditions, in precedence order: the turn ended without a
    stop sequence (eos / length / budget), the parser found no tool call
    (``no_tool_call``), or ``max_turns`` assistant turns completed.
    """

    def __init__(
        self,
        client: Any,
        tools: ToolExecutor,
        parse_tool_call: Callable[[List[int]], Optional[ToolCall]],
        encode_observation: Callable[[ToolCall, str, bool], List[int]],
        max_turns: int = 4,
    ):
        if max_turns < 1:
            raise ValueError(f"max_turns must be >= 1, got {max_turns}")
        self.client = client
        self.tools = tools
        self.parse_tool_call = parse_tool_call
        self.encode_observation = encode_observation
        self.max_turns = int(max_turns)

    # -- client ops with SlotGone recovery --------------------------------

    def _extend_or_readmit(
        self, ep: Episode, obs: List[int]
    ) -> Dict:
        """Append the observation; if the serving side reclaimed our slot
        (eviction under pool pressure, server restart), re-admit the FULL
        conversation — the published transcript prefixes turn that into a
        near-free shared admission plus an observation-sized prefill.
        The observation's tool turn is already on ``ep.turns``, so the
        re-admission transcript ends with ``obs``."""
        try:
            return self.client.extend(ep.episode_id, obs)
        except SlotGoneError as e:
            ep.slot_lost += 1
            transcript = ep.transcript()
            logger.warning(
                f"episode {ep.episode_id}: slot lost ({e.reason}); "
                f"re-admitting {len(transcript)} tokens via the prefix "
                f"cache"
            )
            return self.client.start(ep.episode_id, transcript)

    # -- the loop ---------------------------------------------------------

    def run_episode(
        self,
        episode_id: str,
        prompt_ids: Sequence[int],
        trace_id: Optional[str] = None,
    ) -> Episode:
        ep = Episode(episode_id=episode_id, prompt_ids=list(prompt_ids))
        # Child spans carry the trace_id only when the dispatcher minted
        # one — an untraced in-process episode emits plain spans.
        targs: Dict[str, Any] = (
            {"trace_id": trace_id} if trace_id else {}
        )
        _M_ACTIVE.inc()
        try:
            v0 = self.client.version()
            with tracer.span(
                "episode_turn", episode_id=episode_id, turn=0, **targs
            ):
                out = self.client.start(episode_id, prompt_ids)
            while True:
                reason = str(out.get("stop_reason", ""))
                ep.turns.append(
                    Turn(
                        index=len(ep.turns),
                        role="assistant",
                        tokens=[int(t) for t in out.get("tokens", [])],
                        logprobs=[
                            float(x) for x in out.get("logprobs", [])
                        ],
                        stop_reason=reason,
                        version=self.client.version(),
                        version_start=v0,
                    )
                )
                _M_TURNS.labels(reason or "unknown").inc()
                if reason != "stop":
                    ep.stop_reason = reason or "unknown"
                    break
                if ep.assistant_turns >= self.max_turns:
                    ep.stop_reason = "max_turns"
                    break
                call = self.parse_tool_call(ep.turns[-1].tokens)
                if call is None:
                    ep.stop_reason = "no_tool_call"
                    break
                t0 = time.monotonic()
                try:
                    result = self.tools.run(call)
                    ok = True
                except ToolError as e:
                    result = f"tool error ({e.kind}): {e.detail}"
                    ok = False
                latency = time.monotonic() - t0
                obs = [
                    int(t)
                    for t in self.encode_observation(call, result, ok)
                ]
                ep.turns.append(
                    Turn(
                        index=len(ep.turns),
                        role="tool",
                        tokens=obs,
                        tool_name=call.name,
                        tool_ok=ok,
                        tool_latency_s=latency,
                        version=self.client.version(),
                        version_start=self.client.version(),
                    )
                )
                v0 = self.client.version()
                with tracer.span(
                    "episode_turn",
                    episode_id=episode_id,
                    turn=len(ep.turns),
                    **targs,
                ):
                    out = self._extend_or_readmit(ep, obs)
        finally:
            _M_ACTIVE.dec()
            try:
                self.client.release(ep.episode_id)
            except Exception:  # noqa: BLE001 — slot may already be gone
                pass
        ep.status = "done"
        _M_EPISODES.labels(ep.stop_reason).inc()
        return ep


def make_episode_runner(
    tools: ToolExecutor,
    parse_tool_call: Callable[[List[int]], Optional[ToolCall]],
    encode_observation: Callable[[ToolCall, str, bool], List[int]],
    gconfig: GenerationHyperparameters,
    max_turns: int = 4,
    token_budget: int = 0,
    seed: int = 0,
) -> Callable[[Any, str, Sequence[int]], Episode]:
    """Build the ``episode_runner(client, qid, prompt_ids)`` hook the
    rollout controller dispatches episodes through: each call wraps the
    chosen server's API client in a :class:`ServerEpisodeClient` and
    runs one full episode against it (slot pinning means the whole
    episode stays on that server)."""

    def run(
        api_client: Any,
        qid: str,
        prompt_ids: Sequence[int],
        trace_id: Optional[str] = None,
    ) -> Episode:
        controller = EpisodeController(
            ServerEpisodeClient(
                api_client, gconfig, token_budget=token_budget, seed=seed,
                trace_id=trace_id,
            ),
            tools,
            parse_tool_call,
            encode_observation,
            max_turns=max_turns,
        )
        return controller.run_episode(qid, prompt_ids, trace_id=trace_id)

    return run


# ---------------------------------------------------------------------------
# async reward fabric glue
# ---------------------------------------------------------------------------


class RewardFabric:
    """Async facade over the verifier-backend registry: ``submit`` hands
    a grading job to a bounded thread pool and returns a Future, so
    episode completion never blocks on a sandboxed unit-test run.

    ``remote`` is anything exposing ``verify_batch(items)`` — a
    :class:`~areal_tpu.interfaces.reward_service.RemoteVerifier` (one
    fixed FaaS URL, typed-retry + local fallback) or a
    :class:`~areal_tpu.system.verifier_pool.VerifierPool` (load-balanced
    over the announced verifier fleet with per-server breakers and
    retry-to-a-different-server).  Either way a dead backend degrades to
    in-process grading, never drops rewards; without a remote, jobs
    grade in-process via the same registry the service dispatches on.

    ``on_result(task, passed)`` fires as each grade completes — the hook
    the task-mixture curriculum hangs ``observe_reward`` on, so per-task
    reward curves update live while grading stays async."""

    def __init__(
        self,
        remote: Any = None,
        max_workers: int = 8,
        on_result: Optional[Callable[[str, bool], None]] = None,
    ):
        self.remote = remote
        self.on_result = on_result
        self._pool = ThreadPoolExecutor(
            max_workers=max_workers, thread_name_prefix="reward"
        )

    def _grade(self, item: Dict[str, Any]) -> bool:
        if self.remote is not None:
            ok = bool(self.remote.verify_batch([item])[0])
        else:
            from areal_tpu.interfaces.reward_service import grade_item

            ok = bool(grade_item(item))
        if self.on_result is not None:
            try:
                self.on_result(str(item.get("task", "")), ok)
            except Exception:  # noqa: BLE001 — curriculum is advisory
                logger.exception("reward on_result hook failed")
        return ok

    def submit(
        self, task: str, text: str, payload: Dict[str, Any],
        trace_id: str = "",
    ):
        """Grade asynchronously; the item travels in the opaque
        ``{"task", "text", "payload"}`` schema every registered backend
        round-trips without key remapping.  A ``trace_id`` rides the item
        so the grader's ``graded`` lineage stamp joins the sample's
        causal timeline (with the task echoed for per-task attribution)."""
        item = {"task": task, "text": text, "payload": dict(payload)}
        if trace_id:
            item["trace_id"] = trace_id
        return self._pool.submit(self._grade, item)

    def grade(
        self, task: str, text: str, payload: Dict[str, Any],
        timeout_s: Optional[float] = None,
    ) -> bool:
        return self.submit(task, text, payload).result(timeout=timeout_s)
