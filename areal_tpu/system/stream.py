"""ZMQ master⇄worker request-reply stream for multi-process trials.

Capability parity: realhf/system/request_reply_stream.py (ZMQ PUSH/PULL
pairs + a syn-ack protocol for ordered delivery) — simplified: one ROUTER
socket on the master and a DEALER per worker gives per-peer FIFO ordering
from ZMQ/TCP itself, so no syn-ack layer is needed.  Request/response
matching uses explicit request ids (the master pipelines many concurrent
requests per worker from the asyncio DFG executor).

Discovery mirrors the reference: the master publishes its tcp address via
name_resolve (names.request_reply_stream) and every worker announces itself
with a hello frame carrying its index.

Payloads are pickled python dicts (SequenceSample metadata/arrays are
numpy-based); this is the CONTROL plane — bulk tensors live on device and
move via jax collectives / device_put (areal_tpu/parallel/realloc.py).

Liveness: each worker runs a heartbeat thread on its OWN dealer socket
(zmq sockets are single-threaded; the serve loop blocks for the whole
duration of an inline MFC, so beats must not share its socket) sending
``{"type": "beat", "worker_index": i}`` every ``worker_heartbeat_s``.
``ZMQWorkerPool.request`` takes a deadline (default: the pool's
``mfc_timeout_s``); on expiry a fresh heartbeat means "slow" (the
deadline re-arms), a stale one means "dead" — the worker's in-flight
futures fail with ``WorkerDeadError`` and its hello slot is cleared so
``wait_workers`` re-arms for a relaunched replacement.  With
``mfc_timeout_s=None`` (the default) the request path is the original
single ``await`` — zero overhead off the hot path.
"""

import asyncio
import pickle
from collections import deque
from typing import Any, Dict, Optional, Set, Tuple

import zmq
import zmq.asyncio

from areal_tpu.base import logging, name_resolve, names, network
from areal_tpu.system.master import (
    PoolClosedError,
    WorkerDeadError,
    WorkerPool,
    pool_metrics,
)

logger = logging.getLogger("stream")

STREAM_NAME = "master"

# req_ids of deadline-expired requests, kept so a late reply is dropped as
# an ACCOUNTED orphan (debug log + counter), not warned as an anomaly.
# Bounded: timed-out ids older than this many entries age out and a
# straggler reply for them downgrades to the "unknown" reason.
_TIMED_OUT_KEEP = 4096

_UNSET = object()


class ZMQWorkerPool(WorkerPool):
    """Master side: ROUTER socket, one outstanding-request table."""

    def __init__(
        self,
        experiment_name: str,
        trial_name: str,
        n_workers: int,
        mfc_timeout_s: Optional[float] = None,
        worker_heartbeat_s: float = 5.0,
    ):
        self._n_workers = n_workers
        self.mfc_timeout_s = mfc_timeout_s
        self.worker_heartbeat_s = worker_heartbeat_s
        # A worker is "dead" only when a deadline expired AND its beats
        # are older than this grace (3 missed beats); a long blocking MFC
        # keeps beating from its heartbeat thread and stays "slow".
        self._beat_grace_s = max(3.0 * worker_heartbeat_s, 1.0)
        self._ctx = zmq.asyncio.Context()
        self._sock = self._ctx.socket(zmq.ROUTER)
        # bind_to_random_port probes and binds atomically (no TOCTOU).
        port = self._sock.bind_to_random_port("tcp://*")
        host = network.gethostip()
        self._addr = f"tcp://{host}:{port}"
        name_resolve.add(
            names.request_reply_stream(experiment_name, trial_name, STREAM_NAME),
            self._addr,
            replace=True,
        )
        # req_id -> (future, worker_id); worker_id lets a death fail
        # exactly the futures parked on the dead peer.
        self._pending: Dict[int, Tuple[asyncio.Future, int]] = {}
        self._hello: Dict[int, bytes] = {}  # worker index -> zmq identity
        self._ident2worker: Dict[bytes, int] = {}
        self._hello_event = asyncio.Event()
        self._last_beat: Dict[int, float] = {}  # worker index -> loop time
        self._dead_workers: Set[int] = set()
        self._timed_out: Set[int] = set()
        self._timed_out_order: deque = deque()
        self._next_req_id = 0
        self._recv_task = None
        self._closed = False
        self._m_worker_dead, self._m_mfc_timeout, self._m_orphans = (
            pool_metrics()
        )
        logger.info(f"master stream bound at {self._addr}")

    @property
    def n_workers(self) -> int:
        return self._n_workers

    @property
    def dead_workers(self) -> Set[int]:
        return set(self._dead_workers)

    def _ensure_recv_loop(self):
        if self._recv_task is None:
            self._recv_task = asyncio.get_running_loop().create_task(
                self._recv_loop()
            )

    def _note_beat(self, worker_index: int):
        self._last_beat[worker_index] = asyncio.get_running_loop().time()

    def _fail_pending(self, exc: Exception):
        for fut, _wid in self._pending.values():
            if not fut.done():
                fut.set_exception(exc)
        self._pending.clear()

    async def _recv_loop(self):
        try:
            while True:
                ident, payload = await self._sock.recv_multipart()
                try:
                    msg = pickle.loads(payload)
                except Exception as e:  # corrupt frame: drop, keep serving
                    logger.error(f"undecodable frame from {ident!r}: {e!r}")
                    continue
                mtype = msg.get("type")
                if mtype == "beat":
                    self._note_beat(int(msg["worker_index"]))
                    continue
                if mtype == "hello":
                    widx = int(msg["worker_index"])
                    self._hello[widx] = ident
                    self._ident2worker[ident] = widx
                    self._note_beat(widx)
                    if widx in self._dead_workers:
                        # A relaunched replacement re-announced itself:
                        # it is a fresh peer with no model state (the
                        # master replays it via _restore_worker_state).
                        self._dead_workers.discard(widx)
                        logger.info(f"worker {widx} re-joined the stream")
                    if len(self._hello) >= self._n_workers:
                        self._hello_event.set()
                    continue
                req_id = msg.get("req_id")
                entry = self._pending.pop(req_id, None)
                widx = self._ident2worker.get(ident)
                if widx is not None:
                    # Any traffic is proof of life.
                    self._note_beat(widx)
                if entry is None:
                    if req_id in self._timed_out:
                        # Late reply to a deadline-expired request: the
                        # normal aftermath of a "slow" verdict, accounted
                        # and dropped without alarm.
                        self._m_orphans.labels("timed_out").inc()
                        logger.debug(
                            f"late reply for timed-out req_id={req_id} "
                            "dropped"
                        )
                    else:
                        self._m_orphans.labels("unknown").inc()
                        logger.warning(f"orphan reply req_id={req_id}")
                    continue
                fut, _wid = entry
                if fut.done():  # request cancelled during teardown
                    continue
                if msg.get("error"):
                    fut.set_exception(RuntimeError(msg["error"]))
                else:
                    fut.set_result(msg["result"])
        except asyncio.CancelledError:
            # Pool teardown must not strand awaiting requests: anyone
            # still parked on a future gets a typed "pool closed" error
            # instead of hanging forever.
            self._fail_pending(PoolClosedError("worker pool closed"))
            raise
        except Exception as e:
            # A dead recv loop must not strand awaiting requests: fail them.
            logger.error(f"stream recv loop died: {e!r}")
            self._fail_pending(RuntimeError(f"stream recv loop died: {e!r}"))
            raise

    async def wait_workers(self, timeout: float = 300.0):
        """Block until every worker has said hello.

        Re-armable: a worker declared dead clears its hello slot and the
        event, so a second call waits for the relaunched replacement.
        """
        self._ensure_recv_loop()
        await asyncio.wait_for(self._hello_event.wait(), timeout)
        logger.info(f"all {self._n_workers} workers connected")

    def _record_timed_out(self, req_id: int):
        self._timed_out.add(req_id)
        self._timed_out_order.append(req_id)
        while len(self._timed_out_order) > _TIMED_OUT_KEEP:
            self._timed_out.discard(self._timed_out_order.popleft())

    def _fail_worker(self, worker_id: int, reason: str):
        """Declare a worker dead: fail its in-flight futures, clear its
        hello slot so wait_workers re-arms, count the death."""
        if worker_id in self._dead_workers:
            return
        self._dead_workers.add(worker_id)
        self._m_worker_dead.inc()
        ident = self._hello.pop(worker_id, None)
        if ident is not None:
            self._ident2worker.pop(ident, None)
        self._hello_event.clear()
        err = WorkerDeadError(worker_id, reason)
        for req_id in [
            r for r, (_f, w) in self._pending.items() if w == worker_id
        ]:
            fut, _w = self._pending.pop(req_id)
            self._record_timed_out(req_id)
            if not fut.done():
                fut.set_exception(err)
        logger.error(f"worker {worker_id} declared dead: {reason}")

    async def request(
        self,
        worker_id: int,
        payload: Dict[str, Any],
        timeout: Any = _UNSET,
    ) -> Dict:
        self._ensure_recv_loop()
        if worker_id in self._dead_workers:
            raise WorkerDeadError(
                worker_id, "worker previously declared dead"
            )
        if not self._hello_event.is_set():
            await self.wait_workers()
        req_id = self._next_req_id
        self._next_req_id += 1
        loop = asyncio.get_running_loop()
        fut = loop.create_future()
        self._pending[req_id] = (fut, worker_id)
        msg = pickle.dumps({"req_id": req_id, "request": payload})
        await self._sock.send_multipart([self._hello[worker_id], msg])
        if timeout is _UNSET:
            timeout = self.mfc_timeout_s
        if timeout is None:
            return await fut
        # Deadline lane.  shield() keeps the future alive across each
        # wait_for slice; on expiry a fresh heartbeat re-arms the
        # deadline ("slow"), a stale one declares the worker dead.
        deadline = loop.time() + timeout
        poll_s = min(timeout, max(self.worker_heartbeat_s, 0.05))
        while True:
            try:
                return await asyncio.wait_for(asyncio.shield(fut), poll_s)
            except asyncio.TimeoutError:
                if fut.done():
                    return fut.result()
                if loop.time() < deadline:
                    continue
                self._m_mfc_timeout.inc()
                beat_age = loop.time() - self._last_beat.get(
                    worker_id, -1e18
                )
                if beat_age <= self._beat_grace_s:
                    logger.warning(
                        f"request {req_id} ({payload.get('type')}) to "
                        f"worker {worker_id} exceeded {timeout}s but the "
                        f"worker is beating (last beat {beat_age:.1f}s "
                        "ago): slow, not dead — deadline re-armed"
                    )
                    deadline = loop.time() + timeout
                    continue
                self._fail_worker(
                    worker_id,
                    f"no reply to {payload.get('type')} within {timeout}s "
                    f"and no heartbeat for {beat_age:.1f}s "
                    f"(grace {self._beat_grace_s:.1f}s)",
                )
                # _fail_worker failed this future with WorkerDeadError.
                return await fut

    async def broadcast(self, payload: Dict[str, Any]):
        # Dead workers are skipped: a post-recovery exit/abort broadcast
        # must not hang on (or instantly fail over) a corpse.
        return await asyncio.gather(
            *[
                self.request(w, payload)
                for w in range(self._n_workers)
                if w not in self._dead_workers
            ]
        )

    def close(self):
        if self._closed:
            return
        self._closed = True
        if self._recv_task is not None:
            self._recv_task.cancel()
        # The cancelled recv loop also fails pending, but only once the
        # event loop runs it again — which never happens when close() is
        # the loop's last act.  Fail synchronously too (idempotent).
        try:
            self._fail_pending(PoolClosedError("worker pool closed"))
        except Exception:  # futures on an already-closed loop
            pass
        self._sock.close(linger=0)
        self._ctx.term()


# Request types served on background threads: the transfer-plane recv side
# BLOCKS until the peer's send lands, so a serial loop could deadlock when
# the master dispatches send/recv pairs between two workers concurrently
# (each stuck in recv while the matching send sits queued behind it).
# Compute requests stay serial, matching the reference's one-blocking-
# request-at-a-time model worker (model_worker.py:667).
_THREADED_TYPES = frozenset(
    {"data_send", "data_recv", "param_send", "param_recv"}
)


def _start_heartbeat(
    ctx, addr: str, worker_index: int, heartbeat_s: float
):
    """Heartbeat lane: its OWN dealer socket (zmq sockets are not
    thread-safe and the serve loop's socket blocks for the whole span of
    an inline MFC), beating every ``heartbeat_s`` until stopped.  The
    thread dies with the process — which is exactly the signal: beats
    stop iff the worker process is gone, while a hung or slow MFC keeps
    beating and stays "slow" to the master."""
    import threading

    stop = threading.Event()

    def _beat():
        sock = ctx.socket(zmq.DEALER)
        sock.connect(addr)
        frame = pickle.dumps(
            {"type": "beat", "worker_index": worker_index}
        )
        try:
            while not stop.is_set():
                sock.send(frame)
                stop.wait(heartbeat_s)
        finally:
            sock.close(linger=0)

    t = threading.Thread(
        target=_beat, name=f"heartbeat-{worker_index}", daemon=True
    )
    t.start()
    return stop


def run_worker_stream(
    worker,  # ModelWorker
    experiment_name: str,
    trial_name: str,
    timeout: float = 300.0,
    control=None,  # Optional[worker_control.WorkerServer]
    heartbeat_s: Optional[float] = None,
) -> None:
    """Worker side: connect, announce, serve requests until 'exit'."""
    import os
    import queue
    import threading

    if heartbeat_s is None:
        heartbeat_s = float(
            os.environ.get("AREAL_WORKER_HEARTBEAT_S", "5.0")
        )
    addr = name_resolve.wait(
        names.request_reply_stream(experiment_name, trial_name, STREAM_NAME),
        timeout=timeout,
    )
    ctx = zmq.Context()
    sock = ctx.socket(zmq.DEALER)
    sock.connect(addr)
    sock.send(
        pickle.dumps(
            {"type": "hello", "worker_index": worker.config.worker_index}
        )
    )
    logger.info(
        f"worker {worker.config.worker_index} connected to master at {addr}"
    )
    beat_stop = None
    if heartbeat_s > 0:
        beat_stop = _start_heartbeat(
            ctx, addr, worker.config.worker_index, heartbeat_s
        )

    replies: "queue.Queue[bytes]" = queue.Queue()
    threads: list = []

    def _serve(req, req_id):
        try:
            result = worker.handle_request(req)
            reply = {"req_id": req_id, "result": result}
        except Exception as e:  # noqa: BLE001 — forwarded to master
            logger.error(
                f"worker {worker.config.worker_index} request "
                f"{req.get('type')} failed: {e!r}"
            )
            reply = {"req_id": req_id, "error": repr(e)}
        replies.put(pickle.dumps(reply))

    def _drain_replies():
        while True:
            try:
                sock.send(replies.get_nowait())
            except queue.Empty:
                return

    try:
        while True:
            # Controller-initiated exit (side channel; see worker_control).
            if control is not None and control.state.value == "exiting":
                for t in threads:  # in-flight transfers finish first
                    t.join(timeout=timeout)
                _drain_replies()
                break
            if not sock.poll(100):
                _drain_replies()
                continue
            msg = pickle.loads(sock.recv())
            req = msg["request"]
            # A paused worker parks non-exit requests until the controller
            # resumes it (pausing mid-step stalls the trial; reference:
            # worker_base.py PAUSED state gating _poll).  Exit requests —
            # master shutdown broadcast OR controller side channel — are
            # never parked, so teardown cannot deadlock on a paused
            # worker.
            if control is not None and req.get("type") != "exit":
                while control.paused and control.state.value != "exiting":
                    control.wait_if_paused(timeout=0.5)
            if req.get("type") == "exit":
                for t in threads:
                    t.join(timeout=timeout)
                _drain_replies()
                sock.send(
                    pickle.dumps({"req_id": msg["req_id"], "result": {}})
                )
                break
            if req.get("type") in _THREADED_TYPES:
                t = threading.Thread(
                    target=_serve, args=(req, msg["req_id"]), daemon=True
                )
                t.start()
                threads.append(t)
                threads = [t for t in threads if t.is_alive()]
            else:
                _serve(req, msg["req_id"])
            _drain_replies()
    finally:
        if beat_stop is not None:
            beat_stop.set()
        sock.close(linger=0)
        ctx.term()
