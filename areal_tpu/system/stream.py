"""ZMQ master⇄worker request-reply stream for multi-process trials.

Capability parity: realhf/system/request_reply_stream.py (ZMQ PUSH/PULL
pairs + a syn-ack protocol for ordered delivery) — simplified: one ROUTER
socket on the master and a DEALER per worker gives per-peer FIFO ordering
from ZMQ/TCP itself, so no syn-ack layer is needed.  Request/response
matching uses explicit request ids (the master pipelines many concurrent
requests per worker from the asyncio DFG executor).

Discovery mirrors the reference: the master publishes its tcp address via
name_resolve (names.request_reply_stream) and every worker announces itself
with a hello frame carrying its index.

Payloads are pickled python dicts (SequenceSample metadata/arrays are
numpy-based); this is the CONTROL plane — bulk tensors live on device and
move via jax collectives / device_put (areal_tpu/parallel/realloc.py).
"""

import asyncio
import pickle
from typing import Any, Dict

import zmq
import zmq.asyncio

from areal_tpu.base import logging, name_resolve, names, network
from areal_tpu.system.master import WorkerPool

logger = logging.getLogger("stream")

STREAM_NAME = "master"


class ZMQWorkerPool(WorkerPool):
    """Master side: ROUTER socket, one outstanding-request table."""

    def __init__(self, experiment_name: str, trial_name: str, n_workers: int):
        self._n_workers = n_workers
        self._ctx = zmq.asyncio.Context()
        self._sock = self._ctx.socket(zmq.ROUTER)
        # bind_to_random_port probes and binds atomically (no TOCTOU).
        port = self._sock.bind_to_random_port("tcp://*")
        host = network.gethostip()
        self._addr = f"tcp://{host}:{port}"
        name_resolve.add(
            names.request_reply_stream(experiment_name, trial_name, STREAM_NAME),
            self._addr,
            replace=True,
        )
        self._pending: Dict[int, asyncio.Future] = {}
        self._hello: Dict[int, bytes] = {}  # worker index -> zmq identity
        self._hello_event = asyncio.Event()
        self._next_req_id = 0
        self._recv_task = None
        logger.info(f"master stream bound at {self._addr}")

    @property
    def n_workers(self) -> int:
        return self._n_workers

    def _ensure_recv_loop(self):
        if self._recv_task is None:
            self._recv_task = asyncio.get_running_loop().create_task(
                self._recv_loop()
            )

    async def _recv_loop(self):
        try:
            while True:
                ident, payload = await self._sock.recv_multipart()
                try:
                    msg = pickle.loads(payload)
                except Exception as e:  # corrupt frame: drop, keep serving
                    logger.error(f"undecodable frame from {ident!r}: {e!r}")
                    continue
                if msg.get("type") == "hello":
                    self._hello[int(msg["worker_index"])] = ident
                    if len(self._hello) >= self._n_workers:
                        self._hello_event.set()
                    continue
                fut = self._pending.pop(msg.get("req_id"), None)
                if fut is None:
                    logger.warning(f"orphan reply req_id={msg.get('req_id')}")
                    continue
                if fut.done():  # request cancelled during teardown
                    continue
                if msg.get("error"):
                    fut.set_exception(RuntimeError(msg["error"]))
                else:
                    fut.set_result(msg["result"])
        except asyncio.CancelledError:
            raise
        except Exception as e:
            # A dead recv loop must not strand awaiting requests: fail them.
            logger.error(f"stream recv loop died: {e!r}")
            for fut in self._pending.values():
                if not fut.done():
                    fut.set_exception(
                        RuntimeError(f"stream recv loop died: {e!r}")
                    )
            self._pending.clear()
            raise

    async def wait_workers(self, timeout: float = 300.0):
        """Block until every worker has said hello."""
        self._ensure_recv_loop()
        await asyncio.wait_for(self._hello_event.wait(), timeout)
        logger.info(f"all {self._n_workers} workers connected")

    async def request(self, worker_id: int, payload: Dict[str, Any]) -> Dict:
        self._ensure_recv_loop()
        if not self._hello_event.is_set():
            await self.wait_workers()
        req_id = self._next_req_id
        self._next_req_id += 1
        fut = asyncio.get_running_loop().create_future()
        self._pending[req_id] = fut
        msg = pickle.dumps({"req_id": req_id, "request": payload})
        await self._sock.send_multipart([self._hello[worker_id], msg])
        return await fut

    async def broadcast(self, payload: Dict[str, Any]):
        return await asyncio.gather(
            *[self.request(w, payload) for w in range(self._n_workers)]
        )

    def close(self):
        if self._recv_task is not None:
            self._recv_task.cancel()
        self._sock.close(linger=0)
        self._ctx.term()


# Request types served on background threads: the transfer-plane recv side
# BLOCKS until the peer's send lands, so a serial loop could deadlock when
# the master dispatches send/recv pairs between two workers concurrently
# (each stuck in recv while the matching send sits queued behind it).
# Compute requests stay serial, matching the reference's one-blocking-
# request-at-a-time model worker (model_worker.py:667).
_THREADED_TYPES = frozenset(
    {"data_send", "data_recv", "param_send", "param_recv"}
)


def run_worker_stream(
    worker,  # ModelWorker
    experiment_name: str,
    trial_name: str,
    timeout: float = 300.0,
    control=None,  # Optional[worker_control.WorkerServer]
) -> None:
    """Worker side: connect, announce, serve requests until 'exit'."""
    import queue
    import threading

    addr = name_resolve.wait(
        names.request_reply_stream(experiment_name, trial_name, STREAM_NAME),
        timeout=timeout,
    )
    ctx = zmq.Context()
    sock = ctx.socket(zmq.DEALER)
    sock.connect(addr)
    sock.send(
        pickle.dumps(
            {"type": "hello", "worker_index": worker.config.worker_index}
        )
    )
    logger.info(
        f"worker {worker.config.worker_index} connected to master at {addr}"
    )

    replies: "queue.Queue[bytes]" = queue.Queue()
    threads: list = []

    def _serve(req, req_id):
        try:
            result = worker.handle_request(req)
            reply = {"req_id": req_id, "result": result}
        except Exception as e:  # noqa: BLE001 — forwarded to master
            logger.error(
                f"worker {worker.config.worker_index} request "
                f"{req.get('type')} failed: {e!r}"
            )
            reply = {"req_id": req_id, "error": repr(e)}
        replies.put(pickle.dumps(reply))

    def _drain_replies():
        while True:
            try:
                sock.send(replies.get_nowait())
            except queue.Empty:
                return

    try:
        while True:
            # Controller-initiated exit (side channel; see worker_control).
            if control is not None and control.state.value == "exiting":
                for t in threads:  # in-flight transfers finish first
                    t.join(timeout=timeout)
                _drain_replies()
                break
            if not sock.poll(100):
                _drain_replies()
                continue
            msg = pickle.loads(sock.recv())
            req = msg["request"]
            # A paused worker parks non-exit requests until the controller
            # resumes it (pausing mid-step stalls the trial; reference:
            # worker_base.py PAUSED state gating _poll).  Exit requests —
            # master shutdown broadcast OR controller side channel — are
            # never parked, so teardown cannot deadlock on a paused
            # worker.
            if control is not None and req.get("type") != "exit":
                while control.paused and control.state.value != "exiting":
                    control.wait_if_paused(timeout=0.5)
            if req.get("type") == "exit":
                for t in threads:
                    t.join(timeout=timeout)
                _drain_replies()
                sock.send(
                    pickle.dumps({"req_id": msg["req_id"], "result": {}})
                )
                break
            if req.get("type") in _THREADED_TYPES:
                t = threading.Thread(
                    target=_serve, args=(req, msg["req_id"]), daemon=True
                )
                t.start()
                threads.append(t)
                threads = [t for t in threads if t.is_alive()]
            else:
                _serve(req, msg["req_id"])
            _drain_replies()
    finally:
        sock.close(linger=0)
        ctx.term()
