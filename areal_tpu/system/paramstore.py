"""Versioned parameter store + broadcast-tree distribution fabric.

The missing layer between trainer and gen fleet (ROADMAP open item 2):
until now every in-memory weight push was the master serially shipping
the full tree point-to-point to each server — O(servers) push wall-time
and a single point of failure.  RLAX (arxiv 2512.06392) and Podracer
(arxiv 2104.06272) both decouple learners from actors through a
versioned parameter-distribution layer; this module is that layer for
the TPU process model:

- **ParamStore** — the publisher serializes a params pytree ONCE per
  version into a flat little-endian byte payload plus a (dtype, shape)
  manifest, stamped with the per-leaf-norm checksum from
  ``base/integrity.py``.  Versions carry reference counts: a version is
  retained while any live server or in-flight dispatch pins it, so a
  breaker-open or mid-episode server can still pull version v-1 on its
  next health cycle under the ``max_head_offpolicyness`` staleness
  bound.  Stale pins expire by TTL (a crashed holder never releases).

- **Broadcast tree** — ``plan_tree`` splits the live membership (from
  ``names.gen_servers`` discovery, the same closure
  ``fleet.fleet_discovery`` returns) into a deterministic fan-out tree.
  Each server receives the payload with its OWN subtree spec and relays
  the raw bytes to its children over the existing ZMQ/HTTP transports
  *before* applying locally via the interruptible
  ``update_weights_inmem`` path — push wall-time is O(log N) hops
  instead of O(N) sends.  A relay failure orphans exactly that subtree
  (counted in ``areal_param_push_orphans_total``); orphans keep serving
  their pinned previous version and re-attach to the root on the next
  push, because the tree is rebuilt from live membership every time.

- **BroadcastFabric** — the pusher-side driver: publish → plan → push →
  pin → retire, plus ``repair()`` (point-to-point catch-up for laggards
  the health cycle finds behind head) and a ``p2p`` mode that preserves
  the old serial loop as the A/B baseline ``scripts/measure_push.py``
  measures against.

Wire format (shared by both transports; the payload bytes are relayed
VERBATIM hop to hop — serialized once per version, never re-encoded):

- HTTP ``POST /param_push``: body = 8-byte big-endian meta length +
  meta JSON + raw payload (``frame_push_body``/``unframe_push_body``).
- ZMQ: a 3-frame ``param_push`` request — [identity, meta JSON, payload]
  on the server ROUTER, [meta JSON, payload] from the client DEALER.

Every fabric metric is registered HERE and only here (the metrics-names
lint rule is one-name-one-site); the master/worker push paths and the
gen server import the handles.

jax is imported lazily: serialization helpers accept host numpy pytrees
and arealint's CI job imports modules without jax installed.
"""

import dataclasses
import json
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

from areal_tpu.base import integrity, logging, metrics

logger = logging.getLogger("paramstore")

# ---------------- metrics (one registration site) ----------------

_REG = metrics.default_registry()
M_VERSIONS_LIVE = _REG.gauge(
    "areal_paramstore_versions_live",
    "parameter versions currently retained by the store",
)
M_PINS = _REG.gauge(
    "areal_paramstore_pins",
    "live version pins (servers + in-flight dispatches) across versions",
)
M_PUSH_BYTES = _REG.counter(
    "areal_param_push_bytes_total",
    "parameter payload bytes shipped by push/relay hops",
)
M_PUSH_SECONDS = _REG.histogram(
    "areal_param_push_seconds",
    "wall time of one fleet-wide parameter push",
)
M_PUSH_ORPHANS = _REG.counter(
    "areal_param_push_orphans_total",
    "servers orphaned by a failed relay subtree during a push",
)

# ---------------- serialization (once per version) ----------------


def _np_dtype(name: str) -> np.dtype:
    """np.dtype by name, resolving jax's ml_dtypes extras (bfloat16)."""
    try:
        return np.dtype(name)
    except TypeError:
        import ml_dtypes

        return np.dtype(getattr(ml_dtypes, name))


def serialize_params(tree: Any) -> Tuple[List[Dict], bytes]:
    """Flatten a params pytree into (manifest, payload) — the manifest
    lists (dtype, shape) per leaf in ``jax.tree.leaves`` order, the
    payload is the leaves' raw bytes concatenated.  No pytree-path codec
    is needed: pusher and receiver share the model structure, so the
    receiver rebuilds with its OWN treedef (``deserialize_params``)."""
    import jax

    leaves = jax.tree.leaves(tree)
    arrs = [np.ascontiguousarray(np.asarray(x)) for x in leaves]
    manifest = [
        {"dtype": str(a.dtype), "shape": list(a.shape)} for a in arrs
    ]
    payload = b"".join(a.tobytes() for a in arrs)
    return manifest, payload


def deserialize_params(like: Any, manifest: List[Dict], payload: bytes):
    """Rebuild a params pytree from (manifest, payload) using `like`'s
    treedef.  Leaves are zero-copy read-only views over the payload —
    engines place them onto device anyway.  A structural mismatch
    (different leaf count/shape/dtype) raises before any leaf is built:
    a payload for a different model must never reach the swap."""
    import jax

    like_leaves, treedef = jax.tree.flatten(like)
    if len(like_leaves) != len(manifest):
        raise ValueError(
            f"param payload has {len(manifest)} leaves; this model has "
            f"{len(like_leaves)} — wrong model for this fleet"
        )
    out, off = [], 0
    for i, spec in enumerate(manifest):
        dt = _np_dtype(str(spec["dtype"]))
        shape = tuple(int(s) for s in spec["shape"])
        want = tuple(np.asarray(like_leaves[i]).shape)
        if shape != want:
            raise ValueError(
                f"param payload leaf {i} has shape {shape}; model "
                f"expects {want}"
            )
        n = int(np.prod(shape)) if shape else 1
        arr = np.frombuffer(
            payload, dtype=dt, count=n, offset=off
        ).reshape(shape)
        out.append(arr)
        off += n * dt.itemsize
    if off != len(payload):
        raise ValueError(
            f"param payload is {len(payload)} bytes; manifest describes "
            f"{off}"
        )
    return treedef.unflatten(out)


def frame_push_body(meta: Dict, payload: bytes) -> bytes:
    """HTTP /param_push body: 8-byte big-endian meta length + meta JSON
    + raw payload (binary bodies cannot ride the JSON transport)."""
    mb = json.dumps(meta).encode()
    return len(mb).to_bytes(8, "big") + mb + payload


def unframe_push_body(body: bytes) -> Tuple[Dict, bytes]:
    if len(body) < 8:
        raise ValueError("param_push body too short for its meta prefix")
    mlen = int.from_bytes(body[:8], "big")
    if 8 + mlen > len(body):
        raise ValueError("param_push meta prefix exceeds the body")
    meta = json.loads(body[8 : 8 + mlen])
    return meta, body[8 + mlen :]


# ---------------- the versioned store ----------------


@dataclasses.dataclass
class ParamVersion:
    """One published version: the serialize-once payload + its manifest
    and checksum, reused verbatim across every target, relay hop, and
    checksum-reject retry."""

    version: int
    manifest: List[Dict]
    payload: bytes
    checksum: Optional[np.ndarray]
    published_s: float

    @property
    def nbytes(self) -> int:
        return len(self.payload)


class ParamStore:
    """Versioned parameter store with per-version reference counts.

    ``publish`` serializes once and bumps the head version; ``pin``
    records a named holder on a version (servers pin EXCLUSIVELY — a
    holder serves exactly one version; in-flight dispatches pin
    additively and ``release`` on completion).  ``retire`` drops
    versions that are not the head, not within the ``retain`` newest,
    and hold no live pins — after expiring pins older than
    ``pin_ttl_s`` (a crashed holder never releases; its pins age out
    exactly like its fleet announcement).  ``retain=2`` keeps v-1
    pullable even before anyone pins it, which is what lets a server
    that missed a push catch up within the staleness bound."""

    def __init__(
        self,
        retain: int = 2,
        pin_ttl_s: float = 30.0,
        clock: Callable[[], float] = time.monotonic,
    ):
        if retain < 1:
            raise ValueError(f"retain must be >= 1, got {retain}")
        self.retain = int(retain)
        self.pin_ttl_s = float(pin_ttl_s)
        self._clock = clock
        self._lock = threading.RLock()
        self._head = 0
        self._versions: Dict[int, ParamVersion] = {}
        # version -> holder -> last pin/refresh stamp (clock units)
        self._pins: Dict[int, Dict[str, float]] = {}

    # -- publishing --

    def publish(
        self,
        params: Any = None,
        checksum: Optional[np.ndarray] = None,
        *,
        manifest: Optional[List[Dict]] = None,
        payload: Optional[bytes] = None,
    ) -> int:
        """Serialize ONCE and retain under the next version number.
        Pass either a params pytree (serialized + checksummed here) or a
        pre-serialized (manifest, payload) pair."""
        if params is not None:
            manifest, payload = serialize_params(params)
            if checksum is None:
                checksum = integrity.params_checksum(params)
        if manifest is None or payload is None:
            raise ValueError("publish needs params or (manifest, payload)")
        with self._lock:
            self._head += 1
            v = self._head
            self._versions[v] = ParamVersion(
                version=v,
                manifest=list(manifest),
                payload=bytes(payload),
                checksum=(
                    None if checksum is None
                    else np.asarray(checksum, np.float64)
                ),
                published_s=self._clock(),
            )
            self._retire_locked()
        logger.info(
            f"published version {v} ({len(payload)} bytes, "
            f"{len(manifest)} leaves)"
        )
        return v

    @property
    def head(self) -> int:
        with self._lock:
            return self._head

    def get(self, version: int) -> Optional[ParamVersion]:
        with self._lock:
            return self._versions.get(int(version))

    def live_versions(self) -> List[int]:
        with self._lock:
            return sorted(self._versions)

    # -- reference counts --

    def pin(self, version: int, holder: str, exclusive: bool = True) -> bool:
        """Pin `version` for `holder` (refreshing its TTL stamp).  With
        ``exclusive`` (server semantics: one served version per server)
        the holder's pins on other versions are released.  Returns False
        when the version is unknown/already retired — a pin cannot
        resurrect dropped bytes."""
        version = int(version)
        with self._lock:
            if version not in self._versions:
                if exclusive:
                    self._release_holder_locked(holder)
                    self._retire_locked()
                return False
            if exclusive:
                for v, holders in self._pins.items():
                    if v != version:
                        holders.pop(holder, None)
            self._pins.setdefault(version, {})[holder] = self._clock()
            self._retire_locked()
            return True

    def release(self, version: int, holder: str) -> None:
        with self._lock:
            self._pins.get(int(version), {}).pop(holder, None)
            self._retire_locked()

    def release_holder(self, holder: str) -> None:
        """Drop every pin held by `holder` (server drained/reaped)."""
        with self._lock:
            self._release_holder_locked(holder)
            self._retire_locked()

    def _release_holder_locked(self, holder: str) -> None:
        for holders in self._pins.values():
            holders.pop(holder, None)

    def pins(self, version: int) -> List[str]:
        with self._lock:
            return sorted(self._pins.get(int(version), {}))

    # -- retention --

    def retire(self) -> List[int]:
        """Expire stale pins, then drop every version that is neither
        the head, within the `retain` newest, nor pinned.  Returns the
        versions dropped."""
        with self._lock:
            return self._retire_locked()

    def _retire_locked(self) -> List[int]:
        now = self._clock()
        for holders in self._pins.values():
            for h, stamp in list(holders.items()):
                if now - stamp > self.pin_ttl_s:
                    holders.pop(h)
        dropped = []
        for v in sorted(self._versions):
            if v > self._head - self.retain:
                continue
            if self._pins.get(v):
                continue
            del self._versions[v]
            self._pins.pop(v, None)
            dropped.append(v)
        # Pin maps for versions already gone hold nothing worth keeping.
        for v in [v for v in self._pins if v not in self._versions]:
            if not self._pins[v]:
                del self._pins[v]
        M_VERSIONS_LIVE.set(len(self._versions))
        M_PINS.set(sum(len(h) for h in self._pins.values()))
        if dropped:
            logger.info(f"retired versions {dropped}")
        return dropped

    # -- persistence (RecoverInfo.paramstore_state) --

    def state_dict(self) -> Dict:
        """Version COUNTER state only: payloads are step products a
        restarted trainer re-publishes, but the head number must stay
        monotonic across restarts or rejoining servers would see
        version time run backwards."""
        with self._lock:
            return {"head": self._head}

    def load_state_dict(self, state: Optional[Dict]) -> None:
        if not state:
            return
        with self._lock:
            self._head = max(self._head, int(state.get("head", 0)))


# ---------------- the broadcast tree ----------------


def plan_tree(
    members: List[Tuple[str, str]], fanout: int = 2
) -> List[Dict]:
    """Deterministic fan-out tree over (sid, url) members: the sorted
    membership splits into ≤ `fanout` balanced contiguous chunks, each
    chunk's first member relays to a recursively planned subtree of the
    rest — depth O(log_fanout N).  Returns the root's child nodes, each
    ``{"sid", "url", "children": [...]}``; membership changes between
    pushes simply replan (nothing is stateful)."""
    members = sorted(members)
    fanout = max(1, int(fanout))
    if not members:
        return []
    k = min(fanout, len(members))
    base, extra = divmod(len(members), k)
    nodes, off = [], 0
    for i in range(k):
        size = base + (1 if i < extra else 0)
        chunk = members[off : off + size]
        off += size
        sid, url = chunk[0]
        nodes.append(
            {
                "sid": sid,
                "url": url,
                "children": plan_tree(chunk[1:], fanout),
            }
        )
    return nodes


def subtree_sids(node: Dict) -> List[str]:
    out = [str(node["sid"])]
    for c in node.get("children") or ():
        out.extend(subtree_sids(c))
    return out


def tree_depth(nodes: List[Dict]) -> int:
    if not nodes:
        return 0
    return 1 + max(tree_depth(n.get("children") or []) for n in nodes)


# ---------------- push transport ----------------


def push_payload(
    url: str,
    meta: Dict,
    payload: bytes,
    token: str = "",
    timeout_s: float = 120.0,
) -> Dict:
    """Ship one (meta, payload) push to a server over its transport
    (zmq:// → 2-frame DEALER request; http:// → binary POST
    /param_push).  The payload bytes go out VERBATIM — this is the only
    hop primitive, so every hop counts into the bytes total and no hop
    ever re-serializes."""
    mb_len = len(json.dumps(meta).encode())
    if url.startswith("zmq://"):
        from areal_tpu.system.gen_server import ZMQGenClient

        client = ZMQGenClient(url, timeout_s=timeout_s, token=token)
        try:
            ack = client.push_weights(meta, payload)
        finally:
            client.close()
    else:
        from areal_tpu.api.model_api import LLMAPIClient

        ack = LLMAPIClient(url, timeout_s=timeout_s, token=token)\
            .push_weights(meta, payload)
    M_PUSH_BYTES.inc(len(payload) + mb_len)
    return ack


def relay_subtrees(
    children: List[Dict],
    base_meta: Dict,
    payload: bytes,
    token: str = "",
    timeout_s: float = 120.0,
) -> Tuple[List[str], List[Dict]]:
    """Push `payload` to each child subtree concurrently; aggregate the
    (applied, failed) sid sets the acks report.  A child that cannot be
    reached orphans its WHOLE subtree — degradation is per-subtree, and
    the orphans re-attach when the next push replans over live
    membership."""
    applied: List[str] = []
    failed: List[Dict] = []
    if not children:
        return applied, failed
    from concurrent.futures import ThreadPoolExecutor

    def one(node: Dict):
        return push_payload(
            str(node["url"]),
            dict(base_meta, subtree=node),
            payload,
            token=token,
            timeout_s=timeout_s,
        )

    with ThreadPoolExecutor(len(children)) as pool:
        for node, fut in [
            (n, pool.submit(one, n)) for n in children
        ]:
            try:
                ack = fut.result()
                applied.extend(str(s) for s in ack.get("applied", ()))
                failed.extend(ack.get("failed", ()))
            except Exception as e:  # noqa: BLE001 — orphan the subtree
                logger.warning(
                    f"relay to {node['sid']} failed: {e!r}; subtree "
                    "orphaned until the next push"
                )
                failed.extend(
                    {"sid": s, "error": repr(e)}
                    for s in subtree_sids(node)
                )
    return applied, failed


# ---------------- the pusher-side fabric ----------------


@dataclasses.dataclass
class PushReport:
    version: int
    targets: int
    applied: List[str]
    orphans: List[Dict]  # [{"sid", "error"}]
    seconds: float
    nbytes: int
    depth: int

    @property
    def ok(self) -> bool:
        return not self.orphans and self.targets == len(self.applied)


class BroadcastFabric:
    """Drives pushes from a ParamStore over live fleet membership.

    `discovery` is the ``fleet_discovery(experiment, trial)`` closure
    (sid → url); membership is re-listed on EVERY push, so joins,
    drains, and expiries between pushes rebuild the tree instead of
    wedging it.  ``mode="p2p"`` preserves the old serial point-to-point
    loop as the A/B baseline for ``scripts/measure_push.py``."""

    def __init__(
        self,
        store: ParamStore,
        discovery: Callable[[], Dict[str, str]],
        fanout: int = 2,
        mode: str = "tree",
        token: str = "",
        timeout_s: float = 120.0,
        experiment: str = "",
        trial: str = "trial",
    ):
        if mode not in ("tree", "p2p"):
            raise ValueError(f"unknown push mode {mode!r}")
        if fanout < 1:
            raise ValueError(f"fanout must be >= 1, got {fanout}")
        self.store = store
        self.discovery = discovery
        self.fanout = int(fanout)
        self.mode = mode
        self.token = token
        self.timeout_s = float(timeout_s)
        self.experiment = experiment
        self.trial = trial

    def _base_meta(self, pv: ParamVersion) -> Dict:
        return {
            "cmd": "param_push",
            "version": pv.version,
            "manifest": pv.manifest,
            "checksum": (
                None if pv.checksum is None else pv.checksum.tolist()
            ),
        }

    def _announce_head(self) -> None:
        """Publish the store head under ``names.param_store`` — the
        rendezvous key a late-joining (or multi-slice) trainer reads to
        continue version time instead of restarting it."""
        if not self.experiment:
            return
        from areal_tpu.base import name_resolve, names

        try:
            name_resolve.add(
                names.param_store(self.experiment, self.trial),
                str(self.store.head),
                replace=True,
                delete_on_exit=True,
            )
        except Exception:  # noqa: BLE001 — rendezvous is best-effort
            logger.warning("param_store head announce failed", exc_info=True)

    def push(self, version: Optional[int] = None) -> PushReport:
        """Push `version` (default: head) to the whole live fleet."""
        v = int(version) if version is not None else self.store.head
        pv = self.store.get(v)
        if pv is None:
            raise KeyError(f"version {v} is not retained by the store")
        membership = sorted(dict(self.discovery() or {}).items())
        t0 = time.monotonic()
        base = self._base_meta(pv)
        applied: List[str] = []
        failed: List[Dict] = []
        if self.mode == "p2p":
            # The historic serial loop: one direct send per server, no
            # relaying.  Kept as the measurable A/B baseline.
            roots = [
                {"sid": sid, "url": url, "children": []}
                for sid, url in membership
            ]
            for node in roots:
                a, f = relay_subtrees(
                    [node], base, pv.payload,
                    token=self.token, timeout_s=self.timeout_s,
                )
                applied.extend(a)
                failed.extend(f)
        else:
            roots = plan_tree(membership, self.fanout)
            applied, failed = relay_subtrees(
                roots, base, pv.payload,
                token=self.token, timeout_s=self.timeout_s,
            )
        dt = time.monotonic() - t0
        M_PUSH_SECONDS.observe(dt)
        if failed:
            M_PUSH_ORPHANS.inc(len(failed))
        for sid in applied:
            self.store.pin(v, f"server:{sid}")
        self.store.retire()
        self._announce_head()
        report = PushReport(
            version=v,
            targets=len(membership),
            applied=applied,
            orphans=failed,
            seconds=dt,
            nbytes=pv.nbytes,
            depth=tree_depth(roots),
        )
        logger.info(
            f"pushed v{v} to {len(applied)}/{len(membership)} servers "
            f"in {dt * 1e3:.1f}ms (depth {report.depth}, "
            f"{len(failed)} orphaned)"
        )
        return report

    # -- laggard catch-up --

    def push_to(self, sid: str, url: str, version: int) -> Dict:
        """Direct (no relay) push of one retained version to one server
        — the v-1 pull path: a mid-episode or breaker-recovering server
        catches up to the freshest version its staleness bound admits
        without waiting for the next fleet-wide push."""
        pv = self.store.get(version)
        if pv is None:
            raise KeyError(
                f"version {version} is not retained by the store"
            )
        ack = push_payload(
            url,
            dict(
                self._base_meta(pv),
                subtree={"sid": sid, "url": url, "children": []},
            ),
            pv.payload,
            token=self.token,
            timeout_s=self.timeout_s,
        )
        self.store.pin(version, f"server:{sid}")
        self.store.retire()
        return ack

    def poll_versions(self) -> Dict[str, Optional[int]]:
        """Served weight version per live member (None: unreachable)."""
        out: Dict[str, Optional[int]] = {}
        from areal_tpu.system.gen_server import make_gen_client

        for sid, url in sorted(dict(self.discovery() or {}).items()):
            client = None
            try:
                client = make_gen_client(
                    url, token=self.token, timeout_s=30.0
                )
                out[sid] = int(client.health()["version"])
            except Exception:  # noqa: BLE001 — dead member
                out[sid] = None
            finally:
                if client is not None and hasattr(client, "close"):
                    client.close()
        return out

    def repair(self) -> List[str]:
        """Bring every reachable laggard back to head with a direct
        push.  Orphans from a failed relay subtree land here on the next
        health cycle (or simply on the next fleet-wide push)."""
        head = self.store.head
        if head == 0 or self.store.get(head) is None:
            return []
        repaired = []
        membership = dict(self.discovery() or {})
        for sid, ver in self.poll_versions().items():
            if ver is None or ver >= head:
                continue
            try:
                self.push_to(sid, membership[sid], head)
                repaired.append(sid)
            except Exception:  # noqa: BLE001 — next cycle retries
                logger.warning(
                    f"repair push to {sid} failed", exc_info=True
                )
        if repaired:
            logger.info(f"repaired laggards to v{head}: {repaired}")
        return repaired
