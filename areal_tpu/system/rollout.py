"""Rollout controller: the actor plane of asynchronous RL.

Continuously pulls prompts from a data stream and fans them out to
generation servers (reference: AReaL's rollout worker +
`GenerationServer` pairing, realhf/system/rollout_worker.py; the
Podracer "actor plane", arxiv 2104.06272):

- **Queue-depth-aware load balancing**: each dispatch picks the server
  whose enriched ``/health`` reports the least load (collector queue
  depth + live decode slots) plus the controller's own
  not-yet-acknowledged dispatches to it — the cached health signal is
  refreshed at a bounded rate so balancing never becomes a health-poll
  storm, and the fleet is polled *concurrently* with a per-server
  timeout so one wedged server cannot stall everyone's refresh.
- **Version stamping**: every trajectory records the weight version it
  STARTED sampling under (``version_start``, the head version) and the
  one it finished under — bounded-staleness admission in the
  ``ReplayBuffer`` keys on the head version.
- **Backpressure**: when the replay buffer cannot accept (at capacity),
  the controller stops pulling prompts instead of overrunning the
  buffer and evicting samples the trainer never saw.
- **Bounded fan-out**: a controller-level semaphore caps in-flight
  dispatches, on top of each client's per-loop ``agenerate`` bound.

Elastic-fleet hardening (the RLAX / Podracer preemptible-pool posture,
PAPERS.md arxiv 2512.06392 / 2104.06272):

- **Dynamic membership**: with a ``discovery`` callable (normally
  :func:`areal_tpu.system.fleet.fleet_discovery` over the
  ``names.gen_servers`` keepalive subtree) the controller diffs the
  announced fleet at every health refresh — joins get a client and
  start taking dispatches within one refresh interval; leaves are
  *drained* (no new dispatches; in-flight work runs to completion)
  and reaped once idle.  Statically-passed clients are never drained
  by discovery.
- **Hardened dispatch**: each ``agenerate`` runs under an optional
  deadline (``dispatch_timeout_s``); a failed or timed-out dispatch is
  re-sent — with exponential backoff — to a *different* server
  (excluding every server observed failing this prompt), up to
  ``max_dispatch_retries`` times before the prompt is counted
  ``failed``.  No prompt is ever silently dropped.
- **Circuit breaking**: each server carries a
  :class:`~areal_tpu.system.fleet.CircuitBreaker`; dispatch failures
  AND failed health polls count toward opening it, the half-open probe
  rides the next health poll, and only closed breakers take regular
  dispatches.

The ``cursor`` (prompts consumed from the stream) is persisted in
``RecoverInfo`` so a recovered trial resumes the stream where it
stopped instead of re-sampling consumed prompts; ``membership_epoch``
rides along so fleet churn is observable across restarts.
"""

import asyncio
import dataclasses
import inspect
import time
from typing import (
    Any,
    Callable,
    Dict,
    FrozenSet,
    Iterable,
    Iterator,
    List,
    Optional,
    Sequence,
)

from areal_tpu.api.model_api import APIGenerateInput, GenerationHyperparameters
from areal_tpu.base import logging, metrics, tracer
from areal_tpu.system.fleet import CircuitBreaker
from areal_tpu.system.replay import ReplayBuffer, Trajectory

logger = logging.getLogger("rollout")


@dataclasses.dataclass
class RolloutStat:
    """Reference: AReaL's RolloutStat (submitted/accepted/running)."""

    submitted: int = 0
    completed: int = 0
    accepted: int = 0
    rejected: int = 0
    failed: int = 0
    redispatched: int = 0
    in_flight: int = 0
    backpressure_waits: int = 0

    def as_dict(self) -> Dict[str, int]:
        return dataclasses.asdict(self)


@dataclasses.dataclass
class ServerState:
    """One fleet member as the controller sees it."""

    sid: str
    client: Any  # LLMAPIClient / ZMQGenClient-compatible
    breaker: CircuitBreaker
    # False for clients passed at construction (never drained by
    # discovery); True for discovery-announced members.
    dynamic: bool = False
    health: Dict = dataclasses.field(default_factory=dict)
    # Explicit flag — NOT a sentinel queue depth — so an unreachable
    # server can never leak bogus numbers into version_lag or autosize.
    healthy: bool = False
    # Dispatches sent but not yet completed — the live correction on
    # top of the (staler) polled queue depth.
    local_load: int = 0
    # Draining: takes no new dispatches; in-flight work completes, then
    # the membership sync reaps the entry.
    draining: bool = False


def _normalize_prompt(item, cursor: int):
    """Accept (qid, prompt_ids) pairs, {"qid", "prompt_ids"} dicts, or
    bare token lists; returns ``(qid, prompt_ids, task)``.

    Auto-assigned qids are replay dedup keys, so they must stay unique
    across everything one trial can feed through the controller.  A
    bare ``prompt{cursor}`` collides the moment two task streams share
    a controller, or a cycled dataset rewinds its cursor — so an item
    carrying task metadata (the mixture scheduler stamps ``task`` /
    ``epoch`` / per-task ``index`` on every draw) gets a namespaced
    ``{task}:e{epoch}:p{index}`` qid instead: unique per task, per
    dataset pass, per sample, and stable across recover fast-forwards.
    Plain single-stream items keep the historical ``prompt{cursor}``."""
    if isinstance(item, dict):
        task = str(item.get("task", "") or "")
        ids = list(map(int, item["prompt_ids"]))
        qid = item.get("qid")
        if qid is not None:
            return str(qid), ids, task
        if task or "epoch" in item:
            epoch = int(item.get("epoch", 0) or 0)
            index = int(item.get("index", cursor))
            return f"{task or 'task'}:e{epoch}:p{index}", ids, task
        return f"prompt{cursor}", ids, task
    if (
        isinstance(item, (tuple, list))
        and len(item) == 2
        and isinstance(item[0], str)
    ):
        return item[0], list(map(int, item[1])), ""
    return f"prompt{cursor}", [int(t) for t in item], ""


class RolloutController:
    """Pumps a prompt stream through an elastic gen-server fleet into a
    ReplayBuffer."""

    def __init__(
        self,
        clients: Sequence[Any] = (),  # static members (never drained)
        replay: ReplayBuffer = None,
        gconfig: GenerationHyperparameters = None,
        seed: Optional[int] = None,
        max_concurrency: int = 0,  # 0 = sum of client capacities
        health_refresh_s: float = 0.5,
        backpressure_poll_s: float = 0.05,
        autosize_inflight: bool = True,
        discovery: Optional[Callable[[], Dict[str, Any]]] = None,
        dispatch_timeout_s: float = 0.0,  # 0 = no per-dispatch deadline
        max_dispatch_retries: int = 2,
        retry_backoff_s: float = 0.05,  # doubles per retry, capped at 2s
        health_poll_timeout_s: float = 2.0,
        breaker_threshold: int = 3,
        breaker_cooldown_s: float = 5.0,
        # Agent-serving episodes: when set, each prompt becomes a
        # multi-turn episode instead of a single generate —
        # ``episode_runner(client, qid, prompt_ids)`` drives the full
        # tool-use loop against that server (system/episode.py's
        # ``make_episode_runner``) and returns an Episode, which lands
        # in replay as ONE trajectory with version-stamped turns.  The
        # runner is synchronous (it blocks on each turn); dispatches run
        # it on a worker thread, so deadline/retry/breaker semantics
        # apply to the whole episode.
        episode_runner: Optional[Callable[[Any, str, List[int]], Any]] = None,
        # Versioned parameter store (system/paramstore.py).  When set,
        # the controller maintains the store's refcounts from what it
        # already observes: each health poll pins the server's reported
        # serving version under ``server:{sid}`` (exclusive — the pin
        # FOLLOWS the server as it upgrades), each dispatch pins the
        # trainer version under ``dispatch:{qid}`` until the prompt
        # terminates, and a fleet reap releases every pin the departed
        # server held.  Net effect: a version is retired only when no
        # live server serves it and no in-flight prompt was dispatched
        # against it — the refcount lifecycle that lets a
        # breaker-open/mid-episode laggard still pull head-1.
        paramstore: Optional[Any] = None,
        # Task-mixture curriculum (data/mixture.py).  When set, run()
        # defaults its prompt source to the mixture stream, the
        # mixture's per-task cursors ride in state_dict()["mixture"]
        # (an old record holding only the scalar cursor is backfilled
        # by replaying the deterministic schedule), and every dispatch
        # is task-stamped through lineage and the trajectory.
        mixture: Optional[Any] = None,
    ):
        if not clients and discovery is None:
            raise ValueError(
                "rollout controller needs at least one client or a "
                "fleet-discovery callable"
            )
        if replay is None or gconfig is None:
            raise ValueError("rollout controller needs replay and gconfig")
        self.replay = replay
        self.gconfig = gconfig
        self.seed = seed
        self.health_refresh_s = health_refresh_s
        self.backpressure_poll_s = backpressure_poll_s
        # When True, each health poll resizes the client's agenerate
        # bound to the server-reported decode capacity; False keeps the
        # client's own max_inflight (e.g. to oversubscribe the collector
        # queue on purpose).
        self.autosize_inflight = autosize_inflight
        self.discovery = discovery
        self.dispatch_timeout_s = dispatch_timeout_s
        self.max_dispatch_retries = max_dispatch_retries
        self.retry_backoff_s = retry_backoff_s
        self.health_poll_timeout_s = health_poll_timeout_s
        self.breaker_threshold = breaker_threshold
        self.breaker_cooldown_s = breaker_cooldown_s
        self.episode_runner = episode_runner
        self.paramstore = paramstore
        self.mixture = mixture
        # Lineage: pass trace_id through to the runner only when its
        # signature can take it — external runners predating the causal
        # lineage plane keep working unchanged.
        self._runner_takes_trace = False
        if episode_runner is not None:
            try:
                sig = inspect.signature(episode_runner)
                self._runner_takes_trace = "trace_id" in sig.parameters or any(
                    p.kind == inspect.Parameter.VAR_KEYWORD
                    for p in sig.parameters.values()
                )
            except (TypeError, ValueError):
                pass
        self.stat = RolloutStat()
        # Prompts consumed from the data stream since trial start
        # (persisted via state_dict -> RecoverInfo).
        self.cursor = 0
        # Bumps on every membership change (join/leave/reap) — persisted
        # so fleet churn is observable across recoveries.
        self.membership_epoch = 0
        self._skip_on_run = 0
        self._stop = False
        self._servers: List[ServerState] = []
        self._by_sid: Dict[str, ServerState] = {}
        for i, c in enumerate(clients):
            self._add_server(f"static{i}", c, dynamic=False)
        self._health_ts = 0.0
        self._refresh_lock: Optional[asyncio.Lock] = None
        cap = max_concurrency or sum(
            max(1, int(getattr(c, "max_inflight", 1))) for c in clients
        ) or 16
        self._sem = asyncio.Semaphore(cap)
        self.max_concurrency = cap
        reg = metrics.default_registry()
        self._m_in_flight = reg.gauge(
            "areal_rollout_in_flight", "dispatches awaiting a response"
        )
        self._m_backpressure = reg.counter(
            "areal_rollout_backpressure_total",
            "waits because the replay buffer could not accept",
        )
        self._m_dispatched = reg.counter(
            "areal_rollout_dispatched_total",
            "prompt dispatches, by terminal status",
            ("status",),
        )
        self._m_version_lag = reg.gauge(
            "areal_rollout_version_lag",
            "trainer weight version minus the dispatched server's "
            "serving version, at dispatch time",
        )
        self._m_redispatch = reg.counter(
            "areal_rollout_redispatch_total",
            "prompts re-sent to a different server after a dispatch "
            "failure, by failure reason",
            ("reason",),
        )
        self._m_breaker_open = reg.gauge(
            "areal_rollout_breaker_open",
            "servers whose circuit breaker is currently open",
        )
        self._m_breaker_trans = reg.counter(
            "areal_rollout_breaker_transitions_total",
            "circuit-breaker state transitions, by target state",
            ("state",),
        )
        self._m_servers = reg.gauge(
            "areal_rollout_servers",
            "non-draining fleet members known to the controller",
        )

    # ---------------- fleet membership ----------------

    @property
    def clients(self) -> List[Any]:
        """All known clients (compat shim for pre-elastic callers)."""
        return [s.client for s in self._servers]

    @property
    def servers(self) -> List[ServerState]:
        return list(self._servers)

    def server(self, sid: str) -> Optional[ServerState]:
        return self._by_sid.get(sid)

    def _make_breaker(self) -> CircuitBreaker:
        def on_transition(state: str) -> None:
            self._m_breaker_trans.labels(state).inc()
            self._m_breaker_open.set(
                sum(
                    1
                    for s in self._servers
                    if s.breaker.state == CircuitBreaker.OPEN
                )
            )
            tracer.flight_event("breaker", state=state)

        return CircuitBreaker(
            threshold=self.breaker_threshold,
            cooldown_s=self.breaker_cooldown_s,
            on_transition=on_transition,
        )

    def _add_server(self, sid: str, client: Any, dynamic: bool) -> ServerState:
        st = ServerState(
            sid=sid, client=client, breaker=self._make_breaker(),
            dynamic=dynamic,
        )
        self._servers.append(st)
        self._by_sid[sid] = st
        return st

    def _sync_membership(self, mapping: Dict[str, Any]) -> None:
        """Diff the announced fleet against the known set: add joins,
        drain leaves (dynamic members only), reap drained-and-idle."""
        changed = False
        for sid, target in mapping.items():
            st = self._by_sid.get(sid)
            if st is None:
                if isinstance(target, str):
                    from areal_tpu.system.gen_server import make_gen_client

                    client = make_gen_client(target)
                else:  # tests may announce ready-made client objects
                    client = target
                self._add_server(sid, client, dynamic=True)
                changed = True
                logger.info(f"fleet join: {sid}")
            elif st.draining:
                # Re-announced while draining: welcome back.
                st.draining = False
                changed = True
                logger.info(f"fleet re-join: {sid}")
        for st in self._servers:
            if st.dynamic and not st.draining and st.sid not in mapping:
                st.draining = True
                changed = True
                logger.info(
                    f"fleet leave: {st.sid} draining "
                    f"({st.local_load} in flight)"
                )
        for st in [
            s for s in self._servers if s.draining and s.local_load == 0
        ]:
            self._servers.remove(st)
            del self._by_sid[st.sid]
            changed = True
            logger.info(f"fleet reap: {st.sid}")
            if self.paramstore is not None:
                # A dead/drained server no longer holds its version
                # alive (TTL expiry in the store covers the crash case
                # where no reap is ever observed).
                try:
                    self.paramstore.release_holder(f"server:{st.sid}")
                except Exception:  # noqa: BLE001 — teardown best-effort
                    pass
            close = getattr(st.client, "close", None)
            if st.dynamic and callable(close):
                try:
                    close()
                except Exception:  # noqa: BLE001 — teardown best-effort
                    pass
        if changed:
            self.membership_epoch += 1
        self._m_servers.set(
            sum(1 for s in self._servers if not s.draining)
        )

    def drain(self, sid: str) -> None:
        """Stop dispatching to `sid`; in-flight work completes."""
        st = self._by_sid.get(sid)
        if st is not None:
            st.draining = True

    # ---------------- recover ----------------

    def state_dict(self) -> Dict[str, Any]:
        sd = {
            "cursor": self.cursor,
            "stat": self.stat.as_dict(),
            "membership_epoch": self.membership_epoch,
        }
        if self.mixture is not None:
            sd["mixture"] = self.mixture.state_dict()
        return sd

    def load_state_dict(self, sd: Dict[str, Any]) -> None:
        self.cursor = int(sd.get("cursor", 0))
        self.membership_epoch = int(sd.get("membership_epoch", 0))
        st = sd.get("stat", {})
        for k, v in st.items():
            if hasattr(self.stat, k) and k != "in_flight":
                setattr(self.stat, k, int(v))
        self.stat.in_flight = 0
        if self.mixture is not None:
            ms = sd.get("mixture")
            if ms:
                # Per-task cursors restore exactly; the stream resumes
                # itself, so run() has nothing to skip.
                self.mixture.load_state_dict(ms)
            else:
                # Old-pickle backfill: the record predates the mixture
                # and only holds the scalar draw count — replaying that
                # many draws of the deterministic schedule reconstructs
                # the identical per-task positions.
                self.mixture.fast_forward(self.cursor)
            self._skip_on_run = 0
            return
        # On the next run(), fast-forward the (restarted) prompt stream
        # past everything the pre-restart trial already consumed.
        self._skip_on_run = self.cursor

    def stop(self) -> None:
        self._stop = True

    # ---------------- health / load balancing ----------------

    async def _poll_one(self, st: ServerState) -> None:
        """One server's health poll, breaker-aware.  Open breakers are
        not polled until their cooldown elapses; the poll that follows
        IS the half-open probe."""
        br = st.breaker
        if br.state == CircuitBreaker.OPEN:
            if not br.probe_due():
                st.health = {}
                st.healthy = False
                return
            br.begin_probe()
        try:
            h = await asyncio.wait_for(
                asyncio.to_thread(st.client.health),
                timeout=self.health_poll_timeout_s,
            )
        except Exception as e:  # noqa: BLE001 — deprioritize, don't die
            logger.warning(f"health poll failed for {st.sid}: {e!r}")
            st.health = {}
            st.healthy = False
            # Failed polls count toward the breaker too, so a server
            # that dies between dispatches still trips it open.
            br.record_failure()
            return
        st.health = h
        st.healthy = True
        br.record_success()
        if self.paramstore is not None and h.get("version") is not None:
            # Exclusive pin: the holder tracks the server's CURRENT
            # serving version, releasing its previous pin as it
            # upgrades.  A laggard (breaker-open during a push) keeps
            # head-1 alive in the store until it catches up or is
            # reaped.
            try:
                self.paramstore.pin(
                    int(h["version"]), f"server:{st.sid}", exclusive=True
                )
            except Exception:  # noqa: BLE001 — accounting, not dispatch
                pass
        cap = int(h.get("capacity", 0))
        if cap > 0 and self.autosize_inflight:
            # Size each client's agenerate bound to what its server can
            # actually co-decode.
            st.client.max_inflight = max(cap, 1)

    async def _refresh_health(self) -> None:
        if self.discovery is not None:
            try:
                mapping = await asyncio.to_thread(self.discovery)
            except Exception as e:  # noqa: BLE001 — keep the last view
                logger.warning(f"fleet discovery failed: {e!r}")
            else:
                self._sync_membership(dict(mapping))
        # Concurrent, individually-timed polls: one hung server costs
        # health_poll_timeout_s, not the whole fleet's refresh.
        await asyncio.gather(
            *(self._poll_one(s) for s in self._servers if not s.draining)
        )

    async def _maybe_refresh(self) -> None:
        if self._refresh_lock is None:
            self._refresh_lock = asyncio.Lock()
        async with self._refresh_lock:
            if time.monotonic() - self._health_ts < self.health_refresh_s:
                return
            await self._refresh_health()
            self._health_ts = time.monotonic()

    def _load_score(self, st: ServerState) -> float:
        h = st.health
        return (
            float(h.get("queue_depth", 0))
            + float(h.get("live_slots", 0))
            + st.local_load
        )

    def _eligible(self, exclude: FrozenSet[str]) -> List[ServerState]:
        return [
            s
            for s in self._servers
            if not s.draining
            and s.healthy
            and s.breaker.allow_dispatch()
            and s.sid not in exclude
        ]

    async def _choose_client(
        self, exclude: FrozenSet[str] = frozenset()
    ) -> Optional[ServerState]:
        """Least-loaded dispatchable server, preferring ones not in
        `exclude` (servers observed failing THIS prompt); waits through
        refreshes when nothing is dispatchable.  None only on stop()."""
        while not self._stop:
            await self._maybe_refresh()
            eligible = self._eligible(exclude) or self._eligible(frozenset())
            if eligible:
                return min(eligible, key=self._load_score)
            await asyncio.sleep(min(self.health_refresh_s, 0.1))
        return None

    # ---------------- the pump ----------------

    async def run(
        self,
        prompt_source: Optional[Iterable] = None,
        max_prompts: Optional[int] = None,
    ) -> RolloutStat:
        """Pump prompts until the source is exhausted, `max_prompts` are
        dispatched, or stop() — then await all in-flight dispatches.
        With no explicit source, the configured task-mixture stream is
        pumped (infinite — bound it with ``max_prompts``)."""
        if prompt_source is None:
            prompt_source = self.mixture
        if prompt_source is None:
            raise ValueError(
                "run() needs a prompt source (or a configured mixture)"
            )
        it: Iterator = iter(prompt_source)
        while self._skip_on_run > 0:
            if next(it, None) is None:
                break
            self._skip_on_run -= 1
        tasks: "set[asyncio.Task]" = set()
        dispatched = 0
        while not self._stop and (
            max_prompts is None or dispatched < max_prompts
        ):
            # Backpressure: a full buffer means the trainer is behind —
            # pulling more prompts would only evict unconsumed samples.
            while not self.replay.can_accept() and not self._stop:
                self.stat.backpressure_waits += 1
                self._m_backpressure.inc()
                tracer.counter(
                    "rollout_controller",
                    in_flight=self.stat.in_flight,
                    backpressured=1,
                )
                await asyncio.sleep(self.backpressure_poll_s)
            if self._stop:
                break
            item = next(it, None)
            if item is None:
                break
            qid, prompt_ids, task = _normalize_prompt(item, self.cursor)
            self.cursor += 1
            dispatched += 1
            t = asyncio.create_task(self._dispatch(qid, prompt_ids, task))
            tasks.add(t)
            t.add_done_callback(tasks.discard)
            # Yield so dispatches start promptly even on a fast source.
            await asyncio.sleep(0)
        if tasks:
            await asyncio.gather(*tasks)
        return self.stat

    async def completed_groups(
        self,
        n_groups: Optional[int] = None,
        timeout_per_group: Optional[float] = None,
        poll_s: float = 0.2,
    ):
        """Async iterator over retired GRPO groups, in retirement order.

        The streaming complement of ``replay.get_batch(batch_size)``:
        instead of parking until a whole stamped batch is resident, the
        consumer receives each finished group (one accepted Trajectory =
        one prompt's ``gconfig.n`` responses) as soon as the buffer
        retires it, stamped with ``retired_version`` for per-group
        staleness attribution.  This is the handoff the
        pipeline-overlapped trainer builds on: ref/reward inference for
        group *k* proceeds while groups *k+1..* are still decoding.

        Blocking waits run in a worker thread in short ``poll_s`` slices
        so ``stop()`` is honored promptly (the iterator then ends);
        ``timeout_per_group`` bounds how long any single group may take
        to retire (TimeoutError).  Yields forever when ``n_groups`` is
        None — pair with ``stop()`` or an explicit count.
        """
        yielded = 0
        while not self._stop and (n_groups is None or yielded < n_groups):
            deadline = (
                None
                if timeout_per_group is None
                else time.monotonic() + timeout_per_group
            )
            while True:
                if self._stop:
                    return
                wait = poll_s
                if deadline is not None:
                    remaining = deadline - time.monotonic()
                    if remaining <= 0:
                        raise TimeoutError(
                            f"completed_groups: waited {timeout_per_group}s "
                            "for the next admissible group"
                        )
                    wait = min(wait, remaining)
                try:
                    batch = await asyncio.to_thread(
                        self.replay.get_batch, 1, wait
                    )
                except TimeoutError:
                    continue  # poll slice expired; re-check stop/deadline
                break
            yield batch[0]
            yielded += 1

    async def _generate_with_retries(
        self, qid: str, prompt_ids: List[int], trace_id: str = ""
    ):
        """Dispatch with deadline + bounded redispatch.  Each failure
        excludes the observed-failing server for this prompt, records a
        breaker failure, and backs off exponentially; returns the output
        or None once every attempt is exhausted (or on stop())."""
        exclude: set = set()
        backoff = self.retry_backoff_s
        attempts = 1 + max(0, self.max_dispatch_retries)
        for attempt in range(attempts):
            srv = await self._choose_client(frozenset(exclude))
            if srv is None:  # stopped while waiting for a server
                return None
            srv.local_load += 1
            srv_version = srv.health.get("version")
            if srv_version is not None:
                # Dispatch-time lag between the trainer head and the
                # chosen server's serving weights — a persistently
                # positive gauge means weight sync is falling behind.
                self._m_version_lag.set(self.replay.version - int(srv_version))
            tracer.flight_event(
                "dispatch",
                trace_id=trace_id,
                qid=qid,
                sid=srv.sid,
                attempt=attempt,
            )
            err = reason = None
            try:
                if self.episode_runner is not None:
                    if self._runner_takes_trace:
                        coro = asyncio.to_thread(
                            self.episode_runner,
                            srv.client,
                            qid,
                            prompt_ids,
                            trace_id=trace_id or None,
                        )
                    else:
                        coro = asyncio.to_thread(
                            self.episode_runner, srv.client, qid, prompt_ids
                        )
                else:
                    coro = srv.client.agenerate(
                        APIGenerateInput(
                            qid=qid,
                            prompt_ids=prompt_ids,
                            gconfig=self.gconfig,
                            seed=self.seed,
                            trace_id=trace_id or None,
                        )
                    )
                if self.dispatch_timeout_s > 0:
                    out = await asyncio.wait_for(
                        coro, timeout=self.dispatch_timeout_s
                    )
                else:
                    out = await coro
            except asyncio.TimeoutError:
                err, reason = (
                    f"deadline ({self.dispatch_timeout_s}s) expired",
                    "timeout",
                )
            except Exception as e:  # noqa: BLE001 — one prompt, not the pump
                err, reason = repr(e), "error"
            finally:
                srv.local_load -= 1
            if err is None:
                srv.breaker.record_success()
                return out
            srv.breaker.record_failure()
            exclude.add(srv.sid)
            last = attempt == attempts - 1
            logger.warning(
                f"dispatch {qid} -> {srv.sid} failed ({err}); "
                + ("giving up" if last else "re-dispatching")
            )
            if not last:
                self.stat.redispatched += 1
                self._m_redispatch.labels(reason).inc()
                await asyncio.sleep(backoff)
                backoff = min(backoff * 2, 2.0)
        return None

    async def _dispatch(
        self, qid: str, prompt_ids: List[int], task: str = ""
    ) -> None:
        # Lineage root: every prompt's causal timeline starts here.  The
        # trace_id rides the request (HTTP header / ZMQ frame) through
        # gen server, grader, replay admission, and train consumption;
        # the task stamp lets trace_report attribute e2e latency per
        # task stream.
        trace_id = tracer.new_trace_id()
        t_dispatch = time.monotonic()
        tracer.lineage(
            "dispatch",
            trace_id,
            root=True,
            qid=qid,
            prompt_len=len(prompt_ids),
            trainer_version=self.replay.version,
            **({"task": task} if task else {}),
        )
        async with self._sem:
            self.stat.submitted += 1
            self.stat.in_flight += 1
            self._m_in_flight.set(self.stat.in_flight)
            tracer.counter(
                "rollout_controller",
                in_flight=self.stat.in_flight,
                backpressured=0,
            )
            # In-flight pin: the version this prompt was dispatched
            # against stays resident in the store until the prompt
            # terminates, so a server finishing a long episode can
            # still be repaired to that version if it lags.
            if self.paramstore is not None:
                try:
                    self.paramstore.pin(
                        self.replay.version,
                        f"dispatch:{qid}",
                        exclusive=False,
                    )
                except Exception:  # noqa: BLE001 — accounting only
                    pass
            try:
                out = await self._generate_with_retries(
                    qid, prompt_ids, trace_id
                )
            finally:
                self.stat.in_flight -= 1
                self._m_in_flight.set(self.stat.in_flight)
                if self.paramstore is not None:
                    try:
                        self.paramstore.release_holder(f"dispatch:{qid}")
                    except Exception:  # noqa: BLE001 — accounting only
                        pass
            if out is None:
                # Exhausted every retry: the prompt is explicitly failed
                # — visible in stat/metrics — never silently dropped.
                self.stat.failed += 1
                self._m_dispatched.labels("failed").inc()
                tracer.lineage("failed", trace_id, qid=qid, error="exhausted")
                return
            self.stat.completed += 1
        if self.episode_runner is not None:
            # One Episode -> ONE trajectory: version-stamped turns ride
            # in traj.data["episode"]; tool tokens carry zero logprobs.
            traj = out.to_trajectory(qid, birth_time=time.time())
        else:
            traj = Trajectory(
                qid=out.qid,
                prompt_ids=list(out.prompt_ids),
                output_ids=out.output_ids,
                output_logprobs=out.output_logprobs,
                no_eos=out.no_eos,
                version_start=out.version_start,
                version_end=out.version,
            )
        traj.trace_id = trace_id
        traj.t_dispatch = t_dispatch
        traj.task = task
        # Lossless backpressure on the put side too: a completed response
        # holds until the trainer drains a slot rather than evicting an
        # unconsumed sample.  Too-stale responses fall through to put()
        # and are rejected — waiting would not freshen them.
        while (
            not self._stop
            and len(self.replay) >= self.replay.capacity
            and self.replay.version - traj.version_start
            <= self.replay.max_head_offpolicyness
        ):
            self.stat.backpressure_waits += 1
            self._m_backpressure.inc()
            await asyncio.sleep(self.backpressure_poll_s)
        if self.replay.put(traj):
            self.stat.accepted += 1
            self._m_dispatched.labels("accepted").inc()
        else:
            self.stat.rejected += 1
            self._m_dispatched.labels("rejected").inc()
