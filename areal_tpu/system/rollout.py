"""Rollout controller: the actor plane of asynchronous RL.

Continuously pulls prompts from a data stream and fans them out to
generation servers (reference: AReaL's rollout worker +
`GenerationServer` pairing, realhf/system/rollout_worker.py; the
Podracer "actor plane", arxiv 2104.06272):

- **Queue-depth-aware load balancing**: each dispatch picks the client
  whose server reports the least load (collector queue depth + live
  decode slots from the enriched ``/health``) plus the controller's own
  not-yet-acknowledged dispatches to it — the cached health signal is
  refreshed at a bounded rate so balancing never becomes a health-poll
  storm.
- **Version stamping**: every trajectory records the weight version it
  STARTED sampling under (``version_start``, the head version) and the
  one it finished under — bounded-staleness admission in the
  ``ReplayBuffer`` keys on the head version.
- **Backpressure**: when the replay buffer cannot accept (at capacity),
  the controller stops pulling prompts instead of overrunning the
  buffer and evicting samples the trainer never saw.
- **Bounded fan-out**: a controller-level semaphore caps in-flight
  dispatches, on top of each client's per-loop ``agenerate`` bound.

The ``cursor`` (prompts consumed from the stream) is persisted in
``RecoverInfo`` so a recovered trial resumes the stream where it
stopped instead of re-sampling consumed prompts.
"""

import asyncio
import dataclasses
import time
from typing import Any, Dict, Iterable, Iterator, List, Optional, Sequence

from areal_tpu.api.model_api import APIGenerateInput, GenerationHyperparameters
from areal_tpu.base import logging, metrics, tracer
from areal_tpu.system.replay import ReplayBuffer, Trajectory

logger = logging.getLogger("rollout")


@dataclasses.dataclass
class RolloutStat:
    """Reference: AReaL's RolloutStat (submitted/accepted/running)."""

    submitted: int = 0
    completed: int = 0
    accepted: int = 0
    rejected: int = 0
    failed: int = 0
    in_flight: int = 0
    backpressure_waits: int = 0

    def as_dict(self) -> Dict[str, int]:
        return dataclasses.asdict(self)


def _normalize_prompt(item, cursor: int):
    """Accept (qid, prompt_ids) pairs, {"qid", "prompt_ids"} dicts, or
    bare token lists (qid auto-assigned from the cursor)."""
    if isinstance(item, dict):
        return str(item.get("qid", f"prompt{cursor}")), list(
            map(int, item["prompt_ids"])
        )
    if (
        isinstance(item, (tuple, list))
        and len(item) == 2
        and isinstance(item[0], str)
    ):
        return item[0], list(map(int, item[1]))
    return f"prompt{cursor}", [int(t) for t in item]


class RolloutController:
    """Pumps a prompt stream through gen servers into a ReplayBuffer."""

    def __init__(
        self,
        clients: Sequence[Any],  # LLMAPIClient / ZMQGenClient-compatible
        replay: ReplayBuffer,
        gconfig: GenerationHyperparameters,
        seed: Optional[int] = None,
        max_concurrency: int = 0,  # 0 = sum of client capacities
        health_refresh_s: float = 0.5,
        backpressure_poll_s: float = 0.05,
        autosize_inflight: bool = True,
    ):
        if not clients:
            raise ValueError("rollout controller needs at least one client")
        self.clients = list(clients)
        self.replay = replay
        self.gconfig = gconfig
        self.seed = seed
        self.health_refresh_s = health_refresh_s
        self.backpressure_poll_s = backpressure_poll_s
        # When True, each health poll resizes the client's agenerate
        # bound to the server-reported decode capacity; False keeps the
        # client's own max_inflight (e.g. to oversubscribe the collector
        # queue on purpose).
        self.autosize_inflight = autosize_inflight
        self.stat = RolloutStat()
        # Prompts consumed from the data stream since trial start
        # (persisted via state_dict -> RecoverInfo).
        self.cursor = 0
        self._skip_on_run = 0
        self._stop = False
        self._health: List[Dict] = [{} for _ in self.clients]
        self._health_ts = 0.0
        # Dispatches sent but not yet completed, per client — the live
        # correction on top of the (staler) polled queue depth.
        self._local_load = [0] * len(self.clients)
        cap = max_concurrency or sum(
            max(1, int(getattr(c, "max_inflight", 1))) for c in self.clients
        )
        self._sem = asyncio.Semaphore(cap)
        self.max_concurrency = cap
        reg = metrics.default_registry()
        self._m_in_flight = reg.gauge(
            "areal_rollout_in_flight", "dispatches awaiting a response"
        )
        self._m_backpressure = reg.counter(
            "areal_rollout_backpressure_total",
            "waits because the replay buffer could not accept",
        )
        self._m_dispatched = reg.counter(
            "areal_rollout_dispatched_total",
            "prompt dispatches, by terminal status",
            ("status",),
        )
        self._m_version_lag = reg.gauge(
            "areal_rollout_version_lag",
            "trainer weight version minus the dispatched server's "
            "serving version, at dispatch time",
        )

    # ---------------- recover ----------------

    def state_dict(self) -> Dict[str, Any]:
        return {"cursor": self.cursor, "stat": self.stat.as_dict()}

    def load_state_dict(self, sd: Dict[str, Any]) -> None:
        self.cursor = int(sd.get("cursor", 0))
        st = sd.get("stat", {})
        for k, v in st.items():
            if hasattr(self.stat, k) and k != "in_flight":
                setattr(self.stat, k, int(v))
        self.stat.in_flight = 0
        # On the next run(), fast-forward the (restarted) prompt stream
        # past everything the pre-restart trial already consumed.
        self._skip_on_run = self.cursor

    def stop(self) -> None:
        self._stop = True

    # ---------------- load balancing ----------------

    def _refresh_health(self) -> None:
        for i, c in enumerate(self.clients):
            try:
                self._health[i] = c.health()
                cap = int(self._health[i].get("capacity", 0))
                if cap > 0 and self.autosize_inflight:
                    # Size each client's agenerate bound to what its
                    # server can actually co-decode.
                    c.max_inflight = max(cap, 1)
            except Exception as e:  # noqa: BLE001 — deprioritize, don't die
                logger.warning(f"health poll failed for client {i}: {e!r}")
                self._health[i] = {"queue_depth": 1 << 30}

    def _load_score(self, i: int) -> float:
        h = self._health[i]
        return (
            float(h.get("queue_depth", 0))
            + float(h.get("live_slots", 0))
            + self._local_load[i]
        )

    async def _choose_client(self) -> int:
        now = time.monotonic()
        if now - self._health_ts >= self.health_refresh_s or not any(
            self._health
        ):
            self._health_ts = now
            await asyncio.to_thread(self._refresh_health)
        return min(range(len(self.clients)), key=self._load_score)

    # ---------------- the pump ----------------

    async def run(
        self,
        prompt_source: Iterable,
        max_prompts: Optional[int] = None,
    ) -> RolloutStat:
        """Pump prompts until the source is exhausted, `max_prompts` are
        dispatched, or stop() — then await all in-flight dispatches."""
        it: Iterator = iter(prompt_source)
        while self._skip_on_run > 0:
            if next(it, None) is None:
                break
            self._skip_on_run -= 1
        tasks: "set[asyncio.Task]" = set()
        dispatched = 0
        while not self._stop and (
            max_prompts is None or dispatched < max_prompts
        ):
            # Backpressure: a full buffer means the trainer is behind —
            # pulling more prompts would only evict unconsumed samples.
            while not self.replay.can_accept() and not self._stop:
                self.stat.backpressure_waits += 1
                self._m_backpressure.inc()
                tracer.counter(
                    "rollout_controller",
                    in_flight=self.stat.in_flight,
                    backpressured=1,
                )
                await asyncio.sleep(self.backpressure_poll_s)
            if self._stop:
                break
            item = next(it, None)
            if item is None:
                break
            qid, prompt_ids = _normalize_prompt(item, self.cursor)
            self.cursor += 1
            dispatched += 1
            t = asyncio.create_task(self._dispatch(qid, prompt_ids))
            tasks.add(t)
            t.add_done_callback(tasks.discard)
            # Yield so dispatches start promptly even on a fast source.
            await asyncio.sleep(0)
        if tasks:
            await asyncio.gather(*tasks)
        return self.stat

    async def _dispatch(self, qid: str, prompt_ids: List[int]) -> None:
        async with self._sem:
            idx = await self._choose_client()
            client = self.clients[idx]
            self._local_load[idx] += 1
            self.stat.submitted += 1
            self.stat.in_flight += 1
            self._m_in_flight.set(self.stat.in_flight)
            srv_version = self._health[idx].get("version")
            if srv_version is not None:
                # Dispatch-time lag between the trainer head and the
                # chosen server's serving weights — a persistently
                # positive gauge means weight sync is falling behind.
                self._m_version_lag.set(
                    self.replay.version - int(srv_version)
                )
            tracer.counter(
                "rollout_controller",
                in_flight=self.stat.in_flight,
                backpressured=0,
            )
            try:
                out = await client.agenerate(
                    APIGenerateInput(
                        qid=qid,
                        prompt_ids=prompt_ids,
                        gconfig=self.gconfig,
                        seed=self.seed,
                    )
                )
            except Exception as e:  # noqa: BLE001 — one prompt, not the pump
                self.stat.failed += 1
                self._m_dispatched.labels("failed").inc()
                logger.warning(f"rollout {qid} failed: {e!r}")
                return
            finally:
                self._local_load[idx] -= 1
                self.stat.in_flight -= 1
                self.stat.completed += 1
                self._m_in_flight.set(self.stat.in_flight)
        # Lossless backpressure on the put side too: a completed response
        # holds until the trainer drains a slot rather than evicting an
        # unconsumed sample.  Too-stale responses fall through to put()
        # and are rejected — waiting would not freshen them.
        while (
            not self._stop
            and len(self.replay) >= self.replay.capacity
            and self.replay.version - out.version_start
            <= self.replay.max_head_offpolicyness
        ):
            self.stat.backpressure_waits += 1
            self._m_backpressure.inc()
            await asyncio.sleep(self.backpressure_poll_s)
        traj = Trajectory(
            qid=out.qid,
            prompt_ids=list(out.prompt_ids),
            output_ids=out.output_ids,
            output_logprobs=out.output_logprobs,
            no_eos=out.no_eos,
            version_start=out.version_start,
            version_end=out.version,
        )
        if self.replay.put(traj):
            self.stat.accepted += 1
            self._m_dispatched.labels("accepted").inc()
        else:
            self.stat.rejected += 1
            self._m_dispatched.labels("rejected").inc()
