"""Bulk worker-to-worker transfer plane (data + params).

Capability parity: realhf/system/data_manager.py (NCCL bcast/gather/scatter
of packed tensors between GPU sets) + system/push_pull_stream.py — built for
the TPU process model: bulk payloads are HOST-side numpy pytrees moving
directly worker-to-worker over ZMQ PUSH/PULL (the control plane stays on the
master's request stream).  On-device placement happens at the receiver via
`device_put` onto its own mesh, so arbitrary src/dst layouts compose without
a cross-layout collective plan.

Transfers are tagged with a master-assigned `xfer_id`; receivers stash
out-of-order arrivals so concurrent transfers from different sources cannot
mismatch (the reference serializes with syn-ack ordering instead,
request_reply_stream.py:160-226).

Two implementations:
- InProcTransfer: queues shared between workers in one process (tests,
  single-host trials).
- ZMQTransfer: each worker binds a PULL socket, publishes it via
  name_resolve, and PUSHes to peers lazily.
"""

import pickle
import queue
import threading
from typing import Any, Dict, Optional, Tuple

from areal_tpu.base import logging, name_resolve, names, network

logger = logging.getLogger("transfer")


pushpull_name = names.push_pull_stream


class TransferPlane:
    """send() is addressed; recv() drains this worker's inbox."""

    def send(self, dst: int, xfer_id: int, payload: Any) -> None:
        raise NotImplementedError

    def recv(self, timeout: float = 300.0) -> Tuple[int, Any]:
        """Returns (xfer_id, payload)."""
        raise NotImplementedError

    def close(self) -> None:
        pass


class InProcTransfer(TransferPlane):
    """Shared-queue plane for in-process worker pools."""

    def __init__(self, inboxes: Dict[int, "queue.Queue"], my_index: int):
        self.inboxes = inboxes
        self.my_index = my_index

    @classmethod
    def make_group(cls, n_workers: int):
        inboxes: Dict[int, queue.Queue] = {
            i: queue.Queue() for i in range(n_workers)
        }
        return [cls(inboxes, i) for i in range(n_workers)]

    def send(self, dst: int, xfer_id: int, payload: Any) -> None:
        self.inboxes[dst].put((xfer_id, payload))

    def recv(self, timeout: float = 300.0) -> Tuple[int, Any]:
        try:
            return self.inboxes[self.my_index].get(timeout=timeout)
        except queue.Empty:
            raise TimeoutError(
                f"worker {self.my_index}: no transfer within {timeout}s"
            ) from None


class ZMQTransfer(TransferPlane):
    """PUSH/PULL plane for multi-process trials.

    The PULL socket binds eagerly at construction and its address is
    published via name_resolve; PUSH sockets to peers are created lazily and
    cached.  ZMQ sockets are not thread-safe and transfer handlers run on
    worker threads (stream.py _THREADED_TYPES), so one lock serializes all
    sends/closes, and recv() relies on the caller's single-receiver
    discipline (ModelWorker._recv_xfer: one draining thread at a time)."""

    def __init__(self, experiment: str, trial: str, worker_index: int):
        import zmq

        self.experiment = experiment
        self.trial = trial
        self.worker_index = worker_index
        self._ctx = zmq.Context()
        self._pull = self._ctx.socket(zmq.PULL)
        port = self._pull.bind_to_random_port("tcp://*")
        self._addr = f"tcp://{network.gethostip()}:{port}"
        name_resolve.add(
            pushpull_name(experiment, trial, worker_index),
            self._addr,
            replace=True,
        )
        self._push: Dict[int, Any] = {}
        self._lock = threading.Lock()
        logger.info(
            f"worker {worker_index} transfer plane bound at {self._addr}"
        )

    def send(self, dst: int, xfer_id: int, payload: Any) -> None:
        import zmq

        data = pickle.dumps((xfer_id, payload))
        with self._lock:
            if dst not in self._push:
                addr = name_resolve.wait(
                    pushpull_name(self.experiment, self.trial, dst),
                    timeout=300,
                )
                s = self._ctx.socket(zmq.PUSH)
                s.connect(addr)
                self._push[dst] = s
            self._push[dst].send(data)

    def recv(self, timeout: float = 300.0) -> Tuple[int, Any]:
        import zmq

        if not self._pull.poll(timeout * 1000):
            raise TimeoutError(
                f"worker {self.worker_index}: no transfer within {timeout}s"
            )
        return pickle.loads(self._pull.recv())

    def close(self) -> None:
        with self._lock:
            for s in self._push.values():
                s.close(linger=0)
            self._push.clear()
        self._pull.close(linger=0)
        self._ctx.term()
