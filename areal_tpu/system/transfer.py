"""Bulk worker-to-worker transfer plane (data + params).

Capability parity: realhf/system/data_manager.py (NCCL bcast/gather/scatter
of packed tensors between GPU sets) + system/push_pull_stream.py — built for
the TPU process model: bulk payloads are HOST-side numpy pytrees moving
directly worker-to-worker over ZMQ PUSH/PULL (the control plane stays on the
master's request stream).  On-device placement happens at the receiver via
`device_put` onto its own mesh, so arbitrary src/dst layouts compose without
a cross-layout collective plan.

Transfers are tagged with a master-assigned `xfer_id`; receivers stash
out-of-order arrivals so concurrent transfers from different sources cannot
mismatch (the reference serializes with syn-ack ordering instead,
request_reply_stream.py:160-226).

Two implementations:
- InProcTransfer: queues shared between workers in one process (tests,
  single-host trials).
- ZMQTransfer: each worker binds a PULL socket, publishes it via
  name_resolve, and PUSHes to peers lazily.
"""

import pickle
import queue
import threading
from typing import Any, Dict, List, Optional, Tuple

from areal_tpu.base import logging, name_resolve, names, network, tracer

logger = logging.getLogger("transfer")


pushpull_name = names.push_pull_stream


def encode_oob(payload: Any) -> Tuple[bytes, List]:
    """Pickle-protocol-5 encoding with OUT-OF-BAND buffers: large numpy
    arrays (the bulk of every data/param payload) stay as raw buffers
    instead of being copied into one pickle blob — the zero-copy framing
    the reference gets from NCCL sending device tensors directly
    (data_manager.py).  Returns (metadata_bytes, buffer_list)."""
    buffers: List = []
    meta = pickle.dumps(
        payload, protocol=5, buffer_callback=buffers.append
    )
    return meta, buffers


def payload_nbytes(meta: bytes, buffers: List) -> int:
    return len(meta) + sum(b.raw().nbytes for b in buffers)


class TransferPlane:
    """send() is addressed (returns payload bytes, for the master's
    per-step transfer stats); recv() drains this worker's inbox."""

    def send(self, dst: int, xfer_id: int, payload: Any) -> int:
        raise NotImplementedError

    def send_many(
        self,
        dsts: List[int],
        xfer_ids: List[int],
        payload: Any,
        encoded: Optional[Tuple[bytes, List]] = None,
    ) -> int:
        """Fan one payload out to many targets, ENCODING IT ONCE — the
        param-push fix: the old per-target send() re-walked and
        re-pickled the full tree per destination (and again on a
        checksum-reject retry).  `encoded` lets the caller cache the
        ``encode_oob`` result across retries too.  Returns total wire
        bytes (the per-target payload summed: that is what a pod
        ships)."""
        total = 0
        for dst, xid in zip(dsts, xfer_ids):
            total += self.send(dst, xid, payload)
        return total

    def recv(self, timeout: float = 300.0) -> Tuple[int, Any]:
        """Returns (xfer_id, payload)."""
        raise NotImplementedError

    def close(self) -> None:
        pass


class InProcTransfer(TransferPlane):
    """Shared-queue plane for in-process worker pools."""

    def __init__(self, inboxes: Dict[int, "queue.Queue"], my_index: int):
        self.inboxes = inboxes
        self.my_index = my_index

    @classmethod
    def make_group(cls, n_workers: int):
        inboxes: Dict[int, queue.Queue] = {
            i: queue.Queue() for i in range(n_workers)
        }
        return [cls(inboxes, i) for i in range(n_workers)]

    def send(self, dst: int, xfer_id: int, payload: Any) -> int:
        # The object moves by reference; bytes are still COUNTED with the
        # wire encoding so in-process tests measure what a pod would ship.
        with tracer.span("xfer_send", cat="comms", dst=dst) as targs:
            meta, buffers = encode_oob(payload)
            self.inboxes[dst].put((xfer_id, payload))
            nbytes = payload_nbytes(meta, buffers)
            targs["bytes"] = nbytes
        return nbytes

    def send_many(
        self,
        dsts: List[int],
        xfer_ids: List[int],
        payload: Any,
        encoded: Optional[Tuple[bytes, List]] = None,
    ) -> int:
        # One encode (for the byte count a pod would ship), N reference
        # moves — the in-process mirror of the zero-re-serialization
        # fan-out below.
        with tracer.span(
            "xfer_send", cat="comms", dsts=len(dsts)
        ) as targs:
            meta, buffers = encoded or encode_oob(payload)
            nbytes = payload_nbytes(meta, buffers)
            for dst, xid in zip(dsts, xfer_ids):
                self.inboxes[dst].put((xid, payload))
            total = nbytes * len(dsts)
            targs["bytes"] = total
        return total

    def recv(self, timeout: float = 300.0) -> Tuple[int, Any]:
        with tracer.span("xfer_recv", cat="comms"):
            try:
                return self.inboxes[self.my_index].get(timeout=timeout)
            except queue.Empty:
                raise TimeoutError(
                    f"worker {self.my_index}: no transfer within {timeout}s"
                ) from None


class ZMQTransfer(TransferPlane):
    """PUSH/PULL plane for multi-process trials.

    The PULL socket binds eagerly at construction and its address is
    published via name_resolve; PUSH sockets to peers are created lazily and
    cached.  ZMQ sockets are not thread-safe and transfer handlers run on
    worker threads (stream.py _THREADED_TYPES), so one lock serializes all
    sends/closes, and recv() relies on the caller's single-receiver
    discipline (ModelWorker._recv_xfer: one draining thread at a time)."""

    def __init__(self, experiment: str, trial: str, worker_index: int):
        import zmq

        self.experiment = experiment
        self.trial = trial
        self.worker_index = worker_index
        self._ctx = zmq.Context()
        self._pull = self._ctx.socket(zmq.PULL)
        port = self._pull.bind_to_random_port("tcp://*")
        self._addr = f"tcp://{network.gethostip()}:{port}"
        name_resolve.add(
            pushpull_name(experiment, trial, worker_index),
            self._addr,
            replace=True,
        )
        self._push: Dict[int, Any] = {}
        self._lock = threading.Lock()
        logger.info(
            f"worker {worker_index} transfer plane bound at {self._addr}"
        )

    def _sock_for(self, dst: int):
        # Caller holds self._lock.
        import zmq

        if dst not in self._push:
            addr = name_resolve.wait(
                pushpull_name(self.experiment, self.trial, dst),
                timeout=300,
            )
            s = self._ctx.socket(zmq.PUSH)
            s.connect(addr)
            self._push[dst] = s
        return self._push[dst]

    def send(self, dst: int, xfer_id: int, payload: Any) -> int:
        return self.send_many([dst], [xfer_id], payload)

    def send_many(
        self,
        dsts: List[int],
        xfer_ids: List[int],
        payload: Any,
        encoded: Optional[Tuple[bytes, List]] = None,
    ) -> int:
        # Multipart zero-copy framing: frame 0 = the tiny xfer-id pickle
        # (per-target), frame 1 = payload pickle metadata, frames 2.. =
        # raw array buffers (protocol-5 out-of-band).  The xfer id rides
        # its OWN frame so the big payload encoding is computed ONCE and
        # shared verbatim across every target — and, via `encoded`,
        # across a checksum-reject retry (the old framing pickled
        # (xfer_id, payload) together, re-walking the full tree per
        # target).
        with tracer.span(
            "xfer_send", cat="comms", dsts=len(dsts)
        ) as targs:
            meta, buffers = encoded or encode_oob(payload)
            shared = [meta] + [b.raw() for b in buffers]
            nbytes = payload_nbytes(meta, buffers)
            total = 0
            with self._lock:
                for dst, xid in zip(dsts, xfer_ids):
                    self._sock_for(dst).send_multipart(
                        [pickle.dumps(xid)] + shared, copy=False
                    )
                    total += nbytes
            targs["bytes"] = total
        return total

    def recv(self, timeout: float = 300.0) -> Tuple[int, Any]:
        import zmq

        with tracer.span("xfer_recv", cat="comms"):
            if not self._pull.poll(timeout * 1000):
                raise TimeoutError(
                    f"worker {self.worker_index}: no transfer within "
                    f"{timeout}s"
                )
            frames = self._pull.recv_multipart(copy=False)
            # Reconstruct over WRITABLE bytearrays (one memcpy per buffer):
            # arrays built over read-only zmq frame memory would diverge
            # from the in-process plane (which delivers ordinary writable
            # arrays) and crash any in-place consumer only on
            # multi-process runs — exactly where CI coverage is thinnest.
            # The send side stays zero-copy; this is the single
            # unavoidable receive copy.
            xid = pickle.loads(frames[0].buffer)
            payload = pickle.loads(
                frames[1].buffer,
                buffers=[bytearray(f.buffer) for f in frames[2:]],
            )
            return xid, payload

    def close(self) -> None:
        with self._lock:
            for s in self._push.values():
                s.close(linger=0)
            self._push.clear()
        self._pull.close(linger=0)
        self._ctx.term()
